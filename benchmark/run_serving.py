"""Open-loop mixed-length generation serving load: scheduling AND the
algorithmic serving optimizations, measured one ablation at a time.

The generator is OPEN-LOOP: request arrival times come from the rate
schedule alone (never from completions), which is what exposes a
serving architecture's real saturation behavior — a closed loop slows
its own arrivals down exactly when the server struggles and hides the
collapse.  The request mix is deliberately mixed-length (mostly short
answers plus a tail of long ones): under drain-then-refill scheduling
every batch runs at the speed of its LONGEST member, which is exactly
the pathology continuous batching removes (finished sequences leave
immediately and queued requests take their slots between ticks).

On top of the PR 8 static-vs-continuous comparison this bench drives
the SHARED-PREFIX workload (a configurable pool of system prompts +
hit ratio — the millions-of-users shape) through the ablation ladder:

  static_batch   drain-then-refill baseline
  continuous     PR 8 scheduling (prefix cache off, no draft)
  prefix         + block-level prefix caching
  spec           + speculative decoding (draft model)
  prefix+spec    both
  kernels        + the Pallas serving-kernel tier (serving_kernels=on:
                 fused paged-attention decode instead of the XLA
                 gather composition; interpret mode off-TPU, so the
                 CPU row demonstrates the PATH and its bit-identical
                 numerics, not kernel speed — the speed argument is
                 the static roofline section below)

Every row runs the same request set and reports sustained tokens/s,
p50/p99 request latency, shed rate, peak/mean KV-pool utilization,
prefix-cache hit rate, draft accept rate, and peak resident sequences.
Speculative rows TRAIN the target and a smaller draft briefly on a
cyclic-motif stream first (a random-init draft agrees with a
random-init target at ~1/vocab — no real serving deployment runs an
untrained draft, and the accept rate is the whole mechanism).

A final section sizes KV QUANTIZATION: same device byte budget, pool
blocks re-derived per kv_dtype, long-lived requests — reporting how
many sequences each precision holds resident at once.

The ROOFLINE section closes the loop on the serving-kernel tier:
before/after static rows for the decode step (XLA gather composition
vs fused Pallas paged attention) on the quantized-KV mix, plus a
static_vs_measured calibration of the kernel-backed estimates against
XLA's per-step cost analysis (band: flops [0.5, 2.5]x, bytes
[0.4, 3]x — tests/test_cost_model.py's documented tolerance).

Usage: python benchmark/run_serving.py [--requests 48] [--rate 0]
       [--slots 4] [--kv-blocks 56] [--block-size 8] [--d-model 128]
       [--layers 2] [--heads 4] [--prefix-pool 3] [--prefix-len 24]
       [--prefix-hit 0.75] [--spec-k 4] [--no-spec] [--no-quant]
       [--no-kernels] [--prom_out serving_prom.txt]
(--rate 0 = saturation: the whole request set arrives up front.)
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

VOCAB = 211
MOTIF = [3, 17, 42, 9, 88, 120, 5, 61, 199, 14, 73]


def _train_lm(d_model, n_layers, n_heads, max_len, iters=120, lr=3e-3,
              batch=8, seed=0):
    """Teach one decoder-only LM the cyclic motif (teacher-forced next-
    token loss) and return its trained state dict, extracted under the
    SAME unique-name discipline build_lm_paged_decoder uses.  A few
    seconds on CPU — the motif is trivial — but it makes greedy decode
    PREDICTABLE, which is what gives a smaller draft a real accept
    rate against the target."""
    import paddle_tpu as fluid
    import paddle_tpu.core.framework as fw
    from paddle_tpu.models.transformer import transformer_lm

    fw.reset_unique_names()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data(name="ids", shape=[max_len],
                                dtype="int64")
        lbl = fluid.layers.data(name="lbl", shape=[max_len, 1],
                                dtype="int64")
        probs = transformer_lm(ids, VOCAB, d_model=d_model,
                               n_heads=n_heads, n_layers=n_layers,
                               max_len=max_len)
        p2 = fluid.layers.reshape(probs, shape=[-1, VOCAB])
        l2 = fluid.layers.reshape(lbl, shape=[-1, 1])
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(input=p2, label=l2))
        fluid.Adam(learning_rate=lr).minimize(loss)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    r = np.random.RandomState(seed)
    motif = np.asarray(MOTIF, np.int64)
    for _ in range(iters):
        offs = r.randint(0, len(motif), batch)
        rows = np.stack([
            motif[(np.arange(max_len + 1) + o) % len(motif)]
            for o in offs])
        exe.run(main, feed={
            "ids": rows[:, :max_len].astype(np.int32),
            "lbl": rows[:, 1:, None].astype(np.int32)},
            fetch_list=[loss], scope=scope)
    params = [v.name for v in main.global_block().all_parameters()]
    return {n: np.asarray(scope.find_var(n)) for n in params}


def _build_decoder(d_model, n_layers, n_heads, block_size, max_blocks,
                   kv_dtype=None, states=None):
    import paddle_tpu as fluid
    import paddle_tpu.core.framework as fw
    from paddle_tpu.models.transformer import build_lm_paged_decoder

    fw.reset_unique_names()
    startup, dec = build_lm_paged_decoder(
        VOCAB, block_size, max_blocks, d_model=d_model, n_heads=n_heads,
        n_layers=n_layers, kv_dtype=kv_dtype)
    if states is None:
        scope = fluid.Scope()
        fluid.Executor(fluid.CPUPlace()).run(startup, scope=scope)
        states = {n: np.asarray(scope.find_var(n))
                  for n in dec.state_names}
    return dec, states


def make_requests(n, max_len, rng, long_every=4, prefix_pool=0,
                  prefix_len=0, prefix_hit=0.0):
    """Mixed-length open-loop mix: 1 long pole per `long_every`
    requests, the rest short — the shape that separates the two
    schedulers (a drain-then-refill batch always waits for its pole).

    With `prefix_pool` > 0, a fraction `prefix_hit` of requests draw
    their first `prefix_len` tokens from a pool of `prefix_pool`
    distinct shared prefixes (system prompts) — the workload shape
    block-level prefix caching converts into skipped prefill."""
    prefixes = [list(rng.randint(0, VOCAB, prefix_len))
                for _ in range(prefix_pool)]
    reqs = []
    for i in range(n):
        if prefixes and rng.rand() < prefix_hit:
            prompt = (prefixes[rng.randint(len(prefixes))]
                      + list(rng.randint(0, VOCAB, rng.randint(2, 9))))
        else:
            prompt = list(rng.randint(0, VOCAB, rng.randint(2, 9)))
        if i % long_every == long_every - 1:
            max_new = max_len - len(prompt) - 8   # long pole
        else:
            max_new = int(rng.randint(4, 9))      # short answer
        reqs.append((prompt, max_new))
    return reqs


def run_load(dec, states, reqs, *, static_batch=False, slots=4,
             kv_blocks=56, rate_rps=0.0, deadline_ms=None, place=None,
             prefix_cache=False, draft=None, draft_states=None,
             spec_k=4, mode_label=None):
    """Drive one request set through one server configuration; returns
    the measured row (tokens/s, latency percentiles, shed rate, KV
    util, prefix hit rate, draft accept rate, peak residency)."""
    import paddle_tpu as fluid
    from paddle_tpu.serving import GenerationServer, ServerSaturated

    server = GenerationServer(
        dec, states, slots=slots, kv_blocks=kv_blocks,
        static_batch=static_batch, place=place or fluid.CPUPlace(),
        prefix_cache=prefix_cache, draft_decoder=draft,
        draft_states=draft_states,
        spec_k=spec_k if draft is not None else None)
    n = len(reqs)
    lat = [None] * n
    toks = [0] * n
    shed = [False] * n
    waiters = []
    util_samples = []
    resident_samples = []
    stop_sampling = threading.Event()

    def sample_util():
        while not stop_sampling.wait(0.02):
            st = server.stats()
            util_samples.append(st["kv_pool_utilization"])
            resident_samples.append(st["active_sequences"])

    sampler = threading.Thread(target=sample_util, daemon=True)
    sampler.start()

    def wait_for(i, t0, stream):
        try:
            out = stream.result(timeout=300)
            lat[i] = time.perf_counter() - t0
            toks[i] = len(out)
        except Exception:
            shed[i] = True

    t_start = time.perf_counter()
    for i, (prompt, max_new) in enumerate(reqs):
        if rate_rps > 0:
            target = t_start + i / rate_rps
            delay = target - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
        t0 = time.perf_counter()
        try:
            stream = server.submit(prompt, max_new, seed=i,
                                   deadline_ms=deadline_ms)
        except ServerSaturated:
            shed[i] = True
            continue
        w = threading.Thread(target=wait_for, args=(i, t0, stream),
                             daemon=True)
        w.start()
        waiters.append(w)
    for w in waiters:
        w.join(timeout=300)
    wall = time.perf_counter() - t_start
    stop_sampling.set()
    sampler.join(timeout=1)
    stats = server.stats()
    server.close()

    done_lat = [l for l in lat if l is not None]
    total_tokens = sum(toks)
    lookups = stats["prefix_hits"] + stats["prefix_misses"]
    if mode_label is None:
        mode_label = "static_batch" if static_batch else "continuous"
    return {
        "mode": mode_label,
        "requests": n,
        "completed": len(done_lat),
        "tokens": total_tokens,
        "wall_s": round(wall, 3),
        "tokens_per_sec": round(total_tokens / wall, 1) if wall else 0.0,
        "latency_p50_s": round(float(np.percentile(done_lat, 50)), 4)
        if done_lat else None,
        "latency_p99_s": round(float(np.percentile(done_lat, 99)), 4)
        if done_lat else None,
        "shed_rate": round(sum(shed) / n, 4),
        "kv_util_peak": round(max(util_samples), 3) if util_samples
        else None,
        "kv_util_mean": round(float(np.mean(util_samples)), 3)
        if util_samples else None,
        "resident_peak": int(max(resident_samples))
        if resident_samples else None,
        "decode_ticks": stats["ticks"],
        "decode_kernel": stats.get("decode_kernel", "xla"),
        "prefix_hit_rate": round(stats["prefix_hits"] / lookups, 3)
        if lookups else None,
        "draft_accept_rate": round(
            stats["draft_accepted"] / stats["draft_proposed"], 3)
        if stats["draft_proposed"] else None,
    }


def _quant_residency(d_model, n_layers, n_heads, block_size, max_blocks,
                     states, kv_blocks_fp32, place=None):
    """Same device byte budget per precision, pool blocks re-derived
    from bytes_per_block, long-lived concurrent requests: how many
    sequences does each kv_dtype hold resident at once?"""
    import paddle_tpu as fluid

    rows = {}
    rng = np.random.RandomState(7)
    budget = None
    for kv_dtype in ("fp32", "bf16", "int8"):
        dec, _ = _build_decoder(d_model, n_layers, n_heads, block_size,
                                max_blocks, kv_dtype=kv_dtype,
                                states=states)
        if budget is None:
            budget = kv_blocks_fp32 * dec.bytes_per_block
        kv_blocks = max(1, budget // dec.bytes_per_block)
        from paddle_tpu.serving import GenerationServer

        srv = GenerationServer(dec, states, slots=64,
                               kv_blocks=int(kv_blocks),
                               place=place or fluid.CPUPlace())
        max_len = block_size * max_blocks
        n_req = int(kv_blocks) // max(1, dec.max_blocks_per_seq) + 6
        streams = [srv.submit(list(rng.randint(0, VOCAB, 4)),
                              max_len - 12)
                   for _ in range(n_req)]
        peak = 0
        deadline = time.monotonic() + 120
        while (any(not s.done for s in streams)
               and time.monotonic() < deadline):
            peak = max(peak, srv.stats()["active_sequences"])
            time.sleep(0.01)
        srv.close()
        rows[kv_dtype] = {"kv_blocks": int(kv_blocks),
                          "bytes_per_block": dec.bytes_per_block,
                          "resident_peak": peak}
    rows["int8_vs_fp32_residency"] = round(
        rows["int8"]["resident_peak"]
        / max(rows["fp32"]["resident_peak"], 1), 2)
    rows["byte_budget"] = int(budget)
    return rows


def _build_kernel_decoder(d_model, n_layers, n_heads, block_size,
                          max_blocks, kv_dtype=None, states=None):
    """`_build_decoder` with the serving-kernel tier forced ON for the
    duration of the build (kernel selection happens at build time),
    restoring the user's flag after."""
    from paddle_tpu.core import flags as core_flags

    prev = core_flags.get_flag("serving_kernels")
    core_flags.set_flags({"serving_kernels": "on"})
    try:
        return _build_decoder(d_model, n_layers, n_heads, block_size,
                              max_blocks, kv_dtype=kv_dtype,
                              states=states)
    finally:
        core_flags.set_flags({"serving_kernels": prev})


def _measured_step_cost(d_model, n_layers, n_heads, block_size,
                        max_blocks, kv_dtype, slots, kernels_on):
    """XLA-measured (flops, bytes accessed) for ONE compiled decode
    tick of a freshly built decoder — the calibration denominator.

    The probe right-sizes the KV pool (`max_blocks` blocks) and parks
    every cursor at full context: XLA's accounting is per-OP (a gather
    "accesses" its whole operand), so an oversized pool inflates
    measured bytes with buffer size — traffic the per-step static
    model deliberately does not charge."""
    import jax.numpy as jnp

    build = _build_kernel_decoder if kernels_on else _build_decoder
    dec, states = build(d_model, n_layers, n_heads, block_size,
                        max_blocks, kv_dtype=kv_dtype)
    sj = {n: jnp.asarray(v) for n, v in states.items()}
    pool_k, pool_v = dec.init_pool(max_blocks)
    tables = jnp.zeros((slots, max_blocks), jnp.int32)
    positions = jnp.full((slots,), block_size * max_blocks - 1,
                         jnp.int32)
    zi = jnp.zeros((slots,), jnp.int32)
    lowered = dec.step.lower(sj, pool_k, pool_v, tables, positions,
                             zi, zi, jnp.zeros((slots,), jnp.float32),
                             jnp.ones((slots,), bool))
    ca = lowered.compile().cost_analysis()
    if isinstance(ca, list):
        ca = ca[0] if ca else {}
    backend = dec.kernels.get("paged_attention_decode", "xla")
    return (float((ca or {}).get("flops", 0.0)),
            float((ca or {}).get("bytes accessed", 0.0)), backend)


def kernel_roofline(d_model, n_layers, n_heads, block_size, max_blocks,
                    slots, kv_dtypes=("fp32", "int8"), calibrate=True):
    """Before/after roofline rows for the decode step — the XLA gather
    composition vs the fused Pallas paged-attention kernel — on the
    quantized-KV mix, plus the static_vs_measured calibration of the
    kernel-backed estimates.  Band per tests/test_cost_model.py:
    flops within [0.5, 2.5]x and bytes within [0.4, 3]x of XLA's
    per-step cost analysis (estimated / measured)."""
    from paddle_tpu.analysis.cost_model import (roofline_seconds,
                                                serving_kernel_cost)

    ctx = block_size * max_blocks
    out = {"slots": slots, "context": ctx, "rows": [],
           "band": {"flops": [0.5, 2.5], "bytes": [0.4, 3.0]},
           "pallas_vs_xla_bytes": {}}
    in_band = True
    for kv_dtype in kv_dtypes:
        spec = dict(d_model=d_model, n_layers=n_layers,
                    n_heads=n_heads, vocab_size=VOCAB,
                    block_size=block_size,
                    max_blocks_per_seq=max_blocks, kv_dtype=kv_dtype)
        pair = {}
        for kernels_on, backend in ((False, "xla"), (True, "pallas")):
            est = serving_kernel_cost(
                "paged_decode_step", spec, slots=slots, context=ctx,
                kv_dtype=kv_dtype, backend=backend)
            row = {"kv_dtype": kv_dtype, "backend": backend,
                   "est_flops": est["flops"],
                   "est_bytes": est["bytes"],
                   "ai_flop_per_byte": est["ai_flop_per_byte"],
                   "bound": est["bound"],
                   "floor_s": roofline_seconds(est["flops"],
                                               est["bytes"])}
            if calibrate:
                mf, mb, built = _measured_step_cost(
                    d_model, n_layers, n_heads, block_size,
                    max_blocks, kv_dtype, slots, kernels_on)
                fr = est["flops"] / mf if mf else None
                br = est["bytes"] / mb if mb else None
                row.update(
                    xla_flops=mf, xla_bytes=mb, built_kernel=built,
                    flops_ratio=round(fr, 3) if fr else None,
                    bytes_ratio=round(br, 3) if br else None)
                ok = (fr is not None and br is not None
                      and 0.5 < fr < 2.5 and 0.4 < br < 3.0)
                row["in_band"] = ok
                in_band = in_band and ok
            out["rows"].append(row)
            pair[backend] = est
        out["pallas_vs_xla_bytes"][kv_dtype] = round(
            pair["pallas"]["bytes"] / pair["xla"]["bytes"], 3)
    if calibrate:
        out["static_vs_measured_ok"] = in_band
    return out


def run_serving_bench(requests=48, rate_rps=0.0, slots=4, kv_blocks=56,
                      block_size=8, max_blocks=12, d_model=128,
                      n_layers=2, n_heads=4, deadline_ms=None,
                      prom_out="", trials=2, prefix_pool=3,
                      prefix_len=24, prefix_hit=0.75, spec_k=4,
                      draft_d_model=32, draft_layers=1, with_spec=True,
                      with_quant=True, with_kernels=True):
    """BENCH_SERVING entry point (bench.py): the scheduler ablation
    ladder over the same shared-prefix mixed-length open-loop request
    set; best-of-`trials` per mode; optional Prometheus dump of the
    serving series."""
    from paddle_tpu.observability import exporters
    from paddle_tpu.observability import metrics as obs_metrics

    # armed only for the duration of this bench: later bench.py
    # sections (convergence, book matrix) must run exactly as the
    # user's PADDLE_TPU_METRICS setting asks
    metrics_were_on = obs_metrics.enabled()
    obs_metrics.set_enabled(True)
    try:
        max_len = block_size * max_blocks
        t0 = time.perf_counter()
        states = draft_states = None
        if with_spec:
            states = _train_lm(d_model, n_layers, n_heads, max_len)
            draft_states = _train_lm(draft_d_model, draft_layers,
                                     n_heads, max_len, iters=120,
                                     seed=1)
        train_s = round(time.perf_counter() - t0, 1)
        dec, states = _build_decoder(d_model, n_layers, n_heads,
                                     block_size, max_blocks,
                                     states=states)
        draft = None
        if with_spec:
            draft, draft_states = _build_decoder(
                draft_d_model, draft_layers, n_heads, block_size,
                max_blocks, states=draft_states)
        reqs = make_requests(requests, max_len, np.random.RandomState(0),
                             prefix_pool=prefix_pool,
                             prefix_len=prefix_len,
                             prefix_hit=prefix_hit)
        ladder = [
            ("static_batch", dict(static_batch=True)),
            ("continuous", dict()),
            ("prefix", dict(prefix_cache=True)),
        ]
        if with_spec:
            ladder += [
                ("spec", dict(draft=draft, draft_states=draft_states,
                              spec_k=spec_k)),
                ("prefix+spec", dict(prefix_cache=True, draft=draft,
                                     draft_states=draft_states,
                                     spec_k=spec_k)),
            ]
        kdec = None
        if with_kernels:
            # kernel selection happens at BUILD time; same trained
            # weights through the same unique-name discipline, so the
            # rung isolates the attention path swap
            kdec, _ = _build_kernel_decoder(
                d_model, n_layers, n_heads, block_size, max_blocks,
                states=states)
            kkw = dict(prefix_cache=True)
            if with_spec:
                kkw.update(draft=draft, draft_states=draft_states,
                           spec_k=spec_k)
            ladder.append(("kernels", kkw))
        rows = {}
        for label, kw in ladder:
            best = None
            # the kernels rung runs Pallas in interpret mode off-TPU:
            # one trial — the row demonstrates the path, not CPU speed
            for _ in range(1 if label == "kernels" else trials):
                row = run_load(kdec if label == "kernels" else dec,
                               states, reqs, slots=slots,
                               kv_blocks=kv_blocks, rate_rps=rate_rps,
                               deadline_ms=deadline_ms,
                               mode_label=label, **kw)
                if best is None or row["tokens_per_sec"] > best[
                        "tokens_per_sec"]:
                    best = row
            rows[label] = best
        base = rows["continuous"]["tokens_per_sec"]
        out = {
            "bench": "serving",
            "slots": slots, "kv_blocks": kv_blocks,
            "block_size": block_size, "d_model": d_model,
            "layers": n_layers, "rate_rps": rate_rps,
            "prefix_pool": prefix_pool, "prefix_len": prefix_len,
            "prefix_hit": prefix_hit,
            "spec_k": spec_k if with_spec else 0,
            "train_s": train_s,
            "ablation": rows,
            "continuous_speedup": round(
                base / max(rows["static_batch"]["tokens_per_sec"],
                           1e-9), 2),
            "prefix_speedup": round(
                rows["prefix"]["tokens_per_sec"] / max(base, 1e-9), 2),
        }
        if with_spec:
            out["spec_speedup"] = round(
                rows["spec"]["tokens_per_sec"] / max(base, 1e-9), 2)
            out["stacked_speedup"] = round(
                rows["prefix+spec"]["tokens_per_sec"]
                / max(base, 1e-9), 2)
        if with_kernels:
            out["kernels_vs_continuous"] = round(
                rows["kernels"]["tokens_per_sec"] / max(base, 1e-9), 2)
            out["roofline"] = kernel_roofline(
                d_model, n_layers, n_heads, block_size, max_blocks,
                slots)
        if with_quant:
            out["kv_quantization"] = _quant_residency(
                d_model, n_layers, n_heads, block_size, max_blocks,
                states, kv_blocks)
        out["phase_breakdown"] = phase_breakdown(
            decode_backend=rows["kernels"]["decode_kernel"]
            if with_kernels else None)
        if prom_out:
            out["prometheus_dump"] = exporters.write_prometheus(prom_out)
        return out
    finally:
        obs_metrics.set_enabled(metrics_were_on)


def phase_breakdown(decode_backend=None):
    """This process's per-phase attribution (lifetime sums of the
    paddle_tpu_*_phase_seconds families), as rows plus the rendered
    `cli why` table — the artifact's "where did the bench spend its
    time" section.

    `decode_backend` (the kernels rung's selection, "pallas" or a
    fallback reason) is stamped onto the generation decode/draft_verify
    rows so `cli why` readers see WHAT ran the attention math, not just
    where the time went."""
    from paddle_tpu.observability import attribution, exporters
    from paddle_tpu.observability.collector import parse_prometheus_text

    try:
        parsed = parse_prometheus_text(exporters.prometheus_text())
        rows = attribution.why_rows_from_parsed(parsed)
        if decode_backend:
            for r in rows:
                if (r.get("kind") == "generation"
                        and r.get("phase") in ("decode",
                                               "draft_verify")):
                    r["backend"] = decode_backend
        out = {"rows": rows,
               "table": attribution.format_why_table(rows)}
        if decode_backend:
            out["decode_backend"] = decode_backend
        return out
    except Exception as e:  # attribution must never fail the bench
        return {"error": f"{type(e).__name__}: {e}"}


def write_bench_artifact(out, directory=".", prefix="BENCH_SERVING"):
    """Write `out` as the next free ``<prefix>_rNN.json`` revision in
    `directory` (the repo's committed-artifact convention: BENCH_r05,
    BOOK_MATRIX_r05, ...).  Returns the path."""
    n = 1
    while True:
        path = os.path.join(directory, f"{prefix}_r{n:02d}.json")
        if not os.path.exists(path):
            break
        n += 1
    with open(path, "w") as f:
        json.dump(out, f, indent=1, default=str)
    return path


# ---------------------------------------------------------------------------
# --ramp: open-loop load ramp against a LIVE autoscaling fleet
# (the ROADMAP-4 acceptance driver; reused by tools/mini_fleet.py's
# autoscale drill and tests/test_autoscaler.py)
# ---------------------------------------------------------------------------


def ramp_rates(peak_rps, floor_frac=0.25):
    """The up-then-down open-loop schedule: floor -> half -> peak ->
    half -> floor."""
    return [peak_rps * floor_frac, peak_rps * 0.5, peak_rps,
            peak_rps * 0.5, peak_rps * floor_frac]


def run_ramp(submit, reqs, rates, phase_s, *, result_timeout_s=180.0,
             deadline_ms=None, on_phase=None):
    """Drive an open-loop up-then-down ramp through `submit(prompt,
    max_new, deadline_ms=...) -> stream` (a GenerationServer or a
    ReplicaRouter — the fleet path).  Arrivals follow the rate
    schedule alone; each request is attributed to the phase it ARRIVED
    in.  Returns per-phase tokens/s, p50/p99 completion latency and
    shed rate, plus the totals the zero-failed acceptance pins:
    `failed` counts non-shed errors (sheds are policy answers)."""
    from paddle_tpu.serving import (RequestDeadlineExceeded,
                                    ServerSaturated)

    reqs = list(reqs)
    results = []  # (phase, latency_or_None, ntokens, shed, failed)
    rlock = threading.Lock()
    waiters = []
    it = iter(reqs)

    def wait_for(phase, t0, stream):
        lat = ntok = 0
        shed = failed = False
        try:
            out = stream.result(timeout=result_timeout_s)
            lat, ntok = time.perf_counter() - t0, len(out)
        except (RequestDeadlineExceeded, ServerSaturated):
            shed = True
        except Exception:
            failed = True
        with rlock:
            results.append((phase, lat if ntok else None, ntok, shed,
                            failed))

    t_start = time.perf_counter()
    for phase, rate in enumerate(rates):
        phase_t0 = time.perf_counter()
        n_phase = max(1, int(rate * phase_s))
        for i in range(n_phase):
            target = phase_t0 + i / rate if rate > 0 else phase_t0
            delay = target - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            req = next(it, None)
            if req is None:
                it = iter(reqs)   # recycle the mix
                req = next(it)
            prompt, max_new = req
            t0 = time.perf_counter()
            try:
                stream = submit(prompt, max_new,
                                deadline_ms=deadline_ms)
            except (ServerSaturated, RequestDeadlineExceeded):
                with rlock:
                    results.append((phase, None, 0, True, False))
                continue
            except Exception:
                with rlock:
                    results.append((phase, None, 0, False, True))
                continue
            w = threading.Thread(target=wait_for,
                                 args=(phase, t0, stream), daemon=True)
            w.start()
            waiters.append(w)
        left = phase_s - (time.perf_counter() - phase_t0)
        if left > 0:
            time.sleep(left)
        if on_phase is not None:
            on_phase(phase, rate)
    for w in waiters:
        w.join(timeout=result_timeout_s)
    wall = time.perf_counter() - t_start

    phases = []
    for phase, rate in enumerate(rates):
        rows = [r for r in results if r[0] == phase]
        lats = [r[1] for r in rows if r[1] is not None]
        toks = sum(r[2] for r in rows)
        phases.append({
            "phase": phase, "rate_rps": round(rate, 2),
            "requests": len(rows),
            "tokens_per_sec": round(toks / phase_s, 1),
            "latency_p50_s": round(float(np.percentile(lats, 50)), 4)
            if lats else None,
            "latency_p99_s": round(float(np.percentile(lats, 99)), 4)
            if lats else None,
            "shed_rate": round(sum(r[3] for r in rows)
                               / max(len(rows), 1), 4),
        })
    return {
        "rates_rps": [round(r, 2) for r in rates],
        "phase_s": phase_s,
        "wall_s": round(wall, 2),
        "requests": len(results),
        "tokens": sum(r[2] for r in results),
        "shed": sum(1 for r in results if r[3]),
        "failed": sum(1 for r in results if r[4]),
        "phases": phases,
    }


def run_fleet_ramp_bench(*, requests=64, peak_rps=20.0, phase_s=6.0,
                         min_replicas=1, max_replicas=3,
                         backlog_high=64.0, backlog_low=8.0,
                         sustain_s=1.0, idle_sustain_s=4.0,
                         cooldown_s=4.0, d_model=32, n_layers=1,
                         n_heads=2, block_size=4, max_blocks=8,
                         slots=2, kv_blocks=24, use_tpu=0,
                         workdir=None, spawn_timeout_s=300.0,
                         decode_delay_s=0.02, phase_hook=None,
                         post_hook=None, env_extra=None):
    """BENCH_SERVING_RAMP entry point: save a warm-start model dir,
    front it with ReplicaRouter + Autoscaler spawning REAL `cli serve`
    replicas, drive the open-loop ramp, and report per-phase serving
    stats alongside the scaling timeline and each new replica's
    cold-start accounting (spawn->live seconds; warm-started replicas
    deserialize their executables, so the time-to-first-token of a
    scale-out is bounded by model load, not XLA compile).

    `decode_delay_s` arms a PADDLE_TPU_FAULTS delay rule on the
    replicas' ``serving.decode`` chaos site: the bench model is tiny
    (a laptop CPU decodes it at thousands of tokens/s), so the
    injected per-tick latency stands in for a real accelerator's — it
    makes the overload, and therefore the scale-out/scale-in
    trajectory, deterministic across hosts.  Pass 0 to measure the
    raw fleet instead.

    Chaos-drill hooks (tools/mini_fleet.py --drill autoscale rides
    this function rather than re-building the fleet):
    `phase_hook(phase, rate, router, scaler)` fires after each ramp
    phase (e.g. SIGKILL an owned replica at the peak);
    `post_hook(record, router, scaler)` fires on the finished record
    BEFORE teardown (the autoscaler/router metric series are reclaimed
    on close, so a telemetry scrape must happen here); `env_extra`
    merges into the replica environment."""
    import shutil
    import tempfile

    import paddle_tpu as fluid
    from paddle_tpu.cloud.autoscaler import (Autoscaler,
                                             AutoscalerPolicy,
                                             SubprocessReplicaLauncher)
    from paddle_tpu.cloud.router import ReplicaRouter
    from paddle_tpu.serving import save_generation_model
    from paddle_tpu.serving.replica import replica_call

    own_workdir = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="paddle_ramp_")
    model_dir = os.path.join(workdir, "model")
    max_len = block_size * max_blocks
    dec, states = _build_decoder(d_model, n_layers, n_heads,
                                 block_size, max_blocks)
    t0 = time.perf_counter()
    save_generation_model(
        model_dir, states,
        {"vocab_size": VOCAB, "d_model": d_model, "n_heads": n_heads,
         "n_layers": n_layers, "block_size": block_size,
         "max_blocks_per_seq": max_blocks, "slots": slots,
         "kv_blocks": kv_blocks},
        warm_start=True, place=fluid.CPUPlace())
    artifact_s = round(time.perf_counter() - t0, 2)

    router = ReplicaRouter(desired=max_replicas * 2, refresh_s=0.1)
    policy = AutoscalerPolicy(
        min_replicas, max_replicas, p99_high_s=30.0,
        backlog_high=backlog_high, backlog_low=backlog_low,
        sustain_s=sustain_s, idle_sustain_s=idle_sustain_s,
        cooldown_s=cooldown_s)
    extra = dict(env_extra or {})
    if decode_delay_s > 0:
        extra["PADDLE_TPU_FAULTS"] = ",".join(filter(None, [
            extra.get("PADDLE_TPU_FAULTS",
                      os.environ.get("PADDLE_TPU_FAULTS", "")),
            f"serving.decode:delay:1:1000000000:{decode_delay_s}"]))
    env = dict(os.environ, **extra) if extra else None
    launcher = SubprocessReplicaLauncher(
        model_dir, router.registry_addr, use_tpu=use_tpu, ttl_s=1.5,
        drain_grace_s=30.0, env=env)
    scaler = Autoscaler(router, launcher, policy, poll_s=0.2,
                        window_s=8.0,
                        spawn_timeout_s=spawn_timeout_s,
                        drain_grace_s=30.0)
    reqs = make_requests(requests, max_len, np.random.RandomState(0))
    fleet_sizes = []

    def _on_phase(p, r):
        fleet_sizes.append(
            len(router.live_replicas(include_draining=False)))
        if phase_hook is not None:
            phase_hook(p, r, router, scaler)

    try:
        scaler.ensure_min(timeout_s=spawn_timeout_s)
        scaler.start()
        ramp = run_ramp(
            router.submit, reqs, ramp_rates(peak_rps), phase_s,
            on_phase=_on_phase)
        # ramp-down tail: give the idle-sustain window room to retire
        deadline = time.monotonic() + 4 * (idle_sustain_s
                                           + cooldown_s) + 30
        while (len(router.live_replicas(include_draining=False))
               > min_replicas and time.monotonic() < deadline):
            time.sleep(0.2)
        replicas = {}
        for addr in router.live_replicas():
            try:
                st = replica_call(addr, {"op": "stats"},
                                  timeout_s=10)["stats"]
                replicas[addr] = {
                    "warm_start": st.get("warm_start"),
                    "warmup_s": st.get("warmup_s"),
                    "compile_seconds": st.get("compile_seconds"),
                    "cache_hits": st.get("cache_hits"),
                    "cache_misses": st.get("cache_misses"),
                    "recompiles_after_warmup":
                        st.get("recompiles_after_warmup"),
                }
            except OSError:
                pass
        out = {
            "bench": "serving_ramp",
            "peak_rps": peak_rps, "phase_s": phase_s,
            "decode_delay_s": decode_delay_s,
            "band": [min_replicas, max_replicas],
            "artifact_build_s": artifact_s,
            "ramp": ramp,
            "fleet_size_per_phase": fleet_sizes,
            "fleet_size_final": len(
                router.live_replicas(include_draining=False)),
            "scale_events": list(scaler.events),
            "status": scaler.status(),
            "replicas": replicas,
            "router": router.stats(),
        }
        if post_hook is not None:
            post_hook(out, router, scaler)
        return out
    finally:
        scaler.close(retire_owned=True)
        router.close()
        if own_workdir:
            shutil.rmtree(workdir, ignore_errors=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=48)
    ap.add_argument("--rate", type=float, default=0.0,
                    help="open-loop arrival rate, req/s (0=all up "
                    "front: saturation)")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--kv-blocks", type=int, default=56)
    ap.add_argument("--block-size", type=int, default=8)
    ap.add_argument("--max-blocks", type=int, default=12)
    ap.add_argument("--d-model", type=int, default=128)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--deadline-ms", type=float, default=None)
    ap.add_argument("--trials", type=int, default=2)
    ap.add_argument("--prefix-pool", type=int, default=3,
                    help="distinct shared prefixes (system prompts)")
    ap.add_argument("--prefix-len", type=int, default=24)
    ap.add_argument("--prefix-hit", type=float, default=0.75,
                    help="fraction of requests drawing a pooled prefix")
    ap.add_argument("--spec-k", type=int, default=4)
    ap.add_argument("--no-spec", action="store_true",
                    help="skip the speculative-decoding rows (and the "
                    "brief target/draft training they need)")
    ap.add_argument("--no-quant", action="store_true",
                    help="skip the KV-quantization residency section")
    ap.add_argument("--no-kernels", action="store_true",
                    help="skip the serving-kernel rung and the "
                    "roofline before/after + calibration section")
    ap.add_argument("--prom_out", default="",
                    help="write the Prometheus text dump here")
    ap.add_argument("--ramp", action="store_true",
                    help="instead of the ablation ladder, run the "
                    "open-loop load ramp against a LIVE autoscaling "
                    "fleet (router + autoscaler + `cli serve` "
                    "replicas): rate ramps up then down, reporting "
                    "per-phase tokens/s, p99, shed rate, the scaling "
                    "timeline, and new-replica warm-start accounting")
    ap.add_argument("--ramp-peak", type=float, default=24.0,
                    help="peak arrival rate req/s at the ramp top")
    ap.add_argument("--ramp-phase-s", type=float, default=6.0)
    ap.add_argument("--ramp-max", type=int, default=3,
                    help="max replicas the autoscaler may spawn")
    ap.add_argument("--artifact-dir", default="",
                    help="also write the result as the next free "
                    "BENCH_SERVING_rNN.json (BENCH_SERVING_RAMP_rNN "
                    "for --ramp) revision in this directory")
    a = ap.parse_args()
    if a.ramp:
        out = run_fleet_ramp_bench(
            requests=a.requests, peak_rps=a.ramp_peak,
            phase_s=a.ramp_phase_s, max_replicas=a.ramp_max,
            d_model=a.d_model, n_layers=a.layers, n_heads=a.heads,
            block_size=a.block_size, max_blocks=a.max_blocks,
            slots=a.slots)
        out["phase_breakdown"] = phase_breakdown()
        if a.artifact_dir:
            out["artifact"] = write_bench_artifact(
                out, a.artifact_dir, prefix="BENCH_SERVING_RAMP")
        print(json.dumps(out))
        return
    out = run_serving_bench(
        requests=a.requests, rate_rps=a.rate, slots=a.slots,
        kv_blocks=a.kv_blocks, block_size=a.block_size,
        max_blocks=a.max_blocks, d_model=a.d_model, n_layers=a.layers,
        n_heads=a.heads, deadline_ms=a.deadline_ms, trials=a.trials,
        prefix_pool=a.prefix_pool, prefix_len=a.prefix_len,
        prefix_hit=a.prefix_hit, spec_k=a.spec_k,
        with_spec=not a.no_spec, with_quant=not a.no_quant,
        with_kernels=not a.no_kernels, prom_out=a.prom_out)
    if a.artifact_dir:
        out["artifact"] = write_bench_artifact(out, a.artifact_dir)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
