"""Ridge-crossing transformer benchmark (VERDICT r3 weak #4).

The r3 seq2seq row (d512, 6L, 128+128, bs32) is HBM-bound at ai 49
FLOP/byte vs the v5e ridge of ~240 — mfu 0.295 is that model sitting
4.9x below the ridge, not idle silicon.  The correct response to "this
config is memory-bound" is to also publish one that is NOT: this runner
measures a decoder-only causal LM (models/transformer.py transformer_lm,
flash-attention path) at configs whose arithmetic intensity crosses the
ridge, so the "framework reaches peak" claim no longer rests on VGG-19
alone.

Why a big LM crosses the ridge (the bytes argument, up front): train
FLOPs ~ 6*N*P for N tokens and P params, while step bytes ~ optimizer
traffic (~12-20 B/param with f32 Adam state) + activations (~ tokens *
d * c).  At d_model 2048, 12 layers (P ~ 0.73 G) and 4 k tokens/step,
FLOPs ~ 18 T against ~ 25 GB => ai ~ 700 >> 240: the step is
compute-bound by construction, and mfu measures the MXU, not HBM.

Instrument: the r3 authoritative scan-in-program harness
(harness.gated_time_program — K real optimizer steps inside ONE
executable over distinct batch stacks, replay-immune) with the roofline
plausibility gate.  Reports BOTH the XLA-counted mfu (uniform
convention with the other rows) and the analytic 6*N*P mfu.

Usage: python benchmark/run_ridge.py [--d-model 2048] [--n-layers 12]
       [--seq 512] [--batch 8] [--vocab 30000] [--iters 12]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from harness import bound_fields, gated_time_program


def build_lm(batch, seq, vocab, d_model, n_heads, n_layers,
             optimizer="momentum"):
    import paddle_tpu as fluid
    from paddle_tpu.models.transformer import transformer_lm

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data(name="ids", shape=[seq], dtype="int64")
        lbl = fluid.layers.data(name="lbl", shape=[seq, 1], dtype="int64")
        logits = transformer_lm(ids, vocab, d_model=d_model,
                                n_heads=n_heads, n_layers=n_layers,
                                max_len=max(seq, 2048), dropout_rate=0.0,
                                return_logits=True)
        logits2d = fluid.layers.reshape(logits, shape=[-1, vocab])
        lbl2d = fluid.layers.reshape(lbl, shape=[-1, 1])
        # fused softmax-xent: the [b*s, vocab] probability tensor and its
        # cotangent never round-trip HBM (see run_seq2seq.py)
        cost = fluid.layers.softmax_with_cross_entropy(logits2d, lbl2d)
        avg = fluid.layers.mean(cost)
        if optimizer == "adam":
            fluid.Adam(learning_rate=1e-4).minimize(avg)
        else:
            # momentum (the ResNet headline's optimizer): 8 B/param of
            # state vs Adam's 12 — at ridge-scale P the Adam carry
            # double-buffers past HBM, and its extra traffic is pure
            # denominator for the ai the row exists to demonstrate
            fluid.Momentum(learning_rate=0.01, momentum=0.9).minimize(avg)
    return main, startup, avg


def param_count(vocab, d_model, n_layers, seq):
    """Analytic parameter count for the 6*N*P mfu convention:
    12*d^2 per block (qkvo + 8d^2 ffn) + token/pos/output embeddings."""
    per_block = 12 * d_model * d_model
    emb = vocab * d_model            # input table
    out = vocab * d_model            # output projection
    pos = max(seq, 2048) * d_model
    return n_layers * per_block + emb + out + pos


def run_one(batch, seq, vocab, d_model, n_heads, n_layers, iters,
            force_flash=True, optimizer="momentum"):
    import paddle_tpu as fluid
    from paddle_tpu.core.flags import set_flags

    fluid.amp.enable_bf16()
    # set the flag BOTH ways: the no-force path must measure the kernel's
    # own crossover policy even after a forced run in the same process
    set_flags({"flash_min_seq_k": 0 if force_flash else -1})
    # (force: below the kernel's isolated-attention crossover (~2k) the
    # XLA composition materializes scores+probs f32 for backward — at
    # ridge-scale d_model that dominates HBM bytes AND memory)
    main, startup, avg = build_lm(batch, seq, vocab, d_model, n_heads,
                                  n_layers, optimizer=optimizer)
    r = np.random.RandomState(0)
    feeds = {
        "ids": r.randint(0, vocab, (batch, seq)).astype(np.int32),
        "lbl": r.randint(0, vocab, (batch, seq, 1)).astype(np.int32),
    }
    tokens = batch * seq
    p = param_count(vocab, d_model, n_layers, seq)
    analytic_flops = 6.0 * tokens * p
    ms, cost, fields = gated_time_program(
        main, startup, feeds, avg.name, iters,
        model_flops_per_step=analytic_flops)
    out = {
        "model": "transformer_lm_ridge",
        "d_model": d_model, "n_layers": n_layers, "n_heads": n_heads,
        "seq": seq, "batch": batch, "vocab": vocab,
        "optimizer": optimizer,
        "params_analytic": p,
        "ms_per_step": round(ms, 2),
        "tokens_per_sec": round(tokens / ms * 1000, 1),
        "mfu_analytic": fields.get("mfu"),
    }
    out.update(fields)
    # uniform-convention roofline (XLA-counted flops) for cross-row
    # comparability with the seq2seq/image tables
    from harness import plausibility, roofline_from_cost
    xla_fields = roofline_from_cost(ms, cost)
    out["mfu"] = xla_fields.get("mfu")
    out["tflops"] = xla_fields.get("tflops")
    out.update(bound_fields(ms, cost))
    ok, reason = plausibility(out, ms)
    if not ok:
        out["valid"] = False
        out["invalid_reason"] = reason
    print(json.dumps(out))
    if not out.get("valid", True):
        sys.exit(1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--d-model", type=int, default=2048)
    ap.add_argument("--n-layers", type=int, default=12)
    ap.add_argument("--n-heads", type=int, default=16)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=30000)
    ap.add_argument("--iters", type=int, default=12)
    ap.add_argument("--optimizer", default="momentum",
                    choices=["momentum", "adam"])
    ap.add_argument("--no-force-flash", action="store_true",
                    help="keep the kernel's own crossover policy (the "
                         "score-materializing XLA path below seq 2k) — "
                         "for measuring the delta the forced kernel buys")
    a = ap.parse_args()
    run_one(a.batch, a.seq, a.vocab, a.d_model, a.n_heads, a.n_layers,
            a.iters, force_flash=not a.no_force_flash,
            optimizer=a.optimizer)


if __name__ == "__main__":
    main()
