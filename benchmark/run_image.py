#!/usr/bin/env python
"""Image-model training benchmark (reference benchmark/paddle/image/run.sh
`paddle train --job=time`; published tables benchmark/README.md:33-95).

Prints one JSON line per (model, batch) with ms/batch and images/sec.

    python benchmark/run_image.py --model alexnet --batch 128
    python benchmark/run_image.py --all            # the reference table grid
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from harness import roofline_from_cost, time_program

SPECS = {
    # name -> (input HxW, reference 1xK40m ms/batch table keyed by batch,
    #          from the reference benchmark/README.md:33-95)
    "alexnet": (227, {64: 195.0, 128: 334.0, 256: 602.0, 512: 1629.0}),
    "googlenet": (224, {64: 613.0, 128: 1149.0, 256: 2348.0}),
    "smallnet": (32, {64: 10.5, 128: 18.2, 256: 33.1, 512: 63.0}),
    "resnet50": (224, {}),
    "vgg19": (224, {}),
}


def build(model, img, dtype):
    import paddle_tpu as fluid
    from paddle_tpu import models

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        data = fluid.layers.data(name="img", shape=[3, img, img],
                                 dtype=dtype)
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        if model == "alexnet":
            predict = models.alexnet(data, class_dim=1000)
        elif model == "googlenet":
            predict = models.googlenet(data, class_dim=1000)
        elif model == "smallnet":
            predict = models.smallnet_mnist_cifar(data, class_dim=10)
        elif model == "resnet50":
            predict = models.resnet_imagenet(data, class_dim=1000, depth=50)
        elif model == "vgg19":
            predict = models.vgg(data, class_dim=1000, depth=19)
        else:
            raise SystemExit(f"unknown model {model}")
        cost = fluid.layers.cross_entropy(input=predict, label=label)
        avg = fluid.layers.mean(cost)
        fluid.Momentum(learning_rate=0.01, momentum=0.9).minimize(avg)
    return main, startup, avg, predict


def run_one(model, batch, iters, dtype):
    from paddle_tpu.core.types import np_dtype

    img, ref_table = SPECS[model]
    classes = 10 if model == "smallnet" else 1000
    main, startup, avg, _ = build(model, img, dtype)
    r = np.random.RandomState(0)
    feeds = {
        "img": r.rand(batch, 3, img, img).astype(np_dtype(dtype)),
        "label": r.randint(0, classes, (batch, 1)).astype(np.int32),
    }
    ms, cost = time_program(main, startup, feeds, avg.name, iters,
                            with_cost=True)
    ref = ref_table.get(batch)
    out = {
        "model": model, "batch": batch,
        "ms_per_batch": round(ms, 2),
        "images_per_sec": round(batch / ms * 1000, 1),
        "ref_k40m_ms_per_batch": ref,
        "speedup_vs_ref": round(ref / ms, 2) if ref else None,
    }
    out.update(roofline_from_cost(ms, cost))
    print(json.dumps(out))


def infer_one(model, batch, iters, dtype):
    """Inference img/s (is_test program, no optimizer) — the
    IntelOptimizedPaddle.md CPU-inference table's axis.  Timing is
    tunnel-cache-proof: distinct input per iteration, async chain, one
    final block (docs/design/perf.md)."""
    import time

    import jax
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.core.executor import program_to_fn
    from paddle_tpu.core.types import np_dtype

    img, _ = SPECS[model]
    main_p, startup, _, predict = build(model, img, dtype)
    from paddle_tpu.io import prune

    pred_name = predict.name
    # forward slice only (drop loss + optimizer ops), is_test semantics
    infer_prog = prune(main_p, [predict], for_test=True)
    fn = program_to_fn(infer_prog, ["img"], [pred_name])
    scope = fluid.Scope()
    fluid.Executor(fluid.CPUPlace()).run(startup, scope=scope)
    states = {n: jax.device_put(np.asarray(scope.find_var(n)))
              for n in fn.state_in_names}
    key = jax.random.key(0)
    jfn = jax.jit(lambda feeds, states: fn(feeds, states, key)[0])
    r = np.random.RandomState(0)
    variants = [jax.device_put(r.rand(batch, 3, img, img)
                               .astype(np_dtype(dtype)))
                for _ in range(iters)]
    jax.block_until_ready(variants)
    out = jfn({"img": variants[0]}, states)
    jax.block_until_ready(out)
    outs = []
    t0 = time.perf_counter()
    for v in variants:
        outs.append(jfn({"img": v}, states))
    jax.block_until_ready(outs)
    ms = (time.perf_counter() - t0) / iters * 1000
    print(json.dumps({
        "model": model, "batch": batch, "mode": "inference",
        "ms_per_batch": round(ms, 3),
        "images_per_sec": round(batch / ms * 1000, 1),
    }))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="alexnet", choices=sorted(SPECS))
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--all", action="store_true",
                    help="reference table grid (README.md:33-95)")
    ap.add_argument("--infer", action="store_true",
                    help="inference mode (no optimizer, is_test)")
    args = ap.parse_args()
    if args.all and args.infer:
        for model in ("alexnet", "googlenet", "resnet50", "vgg19"):
            for batch in (1, 8, 16):
                infer_one(model, batch, max(args.iters, 20), args.dtype)
    elif args.all:
        for model in ("alexnet", "googlenet", "smallnet"):
            for batch in sorted(SPECS[model][1]):
                run_one(model, batch, args.iters, args.dtype)
    elif args.infer:
        infer_one(args.model, args.batch, args.iters, args.dtype)
    else:
        run_one(args.model, args.batch, args.iters, args.dtype)


if __name__ == "__main__":
    main()
