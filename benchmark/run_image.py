#!/usr/bin/env python
"""Image-model training benchmark (reference benchmark/paddle/image/run.sh
`paddle train --job=time`; published tables benchmark/README.md:33-95).

Prints one JSON line per (model, batch) with ms/batch and images/sec.

    python benchmark/run_image.py --model alexnet --batch 128
    python benchmark/run_image.py --all            # the reference table grid
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from harness import gated_time_program

SPECS = {
    # name -> (input HxW, reference 1xK40m ms/batch table keyed by batch,
    #          from the reference benchmark/README.md:33-95)
    "alexnet": (227, {64: 195.0, 128: 334.0, 256: 602.0, 512: 1629.0}),
    "googlenet": (224, {64: 613.0, 128: 1149.0, 256: 2348.0}),
    "smallnet": (32, {64: 10.5, 128: 18.2, 256: 33.1, 512: 63.0}),
    "resnet50": (224, {}),
    "vgg19": (224, {}),
}

# reference CPU-inference img/s (2x Xeon Gold 6148, MKL-DNN) keyed by
# batch — benchmark/IntelOptimizedPaddle.md:71-107 via BASELINE.md
INFER_REF = {
    "vgg19": {1: 75.07, 2: 88.64, 4: 82.58, 8: 92.29, 16: 96.75},
    "resnet50": {1: 107.83, 2: 148.84, 4: 177.78, 8: 189.35, 16: 217.69},
    "googlenet": {1: 175.10, 2: 272.92, 4: 450.70, 8: 512.00, 16: 600.94},
    "alexnet": {1: 442.91, 2: 656.41, 4: 719.10, 8: 847.68, 16: 850.51},
}


def build(model, img, dtype):
    import paddle_tpu as fluid
    from paddle_tpu import models

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        data = fluid.layers.data(name="img", shape=[3, img, img],
                                 dtype=dtype)
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        if model == "alexnet":
            predict = models.alexnet(data, class_dim=1000)
        elif model == "googlenet":
            predict = models.googlenet(data, class_dim=1000)
        elif model == "smallnet":
            predict = models.smallnet_mnist_cifar(data, class_dim=10)
        elif model == "resnet50":
            predict = models.resnet_imagenet(data, class_dim=1000, depth=50)
        elif model == "vgg19":
            predict = models.vgg(data, class_dim=1000, depth=19)
        else:
            raise SystemExit(f"unknown model {model}")
        cost = fluid.layers.cross_entropy(input=predict, label=label)
        avg = fluid.layers.mean(cost)
        fluid.Momentum(learning_rate=0.01, momentum=0.9).minimize(avg)
    return main, startup, avg, predict


def run_one(model, batch, iters, dtype):
    from paddle_tpu.core.types import np_dtype

    img, ref_table = SPECS[model]
    classes = 10 if model == "smallnet" else 1000
    main, startup, avg, _ = build(model, img, dtype)
    r = np.random.RandomState(0)
    feeds = {
        "img": r.rand(batch, 3, img, img).astype(np_dtype(dtype)),
        "label": r.randint(0, classes, (batch, 1)).astype(np.int32),
    }
    ms, cost, fields = gated_time_program(main, startup, feeds, avg.name,
                                          iters)
    ref = ref_table.get(batch)
    out = {
        "model": model, "batch": batch,
        "ms_per_batch": round(ms, 2),
        "images_per_sec": round(batch / ms * 1000, 1),
        "ref_k40m_ms_per_batch": ref,
        "speedup_vs_ref": round(ref / ms, 2) if ref else None,
    }
    out.update(fields)
    print(json.dumps(out))
    if not fields["valid"]:
        sys.exit(1)


def infer_one(model, batch, iters, dtype):
    """Inference img/s (is_test program, no optimizer) — the
    IntelOptimizedPaddle.md CPU-inference table's axis.  Timing is
    tunnel-cache-proof: distinct input per iteration, async chain, one
    final block (docs/design/perf.md)."""
    import time

    import jax
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.core.executor import program_to_fn
    from paddle_tpu.core.types import np_dtype

    img, _ = SPECS[model]
    main_p, startup, _, predict = build(model, img, dtype)
    from paddle_tpu.io import prune

    pred_name = predict.name
    # forward slice only (drop loss + optimizer ops), is_test semantics
    infer_prog = prune(main_p, [predict], for_test=True)
    fn = program_to_fn(infer_prog, ["img"], [pred_name])
    scope = fluid.Scope()
    fluid.Executor(fluid.CPUPlace()).run(startup, scope=scope)
    states = {n: jax.device_put(np.asarray(scope.find_var(n)))
              for n in fn.state_in_names}
    key = jax.random.key(0)
    jfn = jax.jit(lambda feeds, states: fn(feeds, states, key)[0])
    r = np.random.RandomState(0)
    # iters+1 buffers: [0] is warmup-only — re-dispatching it in the
    # timed loop would repeat an (executable, inputs) pair the tunnel
    # cache replays for free (states are not donated here)
    variants = [jax.device_put(r.rand(batch, 3, img, img)
                               .astype(np_dtype(dtype)))
                for _ in range(iters + 1)]
    jax.block_until_ready(variants)
    # call the AOT executable directly — a resident server holds exactly
    # this handle; the jit python dispatch layer costs ~0.5 ms/call extra
    # at bs-1 (serving.py design)
    compiled = jfn.lower({"img": variants[0]}, states).compile()
    out = compiled({"img": variants[0]}, states)
    jax.block_until_ready(out)
    outs = []
    t0 = time.perf_counter()
    for v in variants[1:]:
        outs.append(compiled({"img": v}, states))
    jax.block_until_ready(outs)
    ms = (time.perf_counter() - t0) / iters * 1000
    ref = INFER_REF.get(model, {}).get(batch)
    print(json.dumps({
        "model": model, "batch": batch, "mode": "inference",
        "ms_per_batch": round(ms, 3),
        "images_per_sec": round(batch / ms * 1000, 1),
        "ref_xeon_img_s": ref,
        "vs_ref": round(batch / ms * 1000 / ref, 2) if ref else None,
    }))


def serve_one(model, dtype, n_requests=256, floor=False):
    """Resident-server serving numbers (paddle_tpu/serving.py): sustained
    bs-1 request throughput under concurrency (dynamic batching — the
    production serving configuration), single-stream latency, and with
    `floor` the on-device/dispatch-overhead decomposition for the bs-1
    cell (a K-fwd-fused dispatch isolates device time from transport)."""
    import time

    import jax
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.core.executor import program_to_fn
    from paddle_tpu.core.types import np_dtype
    from paddle_tpu.io import prune
    from paddle_tpu.serving import InferenceServer

    img, _ = SPECS[model]
    main_p, startup, _, predict = build(model, img, dtype)
    infer_prog = prune(main_p, [predict], for_test=True)
    scope = fluid.Scope()
    fluid.Executor(fluid.CPUPlace()).run(startup, scope=scope)

    server = InferenceServer(infer_prog, "img", predict, scope,
                             buckets=(1, 2, 4, 8, 16), window_ms=0.3)
    r = np.random.RandomState(0)
    # disjoint request pools: warmup / single-stream / throughput never
    # share contents, so no timed phase re-dispatches anything the
    # transport has already seen (content-keyed replays bias low)
    n_ss = 30
    pool = [r.rand(1, 3, img, img).astype(np_dtype(dtype))
            for _ in range(3 + n_ss + n_requests)]
    warm, ss, reqs = pool[:3], pool[3:3 + n_ss], pool[3 + n_ss:]

    # single-stream latency: one outstanding request at a time
    for q in warm:
        server.submit(q).result()  # warm every path
    t0 = time.perf_counter()
    for q in ss:
        np.asarray(server.submit(q).result())
    single_ms = (time.perf_counter() - t0) / n_ss * 1000

    # sustained throughput: all requests in flight (distinct contents —
    # transport-cache-proof), clock stops when the LAST result lands
    t0 = time.perf_counter()
    futs = [server.submit(q) for q in reqs]
    outs = [f.result() for f in futs]
    jax.block_until_ready(outs)
    wall = time.perf_counter() - t0
    stats = server.stats()
    server.close()

    out = {
        "model": model, "mode": "serving", "requests": n_requests,
        "single_stream_ms": round(single_ms, 3),
        "single_stream_img_s": round(1000 / single_ms, 1),
        "throughput_img_s": round(n_requests / wall, 1),
        "dispatches": stats["dispatches"],
        "ref_xeon_bs1_img_s": INFER_REF.get(model, {}).get(1),
    }
    ref = out["ref_xeon_bs1_img_s"]
    if ref:
        out["vs_ref_bs1"] = round(out["throughput_img_s"] / ref, 2)

    if floor:
        # K forwards fused in one dispatch: wall/K bounds the true
        # on-device time per bs-1 forward; the rest of the single-stream
        # latency is per-dispatch transport overhead
        K = 8
        fn = program_to_fn(infer_prog, ["img"], [predict.name])
        states = {n: jax.device_put(np.asarray(scope.find_var(n)))
                  for n in fn.state_in_names}
        key = jax.random.key(0)

        def multi(feeds, states):
            import jax.numpy as jnp
            outs = []
            for i in range(K):
                x = feeds["img"] + jnp.asarray(i, feeds["img"].dtype) \
                    * 1e-3
                outs.append(fn({"img": x}, states, key)[0][predict.name])
            return jnp.stack(outs).sum(0)

        # 41 staged buffers: [0] warmup-only, [1:] timed once each (a
        # re-dispatched warmup buffer is a tunnel-cache replay)
        vs = [jax.device_put(q) for q in reqs[:41]]
        comp = jax.jit(multi).lower({"img": vs[0]}, states).compile()
        o = comp({"img": vs[0]}, states)
        jax.block_until_ready(o)
        t0 = time.perf_counter()
        outs = [comp({"img": v}, states) for v in vs[1:]]
        jax.block_until_ready(outs)
        fused_ms = (time.perf_counter() - t0) / (len(vs) - 1) * 1000
        out["on_device_ms_per_fwd"] = round(fused_ms / K, 3)
        out["dispatch_overhead_ms"] = round(
            single_ms - fused_ms / K, 3)
        # the chip-side lower bound for serving bs-1 requests: what a
        # resident process co-located with the TPU (no tunnel) gets
        out["on_chip_bs1_img_s_bound"] = round(1000 / (fused_ms / K), 1)
    print(json.dumps(out))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="alexnet", choices=sorted(SPECS))
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--all", action="store_true",
                    help="reference table grid (README.md:33-95)")
    ap.add_argument("--infer", action="store_true",
                    help="inference mode (no optimizer, is_test)")
    ap.add_argument("--serve", action="store_true",
                    help="resident-server serving numbers (dynamic "
                         "batching; paddle_tpu/serving.py)")
    ap.add_argument("--floor", action="store_true",
                    help="with --serve: also measure the on-device vs "
                         "dispatch-overhead decomposition (extra compile)")
    args = ap.parse_args()
    if args.serve:
        models = (("alexnet", "googlenet", "resnet50", "vgg19")
                  if args.all else (args.model,))
        for model in models:
            serve_one(model, args.dtype, floor=args.floor)
    elif args.all and args.infer:
        for model in ("alexnet", "googlenet", "resnet50", "vgg19"):
            for batch in (1, 2, 4, 8, 16):
                infer_one(model, batch, max(args.iters, 20), args.dtype)
    elif args.all:
        for model in ("alexnet", "googlenet", "smallnet"):
            for batch in sorted(SPECS[model][1]):
                run_one(model, batch, args.iters, args.dtype)
    elif args.infer:
        infer_one(args.model, args.batch, args.iters, args.dtype)
    else:
        run_one(args.model, args.batch, args.iters, args.dtype)


if __name__ == "__main__":
    main()
