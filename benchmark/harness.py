"""Shared benchmark scaffold: build -> jit -> warmup -> timed loop.

One copy of the measure loop (reference `paddle train --job=time`
semantics) used by bench.py, run_image.py and run_rnn.py so warmup /
sync / timing changes can't silently diverge between published numbers.

`chip_specs()` + `roofline_fields()` attach the hardware context every
bench JSON must carry (VERDICT r1 #1): model TFLOP/s, MFU against the
chip's peak, and the HBM side of the roofline from XLA's own cost
analysis — on a memory-bound model the HBM utilization, not MFU, says
whether the chip is actually being used.
"""
from __future__ import annotations

import time

import numpy as np

def _chips():
    """device_kind prefix -> (bf16 peak FLOP/s, HBM bytes/s): ONE table,
    owned by the static analyzer (paddle_tpu.analysis.cost_model
    .DEVICE_SPECS) so the measured-side roofline and the compile-free
    estimate can never disagree on a chip's ridge point.  Imported
    lazily: bench entrypoints must set env (cache dirs, platforms)
    before paddle_tpu imports."""
    from paddle_tpu.analysis.cost_model import DEVICE_SPECS

    return DEVICE_SPECS


def _cost_dict(compiled):
    """compiled.cost_analysis() normalized to one flat dict — newer jax
    returns a per-device LIST of dicts (one per participating device)
    where older versions returned the dict itself."""
    ca = compiled.cost_analysis() or {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca)


def _memory_dict(compiled):
    """compiled.memory_analysis() → {kind: bytes} for the OPTIMIZED
    module: temp (intermediates after fusion/donation), argument,
    output, and the input-output alias overlap.  This is the
    physically-meaningful per-step HBM number — `bytes accessed` (cost
    analysis) is TRAFFIC, which over-counts fusion re-reads and was
    read as "76 GB per step" on a 16 GB chip.  Empty dict when this
    jax/backend has no memory analysis."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return {}
    out = {}
    for k in ("temp_size_in_bytes", "argument_size_in_bytes",
              "output_size_in_bytes", "alias_size_in_bytes",
              "generated_code_size_in_bytes"):
        v = getattr(ma, k, None)
        if v is not None:
            out[k] = int(v)
    return out


def _peak_bytes(mem) -> float:
    """Approximate peak live HBM of one step from the memory analysis:
    arguments + outputs + temporaries, minus the aliased (donated)
    overlap counted in both arguments and outputs."""
    if not mem:
        return 0.0
    return float(mem.get("temp_size_in_bytes", 0)
                 + mem.get("argument_size_in_bytes", 0)
                 + mem.get("output_size_in_bytes", 0)
                 - mem.get("alias_size_in_bytes", 0))


def chip_specs():
    """(device_kind, peak_flops, hbm_bytes_per_s) of the default device;
    (kind, None, None) off-TPU (no meaningful peak for CPU hosts)."""
    import jax

    kind = jax.devices()[0].device_kind
    for prefix in ("TPU v5 lite", "TPU v6 lite", "TPU v5", "TPU v4"):
        if kind.startswith(prefix):
            return kind, *_chips()[prefix]
    return kind, None, None


def roofline_fields(ms_per_step, model_flops_per_step, cost, mem=None):
    """The honesty block for one measured config: achieved model TFLOP/s,
    MFU vs chip peak, and the HBM side — `model_flops` is the analytic
    model FLOP count (2*MACs), not XLA's (which also counts pointwise
    work).

    HBM accounting (r6): `hbm_gb_per_step` is the PEAK LIVE footprint of
    the optimized step module (memory_analysis: args + outputs + temps −
    donated aliases) when `mem` is available — a number that must fit
    the chip's HBM, unlike the old reading of `bytes accessed` (traffic)
    under the same name, which "measured" 76 GB/step on a 16 GB chip.
    Traffic stays published as `hbm_traffic_gb` and still drives
    `hbm_util` (achieved bandwidth vs peak)."""
    kind, peak, hbm = chip_specs()
    sec = ms_per_step / 1000.0
    tflops = model_flops_per_step / sec / 1e12
    out = {
        "device": kind,
        "tflops": round(tflops, 2),
        "mfu": round(tflops * 1e12 / peak, 4) if peak else None,
    }
    gb = (cost or {}).get("bytes accessed")
    if gb is not None:
        out["hbm_traffic_gb"] = round(gb / 1e9, 2)
        if hbm:
            out["hbm_util"] = round((gb / sec) / hbm, 4)
    peak_b = _peak_bytes(mem)
    if peak_b:
        out["hbm_gb_per_step"] = round(peak_b / 1e9, 2)
    elif gb is not None:
        # no memory analysis on this jax/backend: fall back to traffic
        # (the pre-r6 reading) rather than dropping the column
        out["hbm_gb_per_step"] = round(gb / 1e9, 2)
    return out


def bound_fields(ms_per_step, cost):
    """The bytes/FLOPs side of the roofline published per config
    (VERDICT r2 #6): XLA-counted FLOPs and bytes, arithmetic intensity,
    the two floors they imply on this chip, and which one binds.  A
    config is proven memory-bound when hbm_floor >= compute_floor and
    measured ms sits near hbm_floor."""
    _, peak, hbm = chip_specs()
    flops = (cost or {}).get("flops", 0.0)
    gb = (cost or {}).get("bytes accessed", 0.0)
    if not (peak and hbm and flops and gb):
        return {}
    hbm_floor = gb / hbm * 1000
    compute_floor = flops / peak * 1000
    return {
        "ai_flop_per_byte": round(flops / gb, 1),
        "ridge_flop_per_byte": round(peak / hbm, 1),
        "hbm_floor_ms": round(hbm_floor, 2),
        "compute_floor_ms": round(compute_floor, 2),
        "bound": "memory" if hbm_floor >= compute_floor else "compute",
        "floor_frac": round(max(hbm_floor, compute_floor) / ms_per_step,
                            3),
    }


# hbm_util values up to this bound are plausible: XLA's bytes-accessed
# over-counts fusion re-reads (calibrate_hbm.py measures the count exact
# on unfused kernels, and the fused transformer step measured up to
# ~1.43x its achievable traffic at a sync-validated step time), so
# "130-140% of peak" can be a REAL step outrunning an over-counted
# floor — only well beyond it is a timing artifact
HBM_UTIL_BOUND = 1.5

# mfu values up to this bound are plausible: VGG-19 bs128 measures 0.645
# by XLA's flop count (which includes pointwise work) at a
# SYNC-VALIDATED step time (220.8 ms sync ≈ 114.6 ms step + ~106 ms
# tunnel RTT), so dense conv stacks genuinely reach the mid-0.6s here.
# The gate exists to refuse physically impossible numbers (the replay
# artifacts measure 4-25), not to adjudicate 0.60 vs 0.65.
MFU_BOUND = 0.72


def plausibility(fields, ms_per_step):
    """(ok, reason): physical-plausibility gate for one measured config —
    the defense BENCH_r02 lacked (it published 196,547 img/s, mfu 24.5,
    hbm_util 71.7 from a tunnel dispatch-cache artifact).  A number is
    implausible if mfu > MFU_BOUND (the most compute-dense model
    measured, VGG-19 bs128, sync-validates at 0.645) or hbm_util >
    HBM_UTIL_BOUND (beyond the chip's memory bandwidth even allowing
    XLA's fusion double-counting — the ms-below-HBM-floor check is
    algebraically the same test, so one bound covers both).
    Off-TPU (no peak specs) everything passes."""
    reasons = []
    mfu = fields.get("mfu")
    hbm_util = fields.get("hbm_util")
    if mfu is not None and mfu > MFU_BOUND:
        reasons.append(f"mfu {mfu} > {MFU_BOUND} (beyond the calibrated "
                       "empirical band; densest measured model reaches "
                       "0.645)")
    if hbm_util is not None and hbm_util > HBM_UTIL_BOUND:
        reasons.append(f"hbm_util {hbm_util} > {HBM_UTIL_BOUND} "
                       "(beyond HBM bandwidth incl. fusion over-count)")
    return (not reasons), "; ".join(reasons)


def roofline_from_cost(ms_per_step, cost):
    """roofline_fields using XLA's own per-step FLOP count as the model
    FLOPs (uniform across models; slightly generous — XLA also counts
    pointwise work — so bench.py's headline uses an analytic count
    instead)."""
    return roofline_fields(ms_per_step, (cost or {}).get("flops", 0.0),
                           cost)


def feed_variants(feeds, n, seed=123):
    """`n` distinct same-shape feed dicts (index 0 = the original).

    The axon device tunnel caches dispatches keyed on (executable,
    input buffers): repeating one jitted call on the SAME input arrays
    can return in ~0.03 ms with no device work (the BENCH_r02 failure
    mode), and because DONATED state buffers keep stable addresses
    across steps, even a training loop replays once the feed pool laps
    (a 4-buffer pool measured "mfu 5.07" at bs64).  Every timed loop
    therefore uses a FRESH feed buffer per iteration — n = iters, each
    variant dispatched exactly once.  Float feeds are regenerated per
    variant, integer feeds rolled along the batch axis.  Callers may
    also pass a list of dicts to use their own variants verbatim."""
    import jax.numpy as jnp

    if isinstance(feeds, (list, tuple)):
        return list(feeds)
    from paddle_tpu.core.lod import LoDTensor

    r = np.random.RandomState(seed)

    def variant(a, i):
        if isinstance(a, LoDTensor):  # vary the data, keep the LoD
            return LoDTensor(variant(np.asarray(a.data), i), a.lod)
        a = np.asarray(a)
        if jnp.issubdtype(a.dtype, jnp.floating):
            return r.uniform(size=a.shape).astype(a.dtype)
        if a.ndim:
            # seeded row permutation: integer feeds (token ids, labels)
            # must differ per variant AND per seed — np.roll(a, i) made
            # every seed produce identical contents, so all-integer
            # benches (seq2seq, RNN) dispatched bit-identical stacks
            return a[r.permutation(a.shape[0])]
        return a

    out = [dict(feeds)]
    for i in range(1, n):
        out.append({k: variant(a, i) for k, a in feeds.items()})
    return out


def time_program(main, startup, feeds, fetch_name, iters,
                 with_cost: bool = False, sync_each_iter: bool = False,
                 n_variants: int = None):
    """Run `iters` steady-state training steps of `main`'s block 0 on the
    default device; returns ms/batch (or (ms, xla_cost_analysis_dict) when
    `with_cost`).  States are donated so param updates stay on device.

    `feeds` (a dict, or a list of same-shape dicts) is expanded to one
    distinct pre-staged batch PER ITERATION (warmup included) — see
    `feed_variants` for why any buffer reuse is disqualifying here.
    `sync_each_iter=True` is the validation fallback: block_until_ready
    every step and report the median, which includes the full
    host<->device round-trip the async-chained loop pipelines away (so
    it OVERSTATES ms on a tunnel — use it to bound, not to headline)."""
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.core.executor import program_to_fn

    feed_list = feed_variants(feeds, n_variants or iters + 1)
    if len(feed_list) < iters + 1:
        # silently wrapping a short caller-supplied list would re-use
        # buffers — the replay hole this function exists to close
        raise ValueError(
            f"need >= iters+1 = {iters + 1} feed variants (warmup + one "
            f"per timed iteration), got {len(feed_list)}")
    fn = program_to_fn(main, list(feed_list[0].keys()), [fetch_name])
    scope = fluid.Scope()
    fluid.Executor(fluid.CPUPlace()).run(startup, scope=scope)
    states = {n: jax.device_put(np.asarray(scope.find_var(n)))
              for n in fn.state_in_names}
    key = jax.random.key(0)

    @jax.jit
    def step(feeds, states):
        fetches, new_states = fn(feeds, states, key)
        return fetches[fetch_name], new_states

    dev_feeds = [jax.device_put(f) for f in feed_list]
    # AOT-compile once and call the executable directly (a separate
    # lower().compile() would not share jit's cache -> double compile)
    compiled = step.lower(dev_feeds[0], states).compile()
    cost = _cost_dict(compiled) if with_cost else None
    loss, states = compiled(dev_feeds[0], states)  # warmup
    jax.block_until_ready(loss)
    n = len(dev_feeds)  # n = iters+1: warmup takes [0], the loop takes
    # [1..iters] — every buffer is dispatched exactly once
    if sync_each_iter:
        times = []
        for i in range(iters):
            t0 = time.perf_counter()
            loss, states = compiled(dev_feeds[(i + 1) % n], states)
            jax.block_until_ready(loss)
            times.append(time.perf_counter() - t0)
        ms = float(np.median(times)) * 1000
    else:
        t0 = time.perf_counter()
        for i in range(iters):
            loss, states = compiled(dev_feeds[(i + 1) % n], states)
        jax.block_until_ready(loss)
        ms = (time.perf_counter() - t0) / iters * 1000
    return (ms, cost) if with_cost else ms


def time_program_scan(main, startup, feeds, fetch_name,
                      outer_iters: int = 4, k_inner: int = 6,
                      with_cost: bool = False, stats_out: dict = None):
    """The AUTHORITATIVE train-step timer for this environment: K real
    training steps run INSIDE one executable (lax.scan threading the
    donated state through `k_inner` distinct batches), timed over
    `outer_iters` dispatches of distinct batch-stacks.

    Why: the device tunnel replays dispatches it has seen — and partial
    replays survived even one-fresh-buffer-per-iteration async chains
    (a ~40 ms step "measured" 26.7 ms while the sync bound said ~39).
    In-program steps cannot be replayed (they are one dispatch's
    internal work), per-dispatch transport overhead amortizes over
    k_inner steps, and no host round-trip sits in the measured region —
    this is also the measurement that transfers to real (non-tunneled)
    TPU hosts.  Returns ms per TRAINING STEP (and the per-step-scaled
    cost analysis when `with_cost`)."""
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.core.executor import program_to_fn

    fn = program_to_fn(main, list(feeds.keys()), [fetch_name])
    scope = fluid.Scope()
    fluid.Executor(fluid.CPUPlace()).run(startup, scope=scope)
    states = {n: jax.device_put(np.asarray(scope.find_var(n)))
              for n in fn.state_in_names}
    key = jax.random.key(0)

    def multi(stack, states):
        def body(st, f):
            fetches, new = fn(f, st, key)
            return new, fetches[fetch_name]
        st, losses = jax.lax.scan(body, states, stack)
        return losses, st

    def make_stack(seed):
        # [1:] drops feed_variants' index-0 passthrough of the original
        # feeds — otherwise row 0 of EVERY stack is the same batch
        vs = feed_variants(feeds, k_inner + 1, seed=seed)[1:]
        return jax.device_put(jax.tree_util.tree_map(
            lambda *xs: np.stack([np.asarray(x) for x in xs]), *vs))

    stacks = [make_stack(1000 + 97 * i) for i in range(outer_iters + 1)]
    jax.block_until_ready(stacks)
    # donation plan (program_to_fn.donation_plan): states are donated
    # always — each dispatch threads the returned dict forward, so the
    # old buffers die with the step; the batch stack joins when every
    # feed's last use is inside the step (it always is here — each
    # stack is dispatched exactly once), halving the steady-state
    # argument footprint of the measured loop
    donate = ((0, 1) if set(feeds.keys()) <= fn.donation_plan.feeds
              else (1,))
    t_c = time.perf_counter()
    compiled = jax.jit(multi, donate_argnums=donate) \
        .lower(stacks[0], states).compile()
    if stats_out is not None:
        stats_out["compile_seconds"] = time.perf_counter() - t_c
    cost = None
    if with_cost:
        # XLA's cost analysis counts a while/scan BODY once, not times
        # the trip count, so this is already the per-step cost (verified:
        # the k=6 scan reports the same bytes as the single-step program)
        cost = _cost_dict(compiled)
    losses, states = compiled(stacks[0], states)  # warmup
    jax.block_until_ready(losses)
    t0 = time.perf_counter()
    for s in stacks[1:]:
        losses, states = compiled(s, states)
    jax.block_until_ready(losses)
    ms = ((time.perf_counter() - t0) / (outer_iters * k_inner)) * 1000
    return (ms, cost) if with_cost else ms


def step_cost_analysis(main, startup, feeds, fetch_name):
    """(cost, memory, compile_s) of ONE compiled training step — the
    per-step accounting module.  The scan timer's cost analysis counts
    its while-body once, but the scan module's MEMORY analysis includes
    the whole k-step batch stack; this compiles the single-step program
    with the executor's donation plan applied (feeds + rw states ride
    donate_argnums), so FLOPs, bytes accessed, and peak footprint all
    describe exactly one step of the executable users run.  The extra
    compile is amortized by the persistent compilation cache across
    bench rounds (PADDLE_TPU_COMPILATION_CACHE_DIR)."""
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.core.executor import program_to_fn

    fn = program_to_fn(main, list(feeds.keys()), [fetch_name])
    scope = fluid.Scope()
    fluid.Executor(fluid.CPUPlace()).run(startup, scope=scope)
    states = {n: jax.device_put(np.asarray(scope.find_var(n)))
              for n in fn.state_in_names}
    key = jax.random.key(0)

    def step(fd, st):
        fetches, new = fn(fd, st, key)
        return fetches[fetch_name], new

    donate = ((0, 1) if set(feeds.keys()) <= fn.donation_plan.feeds
              else (1,))
    # device_put through the pytree: LoDTensor wrappers (registered
    # nodes) keep their LoD — sequence ops need it at trace time
    dev_feeds = jax.device_put(dict(feeds))
    t0 = time.perf_counter()
    compiled = jax.jit(step, donate_argnums=donate) \
        .lower(dev_feeds, states).compile()
    compile_s = time.perf_counter() - t0
    return _cost_dict(compiled), _memory_dict(compiled), compile_s


def static_vs_measured(main, startup, feeds, fetch_name,
                       batch_size=None):
    """Calibration row for the static cost model: the compile-free
    estimate (`paddle_tpu.analysis.estimate_program`) next to the
    XLA-measured per-step accounting (`step_cost_analysis`), with the
    ratios that bound the model's error.

    Conventions differ by design — the static model counts per-op
    traffic (every op boundary), XLA's `bytes accessed` counts per-FUSION
    traffic, and XLA's flop count includes pointwise work the static
    class constants only approximate — so the honest contract is a
    RATIO BAND, not equality: tests/test_cost_model.py pins
    `flops_ratio` and `bytes_ratio` (estimated / measured) inside a
    documented tolerance on the fast book subset, which is what makes
    the analyzer's verdicts trustworthy without a compile."""
    from paddle_tpu import analysis

    # batch for -1-dim substitution: explicit wins; else dim 0 of the
    # first feed that FEEDS a -1-leading-dim var (a replicated table or
    # scalar feed must not masquerade as the batch)
    batch = batch_size or 0
    blk = main.global_block()
    if not batch:
        for name, v in feeds.items():
            arr = np.asarray(getattr(v, "data", v))
            var = blk.vars.get(name)
            if (arr.ndim and var is not None and var.shape
                    and var.shape[0] == -1):
                batch = int(arr.shape[0])
                break
    batch = batch or 1  # reported below = actually used
    est = analysis.estimate_program(main, batch_size=batch,
                                    feed_names=list(feeds.keys()),
                                    fetch_names=[fetch_name])
    cost, mem, compile_s = step_cost_analysis(main, startup, feeds,
                                              fetch_name)
    out = {
        "batch": batch,
        "est_flops": est.total_flops,
        "xla_flops": float((cost or {}).get("flops", 0.0)),
        "est_bytes": est.total_bytes,
        "xla_bytes": float((cost or {}).get("bytes accessed", 0.0)),
        "est_peak_bytes": est.peak_hbm["peak_bytes"],
        "xla_peak_bytes": _peak_bytes(mem),
        "unknown_ops": sum(est.unknown_types.values()),
        "analysis_compile_seconds": round(compile_s, 2),
    }
    for k in ("flops", "bytes", "peak_bytes"):
        meas = out[f"xla_{k}"]
        out[f"{k}_ratio"] = (round(out[f"est_{k}"] / meas, 3)
                             if meas else None)
    return out


def gated_time_program(main, startup, feeds, fetch_name, iters,
                       model_flops_per_step=None, step_analysis=True):
    """The self-validation wrapper every published number goes through:
    measure with `time_program_scan` (K steps per dispatch — immune to
    transport-cache replays and free of host round-trips), attach the
    per-step cost/memory accounting (`step_cost_analysis` — FLOPs and
    HBM from the single-step optimized module, not the whole scan
    program; `step_analysis=False` skips that extra compile), compute
    the roofline fields, and gate them with `plausibility`; a failing
    number is marked `valid: false` + `invalid_reason` so it can never
    be published silently (callers exit non-zero on it).

    Returns (ms, cost, fields); `cost` is the per-step cost dict the
    roofline used, fields carries the roofline block plus
    `compile_seconds` (wall time of the measured executable's XLA
    compile), `measurement` and `valid`."""
    k_inner = max(2, min(6, iters // 2))
    outer = max(2, min(4, iters // k_inner))
    stats = {}
    ms, cost = time_program_scan(main, startup, feeds, fetch_name,
                                 outer_iters=outer, k_inner=k_inner,
                                 with_cost=True, stats_out=stats)
    mem = None
    if step_analysis:
        try:
            cost, mem, stats["analysis_compile_seconds"] = \
                step_cost_analysis(main, startup, feeds, fetch_name)
        except Exception as e:  # pragma: no cover - jax-version specific
            # per-step module analysis is additive telemetry; losing it
            # must not kill the measurement (scan-body cost stands in)
            stats["step_analysis_error"] = f"{type(e).__name__}: {e}"
    if model_flops_per_step is not None:
        fields = roofline_fields(ms, model_flops_per_step, cost, mem)
    else:
        fields = roofline_fields(ms, (cost or {}).get("flops", 0.0),
                                 cost, mem)
    fields["measurement"] = f"scan_in_program_x{k_inner}"
    if "compile_seconds" in stats:
        fields["compile_seconds"] = round(stats["compile_seconds"], 2)
    if "analysis_compile_seconds" in stats:
        fields["analysis_compile_seconds"] = round(
            stats["analysis_compile_seconds"], 2)
    ok, reason = plausibility(fields, ms)
    fields["valid"] = ok
    if not ok:
        fields["invalid_reason"] = reason
    return ms, cost, fields
