"""Shared benchmark scaffold: build -> jit -> warmup -> timed loop.

One copy of the measure loop (reference `paddle train --job=time`
semantics) used by bench.py, run_image.py and run_rnn.py so warmup /
sync / timing changes can't silently diverge between published numbers.

`chip_specs()` + `roofline_fields()` attach the hardware context every
bench JSON must carry (VERDICT r1 #1): model TFLOP/s, MFU against the
chip's peak, and the HBM side of the roofline from XLA's own cost
analysis — on a memory-bound model the HBM utilization, not MFU, says
whether the chip is actually being used.
"""
from __future__ import annotations

import time

import numpy as np

# device_kind prefix -> (bf16 peak FLOP/s, HBM bytes/s)
_CHIPS = {
    "TPU v5 lite": (197e12, 819e9),   # v5e
    "TPU v5": (459e12, 2765e9),       # v5p (checked after v5 lite)
    "TPU v4": (275e12, 1228e9),
    "TPU v6 lite": (918e12, 1640e9),  # v6e / Trillium
}


def chip_specs():
    """(device_kind, peak_flops, hbm_bytes_per_s) of the default device;
    (kind, None, None) off-TPU (no meaningful peak for CPU hosts)."""
    import jax

    kind = jax.devices()[0].device_kind
    for prefix in ("TPU v5 lite", "TPU v6 lite", "TPU v5", "TPU v4"):
        if kind.startswith(prefix):
            return kind, *_CHIPS[prefix]
    return kind, None, None


def roofline_fields(ms_per_step, model_flops_per_step, cost):
    """The honesty block for one measured config: achieved model TFLOP/s,
    MFU vs chip peak, XLA-counted HBM GB/step and HBM utilization —
    `model_flops` is the analytic model FLOP count (2*MACs), not XLA's
    (which also counts pointwise work)."""
    kind, peak, hbm = chip_specs()
    sec = ms_per_step / 1000.0
    tflops = model_flops_per_step / sec / 1e12
    out = {
        "device": kind,
        "tflops": round(tflops, 2),
        "mfu": round(tflops * 1e12 / peak, 4) if peak else None,
    }
    gb = (cost or {}).get("bytes accessed")
    if gb is not None:
        out["hbm_gb_per_step"] = round(gb / 1e9, 2)
        if hbm:
            out["hbm_util"] = round((gb / sec) / hbm, 4)
    return out


def roofline_from_cost(ms_per_step, cost):
    """roofline_fields using XLA's own per-step FLOP count as the model
    FLOPs (uniform across models; slightly generous — XLA also counts
    pointwise work — so bench.py's headline uses an analytic count
    instead)."""
    return roofline_fields(ms_per_step, (cost or {}).get("flops", 0.0),
                           cost)


def time_program(main, startup, feeds, fetch_name, iters,
                 with_cost: bool = False):
    """Run `iters` steady-state training steps of `main`'s block 0 on the
    default device; returns ms/batch (or (ms, xla_cost_analysis_dict) when
    `with_cost`).  `feeds` are device_put as-is; states are donated so
    param updates stay on device."""
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.core.executor import program_to_fn

    fn = program_to_fn(main, list(feeds.keys()), [fetch_name])
    scope = fluid.Scope()
    fluid.Executor(fluid.CPUPlace()).run(startup, scope=scope)
    states = {n: jax.device_put(np.asarray(scope.find_var(n)))
              for n in fn.state_in_names}
    key = jax.random.key(0)

    @jax.jit
    def step(feeds, states):
        fetches, new_states = fn(feeds, states, key)
        return fetches[fetch_name], new_states

    dev_feeds = jax.device_put(feeds)
    # AOT-compile once and call the executable directly (a separate
    # lower().compile() would not share jit's cache -> double compile)
    compiled = step.lower(dev_feeds, states).compile()
    cost = compiled.cost_analysis() or {} if with_cost else None
    loss, states = compiled(dev_feeds, states)  # warmup
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        loss, states = compiled(dev_feeds, states)
    jax.block_until_ready(loss)
    ms = (time.perf_counter() - t0) / iters * 1000
    return (ms, cost) if with_cost else ms
