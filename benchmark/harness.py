"""Shared benchmark scaffold: build -> jit -> warmup -> timed loop.

One copy of the measure loop (reference `paddle train --job=time`
semantics) used by bench.py, run_image.py and run_rnn.py so warmup /
sync / timing changes can't silently diverge between published numbers.

`chip_specs()` + `roofline_fields()` attach the hardware context every
bench JSON must carry (VERDICT r1 #1): model TFLOP/s, MFU against the
chip's peak, and the HBM side of the roofline from XLA's own cost
analysis — on a memory-bound model the HBM utilization, not MFU, says
whether the chip is actually being used.
"""
from __future__ import annotations

import time

import numpy as np

# device_kind prefix -> (bf16 peak FLOP/s, HBM bytes/s)
_CHIPS = {
    "TPU v5 lite": (197e12, 819e9),   # v5e
    "TPU v5": (459e12, 2765e9),       # v5p (checked after v5 lite)
    "TPU v4": (275e12, 1228e9),
    "TPU v6 lite": (918e12, 1640e9),  # v6e / Trillium
}


def chip_specs():
    """(device_kind, peak_flops, hbm_bytes_per_s) of the default device;
    (kind, None, None) off-TPU (no meaningful peak for CPU hosts)."""
    import jax

    kind = jax.devices()[0].device_kind
    for prefix in ("TPU v5 lite", "TPU v6 lite", "TPU v5", "TPU v4"):
        if kind.startswith(prefix):
            return kind, *_CHIPS[prefix]
    return kind, None, None


def roofline_fields(ms_per_step, model_flops_per_step, cost):
    """The honesty block for one measured config: achieved model TFLOP/s,
    MFU vs chip peak, XLA-counted HBM GB/step and HBM utilization —
    `model_flops` is the analytic model FLOP count (2*MACs), not XLA's
    (which also counts pointwise work)."""
    kind, peak, hbm = chip_specs()
    sec = ms_per_step / 1000.0
    tflops = model_flops_per_step / sec / 1e12
    out = {
        "device": kind,
        "tflops": round(tflops, 2),
        "mfu": round(tflops * 1e12 / peak, 4) if peak else None,
    }
    gb = (cost or {}).get("bytes accessed")
    if gb is not None:
        out["hbm_gb_per_step"] = round(gb / 1e9, 2)
        if hbm:
            out["hbm_util"] = round((gb / sec) / hbm, 4)
    return out


def plausibility(fields, ms_per_step):
    """(ok, reason): physical-plausibility gate for one measured config —
    the defense BENCH_r02 lacked (it published 196,547 img/s, mfu 24.5,
    hbm_util 71.7 from a tunnel dispatch-cache artifact).  A number is
    implausible if mfu > 0.6 (no dense model on this stack exceeds ~0.5),
    hbm_util > 1.2 (beyond the chip's memory bandwidth even allowing
    XLA's fusion double-counting, benchmark/README.md calibration), or
    ms/step is below the HBM floor implied by XLA's own bytes-accessed
    count.  Off-TPU (no peak specs) everything passes."""
    reasons = []
    mfu = fields.get("mfu")
    hbm_util = fields.get("hbm_util")
    if mfu is not None and mfu > 0.6:
        reasons.append(f"mfu {mfu} > 0.6 (beyond bf16 roofline)")
    if hbm_util is not None and hbm_util > 1.2:
        reasons.append(f"hbm_util {hbm_util} > 1.2 (beyond HBM bandwidth)")
    gb = fields.get("hbm_gb_per_step")
    _, _, hbm = chip_specs()
    if gb and hbm:
        floor_ms = gb * 1e9 / hbm * 1000
        if ms_per_step < floor_ms / 1.2:
            reasons.append(
                f"ms_per_step {ms_per_step:.2f} < HBM floor "
                f"{floor_ms:.2f}/1.2 from XLA bytes-accessed")
    return (not reasons), "; ".join(reasons)


def roofline_from_cost(ms_per_step, cost):
    """roofline_fields using XLA's own per-step FLOP count as the model
    FLOPs (uniform across models; slightly generous — XLA also counts
    pointwise work — so bench.py's headline uses an analytic count
    instead)."""
    return roofline_fields(ms_per_step, (cost or {}).get("flops", 0.0),
                           cost)


def feed_variants(feeds, n=4, seed=123):
    """`n` distinct same-shape feed dicts (index 0 = the original).

    The axon device tunnel caches identical dispatches: repeating one
    jitted call on the SAME input arrays can return in ~0.03 ms with no
    device work (measured "6000 TFLOP/s" — the BENCH_r02 failure mode).
    Every timed loop must therefore rotate materially different inputs:
    float feeds are regenerated per variant, integer feeds rolled along
    the batch axis.  Callers may also pass a list of dicts to use their
    own variants verbatim."""
    import jax.numpy as jnp

    if isinstance(feeds, (list, tuple)):
        return list(feeds)
    r = np.random.RandomState(seed)
    out = [dict(feeds)]
    for i in range(1, n):
        v = {}
        for k, a in feeds.items():
            a = np.asarray(a)
            if jnp.issubdtype(a.dtype, jnp.floating):
                v[k] = r.uniform(size=a.shape).astype(a.dtype)
            elif a.ndim:
                v[k] = np.roll(a, i, axis=0)
            else:
                v[k] = a
        out.append(v)
    return out


def time_program(main, startup, feeds, fetch_name, iters,
                 with_cost: bool = False, sync_each_iter: bool = False,
                 n_variants: int = 4):
    """Run `iters` steady-state training steps of `main`'s block 0 on the
    default device; returns ms/batch (or (ms, xla_cost_analysis_dict) when
    `with_cost`).  States are donated so param updates stay on device.

    `feeds` (a dict, or a list of same-shape dicts) is expanded to
    `n_variants` distinct pre-staged batches and rotated through the
    timed loop — see `feed_variants` for why identical inputs are
    disqualifying here.  `sync_each_iter=True` is the validation
    fallback: block_until_ready every step and report the median, which
    includes the full host<->device round-trip the async-chained loop
    pipelines away (so it OVERSTATES ms on a tunnel — use it to bound,
    not to headline)."""
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.core.executor import program_to_fn

    feed_list = feed_variants(feeds, n_variants)
    fn = program_to_fn(main, list(feed_list[0].keys()), [fetch_name])
    scope = fluid.Scope()
    fluid.Executor(fluid.CPUPlace()).run(startup, scope=scope)
    states = {n: jax.device_put(np.asarray(scope.find_var(n)))
              for n in fn.state_in_names}
    key = jax.random.key(0)

    @jax.jit
    def step(feeds, states):
        fetches, new_states = fn(feeds, states, key)
        return fetches[fetch_name], new_states

    dev_feeds = [jax.device_put(f) for f in feed_list]
    # AOT-compile once and call the executable directly (a separate
    # lower().compile() would not share jit's cache -> double compile)
    compiled = step.lower(dev_feeds[0], states).compile()
    cost = compiled.cost_analysis() or {} if with_cost else None
    loss, states = compiled(dev_feeds[0], states)  # warmup
    jax.block_until_ready(loss)
    n = len(dev_feeds)
    if sync_each_iter:
        times = []
        for i in range(iters):
            t0 = time.perf_counter()
            loss, states = compiled(dev_feeds[(i + 1) % n], states)
            jax.block_until_ready(loss)
            times.append(time.perf_counter() - t0)
        ms = float(np.median(times)) * 1000
    else:
        t0 = time.perf_counter()
        for i in range(iters):
            loss, states = compiled(dev_feeds[(i + 1) % n], states)
        jax.block_until_ready(loss)
        ms = (time.perf_counter() - t0) / iters * 1000
    return (ms, cost) if with_cost else ms
