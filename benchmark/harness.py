"""Shared benchmark scaffold: build -> jit -> warmup -> timed loop.

One copy of the measure loop (reference `paddle train --job=time`
semantics) used by bench.py, run_image.py and run_rnn.py so warmup /
sync / timing changes can't silently diverge between published numbers.
"""
from __future__ import annotations

import time

import numpy as np


def time_program(main, startup, feeds, fetch_name, iters):
    """Run `iters` steady-state training steps of `main`'s block 0 on the
    default device; returns ms/batch.  `feeds` are device_put as-is;
    states are donated so param updates stay on device."""
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.core.executor import program_to_fn

    fn = program_to_fn(main, list(feeds.keys()), [fetch_name])
    scope = fluid.Scope()
    fluid.Executor(fluid.CPUPlace()).run(startup, scope=scope)
    states = {n: jax.device_put(np.asarray(scope.find_var(n)))
              for n in fn.state_in_names}
    key = jax.random.key(0)

    @jax.jit
    def step(feeds, states):
        fetches, new_states = fn(feeds, states, key)
        return fetches[fetch_name], new_states

    dev_feeds = jax.device_put(feeds)
    loss, states = step(dev_feeds, states)  # compile + warmup
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        loss, states = step(dev_feeds, states)
    jax.block_until_ready(loss)
    return (time.perf_counter() - t0) / iters * 1000
