"""Chip-mode convergence matrix — the 8 book acceptance models trained to
their thresholds ON THE TPU in the benchmark's numeric mode (amp: bf16
compute at the MXU whitelist edges, f32 master weights).

Reference discipline: /root/reference/python/paddle/v2/fluid/tests/book/
— each of the eight book chapters trains to a threshold
(test_fit_a_line.py:24-63 et al.).  The repo's tests/book/ suite proves
the same thresholds on CPU/f32; this runner proves them in the mode the
published benchmark numbers are measured in (VERDICT r3 missing #2).

Method, per model:
  * build the SAME program the book test builds (tiny synthetic configs —
    the claim is "converges on TPU in the bench numeric mode", not SOTA);
  * compile every executable BEFORE the clock starts (one step per
    distinct feed shape, then re-run startup so training begins from a
    fresh init — the r2 lesson: tunnel compiles must never be billed as
    training time);
  * train until the chapter's threshold is reached or the budget
    (BOOK_SECONDS per model, default 120 s post-compile) expires.

Every row carries a `data` tag (r5): the classic 8 rows are tiny
SYNTHETIC configs (the claim is numeric-mode convergence, not SOTA);
two additional rows train on REAL corpora that need no network —
fit_a_line_real (the diabetes study) and recognize_digits_real (the
UCI optical handwritten digits), both shipped inside scikit-learn and
evaluated on held-out splits (VERDICT r4 next #5).

Prints ONE JSON line:
  {"metric": "book_convergence_matrix", "reached": "10/10", "amp": true,
   "models": [{model, metric, target, value, reached, steps, seconds,
               compile_seconds, data}, ...]}
Exit status 1 if any model misses its threshold.  `bench.py` embeds this
matrix when BENCH_BOOK=1; the committed BOOK_MATRIX_r{N}.json is the
published artifact for the round.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# compile-time budget (standalone runs; bench.py sets the same default
# before importing us): pre-warm JAX's persistent compilation cache so
# round N+1 deserializes round N's executables instead of recompiling.
# BOOK_COMPILE_CACHE=0 opts out; an explicit env dir wins.
if (os.environ.get("BOOK_COMPILE_CACHE", "1").lower()
        not in ("0", "false", "no", "off")):
    os.environ.setdefault(
        "PADDLE_TPU_COMPILATION_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                     "xla_cache"))

import numpy as np

import paddle_tpu as fluid

BUDGET = float(os.environ.get("BOOK_SECONDS", "120"))
AMP = os.environ.get("BOOK_AMP", "1").lower() in ("1", "true", "yes", "on")


def _train_loop(exe, scope, main, startup, batches, fetch_list, check,
                max_steps, extra_precompile=()):
    """Shared compile-before-clock training loop.

    batches: fixed cycle of feed dicts (fixed shapes -> a bounded set of
    executables).  check(history) -> (value, reached) where history is the
    list of fetched tuples.  extra_precompile: (program, feed, fetches)
    triples also compiled before the clock (eval paths)."""
    t_c = time.perf_counter()
    seen = set()
    for feed in batches:  # one compile per distinct feed shape
        # the Executor's compile cache keys on the LoD too (aux_data in
        # the LoDTensor pytree) — two ragged batches with colliding flat
        # shapes but different LoD are different executables, and an
        # unprecompiled one would bill its tunnel compile to the clock
        key = tuple(sorted(
            (k, getattr(v, "data", v).shape,
             tuple(map(tuple, getattr(v, "lod", ()) or ())))
            for k, v in feed.items()))
        if key not in seen:
            seen.add(key)
            exe.run(main, feed=feed, fetch_list=fetch_list, scope=scope)
    for prog, feed, fl in extra_precompile:
        exe.run(prog, feed=feed, fetch_list=fl, scope=scope)
    exe.run(startup, scope=scope)  # fresh init for the timed run
    compile_s = time.perf_counter() - t_c

    t0 = time.perf_counter()
    history = []
    steps = 0
    value, reached = None, False
    while steps < max_steps and time.perf_counter() - t0 < BUDGET:
        feed = batches[steps % len(batches)]
        out = exe.run(main, feed=feed, fetch_list=fetch_list, scope=scope)
        history.append([float(np.asarray(o).reshape(-1)[0]) for o in out])
        steps += 1
        if steps % 10 == 0 or steps == max_steps:
            value, reached = check(history)
            if reached:
                break
    if not reached and history:
        # the budget can expire between check intervals — never publish
        # a stale verdict for a model that crossed its threshold late
        value, reached = check(history)
    return {"value": round(float(value), 4), "reached": bool(reached),
            "steps": steps,
            "seconds": round(time.perf_counter() - t0, 1),
            "compile_seconds": round(compile_s, 1),
            # every batch shape was precompiled above, so the timed loop
            # must be recompile-free; a nonzero value here is the
            # compile-churn signature (the r5 recommender paid 85 s of
            # compile for 8 distinct random-LoD configs of one program)
            "recompiles_after_warmup":
                exe.cache_stats()["recompiles_after_warmup"]}


def _result(name, metric, target, r, data="synthetic"):
    """`data` tags the row's corpus honestly: the classic 8 rows train
    tiny synthetic configs (the claim is numeric-mode convergence, not
    SOTA); the *_real rows train on real corpora that ship offline
    inside scikit-learn (dataset/uci_digits.py, dataset/diabetes.py) —
    VERDICT r4 next #5."""
    r.update({"model": name, "metric": metric, "target": target,
              "data": data})
    return r


# ── book/01 fit_a_line ─────────────────────────────────────────────────
def run_fit_a_line():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[13], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(input=x, size=1, act=None)
        cost = fluid.layers.square_error_cost(input=pred, label=y)
        avg = fluid.layers.mean(cost)
        fluid.SGD(learning_rate=0.01).minimize(avg)
    r = np.random.RandomState(0)
    xs = r.randn(512, 13).astype(np.float32)
    ys = (xs @ r.randn(13, 1).astype(np.float32) + 0.3)
    batches = [{"x": xs[i:i + 64], "y": ys[i:i + 64]}
               for i in range(0, 512, 64)]
    exe, scope = fluid.Executor(fluid.TPUPlace()), fluid.Scope()
    exe.run(startup, scope=scope)
    res = _train_loop(exe, scope, main, startup, batches, [avg],
                      lambda h: (h[-1][0], h[-1][0] < 0.1), max_steps=400)
    return _result("fit_a_line", "mse_loss<", 0.1, res)


# ── book/02 recognize_digits (conv) ────────────────────────────────────
def run_recognize_digits():
    from paddle_tpu import nets

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[1, 28, 28],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        cp1 = nets.simple_img_conv_pool(
            input=img, filter_size=5, num_filters=8, pool_size=2,
            pool_stride=2, act="relu")
        cp2 = nets.simple_img_conv_pool(
            input=cp1, filter_size=5, num_filters=16, pool_size=2,
            pool_stride=2, act="relu")
        pred = fluid.layers.fc(input=cp2, size=10, act="softmax")
        cost = fluid.layers.cross_entropy(input=pred, label=label)
        avg = fluid.layers.mean(cost)
        acc = fluid.layers.accuracy(input=pred, label=label)
        fluid.Adam(learning_rate=0.01).minimize(avg)

    templates = np.random.RandomState(123).rand(10, 784).astype(np.float32)
    r = np.random.RandomState(0)

    def mk():
        y = r.randint(0, 10, (64, 1)).astype(np.int64)
        x = templates[y.ravel()] + 0.1 * r.randn(64, 784).astype(np.float32)
        return {"img": x.reshape(64, 1, 28, 28), "label": y}

    batches = [mk() for _ in range(8)]
    exe, scope = fluid.Executor(fluid.TPUPlace()), fluid.Scope()
    exe.run(startup, scope=scope)

    def check(h):
        a = float(np.mean([row[1] for row in h[-5:]]))
        return a, a > 0.9

    res = _train_loop(exe, scope, main, startup, batches, [avg, acc],
                      check, max_steps=200)
    return _result("recognize_digits_conv", "acc>", 0.9, res)


# ── book/03 image_classification (resnet cifar) ────────────────────────
def run_image_classification():
    from paddle_tpu.models.resnet import resnet_cifar10

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        images = fluid.layers.data(name="pixel", shape=[3, 16, 16],
                                   dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        pred = resnet_cifar10(images, class_dim=4, depth=8)
        cost = fluid.layers.cross_entropy(input=pred, label=label)
        avg = fluid.layers.mean(cost)
        acc = fluid.layers.accuracy(input=pred, label=label)
        fluid.Adam(learning_rate=0.01).minimize(avg)

    templates = np.random.RandomState(5).rand(4, 3, 16, 16).astype(
        np.float32)
    r = np.random.RandomState(0)

    def mk():
        y = r.randint(0, 4, (32, 1)).astype(np.int64)
        x = templates[y.ravel()] + 0.05 * r.randn(32, 3, 16, 16).astype(
            np.float32)
        return {"pixel": x, "label": y}

    batches = [mk() for _ in range(8)]
    exe, scope = fluid.Executor(fluid.TPUPlace()), fluid.Scope()
    exe.run(startup, scope=scope)

    def check(h):
        a = float(np.mean([row[1] for row in h[-5:]]))
        return a, a > 0.85

    res = _train_loop(exe, scope, main, startup, batches, [avg, acc],
                      check, max_steps=200)
    return _result("image_classification_resnet", "acc>", 0.85, res)


# ── book/04 word2vec ───────────────────────────────────────────────────
def run_word2vec():
    DICT, EMB = 32, 16
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        words = [fluid.layers.data(name=f"w{i}", shape=[1], dtype="int64")
                 for i in range(4)]
        nxt = fluid.layers.data(name="next", shape=[1], dtype="int64")
        embeds = [fluid.layers.embedding(input=w, size=[DICT, EMB],
                                         param_attr={"name": "shared_w"})
                  for w in words]
        concat = fluid.layers.concat(input=embeds, axis=1)
        hidden = fluid.layers.fc(input=concat, size=64, act="sigmoid")
        pred = fluid.layers.fc(input=hidden, size=DICT, act="softmax")
        cost = fluid.layers.cross_entropy(input=pred, label=nxt)
        avg = fluid.layers.mean(cost)
        fluid.Adam(learning_rate=0.01).minimize(avg)

    r = np.random.RandomState(0)

    def mk():
        base = r.randint(0, DICT, (64, 1)).astype(np.int64)
        feed = {f"w{i}": (base + i) % DICT for i in range(4)}
        feed["next"] = (base + 4) % DICT
        return feed

    batches = [mk() for _ in range(8)]
    exe, scope = fluid.Executor(fluid.TPUPlace()), fluid.Scope()
    exe.run(startup, scope=scope)
    res = _train_loop(exe, scope, main, startup, batches, [avg],
                      lambda h: (h[-1][0], h[-1][0] < 0.3), max_steps=500)
    return _result("word2vec", "xent_loss<", 0.3, res)


# ── book/05 recommender_system ─────────────────────────────────────────
def run_recommender_system():
    USR_N, GENDER_N, AGE_N, JOB_N = 40, 2, 7, 21
    MOV_N, CAT_N, TITLE_VOCAB = 60, 18, 100
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        uid = fluid.layers.data(name="user_id", shape=[1], dtype="int64")
        gender = fluid.layers.data(name="gender_id", shape=[1],
                                   dtype="int64")
        age = fluid.layers.data(name="age_id", shape=[1], dtype="int64")
        job = fluid.layers.data(name="job_id", shape=[1], dtype="int64")
        emb = lambda x, n: fluid.layers.fc(
            input=fluid.layers.embedding(input=x, size=[n, 16]), size=16)
        usr = fluid.layers.fc(
            input=fluid.layers.concat(
                input=[emb(uid, USR_N), emb(gender, GENDER_N),
                       emb(age, AGE_N), emb(job, JOB_N)], axis=1),
            size=32, act="tanh")
        mov_id = fluid.layers.data(name="movie_id", shape=[1],
                                   dtype="int64")
        category = fluid.layers.data(name="category_id", shape=[1],
                                     dtype="int64", lod_level=1)
        title = fluid.layers.data(name="movie_title", shape=[1],
                                  dtype="int64", lod_level=1)
        mov_fc = fluid.layers.fc(
            input=fluid.layers.embedding(input=mov_id, size=[MOV_N, 16]),
            size=16)
        cat_pool = fluid.layers.sequence_pool(
            input=fluid.layers.embedding(input=category, size=[CAT_N, 16]),
            pool_type="sum")
        title_pool = fluid.nets.sequence_conv_pool(
            input=fluid.layers.embedding(input=title,
                                         size=[TITLE_VOCAB, 16]),
            num_filters=16, filter_size=3, act="tanh", pool_type="sum")
        mov = fluid.layers.fc(
            input=fluid.layers.concat(input=[mov_fc, cat_pool, title_pool],
                                      axis=1),
            size=32, act="tanh")
        sim = fluid.layers.cos_sim(X=usr, Y=mov)
        scale_infer = fluid.layers.scale(x=sim, scale=5.0)
        score = fluid.layers.data(name="score", shape=[1],
                                  dtype="float32")
        cost = fluid.layers.square_error_cost(input=scale_infer,
                                              label=score)
        avg = fluid.layers.mean(cost)
        fluid.SGD(learning_rate=0.2).minimize(avg)

    r = np.random.RandomState(0)

    # ONE sequence-length pattern shared by every batch (r6): the
    # executor's executable cache keys on the LoD, so per-batch random
    # lengths made each of the 8 batches a DISTINCT whole-program XLA
    # compile — the 85.3 s compile outlier of BOOK_MATRIX_r05 (2.3 s of
    # actual training).  Fixed lengths = one executable; contents still
    # vary per batch.  Real pipelines get the same effect from
    # reader.bucket_by_length (docs/performance.md, 'recompiles').
    cat_lens = r.randint(1, 5, 32)
    title_lens = r.randint(1, 9, 32)

    def seq(vocab, lens):
        flat = r.randint(0, vocab, (int(lens.sum()), 1)).astype(np.int64)
        return fluid.create_lod_tensor(flat, [list(lens)])

    def mk(n=32):
        ids = lambda k: r.randint(0, k, (n, 1)).astype(np.int64)
        feed = {"user_id": ids(USR_N), "gender_id": ids(GENDER_N),
                "age_id": ids(AGE_N), "job_id": ids(JOB_N),
                "movie_id": ids(MOV_N), "category_id": seq(CAT_N, cat_lens),
                "movie_title": seq(TITLE_VOCAB, title_lens)}
        s = (feed["user_id"] % 5 + feed["movie_id"] % 3).astype(np.float32)
        feed["score"] = s / 6.0 * 4.0 + 1.0
        return feed

    batches = [mk() for _ in range(8)]
    exe, scope = fluid.Executor(fluid.TPUPlace()), fluid.Scope()
    exe.run(startup, scope=scope)
    res = _train_loop(exe, scope, main, startup, batches, [avg],
                      lambda h: (h[-1][0], h[-1][0] < 1.0), max_steps=400)
    return _result("recommender_system", "mse_loss<", 1.0, res)


# ── book/06 understand_sentiment (stacked path: LSTM) ──────────────────
def run_understand_sentiment():
    DICT, EMB, HID, CLS = 40, 16, 32, 2
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        data = fluid.layers.data(name="words", shape=[1], dtype="int64",
                                 lod_level=1)
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        emb = fluid.layers.embedding(input=data, size=[DICT, EMB])
        fc1 = fluid.layers.fc(input=emb, size=HID * 4)
        lstm_h, _ = fluid.layers.dynamic_lstm(input=fc1, size=HID * 4,
                                              use_peepholes=False)
        pooled = fluid.layers.sequence_pool(input=lstm_h, pool_type="max")
        pred = fluid.layers.fc(input=pooled, size=CLS, act="softmax")
        cost = fluid.layers.cross_entropy(input=pred, label=label)
        avg = fluid.layers.mean(cost)
        acc = fluid.layers.accuracy(input=pred, label=label)
        fluid.Adam(learning_rate=0.05).minimize(avg)

    feeder = fluid.DataFeeder(feed_list=[data, label],
                              place=fluid.TPUPlace())
    r = np.random.RandomState(0)

    def mk(n=16):
        rows = []
        for _ in range(n):
            ln = int(r.randint(3, 9))
            cls = int(r.randint(0, CLS))
            lo, hi = (0, DICT // 2) if cls == 0 else (DICT // 2, DICT)
            rows.append((r.randint(lo, hi, (ln,)).astype(np.int64),
                         [cls]))
        return feeder.feed(rows)

    batches = [mk() for _ in range(4)]
    exe, scope = fluid.Executor(fluid.TPUPlace()), fluid.Scope()
    exe.run(startup, scope=scope)

    def check(h):
        a = float(np.mean([row[1] for row in h[-8:]]))
        return a, a > 0.9

    res = _train_loop(exe, scope, main, startup, batches, [avg, acc],
                      check, max_steps=300)
    return _result("understand_sentiment_lstm", "acc>", 0.9, res)


# ── book/07 label_semantic_roles (CRF) ─────────────────────────────────
def run_label_semantic_roles():
    WORD_N, TAG_N = 30, 5
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        word = fluid.layers.data(name="word", shape=[1], dtype="int64",
                                 lod_level=1)
        target = fluid.layers.data(name="target", shape=[1],
                                   dtype="int64", lod_level=1)
        emb = fluid.layers.embedding(input=word, size=[WORD_N, 32])
        hidden = fluid.layers.fc(input=emb, size=64, act="tanh")
        lstm, _ = fluid.layers.dynamic_lstm(
            input=fluid.layers.fc(input=hidden, size=64 * 4), size=64 * 4)
        feature_out = fluid.layers.fc(input=[hidden, lstm], size=TAG_N)
        crf_cost = fluid.layers.linear_chain_crf(
            input=feature_out, label=target, param_attr={"name": "crfw"})
        avg = fluid.layers.mean(crf_cost)
        fluid.SGD(learning_rate=0.05).minimize(avg)
        crf_decode = fluid.layers.crf_decoding(
            input=feature_out, param_attr={"name": "crfw"})
        f1, precision, recall, *_ = fluid.layers.chunk_eval(
            input=crf_decode, label=target, chunk_scheme="IOB",
            num_chunk_types=2)
    eval_prog = fluid.io.get_inference_program([f1, precision, recall],
                                               main)

    def make_seq(r, t):
        words = r.randint(0, WORD_N, t)
        tags = np.full(t, 4, np.int64)
        i = 0
        while i < t:
            w = words[i]
            if w < 6 and i + 1 < t:
                tags[i], tags[i + 1] = 0, 1
                i += 2
            elif w >= 24:
                tags[i] = 2
                i += 1
            else:
                i += 1
        return words, tags

    lens = [3, 5, 8, 4, 6, 8, 7, 3, 5, 8, 4, 6, 8, 7, 5, 6]
    r = np.random.RandomState(0)

    def mk():
        ws, ts = [], []
        for t in lens:
            w, tg = make_seq(r, t)
            ws.append(w)
            ts.append(tg)
        return {"word": fluid.create_lod_tensor(
                    np.concatenate(ws)[:, None].astype(np.int64),
                    [list(lens)]),
                "target": fluid.create_lod_tensor(
                    np.concatenate(ts)[:, None].astype(np.int64),
                    [list(lens)])}

    batches = [mk() for _ in range(6)]
    exe, scope = fluid.Executor(fluid.TPUPlace()), fluid.Scope()
    exe.run(startup, scope=scope)

    # threshold: chunk F1 on a held-out batch through the decode path —
    # absolute (vs the book test's loss-ratio), and it exercises
    # crf_decoding+chunk_eval on-chip too
    held_out = mk()

    def check(h):
        f1_v, _, _ = exe.run(eval_prog, feed=held_out,
                             fetch_list=[f1, precision, recall],
                             scope=scope)
        v = float(np.asarray(f1_v).reshape(-1)[0])
        return v, v > 0.6

    res = _train_loop(exe, scope, main, startup, batches, [avg], check,
                      max_steps=300,
                      extra_precompile=[(eval_prog, held_out,
                                         [f1, precision, recall])])
    return _result("label_semantic_roles_crf", "chunk_f1>", 0.6, res)


# ── book/08 machine_translation (seq2seq) ──────────────────────────────
def run_machine_translation():
    DICT, WORD_DIM, HIDDEN = 12, 16, 32
    START, END = 0, 1
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        src = fluid.layers.data(name="src_word_id", shape=[1],
                                dtype="int64", lod_level=1)
        s_emb = fluid.layers.embedding(input=src, size=[DICT, WORD_DIM],
                                       param_attr={"name": "vemb"})
        fc1 = fluid.layers.fc(input=s_emb, size=HIDDEN * 4, act="tanh")
        hidden, _ = fluid.layers.dynamic_lstm(input=fc1, size=HIDDEN * 4,
                                              use_peepholes=False)
        context = fluid.layers.sequence_last_step(input=hidden)
        trg = fluid.layers.data(name="target_language_word", shape=[1],
                                dtype="int64", lod_level=1)
        trg_emb = fluid.layers.embedding(input=trg, size=[DICT, WORD_DIM],
                                         param_attr={"name": "vemb"})
        rnn = fluid.layers.DynamicRNN()
        with rnn.block():
            w = rnn.step_input(trg_emb)
            pre_state = rnn.memory(init=context)
            state = fluid.layers.fc(input=[w, pre_state], size=HIDDEN,
                                    act="tanh")
            score = fluid.layers.fc(input=state, size=DICT, act="softmax")
            rnn.update_memory(pre_state, state)
            rnn.output(score)
        rnn_out = rnn()
        label = fluid.layers.data(name="target_language_next_word",
                                  shape=[1], dtype="int64", lod_level=1)
        cost = fluid.layers.cross_entropy(input=rnn_out, label=label)
        avg = fluid.layers.mean(cost)
        fluid.Adam(learning_rate=0.01).minimize(avg)

    from paddle_tpu.core.lod import LoDTensor

    def to_lod(seqs, dtype=np.int64):
        flat = np.concatenate(seqs).astype(dtype).reshape(-1, 1)
        lod = [0]
        for s in seqs:
            lod.append(lod[-1] + len(s))
        return LoDTensor(flat, [lod])

    r = np.random.RandomState(0)

    def mk(n=8):
        srcs, ti, tn = [], [], []
        for _ in range(n):
            ln = int(r.randint(2, 5))
            s = r.randint(2, DICT, (ln,))
            srcs.append(s)
            ti.append(np.concatenate([[START], s]))
            tn.append(np.concatenate([s, [END]]))
        return {"src_word_id": to_lod(srcs),
                "target_language_word": to_lod(ti),
                "target_language_next_word": to_lod(tn)}

    batches = [mk() for _ in range(4)]
    exe, scope = fluid.Executor(fluid.TPUPlace()), fluid.Scope()
    exe.run(startup, scope=scope)
    res = _train_loop(exe, scope, main, startup, batches, [avg],
                      lambda h: (h[-1][0], h[-1][0] < 1.0), max_steps=400)
    return _result("machine_translation_seq2seq", "xent_loss<", 1.0, res)


# ── REAL-corpus rows (offline: corpora ship inside scikit-learn) ───────
def run_fit_a_line_real():
    """book/01 on REAL data: linear regression on the diabetes study
    (442 real patients, 10 standardized features; dataset/diabetes.py).
    Threshold mse < 0.65 of target variance — the corpus' linear-model
    ceiling is R^2 ~ 0.5, so 0.65 means the fit is most of the way to
    the best linear model, measured on the HELD-OUT split."""
    from paddle_tpu.dataset import diabetes

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[10], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(input=x, size=1, act=None)
        cost = fluid.layers.square_error_cost(input=pred, label=y)
        avg = fluid.layers.mean(cost)
        test_prog = main.clone(for_test=True)
        fluid.SGD(learning_rate=0.03).minimize(avg)
    (tr_x, tr_y), (te_x, te_y) = diabetes.load_data()
    batches = [{"x": tr_x[i:i + 64], "y": tr_y[i:i + 64]}
               for i in range(0, 320, 64)]
    test_feed = {"x": te_x, "y": te_y}  # ALL 89 held-out rows
    exe, scope = fluid.Executor(fluid.TPUPlace()), fluid.Scope()
    exe.run(startup, scope=scope)

    def check(h):
        v, = exe.run(test_prog, feed=test_feed, fetch_list=[avg],
                     scope=scope)
        v = float(np.asarray(v).reshape(-1)[0])
        return v, v < 0.65

    res = _train_loop(exe, scope, main, startup, batches, [avg], check,
                      max_steps=400,
                      extra_precompile=[(test_prog, test_feed, [avg])])
    return _result("fit_a_line_real", "test_mse<", 0.65, res,
                   data="real (diabetes study, sklearn bundle)")


def run_recognize_digits_real():
    """book/02 on REAL data: the UCI optical handwritten digits (1,797
    real scans at 8x8; dataset/uci_digits.py), conv-pool + softmax,
    accuracy measured on the HELD-OUT 360 digits."""
    from paddle_tpu import nets
    from paddle_tpu.dataset import uci_digits

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[1, 8, 8],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        cp = nets.simple_img_conv_pool(
            input=img, filter_size=3, num_filters=16, pool_size=2,
            pool_stride=2, act="relu")
        pred = fluid.layers.fc(input=cp, size=10, act="softmax")
        cost = fluid.layers.cross_entropy(input=pred, label=label)
        avg = fluid.layers.mean(cost)
        acc = fluid.layers.accuracy(input=pred, label=label)
        test_prog = main.clone(for_test=True)
        fluid.Adam(learning_rate=0.003).minimize(avg)
    (tr_x, tr_y), (te_x, te_y) = uci_digits.load_data()
    batches = [{"img": tr_x[i:i + 128].reshape(-1, 1, 8, 8),
                "label": tr_y[i:i + 128][:, None]}
               for i in range(0, 1280, 128)]
    test_feed = {"img": te_x.reshape(-1, 1, 8, 8),
                 "label": te_y[:, None]}  # ALL 360 held-out digits
    exe, scope = fluid.Executor(fluid.TPUPlace()), fluid.Scope()
    exe.run(startup, scope=scope)

    def check(h):
        _, a = exe.run(test_prog, feed=test_feed,
                       fetch_list=[avg, acc], scope=scope)
        a = float(np.asarray(a).reshape(-1)[0])
        return a, a > 0.9

    res = _train_loop(exe, scope, main, startup, batches, [avg, acc],
                      check, max_steps=400,
                      extra_precompile=[(test_prog, test_feed,
                                         [avg, acc])])
    return _result("recognize_digits_real", "test_acc>", 0.9, res,
                   data="real (UCI optical digits, sklearn bundle)")


RUNNERS = [run_fit_a_line, run_recognize_digits, run_image_classification,
           run_word2vec, run_recommender_system, run_understand_sentiment,
           run_label_semantic_roles, run_machine_translation,
           run_fit_a_line_real, run_recognize_digits_real]


def run_matrix():
    if AMP:
        fluid.amp.enable_bf16()
    else:
        # the host process (e.g. bench.py with BENCH_BOOK=1) may have
        # amp on from its own headline — the reported "amp" field must
        # match the mode the matrix actually ran in
        fluid.amp.disable_bf16()
    results = []
    for fn in RUNNERS:
        res = fn()
        results.append(res)
        print(f"# {res['model']}: {res['metric']}{res['target']} -> "
              f"{res['value']} reached={res['reached']} "
              f"steps={res['steps']} train={res['seconds']}s "
              f"compile={res['compile_seconds']}s", file=sys.stderr)
    n_ok = sum(r["reached"] for r in results)
    return {"metric": "book_convergence_matrix",
            "reached": f"{n_ok}/{len(results)}", "amp": AMP,
            "compile_seconds_total": round(
                sum(r["compile_seconds"] for r in results), 1),
            "compile_cache_dir": os.environ.get(
                "PADDLE_TPU_COMPILATION_CACHE_DIR", ""),
            "models": results}


if __name__ == "__main__":
    out = run_matrix()
    print(json.dumps(out))
    if out["reached"] != f"{len(RUNNERS)}/{len(RUNNERS)}":
        sys.exit(1)
