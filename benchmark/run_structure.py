"""Collective-structure scaling on virtual meshes — the honest stand-in
for BASELINE.json's "1->64 chip scaling" axis in a 1-chip environment
(VERDICT r3 weak #5).

Real ICI bandwidth cannot be measured without a pod, but what breaks
FIRST at scale is structural: sharding propagation, collective
insertion, placement, and compile success at large device counts.  Per
device count N this tool compiles, on an N-device virtual CPU mesh:

  dp    — ResNet training step, {dp: N}           (ParallelExecutor)
  pp    — transformer LM from the DSL, {dp: N/4, pp: 4}
          (PipelineExecutor, GPipe schedule)
  pp_1f1b — the SAME program under schedule='1f1b' (r5): fwd and
          reverse-cotangent hops in one scan, >=2 permutes asserted
  comp  — composed transformer, {dp: N/4, pp: 2, tp: 2} + ZeRO-1 +
          grad accumulation (make_transformer_composite_step)
  ep    — MoE all_to_all dispatch, {ep: N}

and records the optimized HLO's collective-op counts plus compile wall
time, asserting the per-axis invariants:

  dp   : >=1 all-reduce (grad sum), no pipeline permutes
  pp   : >=1 collective-permute (fwd ring hop + reverse-schedule hop)
  comp : both of the above classes present
  ep   : >=2 all-to-all (dispatch + return), count independent of N

Counts are structure (ops in the program), not hop counts — a ppermute
inside lax.scan appears once however many microbatches flow through it —
so the scaling claim is that the structure stays CONSTANT per axis while
N grows; growth in collective count with N would mean the partitioner is
inserting unplanned resharding (the thing that would eat a real pod's
ICI).  Non-power-of-two meshes may legitimately add resharding
collectives; the sweep uses powers of two.

Usage:
  python benchmark/run_structure.py [--devices 16,32,64] [--json out]
  python benchmark/run_structure.py --single N    (internal: one mesh)
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))


def _measure(n: int) -> dict:
    import jax

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # the env var alone is NOT enough: the TPU-tunnel site hook
        # (axon) force-sets jax_platforms at interpreter boot, so the
        # parent's "run me on cpu" request must be pinned via config
        # (same dance as tests/conftest.py)
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu import parallel
    from paddle_tpu.core.framework import reset_unique_names
    from paddle_tpu.models.resnet import resnet_cifar10
    from paddle_tpu.models.transformer import transformer_lm

    out = {"n": n}

    # ---- dp: ResNet train step --------------------------------------
    reset_unique_names()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[3, 16, 16],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        predict = resnet_cifar10(img, class_dim=4, depth=8)
        avg = fluid.layers.mean(
            fluid.layers.cross_entropy(input=predict, label=label))
        fluid.Momentum(learning_rate=0.1, momentum=0.9).minimize(avg)
    t0 = time.perf_counter()
    pe = parallel.ParallelExecutor(
        main, ["img", "label"], [avg], mesh={"dp": n},
        startup_program=startup, shard_optimizer_states=True)
    r = np.random.RandomState(0)
    feed = {"img": r.rand(2 * n, 3, 16, 16).astype(np.float32),
            "label": r.randint(0, 4, (2 * n, 1)).astype(np.int32)}
    out["dp"] = pe.compiled_collectives(feed)
    out["dp_compile_s"] = round(time.perf_counter() - t0, 2)

    # ---- pp: DSL transformer pipeline -------------------------------
    V, S, D = 8, 8, 8
    pdp = max(1, n // 4)
    reset_unique_names()
    def build_pp_program():
        pm, ps = fluid.Program(), fluid.Program()
        with fluid.program_guard(pm, ps):
            ids = fluid.layers.data(name="ids", shape=[S], dtype="int64")
            lab = fluid.layers.data(name="lab", shape=[S, 1],
                                    dtype="int64")
            lg = transformer_lm(ids, V, d_model=D, n_heads=2, n_layers=4,
                                max_len=S, return_logits=True,
                                pipeline_stages=4)
            pl = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(
                    fluid.layers.reshape(lg, shape=[-1, V]),
                    fluid.layers.reshape(lab, shape=[-1, 1])))
            fluid.Momentum(learning_rate=0.05, momentum=0.9).minimize(pl)
        return pm, ps, pl

    pfeed = {"ids": r.randint(0, V, (2 * pdp, S)).astype(np.int64),
             "lab": r.randint(0, V, (2 * pdp, S, 1)).astype(np.int64)}
    # SAME program under both schedules — a one-sided config edit would
    # silently compare different models
    for sched, key in (("gpipe", "pp"), ("1f1b", "pp_1f1b")):
        reset_unique_names()
        pm, ps, pl = build_pp_program()
        t0 = time.perf_counter()
        ppe = parallel.PipelineExecutor(
            pm, ["ids", "lab"], [pl], mesh={"dp": pdp, "pp": 4},
            startup_program=ps, n_micro=2, schedule=sched)
        out[key] = ppe.compiled_collectives(pfeed)
        out[key + "_compile_s"] = round(time.perf_counter() - t0, 2)

    # ---- comp: composed dp x pp x tp transformer --------------------
    cdp = max(1, n // 4)
    cmesh = parallel.make_mesh({"dp": cdp, "pp": 2, "tp": 2})
    t0 = time.perf_counter()
    cstep, cparams, cvel, cmeta = \
        parallel.make_transformer_composite_step(cmesh)
    ids = jnp.asarray(r.randint(0, cmeta["vocab"],
                                (2, 4 * cdp, cmeta["seq"]))
                      .astype(np.int32))
    lab = jnp.asarray(r.randint(0, cmeta["vocab"],
                                (2, 4 * cdp, cmeta["seq"]))
                      .astype(np.int32))
    out["comp"] = parallel.collective_counts(cstep, cparams, cvel,
                                             ids, lab)
    out["comp_compile_s"] = round(time.perf_counter() - t0, 2)

    # ---- ep: MoE all_to_all dispatch --------------------------------
    ep_mesh = parallel.make_mesh({"ep": n})
    E, Dm, H = n, 8, 16
    x = jnp.asarray(r.randn(8 * n, Dm).astype(np.float32))
    gw = jnp.asarray(r.randn(Dm, E).astype(np.float32) * 0.1)
    wi = jnp.asarray(r.randn(E, Dm, H).astype(np.float32) * 0.1)
    wo = jnp.asarray(r.randn(E, H, Dm).astype(np.float32) * 0.1)

    def moe_loss(x, gw, wi, wo):
        y, aux = parallel.moe_ffn_a2a(x, gw, wi, wo, ep_mesh, top_k=2)
        return jnp.mean(y * y) + 0.01 * aux

    t0 = time.perf_counter()
    g = jax.jit(jax.grad(moe_loss, argnums=(1, 2, 3)))
    txt = g.lower(x, gw, wi, wo).compile().as_text()
    from paddle_tpu.parallel.mesh import count_collectives
    out["ep"] = count_collectives(txt)
    out["ep_compile_s"] = round(time.perf_counter() - t0, 2)
    return out


def check_invariants(row: dict) -> list:
    """Per-axis structural invariants; returns failure strings."""
    bad = []
    if row["dp"].get("all-reduce", 0) < 1:
        bad.append(f"N={row['n']} dp: no grad all-reduce {row['dp']}")
    if row["dp"].get("collective-permute", 0) != 0:
        bad.append(f"N={row['n']} dp: unexpected permutes {row['dp']}")
    if row["pp"].get("collective-permute", 0) < 1:
        bad.append(f"N={row['n']} pp: no pipeline permute {row['pp']}")
    # 1f1b runs fwd AND reverse hops inside one scan: at least the fwd
    # permute plus the reverse-cotangent permute
    if row["pp_1f1b"].get("collective-permute", 0) < 2:
        bad.append(f"N={row['n']} pp_1f1b: missing fwd+bwd permutes "
                   f"{row['pp_1f1b']}")
    if row["comp"].get("collective-permute", 0) < 1 or \
            row["comp"].get("all-reduce", 0) < 1:
        bad.append(f"N={row['n']} comp: structure missing {row['comp']}")
    if row["ep"].get("all-to-all", 0) < 2:
        bad.append(f"N={row['n']} ep: a2a dispatch/return missing "
                   f"{row['ep']}")
    return bad


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", default="16,32,64")
    ap.add_argument("--json", default=None)
    ap.add_argument("--single", type=int, default=None)
    a = ap.parse_args()

    if a.single is not None:
        row = _measure(a.single)
        print(json.dumps(row))
        bad = check_invariants(row)
        for b in bad:
            print(f"invariant violated: {b}", file=sys.stderr)
        sys.exit(0 if not bad else 1)

    rows, failures = [], []
    for n in [int(x) for x in a.devices.split(",")]:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            f" --xla_force_host_platform_device_count={n}")
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--single",
             str(n)],
            env=env, capture_output=True, text=True)
        if p.returncode != 0:
            failures.append(f"N={n}: rc={p.returncode}\n{p.stderr[-2000:]}")
            continue
        # per-row invariants already enforced by the child (rc != 0 +
        # stderr diagnostics above); the parent checks cross-N constancy
        row = json.loads(p.stdout.strip().splitlines()[-1])
        rows.append(row)

    # structure must stay CONSTANT per axis as N grows (see docstring).
    # pp and ep pin the full count vector; for comp the partitioner may
    # route ZeRO-1 state resharding through one extra collective-permute
    # at small dp (measured: 8 at dp=4 vs 7 at dp=8/16), so comp pins
    # the planned classes (all-reduce = dp grads + tp psums, all-to-all,
    # all-gather) exactly and permutes as a +-1 band
    for key in ("pp", "pp_1f1b", "ep"):
        counts = {json.dumps(r[key], sort_keys=True) for r in rows}
        if len(counts) > 1:
            failures.append(
                f"{key}: collective structure varies with N: {counts}")
    if rows:
        comp_fixed = {json.dumps({k: v for k, v in r["comp"].items()
                                  if k != "collective-permute"},
                                 sort_keys=True) for r in rows}
        if len(comp_fixed) > 1:
            failures.append(
                f"comp: non-permute structure varies with N: {comp_fixed}")
        perms = [r["comp"].get("collective-permute", 0) for r in rows]
        if max(perms) - min(perms) > 1:
            failures.append(f"comp: permute count drifts with N: {perms}")

    hdr = ("| N | dp (ResNet) | pp (DSL transformer) | "
           "pp 1f1b | comp (dp x pp2 x tp2) | ep (MoE a2a) | compile s "
           "(dp/pp/comp/ep) |")
    print(hdr)
    print("|" + "---|" * 7)
    for r in rows:
        fmt = lambda d: ", ".join(f"{k.replace('collective-', '')}:{v}"
                                  for k, v in sorted(d.items())) or "none"
        print(f"| {r['n']} | {fmt(r['dp'])} | {fmt(r['pp'])} | "
              f"{fmt(r['pp_1f1b'])} | {fmt(r['comp'])} | {fmt(r['ep'])} | "
              f"{r['dp_compile_s']}/{r['pp_compile_s']}/"
              f"{r['pp_1f1b_compile_s']}/"
              f"{r['comp_compile_s']}/{r['ep_compile_s']} |")
    if a.json:
        with open(a.json, "w") as f:
            json.dump({"rows": rows, "failures": failures}, f, indent=1)
    if failures:
        print("\nFAILURES:")
        for f_ in failures:
            print(" -", f_)
        sys.exit(1)
    print("\nall structural invariants hold")


if __name__ == "__main__":
    main()
