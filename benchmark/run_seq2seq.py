"""seq2seq training tokens/sec — the BASELINE.json headline's second
metric ("ResNet-50 images/sec/chip + seq2seq tokens/sec").

Two models:
  * `--model transformer` (default): encoder-decoder transformer
    translator (models/transformer.py), the modern seq2seq; bf16 by
    default so attention + FFN matmuls ride the MXU.
  * `--model rnn`: the reference-era seq2seq — the book/08
    machine-translation shape (embedding + scan-based GRU encoder-decoder
    with attention, built from the same layers the book test uses).

The reference has no published seq2seq throughput number (its NMT
benchmark tables were left unfilled, reference benchmark/cluster/README.md
:33-74), so tokens/sec here stands alone; `vs_baseline` is null.

Usage:  python benchmark/run_seq2seq.py [--model transformer] [--batch 32]
        [--src-len 128] [--tgt-len 128] [--iters 20] [--dtype bfloat16]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from harness import (  # noqa: E402  (benchmark/ on path via bench.py)
    bound_fields,
    gated_time_program,
)

SRC_VOCAB = 30000
TGT_VOCAB = 30000


def build_transformer(batch, src_len, tgt_len, dtype, remat=False):
    import paddle_tpu as fluid
    from paddle_tpu.models.transformer import transformer_translate

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        src = fluid.layers.data(name="src", shape=[src_len], dtype="int64")
        tgt = fluid.layers.data(name="tgt", shape=[tgt_len], dtype="int64")
        lbl = fluid.layers.data(name="lbl", shape=[tgt_len, 1],
                                dtype="int64")
        logits = transformer_translate(
            src, tgt, SRC_VOCAB, TGT_VOCAB, d_model=512, n_heads=8,
            n_layers=6, dropout_rate=0.0, is_test=False,
            return_logits=True, remat=remat)
        logits2d = fluid.layers.reshape(logits, shape=[-1, TGT_VOCAB])
        lbl2d = fluid.layers.reshape(lbl, shape=[-1, 1])
        # fused softmax-xent on logits: the [b*t, 30k] probability tensor
        # (and its cotangent) never round-trips HBM
        cost = fluid.layers.softmax_with_cross_entropy(logits2d, lbl2d)
        avg = fluid.layers.mean(cost)
        fluid.Adam(learning_rate=1e-4).minimize(avg)
    return main, startup, avg


def build_rnn(batch, src_len, tgt_len, dtype):
    """Reference-era seq2seq at bench scale: the book/08 training shape
    (LoD sequences, LSTM encoder -> last state -> LSTM decoder;
    reference tests/book/test_machine_translation.py:24-49 — the
    reference's book model has no attention, SURVEY.md §5.7)."""
    import paddle_tpu as fluid

    hidden = 512
    emb_dim = 512
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        src = fluid.layers.data(name="src", shape=[1], dtype="int64",
                                lod_level=1)
        tgt = fluid.layers.data(name="tgt", shape=[1], dtype="int64",
                                lod_level=1)
        lbl = fluid.layers.data(name="lbl", shape=[1], dtype="int64",
                                lod_level=1)
        src_emb = fluid.layers.embedding(input=src,
                                         size=[SRC_VOCAB, emb_dim])
        enc_in = fluid.layers.fc(input=src_emb, size=hidden * 4,
                                 act="tanh")
        enc, _ = fluid.layers.dynamic_lstm(input=enc_in, size=hidden * 4,
                                           use_peepholes=False)
        context = fluid.layers.sequence_last_step(input=enc)
        tgt_emb = fluid.layers.embedding(input=tgt,
                                         size=[TGT_VOCAB, emb_dim])
        ctx_exp = fluid.layers.sequence_expand(x=context, y=tgt_emb)
        dec_in = fluid.layers.concat([tgt_emb, ctx_exp], axis=1)
        dec_proj = fluid.layers.fc(input=dec_in, size=hidden * 4,
                                   act="tanh")
        dec, _ = fluid.layers.dynamic_lstm(input=dec_proj,
                                           size=hidden * 4,
                                           use_peepholes=False)
        probs = fluid.layers.fc(input=dec, size=TGT_VOCAB, act="softmax")
        cost = fluid.layers.cross_entropy(input=probs, label=lbl)
        avg = fluid.layers.mean(cost)
        fluid.Adam(learning_rate=1e-4).minimize(avg)
    return main, startup, avg


def run_one(model, batch, src_len, tgt_len, iters, dtype, remat=False):
    import paddle_tpu as fluid

    if dtype == "bfloat16":
        # f32 master weights, bf16 compute on the MXU ops (amp.py)
        fluid.amp.enable_bf16()
    if model == "transformer":
        main, startup, avg = build_transformer(batch, src_len, tgt_len,
                                               dtype, remat=remat)
    else:
        if remat:
            raise SystemExit("--remat only applies to the transformer "
                             "model (the rnn build has no remat path)")
        main, startup, avg = build_rnn(batch, src_len, tgt_len, dtype)
    r = np.random.RandomState(0)
    if model == "transformer":
        feeds = {
            "src": r.randint(0, SRC_VOCAB,
                             (batch, src_len)).astype(np.int32),
            "tgt": r.randint(0, TGT_VOCAB,
                             (batch, tgt_len)).astype(np.int32),
            "lbl": r.randint(0, TGT_VOCAB,
                             (batch, tgt_len, 1)).astype(np.int32),
        }
    else:
        from paddle_tpu.core.lod import LoDTensor, lod_from_seq_lens

        def seq(vocab, length):
            return LoDTensor(
                r.randint(0, vocab,
                          (batch * length, 1)).astype(np.int32),
                [lod_from_seq_lens([length] * batch)])

        feeds = {"src": seq(SRC_VOCAB, src_len),
                 "tgt": seq(TGT_VOCAB, tgt_len),
                 "lbl": seq(TGT_VOCAB, tgt_len)}
    ms, cost, fields = gated_time_program(main, startup, feeds, avg.name,
                                          iters)
    tokens = batch * (src_len + tgt_len)
    out = {
        "model": f"seq2seq_{model}", "batch": batch, "remat": remat,
        "src_len": src_len, "tgt_len": tgt_len, "dtype": dtype,
        "ms_per_batch": round(ms, 2),
        "tokens_per_sec": round(tokens / ms * 1000, 1),
        "vs_baseline": None,   # reference published no seq2seq throughput
    }
    out.update(fields)
    out.update(bound_fields(ms, cost))
    print(json.dumps(out))
    if not fields["valid"]:
        sys.exit(1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="transformer",
                    choices=["transformer", "rnn"])
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--src-len", type=int, default=128)
    ap.add_argument("--tgt-len", type=int, default=128)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--remat", action="store_true",
                    help="rematerialize transformer blocks "
                         "(bytes-for-FLOPs trade on the memory-bound step)")
    a = ap.parse_args()
    run_one(a.model, a.batch, a.src_len, a.tgt_len, a.iters, a.dtype,
            remat=a.remat)


if __name__ == "__main__":
    main()
