#!/usr/bin/env python
"""Calibrate XLA's "bytes accessed" against measured HBM time.

`hbm_util` (harness.roofline_fields) divides XLA's cost-analysis byte
count by measured time x the chip's peak bandwidth.  Two questions
decide whether that number is an instrument or noise:

1. **Is the COUNT right?**  Checked statically (no timing involved):
   for streaming kernels whose traffic is known analytically (copy,
   axpy), XLA's count must equal ground truth.  It does, exactly
   (`count_ratio = 1.0` below).  For FUSED model steps the count
   over-reads (a buffer consumed by two fusions counts twice): the
   seq2seq transformer step measures hbm_util ~1.43 at a
   sync-validated step time, bounding the over-count at ~1.43x — the
   origin of the plausibility band `hbm_util <= 1.5`
   (harness.HBM_UTIL_BOUND).

2. **Is the TIME right?**  Pure-bandwidth microkernels are NOT
   measurable through this environment's device tunnel: it defers
   execution of some program shapes past `block_until_ready` (a
   512-matvec chain "completed" in 0.2 ms; the value readback then took
   178 s), so this script calibrates on the ResNet-50 bs256 training
   step instead — a config whose wall-clock was independently
   reproduced with synchronous per-step probes, whose arithmetic
   intensity (~82 FLOP/B) sits 3x below the v5e ridge point, and whose
   XLA count matched hand analysis within a few percent.  The achieved
   fraction of datasheet bandwidth on that step is the empirical
   "speed of light" for fused real models on this chip.

Run on the real chip: python benchmark/calibrate_hbm.py
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from harness import (HBM_UTIL_BOUND, chip_specs, plausibility,
                     roofline_from_cost, time_program_scan)


def count_exactness():
    """XLA bytes-accessed vs analytic ground truth on unfused streaming
    kernels — a pure cost-analysis check, no device timing involved."""
    import jax
    import jax.numpy as jnp

    n = 16 * 1024 * 1024  # 64 MB f32
    x = jnp.zeros((n,), jnp.float32)
    rows = []
    for name, fn, analytic in (
            ("copy", lambda v: v + 1.0, 2 * 4 * n),
            ("axpy", lambda v: 0.5 * v + 0.25, 2 * 4 * n),
            ("sum", lambda v: jnp.sum(v), 4 * n)):
        cost = jax.jit(fn).lower(x).compile().cost_analysis() or {}
        got = cost.get("bytes accessed", 0.0)
        rows.append({"case": name,
                     "analytic_mb": round(analytic / 1e6, 1),
                     "xla_mb": round(got / 1e6, 1),
                     "count_ratio": round(got / analytic, 3)})
    return rows


def measured_band():
    """ResNet-50 bs256 amp step via the scan instrument: the achieved
    fraction of datasheet HBM bandwidth on a sync-validated,
    memory-bound real model."""
    import paddle_tpu as fluid

    import bench  # noqa: E402  (repo-root bench.py, on path via line 38)

    fluid.amp.enable_bf16()
    main_p, startup, avg = bench.build_resnet50_train(256, "bfloat16")
    r = np.random.RandomState(0)
    from paddle_tpu.core.types import np_dtype
    feeds = {
        "img": r.rand(256, 3, 224, 224).astype(np_dtype("bfloat16")),
        "label": r.randint(0, 1000, (256, 1)).astype(np.int32),
    }
    ms, cost = time_program_scan(main_p, startup, feeds, avg.name,
                                 outer_iters=3, k_inner=4,
                                 with_cost=True)
    fields = roofline_from_cost(ms, cost)
    ok, reason = plausibility(fields, ms)
    return {
        "model": "resnet50_bs256_amp_train",
        "ms_per_step": round(ms, 2),
        "hbm_gb_per_step": fields.get("hbm_gb_per_step"),
        "achieved_bw_frac_of_peak": fields.get("hbm_util"),
        "valid": ok, **({"invalid_reason": reason} if not ok else {}),
    }


def main():
    kind, peak, hbm = chip_specs()
    if hbm is None:
        raise SystemExit(f"no HBM spec for device {kind!r} — run on TPU")
    band = measured_band()
    out = {
        "device": kind,
        "hbm_peak_gb_s": hbm / 1e9,
        "count_exactness": count_exactness(),
        "measured": band,
        "fused_overcount_bound": 1.43,  # seq2seq step, sync-validated
        "acceptance_band": f"hbm_util <= {HBM_UTIL_BOUND} is plausible "
                           "(fused over-count allowance); beyond it is "
                           "a timing artifact (harness.plausibility, "
                           "benches exit non-zero)",
        "valid": band["valid"],
    }
    print(json.dumps(out))
    if not out["valid"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
