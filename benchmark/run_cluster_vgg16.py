#!/usr/bin/env python
"""Cluster-VGG16 protocol cells (reference benchmark/cluster/vgg16):
CIFAR-shape vgg16_bn_drop samples/s.

The reference's published cells are 20-trainer/10-pserver k8s pods
(190-258 samples/s at bs 32-256) plus a single-node single-thread row
(15.4-16.8 samples/s).  One chip + one host cannot reproduce the pod
grid; this script fills what is honest here:

  * default          — single-process samples/s on the current backend
                       (pin to one CPU core via
                       `taskset -c 0` + XLA_FLAGS=--xla_cpu_multi_thread_eigen=false
                       to compare against the single-thread row)
  * --cluster P T    — a REAL local pserver cluster (P pservers x T
                       trainer subprocesses over the TCP transport,
                       DistributeTranspiler) reporting aggregate
                       samples/s — the protocol at laptop scale, not a
                       pod-grid claim.

Prints one JSON line per measurement.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

if os.environ.get("JAX_PLATFORMS", "") == "cpu":
    # the device-tunnel site hook force-sets jax_platforms at boot; the
    # env var alone does not stick (same guard as __graft_entry__.py)
    import jax

    jax.config.update("jax_platforms", "cpu")


def build(batch):
    import paddle_tpu as fluid
    from paddle_tpu.models.vgg import vgg16_bn_drop

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="pixel", shape=[3, 32, 32],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        predict = vgg16_bn_drop(img, class_dim=10)
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(input=predict, label=label))
        opt_ops, params_grads = fluid.SGD(
            learning_rate=0.01).minimize(loss)
    return main, startup, loss, opt_ops, params_grads


def run_single(batch, iters):
    import numpy as np

    sys.path.insert(0, HERE)
    from harness import time_program

    main, startup, loss, _, _ = build(batch)
    r = np.random.RandomState(0)
    feeds = {"pixel": r.rand(batch, 3, 32, 32).astype(np.float32),
             "label": r.randint(0, 10, (batch, 1)).astype(np.int32)}
    ms = time_program(main, startup, feeds, loss.name, iters)
    print(json.dumps({
        "bench": "cluster_vgg16", "mode": "single", "batch": batch,
        "ms_per_batch": round(ms, 2),
        "samples_per_sec": round(batch / ms * 1000, 2),
        "ref_single_thread_samples_per_sec":
            {32: 15.44, 64: 16.32, 128: 16.74, 256: 16.79}.get(batch),
    }))


def run_trainer_role(batch, iters):
    """Body for one cluster role process (env-var convention)."""
    import numpy as np

    import paddle_tpu as fluid

    role = os.environ["TRAINING_ROLE"]
    trainers = int(os.environ["PADDLE_INIT_NUM_GRADIENT_SERVERS"])
    main, startup, loss, opt_ops, params_grads = build(batch)
    with fluid.program_guard(main, startup):
        t = fluid.DistributeTranspiler()
        t.transpile(optimize_ops=opt_ops, params_grads=params_grads,
                    trainers=trainers, pservers=os.environ["PSERVERS"])
    exe = fluid.Executor(fluid.CPUPlace())
    if role == "PSERVER":
        ep = os.environ["SERVER_ENDPOINT"]
        exe.run(t.get_startup_program(ep))
        exe.run(t.get_pserver_program(ep))
        return
    exe.run(startup)
    prog = t.get_trainer_program()
    r = np.random.RandomState(0)
    feeds = {"pixel": r.rand(batch, 3, 32, 32).astype(np.float32),
             "label": r.randint(0, 10, (batch, 1)).astype(np.int32)}
    exe.run(prog, feed=feeds, fetch_list=[loss])  # warmup/compile
    t0 = time.perf_counter()
    for _ in range(iters):
        exe.run(prog, feed=feeds, fetch_list=[loss])
    dt = time.perf_counter() - t0
    print(json.dumps({"role_samples_per_sec":
                      round(batch * iters / dt, 2)}), flush=True)


def run_cluster(batch, iters, n_pservers, n_trainers):
    import threading

    sys.path.insert(0, os.path.join(REPO, "tools"))
    from launch import launch_pserver_cluster

    # child processes rebuild env from os.environ (launch.py); APPEND to
    # XLA_FLAGS — clobbering would silently drop operator-set flags like
    # --xla_cpu_multi_thread_eigen=false and invalidate the measurement
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=1"
                               ).strip()
    procs = launch_pserver_cluster(
        os.path.abspath(__file__),
        ["--role-body", "--batch", str(batch), "--iters", str(iters)],
        n_pservers, n_trainers,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    total = 0.0
    ok = True
    try:
        # drain every trainer pipe CONCURRENTLY: sync-SGD trainers move in
        # lock-step through the pserver barrier, so one trainer blocked on
        # a full unread pipe would stall the whole cluster
        outs = {}

        def drain(p):
            outs[p] = p.communicate(timeout=1800)[0]

        threads = [threading.Thread(target=drain, args=(p,), daemon=True)
                   for role, p in procs if role == "trainer"]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=1800)
        for role, p in procs:
            if role != "trainer":
                continue
            m = re.search(r'\{"role_samples_per_sec": ([0-9.]+)\}',
                          outs.get(p) or "")
            if m:
                total += float(m.group(1))
            else:
                ok = False
    finally:
        for role, p in procs:
            if p.poll() is None:
                p.terminate()
    print(json.dumps({
        "bench": "cluster_vgg16", "mode": "pserver_cluster",
        "pservers": n_pservers, "trainers": n_trainers, "batch": batch,
        "aggregate_samples_per_sec": round(total, 2), "ok": ok,
        "note": "local-host protocol run (TCP pserver transport); the "
                "reference's 20-trainer k8s cells are not reproducible "
                "on one host",
    }))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--cluster", nargs=2, type=int, metavar=("P", "T"))
    ap.add_argument("--role-body", action="store_true")
    args = ap.parse_args()
    if args.role_body:
        run_trainer_role(args.batch, args.iters)
    elif args.cluster:
        run_cluster(args.batch, args.iters, *args.cluster)
    else:
        run_single(args.batch, args.iters)


if __name__ == "__main__":
    main()
