"""Data-parallel scaling efficiency — the BASELINE.json headline's third
metric ("1→64 chip scaling eff"); the reference's analogue tables are the
4×K40m speedups (benchmark/README.md:70-84, e.g. AlexNet 3.85×/4 GPUs) and
the k8s trainer-count scaling grid (benchmark/cluster/vgg16/README.md:43-48,
60-93% efficiency at 20-100 trainers).

Per device-count N: jit one ResNet training step over a {"dp": N} mesh
(ParallelExecutor — same psum-over-ICI path `dryrun_multichip` validates),
batch = N × per-device batch, report images/sec and efficiency vs N=1.

With real multi-chip hardware this measures ICI scaling directly.  With a
single chip / CPU, pass `--virtual` to respawn per-N subprocesses with
`--xla_force_host_platform_device_count=N` (validates the SPMD path and
measures collective+partitioning overhead; physical cores are shared, so
virtual "efficiency" is a lower bound, not an ICI measurement).

Usage: python benchmark/run_scaling.py [--devices 1,2,4,8] [--virtual]
       [--batch-per-dev 64] [--iters 10] [--depth 50] [--img 32]
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))


def run_single(n, batch_per_dev, iters, depth, img, overlap="off"):
    import jax

    # honor an explicit JAX_PLATFORMS=cpu even when the TPU-tunnel site
    # hook force-set jax_platforms at boot (same guard as __graft_entry__)
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu import parallel
    from paddle_tpu.models.resnet import resnet_cifar10, resnet_imagenet

    batch = n * batch_per_dev
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        data = fluid.layers.data(name="img", shape=[3, img, img],
                                 dtype="bfloat16")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        if img <= 64:
            predict = resnet_cifar10(data, class_dim=10, depth=min(depth, 32))
        else:
            predict = resnet_imagenet(data, class_dim=1000, depth=depth)
        cost = fluid.layers.cross_entropy(input=predict, label=label)
        avg = fluid.layers.mean(cost)
        fluid.Momentum(learning_rate=0.1, momentum=0.9).minimize(avg)

    if overlap != "off":
        # the MAINLINE multichip path (docs/performance.md "Multichip
        # sharding"): spmd transpile + bucketed-psum grad overlap, so
        # scaling rounds measure the transpiler, not a hand-built
        # executor.  ResNet's training-mode batch_norm makes 'auto'
        # stand down to the GSPMD step — the record says why.
        t = fluid.ShardingTranspiler()
        # shard_optimizer_states=False: the direct ParallelExecutor arm
        # below runs without ZeRO-1, and an A/B between the arms must
        # not attribute ZeRO's placement collectives to the overlap path
        t.transpile(program=main, startup_program=startup,
                    mesh={"dp": n}, overlap=overlap,
                    shard_optimizer_states=False)
        pe = t.build_executor(["img", "label"], [avg])
    else:
        pe = parallel.ParallelExecutor(main, ["img", "label"], [avg],
                                       mesh={"dp": n},
                                       startup_program=startup)
    r = np.random.RandomState(0)
    feed = {"img": r.rand(batch, 3, img, img).astype("float32")
            .astype("bfloat16"),
            "label": r.randint(0, 10, (batch, 1)).astype(np.int32)}
    out = pe.run(feed)          # compile + warmup
    jax.block_until_ready(out[0])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = pe.run(feed)
    jax.block_until_ready(out[0])
    ms = (time.perf_counter() - t0) / iters * 1000
    out = {"devices": n, "batch": batch, "ms_per_batch": round(ms, 2),
           "images_per_sec": round(batch / ms * 1000, 1)}
    if overlap != "off":
        out["overlap"] = dict(pe.overlap_info)
    if jax.default_backend() != "tpu":
        # the communication structure is meaningful even when virtual
        # throughput is not: dp-N must show grad all-reduces (and only
        # those), pinned per N from the compiled HLO.  Skipped on real
        # chips: compiled_collectives lowers+compiles a second copy of
        # the step (minutes of compile for a structure that is identical
        # to the CPU lowering's).
        out["collectives"] = pe.compiled_collectives(feed)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", default="1,2,4,8")
    ap.add_argument("--batch-per-dev", type=int, default=64)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--depth", type=int, default=32)
    ap.add_argument("--img", type=int, default=32)
    ap.add_argument("--virtual", action="store_true",
                    help="respawn per-N with virtual CPU devices")
    ap.add_argument("--overlap", default="off",
                    choices=["off", "auto"],
                    help="'auto': run the mainline spmd-transpiler "
                         "path; the bucketed compute/collective "
                         "overlap engages where eligible and the "
                         "record carries overlap_info (ResNet's "
                         "training-mode batch_norm makes it stand "
                         "down with the reason recorded)")
    ap.add_argument("--single", type=int, default=0,
                    help="(internal) run one N in this process")
    a = ap.parse_args()

    if a.single:
        print(json.dumps(run_single(a.single, a.batch_per_dev, a.iters,
                                    a.depth, a.img, a.overlap)))
        return

    counts = [int(x) for x in a.devices.split(",")]
    results = []
    for n in counts:
        if a.virtual:
            env = dict(os.environ,
                       JAX_PLATFORMS="cpu",
                       XLA_FLAGS=(os.environ.get("XLA_FLAGS", "") +
                                  f" --xla_force_host_platform_device_count={n}"))
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--single", str(n),
                 "--batch-per-dev", str(a.batch_per_dev),
                 "--iters", str(a.iters), "--depth", str(a.depth),
                 "--img", str(a.img), "--overlap", a.overlap],
                env=env, capture_output=True, text=True, check=True)
            results.append(json.loads(out.stdout.strip().splitlines()[-1]))
        else:
            import jax

            if n > len(jax.devices()):
                print(json.dumps({"devices": n,
                                  "skipped": "not enough devices"}))
                continue
            results.append(run_single(n, a.batch_per_dev, a.iters,
                                      a.depth, a.img, a.overlap))
    if results:
        base = results[0]["images_per_sec"] / results[0]["devices"]
        for rec in results:
            rec["scaling_efficiency"] = round(
                rec["images_per_sec"] / (rec["devices"] * base), 3)
            print(json.dumps(rec))


if __name__ == "__main__":
    main()
