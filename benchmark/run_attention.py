#!/usr/bin/env python
"""Flash-attention on-chip regression artifact (VERDICT r1 #7).

Asserts Pallas-vs-XLA numerics ON THE REAL DEVICE (round 1 only verified
interpret mode in CI; the real Mosaic lowering broke once, commit
f97f7dd, and nothing would have caught a regression) and reports the
kernel's speedup + achieved FLOP/s at serious sequence lengths.

Prints one JSON line per (seq, causal) config plus a final summary line:
  {"model": "flash_attention", "seq": 4096, "causal": true,
   "pallas_ms": ..., "xla_ms": ..., "speedup": ...,
   "max_err": ..., "grad_max_err": ..., "numerics_ok": true, ...}

Exit code 1 when any numerics check fails — the driver artifact records
pass/fail, so a silently-broken lowering cannot ship.

Usage: python benchmark/run_attention.py [--seq 4096] [--quick]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

import jax
import jax.numpy as jnp


def _attention_flops(batch, heads, seq_q, seq_k, dim, causal):
    """Model FLOPs (2*MACs) of QK^T + PV; causal halves the useful work."""
    f = 2 * 2 * batch * heads * seq_q * seq_k * dim
    return f / 2 if causal else f


def bench_one(batch, heads, seq, dim, causal, dtype, iters, atol):
    from harness import chip_specs
    from paddle_tpu.kernels.flash_attention import (
        flash_attention, flash_attention_reference)

    r = np.random.RandomState(0)
    shape = (batch, seq, heads, dim)
    q = jnp.asarray(r.randn(*shape), dtype)
    k = jnp.asarray(r.randn(*shape), dtype)
    v = jnp.asarray(r.randn(*shape), dtype)

    def loss_pallas(q, k, v):
        # min_seq_k=0: the artifact must exercise the KERNEL even at
        # sizes where the production policy would route to XLA
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       min_seq_k=0)
                       .astype(jnp.float32))

    def loss_ref(q, k, v):
        return jnp.sum(flash_attention_reference(q, k, v, causal=causal)
                       .astype(jnp.float32))

    fwd_p = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=causal,
                                                    min_seq_k=0))
    fwd_x = jax.jit(
        lambda q, k, v: flash_attention_reference(q, k, v, causal=causal))
    grad_p = jax.jit(jax.grad(loss_pallas, argnums=(0, 1, 2)))
    grad_x = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))

    # ---- numerics: Pallas vs XLA on the real device -----------------------
    o_p = np.asarray(fwd_p(q, k, v), np.float32)
    o_x = np.asarray(fwd_x(q, k, v), np.float32)
    max_err = float(np.max(np.abs(o_p - o_x)))
    g_p = grad_p(q, k, v)
    g_x = grad_x(q, k, v)
    grad_err = float(max(
        np.max(np.abs(np.asarray(a, np.float32) -
                      np.asarray(b, np.float32)))
        for a, b in zip(g_p, g_x)))
    ok = max_err <= atol and grad_err <= 20 * atol  # grads accumulate err

    # ---- timing -----------------------------------------------------------
    # methodology for the device tunnel: (a) EVERY iteration feeds a
    # DISTINCT input — the tunnel caches identical dispatches (same
    # executable + same buffers can return in ~30us with no device work);
    # (b) dispatches are chained async with ONE final block — a sync per
    # call pays the ~110ms tunnel round-trip instead of device time
    q_variants = [jax.device_put(jnp.asarray(r.randn(*shape), dtype))
                  for i in range(iters)]
    jax.block_until_ready(q_variants)

    def timeit(fn):
        jax.block_until_ready(fn(q))  # warmup (compile)
        outs = []
        t0 = time.perf_counter()
        for qv in q_variants:
            outs.append(fn(qv))
        jax.block_until_ready(outs)
        return (time.perf_counter() - t0) / iters * 1000

    pallas_ms = timeit(lambda qv: fwd_p(qv, k, v))
    xla_ms = timeit(lambda qv: fwd_x(qv, k, v))

    flops = _attention_flops(batch, heads, seq, seq, dim, causal)
    kind, peak, _ = chip_specs()
    tflops = flops / (pallas_ms / 1000) / 1e12
    out = {
        "model": "flash_attention", "batch": batch, "heads": heads,
        "seq": seq, "head_dim": dim, "causal": causal,
        "dtype": str(np.dtype(dtype) if dtype != jnp.bfloat16
                     else "bfloat16"),
        "pallas_ms": round(pallas_ms, 3),
        "xla_ms": round(xla_ms, 3),
        "speedup": round(xla_ms / pallas_ms, 2),
        "tflops": round(tflops, 2),
        "mfu": round(tflops * 1e12 / peak, 4) if peak else None,
        "device": kind,
        "max_err": round(max_err, 5),
        "grad_max_err": round(grad_err, 5),
        "numerics_ok": ok,
    }
    print(json.dumps(out))
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--head-dim", type=int, default=128)
    ap.add_argument("--seq", type=int, default=4096)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--quick", action="store_true",
                    help="single small config (CI smoke)")
    args = ap.parse_args()

    # bf16 tolerance: online-softmax vs materialized-softmax differ by
    # accumulation order; errors scale with sqrt(seq)
    atol = 0.02
    configs = ([(512, False)] if args.quick else
               [(args.seq, False), (args.seq, True), (8192, True)])
    results = []
    for seq, causal in configs:
        batch = max(1, args.batch * args.seq // seq)
        results.append(bench_one(batch, args.heads, seq, args.head_dim,
                                 causal, jnp.bfloat16, args.iters, atol))
    ok = all(r["numerics_ok"] for r in results)
    print(json.dumps({
        "model": "flash_attention_summary",
        "numerics_ok": ok,
        "configs": len(results),
        "min_speedup": min(r["speedup"] for r in results),
        "max_speedup": max(r["speedup"] for r in results),
    }))
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
