"""Benchmark entry — prints ONE JSON line:
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Headline: ResNet-50 ImageNet-shape training throughput (images/sec), 1
chip, measured in the CONVERGENCE-VALID config — bf16 compute under amp
(f32 master weights; batch-norm statistics always accumulate f32
in-register, see ops/norm.py).  Baseline: the reference's best published
ResNet-50 training number, 84.08 img/s (2x Xeon 6148, MKL-DNN, bs=256;
BASELINE.md — the reference has no GPU ResNet-50 number in-tree).

The JSON also carries the honesty block (VERDICT r1 #1/#2):
  * tflops / mfu — achieved model FLOP/s vs chip bf16 peak, with the
    per-step XLA cost analysis taken from the SINGLE-STEP optimized
    module (harness.step_cost_analysis), not the whole scan program;
  * hbm_gb_per_step — peak live HBM of the optimized step module
    (memory_analysis: args + outputs + temps − donated aliases), a
    number that must fit the chip; hbm_traffic_gb / hbm_util — the
    XLA-counted traffic and achieved bandwidth vs the chip's HBM peak.
    ResNet-50 bs256 is MEMORY-bound on TPU (arithmetic intensity ~37
    FLOP/byte vs the v5e ridge point of ~240), so hbm_util ~1.0 means
    the chip is saturated even though mfu sits near the ~0.16 roofline
    ceiling for this model+batch;
  * compile_seconds — XLA compile wall time of the measured executable
    (the persistent compilation cache is pre-warmed across rounds:
    BENCH_COMPILE_CACHE=0 opts out);
  * convergence — a timed CIFAR-10 ResNet-20 run in the SAME numeric
    config (amp bf16) trained to a fixed accuracy, so the measured mode
    is demonstrably one that learns (reference --job=time + book-test
    discipline).  BENCH_CONVERGENCE=0 skips it.

Knobs: BENCH_BATCH, BENCH_ITERS, BENCH_DTYPE, BENCH_LAYOUT,
BENCH_REMAT=1 (rematerialized residual blocks), BENCH_MEMOPT=1 (arm
the memory_optimize flag: feed-buffer donation + dead-var freeing in
the executor legs), BENCH_STEP_ANALYSIS=0 (skip the single-step
cost/memory analysis compile), BENCH_COMPILE_CACHE=0 (no persistent
compile cache pre-warm), BENCH_AMP=0 (pure-bf16 mode, reported as the
secondary number in benchmark/README.md), BENCH_CONVERGENCE=0,
BENCH_PREFETCH=N (input
pipeline microbench: serial vs prefetch-depth-N + lazy-fetch steps/s
with the host-blocked fraction of each loop; BENCH_PREFETCH_ITERS
steps), BENCH_COMM=1 (pserver comm microbench: per-var serial wire
path vs bucketed+concurrent CommPool over 2 in-process pservers x 64
small grads, with a byte-identical final-params check), BENCH_SERVING=1
(generation serving microbench: the scheduler/optimization ablation
ladder — static batch, continuous, +prefix caching, +speculative
decoding, both — under the shared-prefix mixed-length open-loop load
generator, benchmark/run_serving.py, with tokens/s, p50/p99, shed
rate, KV-pool utilization, prefix hit rate, draft accept rate, the
KV-quantization residency table, and a Prometheus dump at
BENCH_SERVING_PROM if set.  Knobs: BENCH_SERVING_PREFIX_POOL/
_PREFIX_LEN/_PREFIX_HIT shape the shared-prefix workload,
BENCH_SERVING_SPEC_K sets the draft length, BENCH_SERVING_SPEC=0 /
BENCH_SERVING_QUANT=0 / BENCH_SERVING_KERNELS=0 skip those sections),
BENCH_KERNELS=1 (serving-kernel microbench: each fused Pallas kernel —
paged-attention decode fp32+int8, MoE gate+dispatch, fused bucket
update — vs its XLA oracle path, best-of-BENCH_KERNELS_TRIALS
throughput plus the kernel-backed static bytes-moved rows; off-TPU the
Pallas legs run interpret mode, so the CPU numbers demonstrate the
path, the bytes delta is the TPU argument), BENCH_SERVING_RAMP=1
(open-loop load ramp against a LIVE autoscaling fleet — router +
autoscaler + `cli serve` replicas from a warm-start model dir: rate
ramps up then down, reporting per-phase tokens/s and p99, the scaling
timeline, zero-failed accounting, and new-replica warm-start stats;
knobs BENCH_SERVING_RAMP_PEAK/_PHASE_S/_MAX).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "benchmark"))

# compile-time budget: pre-warm JAX's persistent compilation cache
# across bench rounds — round N+1 deserializes every executable round N
# compiled (the book matrix alone was paying 15-85 s of XLA compile per
# model per round).  Must happen BEFORE paddle_tpu imports read the env.
# BENCH_COMPILE_CACHE=0 opts out; an explicit
# PADDLE_TPU_COMPILATION_CACHE_DIR always wins.
if (os.environ.get("BENCH_COMPILE_CACHE", "1").lower()
        not in ("0", "false", "no", "off")):
    os.environ.setdefault(
        "PADDLE_TPU_COMPILATION_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                     "xla_cache"))

import numpy as np

BASELINE_RESNET50_IMG_S = 84.08
BATCH = int(os.environ.get("BENCH_BATCH", "256"))
IMG = 224
DTYPE = os.environ.get("BENCH_DTYPE", "bfloat16")
ITERS = int(os.environ.get("BENCH_ITERS", "20"))
# amp (f32 master weights + bf16 compute) is the DEFAULT: the headline
# number must be a config somebody should actually train in (VERDICT r1
# weak #2); BENCH_AMP=0 measures the pure-bf16 path
AMP = os.environ.get("BENCH_AMP", "1").lower() in ("1", "true", "yes",
                                                   "on")
# NCHW measured faster end-to-end than NHWC on v5e with the affine BN
# (2535 vs 2359 img/s; XLA's layout assignment already places batch in
# the vector lanes where C < 128, see benchmark/README.md)
LAYOUT = os.environ.get("BENCH_LAYOUT", "NCHW").upper()
# BENCH_REMAT=1: rematerialize every residual block (jax.checkpoint) —
# the bytes-for-FLOPs trade for this memory-bound model (defaults to
# the framework `remat` flag, env PADDLE_TPU_REMAT)
REMAT = os.environ.get(
    "BENCH_REMAT",
    os.environ.get("PADDLE_TPU_REMAT", "0")).lower() in ("1", "true",
                                                         "yes", "on")
# BENCH_MEMOPT=1 arms the memory_optimize flag for the convergence/book
# legs (feed-buffer donation + dead-var freeing in the executors); the
# scan-timed headline always runs the donation plan via the harness
MEMOPT = os.environ.get(
    "BENCH_MEMOPT",
    os.environ.get("PADDLE_TPU_MEMORY_OPTIMIZE", "0")).lower() in (
        "1", "true", "yes", "on")
# ResNet-50 fwd at 224x224 is ~4.1 GMACs = ~8.2 GFLOPs (2*MACs — the MFU
# convention); train ~= 3x fwd.  Cross-check: XLA's own cost analysis
# counts 22.5 GFLOP/img for the whole train step
RESNET50_TRAIN_FLOPS_PER_IMG = 3 * 8.2e9


def build_resnet50_train(batch, dtype):
    import paddle_tpu as fluid
    from paddle_tpu.models.resnet import resnet_imagenet

    img_shape = ([IMG, IMG, 3] if LAYOUT == "NHWC" else [3, IMG, IMG])
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=img_shape, dtype=dtype)
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        predict = resnet_imagenet(img, class_dim=1000, depth=50,
                                  data_format=LAYOUT, remat=REMAT)
        cost = fluid.layers.cross_entropy(input=predict, label=label)
        avg_cost = fluid.layers.mean(cost)
        fluid.Momentum(learning_rate=0.1, momentum=0.9).minimize(avg_cost)
    return main, startup, avg_cost


def run_convergence(target_acc=0.85, max_seconds=None, batch=128):
    """CIFAR-10 ResNet-20 trained in the SAME numeric config as the
    headline (amp/pure-bf16 per BENCH_AMP) until test accuracy >=
    target_acc; returns a compact result dict with wall-clock.  Uses the
    real corpus when cached, the deterministic synthetic fallback
    offline (dataset/common.py policy) — the point is that the measured
    numeric mode LEARNS, not the dataset.

    BOTH executables (train step, test eval) are compiled BEFORE the
    clock starts — r2's driver run burned its whole 120 s budget on
    tunnel compiles and recorded steps=2, best_acc=0.0.  The training
    budget (BENCH_CONV_SECONDS, default 180) is pure post-compile
    wall-clock."""
    import paddle_tpu as fluid
    from paddle_tpu import dataset, reader
    from paddle_tpu.core.types import np_dtype
    from paddle_tpu.models.resnet import resnet_cifar10

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[3, 32, 32], dtype=DTYPE)
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        predict = resnet_cifar10(img, class_dim=10, depth=20)
        cost = fluid.layers.cross_entropy(input=predict, label=label)
        avg = fluid.layers.mean(cost)
        acc = fluid.layers.accuracy(input=predict, label=label)
        # clone BEFORE minimize: the test program must not carry the
        # optimizer ops (they would train on the test batch)
        test_prog = main.clone(for_test=True)
        fluid.Momentum(learning_rate=0.01, momentum=0.9).minimize(avg)

    exe = fluid.Executor(fluid.TPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)

    def batches(rd):
        for b in reader.batch(rd, batch, drop_last=True)():
            imgs = np.stack([np.asarray(s[0], np_dtype(DTYPE))
                             .reshape(3, 32, 32) for s in b])
            lbls = np.asarray([s[1] for s in b], np.int64)[:, None]
            yield {"img": imgs, "label": lbls}

    if max_seconds is None:
        max_seconds = float(os.environ.get("BENCH_CONV_SECONDS", "180"))
    train_rd = dataset.cifar.train10()
    test_feed = next(batches(dataset.cifar.test10()))
    # precompile both executables, then re-run startup so the timed run
    # starts from a FRESH init (the executor folds a per-run step counter
    # into the RNG key, so these are new random weights, not a bit-exact
    # restore — the benchmark only needs an untrained start)
    t_c = time.perf_counter()
    exe.run(main, feed=next(batches(train_rd)), fetch_list=[avg],
            scope=scope)
    exe.run(test_prog, feed=test_feed, fetch_list=[acc], scope=scope)
    exe.run(startup, scope=scope)
    compile_seconds = time.perf_counter() - t_c
    t0 = time.perf_counter()
    steps = 0
    best = 0.0
    reached = False
    while time.perf_counter() - t0 < max_seconds and not reached:
        for feed in batches(train_rd):
            exe.run(main, feed=feed, fetch_list=[avg], scope=scope)
            steps += 1
            if steps % 20 == 0:
                a, = exe.run(test_prog, feed=test_feed, fetch_list=[acc],
                             scope=scope)
                best = max(best, float(np.asarray(a).reshape(-1)[0]))
                if best >= target_acc:
                    reached = True
                    break
            if time.perf_counter() - t0 >= max_seconds:
                break
    return {"model": "resnet20_cifar10", "target_acc": target_acc,
            "best_acc": round(best, 4), "reached": reached,
            "steps": steps,
            "seconds": round(time.perf_counter() - t0, 1),
            "compile_seconds": round(compile_seconds, 1)}


def run_prefetch_bench(depth, steps=None):
    """Input-pipeline microbench (BENCH_PREFETCH=N): one pass of a
    host-bound training loop measured serial, then with the prefetch
    pipeline (reader/pipeline.py) + lazy fetches.  Reports steps/s and
    samples/s for both modes and each loop's host-blocked fraction —
    serial blocks in feed packing (timed inline), the prefetched loop
    only in queue waits (PrefetchIterator.wait_s) — so the JSON shows
    both the speedup AND where the remaining stall is."""
    import paddle_tpu as fluid
    from paddle_tpu import reader as rdr
    from paddle_tpu.data_feeder import DataFeeder
    from paddle_tpu.reader.pipeline import prefetch_feeder

    steps = steps or int(os.environ.get("BENCH_PREFETCH_ITERS", "40"))
    bs, dim = 128, 256
    place = fluid.TPUPlace()

    def build():
        main_p, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_p, startup):
            x = fluid.layers.data(name="x", shape=[dim], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="float32")
            h = fluid.layers.fc(input=x, size=512, act="relu")
            h = fluid.layers.fc(input=h, size=512, act="relu")
            p = fluid.layers.fc(input=h, size=1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(input=p, label=y))
            fluid.SGD(learning_rate=0.01).minimize(loss)
        return main_p, startup, loss, [x, y]

    def sample_reader():
        # chunked numpy generate + normalize: real host work standing in
        # for decode/augment, sized so the serial loop is host-BOUND —
        # the regime the pipeline exists for (on a compute-bound loop
        # BENCH_PREFETCH correctly reports speedup ~1.0).  Work is done
        # in batch-size chunks like a real decoder: large numpy ops
        # release the GIL, so the worker thread genuinely overlaps the
        # consumer's dispatch (per-sample tiny-op python loops would
        # serialize on the GIL and measure contention, not the pipeline)
        r = np.random.RandomState(0)
        for _ in range(steps):
            v = r.standard_normal((bs, 12, dim)).astype(np.float32)
            v = (v - v.mean(axis=1, keepdims=True)) \
                / (v.std(axis=1, keepdims=True) + 1e-6)
            x = v.mean(axis=1)
            y = r.rand(bs, 1).astype(np.float32)
            for i in range(bs):
                yield (x[i], y[i])

    batches = rdr.batch(sample_reader, bs, drop_last=True)

    def measure(prefetch_depth):
        main_p, startup, loss, feed_vars = build()
        exe = fluid.Executor(place)
        scope = fluid.Scope()
        exe.run(startup, scope=scope)
        feeder = DataFeeder(feed_vars, place)
        warm = feeder.feed(next(iter(batches())))
        exe.run(main_p, feed=warm, fetch_list=[loss], scope=scope)
        misses_warm = exe.cache_stats()["misses"]
        host_blocked = 0.0
        t0 = time.perf_counter()
        if prefetch_depth == 0:
            it = iter(batches())
            while True:
                f0 = time.perf_counter()  # reader + pack both block here
                b = next(it, None)
                if b is None:
                    break
                feed = feeder.feed(b)
                host_blocked += time.perf_counter() - f0
                exe.run(main_p, feed=feed, fetch_list=[loss], scope=scope)
        else:
            it = prefetch_feeder(batches, feeder, place,
                                 depth=prefetch_depth)()
            fence_s = 0.0
            last = None
            for i, feed in enumerate(it):
                last, = exe.run(main_p, feed=feed, fetch_list=[loss],
                                scope=scope, return_numpy=False)
                if (i + 1) % 8 == 0:  # periodic fence (sync_every_n=8)
                    f0 = time.perf_counter()
                    np.asarray(last)
                    fence_s += time.perf_counter() - f0
            f0 = time.perf_counter()
            np.asarray(last)  # final fence: count finished work only
            fence_s += time.perf_counter() - f0
            # blocked = input starvation (queue waits) + fetch fences —
            # the two stalls the prefetched loop can still suffer
            host_blocked = it.wait_s + fence_s
        wall = time.perf_counter() - t0
        recompiles = exe.cache_stats()["misses"] - misses_warm
        return {"steps_per_sec": round(steps / wall, 2),
                "samples_per_sec": round(steps * bs / wall, 1),
                "host_blocked_fraction": round(host_blocked / wall, 4),
                "recompiles_after_warmup": recompiles}

    serial = measure(0)
    prefetched = measure(depth)
    return {"depth": depth, "steps": steps, "batch": bs,
            "serial": serial, "prefetch": prefetched,
            "speedup": round(prefetched["steps_per_sec"]
                             / serial["steps_per_sec"], 3)}


def run_comm_bench(n_grads=64, dim=16, rounds=4, pservers=2, trials=3):
    """Pserver comm microbench (BENCH_COMM=1): one trainer, `pservers`
    in-process VariableServers, `n_grads` small grads per sync round.
    Baseline = the pre-bucketing wire path (one SEND frame per var,
    endpoints visited serially, per-var GETs); fused = parallel/comm's
    CommPool (arrival-order SEND_BATCH buckets, concurrent endpoints,
    one batched GET per endpoint).  Walls are best-of-`trials` over the
    post-warmup rounds — round 0 absorbs the optimize-program compile on
    both sides — and the dict also reports whether both paths left the
    pservers with byte-identical parameters (they must)."""
    import paddle_tpu as fluid
    from paddle_tpu.parallel import comm
    from paddle_tpu.parallel.pserver import VariableClient, VariableServer

    names = [f"bw{i}" for i in range(n_grads)]
    owner = {n: i % pservers for i, n in enumerate(names)}
    rng = np.random.RandomState(7)
    grads = [{n: rng.rand(dim).astype(np.float32) for n in names}
             for _ in range(rounds + 1)]  # +1: untimed warmup round

    def build_servers():
        servers = []
        for s in range(pservers):
            scope = fluid.Scope()
            prog = fluid.Program()
            with fluid.program_guard(prog, fluid.Program()):
                blk = prog.global_block()
                blk.create_var(name="lr", shape=[1], dtype="float32",
                               persistable=True)
                for n in names:
                    if owner[n] != s:
                        continue
                    blk.create_var(name=n, shape=[dim], dtype="float32",
                                   persistable=True)
                    blk.create_var(name=n + "@GRAD", shape=[dim],
                                   dtype="float32", persistable=True)
                    blk.append_op("sgd",
                                  {"Param": [n], "Grad": [n + "@GRAD"],
                                   "LearningRate": ["lr"]},
                                  {"ParamOut": [n]}, {})
            scope.set_var("lr", np.asarray([0.1], np.float32))
            for n in names:
                if owner[n] == s:
                    scope.set_var(n, np.ones(dim, np.float32))
            srv = VariableServer(prog, scope,
                                 fluid.Executor(fluid.CPUPlace()),
                                 fan_in=1)
            srv.serve(0)
            servers.append(srv)
        return servers, [f"127.0.0.1:{s.port}" for s in servers]

    def run_serial(eps):
        clients = {ep: VariableClient(ep, client_id="bench-serial")
                   for ep in eps}

        def one_round(r):
            for n in names:
                clients[eps[owner[n]]].send_var(n + "@GRAD", grads[r][n])
            for ep in eps:
                clients[ep].send_batch_barrier()
            for n in names:
                clients[eps[owner[n]]].get_var(n)

        one_round(0)  # warmup: optimize-program compile on the servers
        t0 = time.perf_counter()
        for r in range(1, rounds + 1):
            one_round(r)
        wall = time.perf_counter() - t0
        params = {n: np.asarray(clients[eps[owner[n]]].get_var(n))
                  for n in names}
        for c in clients.values():
            c.close()
        return wall, params

    def run_fused(eps):
        pool = comm.CommPool()

        def one_round(r):
            pool.send_round(
                [(eps[owner[n]], n + "@GRAD", grads[r][n])
                 for n in names],
                [(eps[owner[n]], n) for n in names])

        one_round(0)
        t0 = time.perf_counter()
        for r in range(1, rounds + 1):
            one_round(r)
        wall = time.perf_counter() - t0
        vals = pool.send_round([], [(eps[owner[n]], n) for n in names])
        params = {n: np.asarray(v) for n, v in zip(names, vals)}
        pool.close()
        return wall, params

    best = {"serial": float("inf"), "fused": float("inf")}
    params_serial = params_fused = None
    for _ in range(trials):
        for mode, runner in (("serial", run_serial), ("fused", run_fused)):
            servers, eps = build_servers()
            try:
                wall, params = runner(eps)
            finally:
                for s in servers:
                    s.stop()
            best[mode] = min(best[mode], wall)
            if mode == "serial":
                params_serial = params
            else:
                params_fused = params
    identical = all(params_serial[n].tobytes() == params_fused[n].tobytes()
                    for n in names)
    return {"n_grads": n_grads, "dim": dim, "rounds": rounds,
            "pservers": pservers,
            "serial_seconds": round(best["serial"], 4),
            "fused_seconds": round(best["fused"], 4),
            "speedup": round(best["serial"] / best["fused"], 3),
            "params_identical": identical}


# serving-kernel microbench decoders are cached at module level: both
# trials AND any later bench section reuse the same compiled step —
# no per-row rebuilds (the PR 8 compile-budget discipline)
_KERNEL_DECODERS = {}


def run_kernels_bench(trials=None, ticks=None):
    """Serving-kernel microbench (BENCH_KERNELS=1): each fused Pallas
    kernel against the XLA oracle path it replaces — paged-attention
    decode (fp32 + quantized int8 KV), fused MoE gate+dispatch, fused
    per-bucket optimizer update.  Rows are best-of-`trials` measured
    throughput plus the kernel-backed static bytes-moved from
    analysis/cost_model.py (what each path charges the roofline).

    Off-TPU the Pallas rows run in interpret mode, so measured CPU
    throughput favors XLA by construction — those rows demonstrate the
    PATH and its numerics; the bytes-moved delta is the TPU argument
    (docs/performance.md "Serving kernels")."""
    import jax
    import jax.numpy as jnp

    from run_serving import VOCAB, _build_decoder, _build_kernel_decoder
    from paddle_tpu.analysis.cost_model import serving_kernel_cost
    from paddle_tpu.kernels import (build_fused_bucket_update,
                                    build_moe_gate_dispatch)
    from paddle_tpu.parallel.moe import moe_gate

    trials = trials or int(os.environ.get("BENCH_KERNELS_TRIALS", "2"))
    ticks = ticks or int(os.environ.get("BENCH_KERNELS_TICKS", "8"))
    d_model, n_heads, n_layers, bs, nb, slots = 128, 4, 2, 8, 12, 4
    rng = np.random.RandomState(0)

    def best_rate(fn, units):
        b = 0.0
        for _ in range(trials):
            t0 = time.perf_counter()
            fn()
            b = max(b, units / (time.perf_counter() - t0))
        return round(b, 1)

    # -- paged-attention decode: full decode tick, gather vs fused ----
    att = {}
    for kv_dtype in ("fp32", "int8"):
        spec = dict(d_model=d_model, n_layers=n_layers,
                    n_heads=n_heads, vocab_size=VOCAB, block_size=bs,
                    max_blocks_per_seq=nb, kv_dtype=kv_dtype)
        row = {}
        for label, build in (("xla", _build_decoder),
                             ("pallas", _build_kernel_decoder)):
            key = (label, kv_dtype)
            if key not in _KERNEL_DECODERS:
                _KERNEL_DECODERS[key] = build(
                    d_model, n_layers, n_heads, bs, nb,
                    kv_dtype=kv_dtype)
            dec, states = _KERNEL_DECODERS[key]
            sj = {k: jnp.asarray(v) for k, v in states.items()}
            tables = jnp.zeros((slots, nb), jnp.int32)
            positions = jnp.full((slots,), bs * nb // 2, jnp.int32)
            zi = jnp.zeros((slots,), jnp.int32)
            temps = jnp.zeros((slots,), jnp.float32)
            act = jnp.ones((slots,), bool)

            def run(dec=dec, sj=sj):
                # pools re-initialized per trial: step() donates them
                pk, pv = dec.init_pool(nb)
                for _ in range(ticks):
                    toks, pk, pv = dec.step(sj, pk, pv, tables,
                                            positions, zi, zi, temps,
                                            act)
                jax.block_until_ready(toks)

            run()  # warmup: compile outside the timed trials
            est = serving_kernel_cost(
                "paged_decode_step", spec, slots=slots,
                context=bs * nb // 2, kv_dtype=kv_dtype, backend=label)
            row[label] = {
                "tokens_per_sec": best_rate(run, slots * ticks),
                "est_bytes_per_tick": est["bytes"],
                "kernel": dec.kernels.get("paged_attention_decode")}
        row["bytes_ratio_pallas_vs_xla"] = round(
            row["pallas"]["est_bytes_per_tick"]
            / row["xla"]["est_bytes_per_tick"], 3)
        att[kv_dtype] = row
    out = {"paged_attention_decode": att}

    # -- fused MoE gate+dispatch vs the oracle op chain ---------------
    T, D, E, C, top_k = 64, 64, 4, 24, 2
    x = jnp.asarray(rng.standard_normal((T, D)).astype(np.float32))
    gw = jnp.asarray(rng.standard_normal((D, E)).astype(np.float32))

    @jax.jit
    def moe_oracle(x, gw):
        dispatch, combine, aux = moe_gate(x, gw, E, C, top_k=top_k)
        expert_in = jnp.einsum("td,tec->ecd", x.astype(jnp.float32),
                               dispatch).astype(x.dtype)
        return expert_in, combine, aux

    fused = jax.jit(build_moe_gate_dispatch(
        tokens=T, d_model=D, num_experts=E, capacity=C, top_k=top_k,
        interpret=True))
    moe_iters = 4 * ticks
    est = serving_kernel_cost(
        "moe_gate_dispatch", {"d_model": D, "n_heads": 1,
                              "n_layers": 1, "vocab_size": VOCAB},
        tokens=T, num_experts=E, capacity=C, top_k=top_k)

    def run_moe(fn):
        def go():
            for _ in range(moe_iters):
                r = fn(x, gw)
            jax.block_until_ready(r)
        go()  # warmup
        return best_rate(go, T * moe_iters)

    out["moe_gate_dispatch"] = {
        "xla": {"tokens_per_sec": run_moe(moe_oracle)},
        "pallas": {"tokens_per_sec": run_moe(fused)},
        "est_bytes": est["bytes"],
        "routing_bytes_avoided": est["routing_bytes_avoided"],
        "tokens": T, "num_experts": E, "capacity": C, "top_k": top_k}

    # -- fused bucket update vs the per-parameter chain ---------------
    n_params, per = 16, 4096
    numel = n_params * per
    parts = [jnp.asarray(rng.standard_normal(per).astype(np.float32))
             for _ in range(n_params)]
    gparts = [jnp.asarray(rng.standard_normal(per).astype(np.float32))
              for _ in range(n_params)]
    lr = jnp.float32(0.01)

    @jax.jit
    def chain(ps, gs, lr):
        return [p - lr * g for p, g in zip(ps, gs)]

    upd = build_fused_bucket_update(numel=numel, interpret=True)

    @jax.jit
    def fused_upd(ps, gs, lr):
        return upd(jnp.concatenate(ps), jnp.concatenate(gs), lr)

    upd_iters = 8 * ticks

    def run_upd(fn):
        def go():
            for _ in range(upd_iters):
                r = fn(parts, gparts, lr)
            jax.block_until_ready(r)
        go()  # warmup
        return best_rate(go, numel * upd_iters)

    est = serving_kernel_cost("fused_bucket_update", {}, numel=numel,
                              n_params=n_params)
    out["fused_bucket_update"] = {
        "xla_chain": {"elems_per_sec": run_upd(chain)},
        "pallas": {"elems_per_sec": run_upd(fused_upd)},
        "est_bytes": est["bytes"],
        "launches_replaced": est["launches_replaced"],
        "numel": numel, "n_params": n_params}
    return out


def main():
    import paddle_tpu as fluid
    from harness import gated_time_program

    if AMP:
        fluid.amp.enable_bf16()
    if MEMOPT:
        from paddle_tpu.core.flags import set_flags
        set_flags({"memory_optimize": True})
    main_p, startup, avg = build_resnet50_train(BATCH, DTYPE)

    r = np.random.RandomState(0)
    from paddle_tpu.core.types import np_dtype

    img_shape = ((BATCH, IMG, IMG, 3) if LAYOUT == "NHWC"
                 else (BATCH, 3, IMG, IMG))
    feeds = {
        "img": r.rand(*img_shape).astype(np_dtype(DTYPE)),
        "label": r.randint(0, 1000, (BATCH, 1)).astype(np.int32),
    }
    # harness.gated_time_program: K real steps inside one executable
    # (replay-immune scan instrument) + the roofline plausibility gate —
    # an implausible number is published as valid:false and exits 1,
    # never as a silent headline
    step_analysis = os.environ.get(
        "BENCH_STEP_ANALYSIS", "1").lower() not in ("0", "false", "no",
                                                    "off")
    ms, cost, fields = gated_time_program(
        main_p, startup, feeds, avg.name, ITERS,
        model_flops_per_step=RESNET50_TRAIN_FLOPS_PER_IMG * BATCH,
        step_analysis=step_analysis)
    img_per_sec = BATCH / ms * 1000
    out = {
        "metric": "resnet50_train_images_per_sec",
        "value": round(img_per_sec, 2),
        "unit": "images/s",
        "vs_baseline": round(img_per_sec / BASELINE_RESNET50_IMG_S, 3),
        "batch": BATCH,
        "amp": AMP,
        "layout": LAYOUT,
        "remat": REMAT,
        "memory_optimize": MEMOPT,
        "ms_per_step": round(ms, 2),
    }
    out.update(fields)
    prefetch_depth = int(os.environ.get("BENCH_PREFETCH", "0"))
    if prefetch_depth > 0:
        out["prefetch_pipeline"] = run_prefetch_bench(prefetch_depth)
    if os.environ.get("BENCH_COMM", "0").lower() in ("1", "true", "yes",
                                                     "on"):
        out["comm"] = run_comm_bench()
    if os.environ.get("BENCH_SERVING", "0").lower() in ("1", "true",
                                                        "yes", "on"):
        from run_serving import run_serving_bench
        env = os.environ.get
        out["serving"] = run_serving_bench(
            prom_out=env("BENCH_SERVING_PROM", ""),
            prefix_pool=int(env("BENCH_SERVING_PREFIX_POOL", "3")),
            prefix_len=int(env("BENCH_SERVING_PREFIX_LEN", "24")),
            prefix_hit=float(env("BENCH_SERVING_PREFIX_HIT", "0.75")),
            spec_k=int(env("BENCH_SERVING_SPEC_K", "4")),
            with_spec=env("BENCH_SERVING_SPEC", "1").lower() not in (
                "0", "false", "no", "off"),
            with_quant=env("BENCH_SERVING_QUANT", "1").lower() not in (
                "0", "false", "no", "off"),
            with_kernels=env("BENCH_SERVING_KERNELS",
                             "1").lower() not in ("0", "false", "no",
                                                  "off"))
    if os.environ.get("BENCH_KERNELS", "0").lower() in ("1", "true",
                                                        "yes", "on"):
        out["kernels"] = run_kernels_bench()
    if os.environ.get("BENCH_SERVING_RAMP", "0").lower() in (
            "1", "true", "yes", "on"):
        from run_serving import run_fleet_ramp_bench
        env = os.environ.get
        out["serving_ramp"] = run_fleet_ramp_bench(
            peak_rps=float(env("BENCH_SERVING_RAMP_PEAK", "24")),
            phase_s=float(env("BENCH_SERVING_RAMP_PHASE_S", "6")),
            max_replicas=int(env("BENCH_SERVING_RAMP_MAX", "3")))
    if os.environ.get("BENCH_CONVERGENCE", "1").lower() not in (
            "0", "false", "no", "off"):
        conv = run_convergence()
        out["convergence"] = conv
        if not conv["reached"]:
            out["valid"] = False
            out.setdefault("invalid_reason",
                           "convergence target not reached in budget")
    # book acceptance matrix (benchmark/run_book.py): the 8 reference
    # book models trained to their thresholds in this same numeric mode
    # (~2 min incl. compiles; measured reach times are all <= 21 s, the
    # 45 s/model cap is 2x margin).  Reported, not validity-gating —
    # the headline's validity stays with its own roofline + convergence
    # gates.  BENCH_BOOK=0 skips; BOOK_MATRIX_r04.json is the committed
    # reference artifact.
    if (os.environ.get("BENCH_BOOK", "1").lower() in ("1", "true", "yes",
                                                      "on")
            and out.get("valid", True)):
        # skipped when the headline already failed its gates: the matrix
        # would delay the nonzero exit by ~2 min without changing it
        os.environ.setdefault("BOOK_SECONDS", "45")
        amp_was = fluid.amp.is_bf16_enabled()
        try:
            from run_book import run_matrix
            out["book_matrix"] = run_matrix()
        except Exception as e:  # a matrix crash must not destroy the
            out["book_matrix"] = {  # headline artifact — record it
                "error": f"{type(e).__name__}: {e}"}
        finally:  # run_matrix flips the process-global amp flag
            (fluid.amp.enable_bf16 if amp_was
             else fluid.amp.disable_bf16)()
    print(json.dumps(out))
    if not out["valid"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
