"""Benchmark entry — prints ONE JSON line:
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Benchmark: ResNet-50 ImageNet-shape training throughput (images/sec) on the
available chip — the BASELINE.json headline metric.  Baseline value: the
reference's best published ResNet-50 training number, 84.08 img/s
(2x Xeon 6148, MKL-DNN, bs=256; BASELINE.md — the reference has no
GPU ResNet-50 number in-tree).
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "benchmark"))

import numpy as np

BASELINE_RESNET50_IMG_S = 84.08
BATCH = int(os.environ.get("BENCH_BATCH", "256"))
IMG = 224
DTYPE = os.environ.get("BENCH_DTYPE", "bfloat16")
ITERS = int(os.environ.get("BENCH_ITERS", "20"))
# mixed precision (paddle_tpu.amp): bf16 compute with f32 master weights.
# The bench model is already end-to-end bf16 (params follow the input
# dtype), so amp only adds f32-stat batch-norms here — off by default;
# BENCH_AMP=1 to measure the amp path.
AMP = os.environ.get("BENCH_AMP", "0").lower() in ("1", "true", "yes",
                                                   "on")
# BENCH_LAYOUT=NHWC runs channels-last; measured equal-or-slightly-slower
# than NCHW end-to-end on v5e (XLA's layout assignment already converts
# internally), so the reference-parity NCHW stays the default
LAYOUT = os.environ.get("BENCH_LAYOUT", "NCHW").upper()


def build_resnet50_train(batch, dtype):
    import paddle_tpu as fluid
    from paddle_tpu.models.resnet import resnet_imagenet

    img_shape = ([IMG, IMG, 3] if LAYOUT == "NHWC" else [3, IMG, IMG])
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=img_shape, dtype=dtype)
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        predict = resnet_imagenet(img, class_dim=1000, depth=50,
                                  data_format=LAYOUT)
        cost = fluid.layers.cross_entropy(input=predict, label=label)
        avg_cost = fluid.layers.mean(cost)
        fluid.Momentum(learning_rate=0.1, momentum=0.9).minimize(avg_cost)
    return main, startup, avg_cost


def main():
    import paddle_tpu as fluid
    from harness import time_program

    if AMP:
        fluid.amp.enable_bf16()
    main_p, startup, avg = build_resnet50_train(BATCH, DTYPE)

    r = np.random.RandomState(0)
    from paddle_tpu.core.types import np_dtype

    img_shape = ((BATCH, IMG, IMG, 3) if LAYOUT == "NHWC"
                 else (BATCH, 3, IMG, IMG))
    feeds = {
        "img": r.rand(*img_shape).astype(np_dtype(DTYPE)),
        "label": r.randint(0, 1000, (BATCH, 1)).astype(np.int32),
    }
    ms = time_program(main_p, startup, feeds, avg.name, ITERS)
    img_per_sec = BATCH / ms * 1000
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec",
        "value": round(img_per_sec, 2),
        "unit": "images/s",
        "vs_baseline": round(img_per_sec / BASELINE_RESNET50_IMG_S, 3),
    }))


if __name__ == "__main__":
    main()
