"""Benchmark entry — prints ONE JSON line:
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Current benchmark: flagship training-step throughput on the available chip.
Baseline: reference ResNet-50 CPU training 84.08 img/s (2x Xeon 6148,
MKL-DNN, bs 256 — BASELINE.md); upgraded to the ResNet-50 model as the
model zoo lands.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

BASELINE_RESNET50_IMG_S = 84.08


def main():
    import jax

    from __graft_entry__ import _build_mlp, _init_states
    from paddle_tpu.core.executor import program_to_fn

    batch = 512
    main_p, startup, avg = _build_mlp(hidden=1024, classes=1000,
                                      features=784)
    fn = program_to_fn(main_p, ["x", "y"], [avg.name])
    states = _init_states(startup, fn.state_in_names)
    states = {k: jax.device_put(v) for k, v in states.items()}
    key = jax.random.key(0)

    @jax.jit
    def step(feeds, states):
        fetches, new_states = fn(feeds, states, key)
        return fetches[avg.name], new_states

    feeds = {
        "x": jax.device_put(
            np.random.rand(batch, 784).astype(np.float32)),
        "y": jax.device_put(
            np.random.randint(0, 1000, (batch, 1)).astype(np.int32)),
    }
    # warmup/compile
    loss, states = step(feeds, states)
    loss.block_until_ready()
    iters = 50
    t0 = time.perf_counter()
    for _ in range(iters):
        loss, states = step(feeds, states)
    loss.block_until_ready()
    dt = time.perf_counter() - t0
    samples_per_sec = iters * batch / dt
    print(json.dumps({
        "metric": "mlp_train_samples_per_sec",
        "value": round(samples_per_sec, 2),
        "unit": "samples/s",
        "vs_baseline": round(samples_per_sec / BASELINE_RESNET50_IMG_S, 3),
    }))


if __name__ == "__main__":
    main()
