"""Benchmark entry — prints ONE JSON line:
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Benchmark: ResNet-50 ImageNet-shape training throughput (images/sec) on the
available chip — the BASELINE.json headline metric.  Baseline value: the
reference's best published ResNet-50 training number, 84.08 img/s
(2x Xeon 6148, MKL-DNN, bs=256; BASELINE.md — the reference has no
GPU ResNet-50 number in-tree).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

BASELINE_RESNET50_IMG_S = 84.08
BATCH = int(os.environ.get("BENCH_BATCH", "64"))
IMG = 224
DTYPE = os.environ.get("BENCH_DTYPE", "bfloat16")
ITERS = int(os.environ.get("BENCH_ITERS", "20"))
# mixed precision (paddle_tpu.amp): bf16 compute with f32 master weights.
# The bench model is already end-to-end bf16 (params follow the input
# dtype), so amp only adds f32-stat batch-norms here — off by default;
# BENCH_AMP=1 to measure the amp path.
AMP = os.environ.get("BENCH_AMP", "0").lower() in ("1", "true", "yes",
                                                   "on")


def build_resnet50_train(batch, dtype):
    import paddle_tpu as fluid
    from paddle_tpu.models.resnet import resnet_imagenet

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[3, IMG, IMG],
                                dtype=dtype)
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        predict = resnet_imagenet(img, class_dim=1000, depth=50)
        cost = fluid.layers.cross_entropy(input=predict, label=label)
        avg_cost = fluid.layers.mean(cost)
        fluid.Momentum(learning_rate=0.1, momentum=0.9).minimize(avg_cost)
    return main, startup, avg_cost


def main():
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.core.executor import program_to_fn

    if AMP:
        fluid.amp.enable_bf16()
    main_p, startup, avg = build_resnet50_train(BATCH, DTYPE)
    fn = program_to_fn(main_p, ["img", "label"], [avg.name])

    scope = fluid.Scope()
    cpu_exe = fluid.Executor(fluid.CPUPlace())
    cpu_exe.run(startup, scope=scope)
    states = {n: jax.device_put(np.asarray(scope.find_var(n)))
              for n in fn.state_in_names}
    key = jax.random.key(0)

    @jax.jit
    def step(feeds, states):
        fetches, new_states = fn(feeds, states, key)
        return fetches[avg.name], new_states

    r = np.random.RandomState(0)
    from paddle_tpu.core.types import np_dtype

    feeds = {
        "img": jax.device_put(
            r.rand(BATCH, 3, IMG, IMG).astype(np_dtype(DTYPE))),
        "label": jax.device_put(
            r.randint(0, 1000, (BATCH, 1)).astype(np.int32)),
    }
    loss, states = step(feeds, states)          # compile + warmup
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        loss, states = step(feeds, states)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    img_per_sec = ITERS * BATCH / dt
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec",
        "value": round(img_per_sec, 2),
        "unit": "images/s",
        "vs_baseline": round(img_per_sec / BASELINE_RESNET50_IMG_S, 3),
    }))


if __name__ == "__main__":
    main()
