#!/usr/bin/env python
"""Mini-fleet telemetry smoke: 1 trainer x 1 pserver + 1 serving
replica under a TelemetryCollector (tools/ci_check.sh step 11).

The driver hosts the TTL-lease registry and the collector, then spawns
three REAL processes with PADDLE_TPU_METRICS=on and
PADDLE_TPU_TELEMETRY_REGISTRY pointed at the registry:

  * a pserver (`--role pserver`): VariableServer + SGD optimize
    program; its serve() auto-announces the /metrics endpoint;
  * a trainer (`--role trainer`): VariableClient rounds
    (send grad -> barrier -> get) under trainer.step spans, moving the
    real trainer series;
  * a generation replica: `python -m paddle_tpu.cli serve` over a tiny
    saved model dir; the driver streams a few generate requests at it.

While traffic flows the collector scrapes on a period; the driver then
asserts the FEDERATED Prometheus dump carries member-labeled series
from all three kinds, renders the `cli top` fleet table, SIGKILLs the
pserver and asserts its flight-recorder dump (PADDLE_TPU_FLIGHT_DIR)
survived on disk with the pserver's final spans.  The federation dump
is written to --out for the `cli slo --check --prom` gate that follows
in ci_check.

Usage:  python tools/mini_fleet.py [--out /tmp/fleet.prom]
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # `python tools/mini_fleet.py` from anywhere
    sys.path.insert(0, REPO)


# ---------------------------------------------------------------------------
# member roles (run in child processes)
# ---------------------------------------------------------------------------


def role_pserver(args):
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.parallel.pserver import VariableServer

    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        blk = prog.global_block()
        p = blk.create_var(name="w", shape=[8], dtype="float32",
                           persistable=True)
        g = blk.create_var(name="w@GRAD", shape=[8], dtype="float32",
                           persistable=True)
        lr = blk.create_var(name="pserver_lr", shape=[1],
                            dtype="float32", persistable=True)
        blk.append_op("sgd",
                      {"Param": [p.name], "Grad": [g.name],
                       "LearningRate": [lr.name]},
                      {"ParamOut": [p.name]}, {})
    scope = fluid.Scope()
    scope.set_var("w", np.ones(8, np.float32))
    scope.set_var("pserver_lr", np.array([0.1], np.float32))
    exe = fluid.Executor(fluid.CPUPlace())
    server = VariableServer(prog, scope, exe, fan_in=1)
    port = server.serve(0)  # announces via PADDLE_TPU_TELEMETRY_REGISTRY
    print(f"PSERVER_PORT {port}", flush=True)
    time.sleep(args.run_s)  # serve until the driver kills us
    server.stop()
    return 0


def role_comm_trainer(args):
    """Trainer driving FUSED rounds through a CommPool against SEVERAL
    pservers (--endpoint ep1,ep2) — the per-endpoint round histogram
    the straggler detector z-scores only exists on this path."""
    import numpy as np

    import paddle_tpu as fluid  # noqa: F401 (registers the series)
    from paddle_tpu.observability import tracing
    from paddle_tpu.observability.collector import maybe_announce
    from paddle_tpu.parallel.comm import CommPool

    maybe_announce("trainer")
    eps = [e for e in args.endpoint.split(",") if e]
    pool = CommPool()
    for i in range(args.rounds):
        with tracing.span("trainer.step", batch_id=i):
            pool.send_round(
                [(ep, "w@GRAD", np.full(8, 0.1, np.float32))
                 for ep in eps],
                [(ep, "w") for ep in eps])
        print(f"TRAINER_ROUND {i}", flush=True)
        time.sleep(0.1)
    print("TRAINER_DONE", flush=True)
    time.sleep(args.linger_s)  # stay scrape-able until the driver kills
    pool.close()
    return 0


def role_trainer(args):
    import numpy as np

    import paddle_tpu as fluid  # noqa: F401 (registers the series)
    from paddle_tpu.observability import metrics, tracing
    from paddle_tpu.observability.collector import maybe_announce
    from paddle_tpu.parallel.pserver import VariableClient

    maybe_announce("trainer")
    # get-or-create the REAL trainer series (paddle_tpu.trainer may
    # not be imported yet; same names, so a real Trainer would share)
    steps = metrics.counter("paddle_tpu_trainer_steps_total",
                            "training steps completed")
    step_s = metrics.histogram(
        "paddle_tpu_trainer_step_seconds",
        "train-loop iteration wall latency (feed ready -> dispatch "
        "done)")
    client = VariableClient(args.endpoint, client_id="mini-fleet")
    for i in range(args.rounds):
        t0 = time.perf_counter()
        with tracing.span("trainer.step", batch_id=i):
            client.send_var("w@GRAD",
                            np.full(8, 0.1, np.float32))
            client.send_batch_barrier()
            client.get_var("w")
        steps.inc()
        step_s.observe(time.perf_counter() - t0)
        print(f"TRAINER_ROUND {i}", flush=True)
        time.sleep(0.15)
    print("TRAINER_DONE", flush=True)
    # stay alive (and scrape-able, lease held) until the driver kills
    # us — exiting releases the lease and delists the member, which
    # would race the driver's final assertions
    time.sleep(args.linger_s)
    client.close()
    return 0


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def _spawn(cmd, env, logf):
    import queue
    import threading

    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=logf, text=True, cwd=REPO)
    # a reader thread drains stdout into a queue so _wait_line can
    # time out on a child that wedges WITHOUT printing (select() on
    # the raw fd misses lines already pulled into the TextIOWrapper
    # buffer, and a bare readline() blocks past any deadline)
    proc._lines = queue.Queue()

    def _drain():
        for line in proc.stdout:
            proc._lines.put(line)
        proc._lines.put(None)  # EOF marker

    threading.Thread(target=_drain, daemon=True).start()
    return proc


def _wait_line(proc, prefix, timeout_s, what):
    import queue

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            line = proc._lines.get(timeout=1.0)
        except queue.Empty:
            continue
        if line is None:
            raise SystemExit(f"{what}: exited before '{prefix}' "
                             f"(rc {proc.poll()})")
        print(f"  [{what}] {line.rstrip()}")
        if line.startswith(prefix):
            return line.split()
    raise SystemExit(f"{what}: no '{prefix}' within {timeout_s}s")


def _build_model_dir(workdir):
    import numpy as np

    import paddle_tpu as fluid
    import paddle_tpu.core.framework as fw
    from paddle_tpu.models.transformer import build_lm_paged_decoder
    from paddle_tpu.serving import save_generation_model

    fw.reset_unique_names()
    startup, dec = build_lm_paged_decoder(23, 4, 4, d_model=16,
                                          n_heads=2, n_layers=1)
    scope = fluid.Scope()
    fluid.Executor(fluid.CPUPlace()).run(startup, scope=scope)
    states = {n: np.asarray(scope.find_var(n))
              for n in dec.state_names}
    model_dir = os.path.join(workdir, "model")
    save_generation_model(model_dir, states, {
        "vocab_size": 23, "d_model": 16, "n_heads": 2, "n_layers": 1,
        "block_size": 4, "max_blocks_per_seq": 4, "slots": 2,
        "kv_blocks": 16})
    return model_dir


def driver(args):
    from paddle_tpu.cli import format_fleet_table
    from paddle_tpu.cloud.registry import Registry
    from paddle_tpu.observability.collector import TelemetryCollector
    from paddle_tpu.serving.replica import replica_call, replica_stream

    workdir = tempfile.mkdtemp(prefix="paddle_mini_fleet_")
    flight_dir = os.path.join(workdir, "flight")
    trace_dir = os.path.join(workdir, "traces")
    print(f"mini-fleet workdir: {workdir}")

    registry = Registry()
    reg_addr = f"127.0.0.1:{registry.serve(0)}"
    coll = TelemetryCollector(registry_addr=reg_addr, period_s=0.3,
                              scrape_timeout_s=1.0)

    env = dict(os.environ,
               JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"),
               PADDLE_TPU_METRICS="on",
               PADDLE_TPU_TELEMETRY_REGISTRY=reg_addr,
               PADDLE_TPU_FLIGHT_DIR=flight_dir,
               PADDLE_TPU_TRACE_DIR=trace_dir)
    logf = open(os.path.join(workdir, "children.log"), "w")
    me = [sys.executable, os.path.abspath(__file__)]
    procs = []
    try:
        pserver = _spawn(me + ["--role", "pserver",
                               "--run_s", "600"], env, logf)
        procs.append(pserver)
        port = int(_wait_line(pserver, "PSERVER_PORT", 180,
                              "pserver")[1])
        # scrape THROUGH the traffic window: windowed rates/quantiles
        # need samples on both sides of the counters moving
        coll.start()

        trainer = _spawn(me + ["--role", "trainer", "--endpoint",
                               f"127.0.0.1:{port}",
                               "--rounds", str(args.rounds)],
                         env, logf)
        procs.append(trainer)

        model_dir = _build_model_dir(workdir)
        replica = _spawn([sys.executable, "-m", "paddle_tpu.cli",
                          "serve", model_dir, "--use_tpu", "0"],
                         env, logf)
        procs.append(replica)
        line = _wait_line(replica, "serving ", 300, "replica")
        replica_addr = line[3]

        _wait_line(trainer, "TRAINER_DONE", 180, "trainer")

        # a few generate streams so the serving series move, spaced so
        # scrapes land between them
        for i in range(4):
            toks = list(replica_stream(
                replica_addr,
                {"op": "generate", "prompt": [1, 2, 3], "max_new": 5},
                timeout_s=300))
            assert toks, "replica generated nothing"
            time.sleep(0.4)
        print(f"  [driver] replica streamed 4 requests "
              f"({len(toks)} tokens last)")
        assert replica_call(replica_addr,
                            {"op": "flight"})["ok"], "flight op"

        time.sleep(0.5)
        coll.scrape_once()  # one deterministic final sweep

        members = coll.members()
        kinds = {m["kind"] for m in members}
        assert {"trainer", "pserver", "generation"} <= kinds, members
        text = coll.federation_text()
        for kind, series in (
                ("pserver", "paddle_tpu_pserver_requests_total"),
                ("trainer", "paddle_tpu_trainer_steps_total"),
                ("generation",
                 "paddle_tpu_serving_generation_requests_total")):
            member = next(m["member"] for m in members
                          if m["kind"] == kind)
            assert f'kind="{kind}"' in text, f"no {kind} series"
            assert f'member="{member}"' in text, f"no {member} label"
            assert series in text, f"missing {series}"
        print()
        print(format_fleet_table(coll, window_s=60))
        print()

        out = coll.write_federation(args.out)
        print(f"federated Prometheus dump -> {out} "
              f"({len(text.splitlines())} lines, "
              f"{len(members)} members)")

        # flight-recorder recovery from a SIGKILLed pserver: the
        # periodic flush (0.5 s) must have left its final seconds on
        # disk — no handler runs for SIGKILL
        time.sleep(1.5)
        flight_path = os.path.join(flight_dir,
                                   f"flight_{pserver.pid}.json")
        os.kill(pserver.pid, signal.SIGKILL)
        pserver.wait(timeout=30)
        assert os.path.exists(flight_path), \
            f"no flight dump at {flight_path}"
        import json
        with open(flight_path) as f:
            dump = json.load(f)
        span_names = {s["name"] for s in dump["spans"]}
        assert any(n.startswith("pserver.") for n in span_names), \
            span_names
        print(f"flight dump recovered from SIGKILLed pserver: "
              f"{len(dump['spans'])} spans, "
              f"{len(dump['events'])} events")
        print("mini-fleet: all green")
        return 0
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        coll.close()
        registry.close()
        logf.close()


# ---------------------------------------------------------------------------
# autoscale drill (tools/ci_check.sh step 12)
# ---------------------------------------------------------------------------


def drill_autoscale(args):
    """Chaos acceptance for the autoscaling fleet (docs/serving.md
    "Autoscaling"): ride `run_fleet_ramp_bench` — the BENCH_SERVING_RAMP
    fleet driver owns the model/router/autoscaler/teardown — with its
    chaos hooks: ramp open-loop load until a second `cli serve` replica
    spawns, SIGKILL one AT THE PEAK (phase_hook), keep ramping down
    until the fleet scales back in — asserting ZERO failed requests end
    to end (the router's resume contract holds through spawn, drain,
    and the SIGKILL), that the fleet actually grew and shrank, and that
    the warm-started scale-out replicas deserialized their executables.
    The federated Prometheus dump (driver announces the
    router/autoscaler series; post_hook scrapes before teardown
    reclaims them) is written to --out for the `cli slo --check --prom`
    fleet-size / crash-loop / zero-failed gate that follows in
    ci_check."""
    import signal as _signal

    sys.path.insert(0, os.path.join(REPO, "benchmark"))
    from run_serving import run_fleet_ramp_bench

    from paddle_tpu.cloud.registry import Registry
    from paddle_tpu.observability.collector import (TelemetryCollector,
                                                    maybe_announce)

    telem_registry = Registry()
    telem_addr = f"127.0.0.1:{telem_registry.serve(0)}"
    # federate the driver's own series (router + autoscaler gauges/
    # counters) so the SLO gate sees fleet.replicas / crashloops /
    # router outcome counters
    os.environ["PADDLE_TPU_TELEMETRY_REGISTRY"] = telem_addr
    ann = maybe_announce("router")
    coll = TelemetryCollector(registry_addr=telem_addr, period_s=0.3)
    coll.start()

    killed = {"pid": None}

    def phase_hook(phase, rate, router, scaler):
        live = router.live_replicas(include_draining=False)
        print(f"  [drill] phase {phase} (rate {rate:.0f}/s) done: "
              f"fleet size {len(live)}", flush=True)
        if phase == 2 and killed["pid"] is None:
            owned = scaler.owned_pids()
            if len(owned) >= 2:
                addr, pid = sorted(owned.items())[-1]
                killed["pid"] = pid
                print(f"  [drill] SIGKILL replica {addr} (pid {pid}) "
                      "at the peak", flush=True)
                os.kill(pid, _signal.SIGKILL)

    def post_hook(record, router, scaler):
        # scrape while the driver's router/autoscaler series still
        # exist — teardown reclaims them on close()
        time.sleep(1.0)
        coll.scrape_once()

    try:
        record = run_fleet_ramp_bench(
            requests=64, peak_rps=args.peak_rps, phase_s=args.phase_s,
            max_replicas=args.max_replicas, backlog_low=6.0,
            sustain_s=0.8, idle_sustain_s=3.0, cooldown_s=3.0,
            d_model=16, decode_delay_s=args.decode_delay,
            phase_hook=phase_hook, post_hook=post_hook,
            env_extra={
                "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS",
                                                "cpu"),
                "PADDLE_TPU_METRICS": "on",
                "PADDLE_TPU_TELEMETRY_REGISTRY": telem_addr})
        ramp = record["ramp"]
        sizes = record["fleet_size_per_phase"]
        print(f"  [drill] ramp: {ramp['requests']} requests, "
              f"{ramp['shed']} shed, {ramp['failed']} failed")
        for e in record["scale_events"]:
            print(f"  [drill] {e}")
        assert ramp["failed"] == 0, \
            f"{ramp['failed']} requests FAILED (zero-failed contract)"
        assert max(sizes) >= 2, \
            f"fleet never scaled out (sizes {sizes})"
        assert killed["pid"] is not None, \
            "drill never found a second owned replica to SIGKILL"
        assert record["fleet_size_final"] == 1, record
        assert record["status"]["crashloops"] == 0, record["status"]
        # the warm-start contract on the surviving replica(s)
        assert record["replicas"], record
        for addr, rs in record["replicas"].items():
            assert rs["warm_start"], (addr, rs)
            assert rs["cache_misses"] == 0, \
                f"scale-out replica {addr} COMPILED: {rs}"
            assert rs["recompiles_after_warmup"] == 0, (addr, rs)
        text = coll.federation_text()
        for series in ("paddle_tpu_autoscaler_replicas_live",
                       "paddle_tpu_autoscaler_scale_events_total",
                       "paddle_tpu_serving_router_requests_total"):
            assert series in text, f"missing {series} in federation"
        out = coll.write_federation(args.out)
        print(f"federated Prometheus dump -> {out}")
        print("autoscale drill: all green "
              f"(sizes {sizes} -> {record['fleet_size_final']}, "
              f"{ramp['requests']} requests, 0 failed)")
        return 0
    finally:
        if ann is not None:
            ann.close()
        coll.close()
        telem_registry.close()


# ---------------------------------------------------------------------------
# time-attribution drill (tools/ci_check.sh step 13)
# ---------------------------------------------------------------------------

_PHASE_OVERHEAD_PROBE = r"""
import json, time
import numpy as np
from paddle_tpu.observability import attribution, exemplars, metrics, tracing

assert not metrics.enabled() and not tracing.enabled()
x = np.random.RandomState(0).rand(512, 512)
n = 100


def step_light():
    return float(x.sum())          # ~100 us: worst case for noop sites


def step_tick():
    return float((x @ x)[0, 0])    # ~ms: a realistic serving-tick body


def plain(step):
    acc = 0.0
    for _ in range(n):
        acc += step()
    return acc


def attributed(step, traced):
    acc = 0.0
    for _ in range(n):
        if traced:
            with tracing.span("probe.tick"):
                with attribution.phase("generation", "decode"):
                    acc += step()
                for ph in ("sample", "deliver", "kv_alloc", "admit"):
                    with attribution.phase("generation", ph):
                        pass
        else:
            with attribution.phase("generation", "decode"):
                acc += step()
            for ph in ("sample", "deliver", "kv_alloc", "admit"):
                with attribution.phase("generation", ph):
                    pass
    return acc


def measure(step, traced):
    plain(step)  # warm both paths
    attributed(step, traced)
    ratios = []
    for _ in range(7):
        t0 = time.perf_counter()
        plain(step)
        t_plain = time.perf_counter() - t0
        t0 = time.perf_counter()
        attributed(step, traced)
        t_attr = time.perf_counter() - t0
        ratios.append(t_attr / t_plain)
        tracing.clear()
    return min(ratios) - 1.0, [round(r, 3) for r in ratios]

# (1) whole stack off: five noop phase() sites on the ~100 us step
off, off_ratios = measure(step_light, traced=False)
# (2) everything armed — metrics + tracing + exemplars + tail sampler —
# on a tick-sized step, each iteration under a root span so every
# histogram observation records an exemplar and the sampler sees the
# full span tree (threshold high enough that nothing is ever kept:
# steady-state cost, not flush cost)
metrics.set_enabled(True)
tracing.set_enabled(True)
exemplars.set_armed(True)
tracing.arm_tail_sampler(threshold_s=3600.0)
on, on_ratios = measure(step_tick, traced=True)
print(json.dumps({"overhead_off": off, "off_ratios": off_ratios,
                  "overhead_on": on, "on_ratios": on_ratios}))
"""


def _phase_overhead_guard(attempts=2):
    """Both ends of the attribution cost spectrum must stay < 5%:
    five disarmed phase() sites on a ~100 us step (noop path), and the
    fully armed plane — metrics + tracing + exemplars + tail sampler —
    on a tick-sized step.  Same fresh-subprocess + one-retry ladder as
    the tests/test_observability.py guards (noise only ever INFLATES a
    round, so min-of-rounds + best-of-attempts is the honest floor)."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("PADDLE_TPU_METRICS",
                                "PADDLE_TPU_TRACE",
                                "PADDLE_TPU_FLIGHT",
                                "PADDLE_TPU_EXEMPLARS",
                                "PADDLE_TPU_TAIL_SAMPLE"))}
    env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    import json
    best = None
    for _ in range(attempts):
        out = subprocess.run(
            [sys.executable, "-c", _PHASE_OVERHEAD_PROBE], text=True,
            capture_output=True, env=env, timeout=180)
        assert out.returncode == 0, out.stderr
        verdict = json.loads(out.stdout.strip().splitlines()[-1])
        verdict["worst"] = max(verdict["overhead_off"],
                               verdict["overhead_on"])
        if best is None or verdict["worst"] < best["worst"]:
            best = verdict
        if best["worst"] < 0.05:
            break
    assert best["worst"] < 0.05, \
        (f"attribution overhead: off {best['overhead_off']:.1%} "
         f"({best['off_ratios']}), armed {best['overhead_on']:.1%} "
         f"({best['on_ratios']})")
    print(f"  [drill] attribution overhead: disarmed "
          f"{best['overhead_off']:.1%}, fully armed "
          f"{best['overhead_on']:.1%} (< 5% guard)")


def drill_attribution(args):
    """Time-attribution acceptance (docs/observability.md "Time
    attribution"): a mini-fleet with the attribution plane armed —
    2 pservers (one delay-faulted into a straggler), a CommPool
    trainer, a decode-delay-faulted serving replica with exemplars +
    tail sampling on.  Asserts per-phase series federate from all
    three member kinds, the `cli why` table shows the decode-delay
    fault as the dominant generation phase, a latency exemplar
    resolves through `cli trace-of` to a JOINED Chrome trace, the
    straggler endpoint is flagged within one collector window, and
    the plane stays under the 5% overhead guard both disarmed and
    fully armed (exemplars + tail sampling on).  The
    federated dump goes to --out for the `cli slo --check --prom`
    gate that follows in ci_check."""
    import json

    from paddle_tpu import cli as cli_mod
    from paddle_tpu.cloud.registry import Registry
    from paddle_tpu.observability import attribution
    from paddle_tpu.observability.collector import (TelemetryCollector,
                                                    assemble_traces,
                                                    parse_prometheus_text)
    from paddle_tpu.serving.replica import replica_stream

    _phase_overhead_guard()

    workdir = tempfile.mkdtemp(prefix="paddle_attr_drill_")
    trace_dir = os.path.join(workdir, "traces")
    print(f"attribution drill workdir: {workdir}")

    registry = Registry()
    reg_addr = f"127.0.0.1:{registry.serve(0)}"
    coll = TelemetryCollector(registry_addr=reg_addr, period_s=0.3,
                              scrape_timeout_s=1.0)

    base_env = dict(os.environ,
                    JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS",
                                                 "cpu"),
                    PADDLE_TPU_METRICS="on",
                    PADDLE_TPU_TELEMETRY_REGISTRY=reg_addr,
                    PADDLE_TPU_TRACE_DIR=trace_dir,
                    PADDLE_TPU_EXEMPLARS="on",
                    PADDLE_TPU_TAIL_SAMPLE="0.05")
    logf = open(os.path.join(workdir, "children.log"), "w")
    me = [sys.executable, os.path.abspath(__file__)]
    procs = []
    try:
        # pserver A healthy; pserver B serves every frame 50 ms late —
        # the client-side per-endpoint round histogram pins the drift
        # on B alone
        ports = []
        for fault in ("", "pserver.serve:delay:1:1000000000:0.05"):
            env = dict(base_env)
            if fault:
                env["PADDLE_TPU_FAULTS"] = fault
            p = _spawn(me + ["--role", "pserver", "--run_s", "600"],
                       env, logf)
            procs.append(p)
            ports.append(int(_wait_line(
                p, "PSERVER_PORT", 180,
                f"pserver{'B' if fault else 'A'}")[1]))
        straggler_ep = f"127.0.0.1:{ports[1]}"
        coll.start()

        trainer = _spawn(
            me + ["--role", "comm_trainer", "--endpoint",
                  ",".join(f"127.0.0.1:{p}" for p in ports),
                  "--rounds", str(args.rounds)], base_env, logf)
        procs.append(trainer)

        # the replica's decode phase eats a 30 ms injected delay per
        # tick: `cli why` must show decode dominating, and every
        # request is slow enough for the tail sampler to keep
        model_dir = _build_model_dir(workdir)
        env = dict(base_env,
                   PADDLE_TPU_FAULTS="serving.decode:delay:1:"
                   "1000000000:0.03")
        replica = _spawn([sys.executable, "-m", "paddle_tpu.cli",
                          "serve", model_dir, "--use_tpu", "0"],
                         env, logf)
        procs.append(replica)
        replica_addr = _wait_line(replica, "serving ", 300,
                                  "replica")[3]

        for i in range(4):
            toks = list(replica_stream(
                replica_addr,
                {"op": "generate", "prompt": [1, 2, 3], "max_new": 5},
                timeout_s=300))
            assert toks, "replica generated nothing"
            time.sleep(0.4)
        _wait_line(trainer, "TRAINER_DONE", 180, "trainer")

        time.sleep(1.2)  # tail-sampler flush cadence + a scrape period
        coll.scrape_once()  # deterministic final sweep + detector pass

        text = coll.federation_text()
        # (a) per-phase series federated from all three member kinds
        for kind in ("generation", "trainer", "pserver"):
            series = f"paddle_tpu_{kind}_phase_seconds"
            assert series in text, f"missing {series}"
            assert f'kind="{kind}"' in text, f"no {kind} member"
        parsed = parse_prometheus_text(text)
        rows = attribution.why_rows_from_parsed(parsed)
        print()
        print(attribution.format_why_table(rows))
        print()
        gen = {r["phase"]: r for r in rows
               if r["kind"] == "generation"}
        assert gen["decode"]["share"] > 0.35, \
            f"decode-delay fault invisible in why-table: {gen}"
        assert rows[0] is not None and len(
            {r["kind"] for r in rows}) == 3

        # (b) straggler flagged within one collector window
        strag = parsed.get(attribution.STRAGGLER_METRIC)
        assert strag, "no straggler scores in federation"
        scores = {s["labels"]["endpoint"]: s["value"]
                  for s in strag["samples"]}
        assert scores.get(straggler_ep, 0.0) >= 3.0, \
            f"straggler {straggler_ep} not flagged: {scores}"
        footer = cli_mod.format_straggler_lines(coll)
        assert "STRAGGLER" in footer, footer
        print(footer)

        # (c) exemplar -> joined end-to-end Chrome trace
        ex = attribution.pick_exemplar(
            parsed, "paddle_tpu_serving_generation_seconds")
        assert ex, "no exemplar on the generation latency histogram"
        joined = assemble_traces(trace_dir)
        assert ex["trace_id"] in joined, \
            (ex["trace_id"], sorted(joined))
        with open(joined[ex["trace_id"]]) as f:
            names = {e["name"]
                     for e in json.load(f)["traceEvents"]}
        assert "serving.request" in names, names
        print(f"  [drill] p99 exemplar {ex['value']:.3f}s -> trace "
              f"{ex['trace_id']} -> {joined[ex['trace_id']]} "
              f"({len(names)} span names)")

        out = coll.write_federation(args.out)
        print(f"federated Prometheus dump -> {out}")

        # the `cli trace-of` surface end to end, off the written dump
        rc = cli_mod.cmd_trace_of(
            ["--metric", "paddle_tpu_serving_generation_seconds",
             "--prom", out, "--p99", "--trace-dir", trace_dir])
        assert rc == 0, "cli trace-of failed"
        print("attribution drill: all green")
        return 0
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        coll.close()
        registry.close()
        logf.close()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--role", default="driver",
                    choices=["driver", "pserver", "trainer",
                             "comm_trainer"])
    ap.add_argument("--drill", default="telemetry",
                    choices=["telemetry", "autoscale", "attribution"],
                    help="telemetry: the step-11 federation smoke; "
                    "autoscale: the step-12 scale-out/SIGKILL/"
                    "scale-in chaos drill; attribution: the step-13 "
                    "time-attribution drill (phases, exemplars, "
                    "stragglers)")
    ap.add_argument("--out", default="/tmp/paddle_tpu_fleet.prom")
    ap.add_argument("--endpoint", default="")
    ap.add_argument("--rounds", type=int, default=8)
    ap.add_argument("--scrapes", type=int, default=8)
    ap.add_argument("--run_s", type=float, default=600.0)
    ap.add_argument("--linger_s", type=float, default=600.0)
    ap.add_argument("--peak_rps", type=float, default=20.0)
    ap.add_argument("--phase_s", type=float, default=6.0)
    ap.add_argument("--max_replicas", type=int, default=3)
    ap.add_argument("--decode_delay", type=float, default=0.02)
    args = ap.parse_args(argv)
    if args.role == "pserver":
        return role_pserver(args)
    if args.role == "trainer":
        return role_trainer(args)
    if args.role == "comm_trainer":
        return role_comm_trainer(args)
    if args.drill == "autoscale":
        return drill_autoscale(args)
    if args.drill == "attribution":
        return drill_attribution(args)
    return driver(args)


if __name__ == "__main__":
    sys.exit(main())
