#!/usr/bin/env python
"""AST-based repo lint: codebase invariants the analysis passes rely on.

The static verifier (paddle_tpu/analysis) is only as good as the metadata
it checks against, so this lint enforces at the SOURCE level:

  1. every `register_op(...)` call declares its slots — both `inputs=`
     and `outputs=` must be bound (an op with genuinely no inputs says
     `inputs=()` explicitly).  The op-arity pass validates emitted op
     descs against these declarations; an undeclared slot list silently
     weakens it to "anything goes".
  2. no bare `except Exception: pass` (or bare `except: pass`) inside
     `paddle_tpu/core` or `paddle_tpu/serving` — the silent-swallow
     pattern that hid per-op shape-inference failures for months, and
     that in the serving worker swallowed worker bugs along with the
     client-cancellation it meant to tolerate.  Handle the exception,
     narrow it, or surface it (log/warn/report).
  3. no bare `print(` inside `paddle_tpu/core` or `paddle_tpu/parallel`
     — runtime-layer diagnostics go through `logging` or the
     observability registry/exporters (docs/observability.md) so
     production processes (pservers, serving workers) stay scrape-able
     instead of spraying stdout.
  4. no blocking call inside a `with <lock>:` body in
     `paddle_tpu/parallel`, `paddle_tpu/cloud`, or `paddle_tpu/serving`
     — a peer that stalls mid-frame then holds the lock for the
     socket-timeout duration and every other thread (the serving
     scheduler, the controller watch loop) convoys behind it; the PR 7/8
     reviews repeatedly moved IO outside locks for exactly this.
     This rule DELEGATES to the concurrency analyzer's
     `blocking-under-lock` check (paddle_tpu/analysis/concurrency.py,
     file-loaded standalone so lint stays import-light), which
     generalizes the original socket-send/recv check to condition
     waits, Thread.join, blocking queue ops, time.sleep, and
     subprocess calls.  The per-endpoint worker allowlist
     (`*conn_lock`/`*ep_lock`/`*endpoint_lock` lock names) and the
     `# lint: send-under-lock-ok` comment still apply, plus the
     analyzer's own `# lint: blocking-under-lock-ok`.  The full rule
     set (lock-order cycles, unguarded attrs, thread hygiene) runs as
     `python -m paddle_tpu.cli concurrency` in ci_check step 10.

Run: `python tools/lint.py [paths...]` (default: the paddle_tpu
package).  Exits non-zero listing `file:line: message` per violation.
Used by tools/ci_check.sh.
"""
from __future__ import annotations

import ast
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_PATHS = [os.path.join(REPO_ROOT, "paddle_tpu")]

# rule 2 scope: the core package (ISSUE: silent failures in the
# executor/inference layer are the ones that ate diagnostics) plus the
# serving subsystem (a resident scheduler thread that swallows its own
# exceptions hangs every queued request with no trace)
CORE_DIR = os.path.join(REPO_ROOT, "paddle_tpu", "core")
SILENT_EXCEPT_DIRS = (CORE_DIR,
                      os.path.join(REPO_ROOT, "paddle_tpu", "serving"))

# rule 3 scope: runtime layers that run inside long-lived server
# processes (core + the pserver/parallel machinery)
NO_PRINT_DIRS = (CORE_DIR, os.path.join(REPO_ROOT, "paddle_tpu",
                                        "parallel"))

# rule 4 scope: every layer that mixes threading locks with sockets
LOCKED_IO_DIRS = tuple(
    os.path.join(REPO_ROOT, "paddle_tpu", d)
    for d in ("parallel", "cloud", "serving"))

_CONCURRENCY_PY = os.path.join(REPO_ROOT, "paddle_tpu", "analysis",
                               "concurrency.py")
_concurrency_mod = None


def _concurrency():
    """File-load the concurrency analyzer WITHOUT importing the
    paddle_tpu package (keeps lint dependency-free and fast); the
    module is deliberately stdlib-only at module scope."""
    global _concurrency_mod
    if _concurrency_mod is None:
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "_lint_concurrency", _CONCURRENCY_PY)
        mod = importlib.util.module_from_spec(spec)
        # dataclasses resolve string annotations through sys.modules
        sys.modules["_lint_concurrency"] = mod
        spec.loader.exec_module(mod)
        _concurrency_mod = mod
    return _concurrency_mod


def _is_register_op_call(node: ast.Call) -> bool:
    f = node.func
    return (isinstance(f, ast.Name) and f.id == "register_op") or (
        isinstance(f, ast.Attribute) and f.attr == "register_op")


def check_register_op_slots(tree: ast.AST, path: str):
    """Rule 1: register_op must bind `inputs` and `outputs`."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not _is_register_op_call(node):
            continue
        bound = {kw.arg for kw in node.keywords if kw.arg}
        # positional binding: register_op(type, inputs, outputs, ...)
        if len(node.args) >= 2:
            bound.add("inputs")
        if len(node.args) >= 3:
            bound.add("outputs")
        missing = [s for s in ("inputs", "outputs") if s not in bound]
        if missing:
            yield (path, node.lineno,
                   "register_op call does not declare "
                   + " or ".join(repr(m) for m in missing)
                   + " — declare every slot list explicitly (use "
                   "inputs=() / outputs=() for none) so the analysis "
                   "op-arity pass can validate op descs")


def check_silent_excepts(tree: ast.AST, path: str):
    """Rule 2 (core only): no `except [Exception]: pass`."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        broad = node.type is None or (
            isinstance(node.type, ast.Name)
            and node.type.id in ("Exception", "BaseException"))
        body_is_pass = (len(node.body) == 1
                        and isinstance(node.body[0], ast.Pass))
        if broad and body_is_pass:
            yield (path, node.lineno,
                   "bare `except Exception: pass` swallows failures "
                   "silently — narrow the exception type or surface it "
                   "(warn/log/report)")


def check_no_prints(tree: ast.AST, path: str):
    """Rule 3 (core + parallel): no `print(...)` calls."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Name) and node.func.id == "print":
            yield (path, node.lineno,
                   "bare print() in a runtime layer — use logging or "
                   "the observability registry/exporters "
                   "(docs/observability.md) so server processes stay "
                   "scrape-able")


def check_locked_io(tree: ast.AST, path: str, source_lines):
    """Rule 4 (parallel/cloud/serving): no blocking call while holding
    a lock — delegated to the concurrency analyzer so this lint and
    `cli concurrency` share ONE lock-name heuristic, allowlist, and
    blocking-call inventory instead of drifting apart."""
    del tree  # the analyzer re-parses (shared machinery)
    conc = _concurrency()
    source = "\n".join(source_lines)
    for f in conc.analyze_source(source, filename=path,
                                 rules=["blocking-under-lock"]):
        if f.severity != "error":
            continue  # suppressed/transitive findings don't gate lint
        yield (path, f.line, f.message + " — " + f.hint)


def iter_py_files(paths):
    # one walker, shared with `cli concurrency` — the lint and
    # analyzer file sets must not silently drift apart
    return _concurrency().iter_py_files(paths)


def lint(paths) -> int:
    violations = []
    for path in iter_py_files(paths):
        try:
            with open(path) as f:
                source = f.read()
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            violations.append((path, e.lineno or 0,
                               f"syntax error: {e.msg}"))
            continue
        violations.extend(check_register_op_slots(tree, path))
        abspath = os.path.abspath(path)
        if any(abspath.startswith(d + os.sep)
               for d in SILENT_EXCEPT_DIRS):
            violations.extend(check_silent_excepts(tree, path))
        if any(abspath.startswith(d + os.sep) for d in NO_PRINT_DIRS):
            violations.extend(check_no_prints(tree, path))
        if any(abspath.startswith(d + os.sep) for d in LOCKED_IO_DIRS):
            violations.extend(
                check_locked_io(tree, path, source.splitlines()))
    for path, line, msg in sorted(violations):
        rel = os.path.relpath(path, REPO_ROOT)
        print(f"{rel}:{line}: {msg}")
    if violations:
        print(f"lint: {len(violations)} violation(s)")
        return 1
    print("lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(lint(sys.argv[1:] or DEFAULT_PATHS))
