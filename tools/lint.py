#!/usr/bin/env python
"""AST-based repo lint: codebase invariants the analysis passes rely on.

The static verifier (paddle_tpu/analysis) is only as good as the metadata
it checks against, so this lint enforces at the SOURCE level:

  1. every `register_op(...)` call declares its slots — both `inputs=`
     and `outputs=` must be bound (an op with genuinely no inputs says
     `inputs=()` explicitly).  The op-arity pass validates emitted op
     descs against these declarations; an undeclared slot list silently
     weakens it to "anything goes".
  2. no bare `except Exception: pass` (or bare `except: pass`) inside
     `paddle_tpu/core` or `paddle_tpu/serving` — the silent-swallow
     pattern that hid per-op shape-inference failures for months, and
     that in the serving worker swallowed worker bugs along with the
     client-cancellation it meant to tolerate.  Handle the exception,
     narrow it, or surface it (log/warn/report).
  3. no bare `print(` inside `paddle_tpu/core` or `paddle_tpu/parallel`
     — runtime-layer diagnostics go through `logging` or the
     observability registry/exporters (docs/observability.md) so
     production processes (pservers, serving workers) stay scrape-able
     instead of spraying stdout.
  4. no blocking socket `send*`/`recv*` call (raw socket methods OR the
     pserver wire helpers `_send_frame`/`_recv_frame`/`_read_exact`/
     `_sendall_parts`) inside a `with <lock>:` body in
     `paddle_tpu/parallel`, `paddle_tpu/cloud`, or `paddle_tpu/serving`
     — a peer that stalls mid-frame then holds the lock for the
     socket-timeout duration and every other thread (the serving
     scheduler, the controller watch loop) convoys behind it; the PR 7/8
     reviews repeatedly moved IO outside locks for exactly this.
     Allowlist for the per-endpoint worker pattern (one worker thread
     owns one socket and a PER-CONNECTION lock only serializes access
     to that one endpoint): a `with` statement over a lock whose name
     matches `*conn_lock`/`*ep_lock`/`*endpoint_lock`, or an explicit
     `# lint: send-under-lock-ok` comment on the `with` line.

Run: `python tools/lint.py [paths...]` (default: the paddle_tpu
package).  Exits non-zero listing `file:line: message` per violation.
Used by tools/ci_check.sh.
"""
from __future__ import annotations

import ast
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_PATHS = [os.path.join(REPO_ROOT, "paddle_tpu")]

# rule 2 scope: the core package (ISSUE: silent failures in the
# executor/inference layer are the ones that ate diagnostics) plus the
# serving subsystem (a resident scheduler thread that swallows its own
# exceptions hangs every queued request with no trace)
CORE_DIR = os.path.join(REPO_ROOT, "paddle_tpu", "core")
SILENT_EXCEPT_DIRS = (CORE_DIR,
                      os.path.join(REPO_ROOT, "paddle_tpu", "serving"))

# rule 3 scope: runtime layers that run inside long-lived server
# processes (core + the pserver/parallel machinery)
NO_PRINT_DIRS = (CORE_DIR, os.path.join(REPO_ROOT, "paddle_tpu",
                                        "parallel"))

# rule 4 scope: every layer that mixes threading locks with sockets
LOCKED_IO_DIRS = tuple(
    os.path.join(REPO_ROOT, "paddle_tpu", d)
    for d in ("parallel", "cloud", "serving"))

# rule 4: blocking wire calls — raw socket methods plus this repo's
# pserver frame helpers (parallel/pserver.py); calling any of these with
# a lock held convoys every other thread behind one slow peer
BLOCKING_IO_CALLS = frozenset(
    "send sendall sendmsg sendto recv recv_into recvfrom recvmsg "
    "_send_frame _send_frame_parts _recv_frame _read_exact "
    "_sendall_parts".split())

# rule 4 allowlist: per-connection locks of the per-endpoint worker
# pattern (one thread owns one socket; the lock serializes only that
# endpoint, so a slow peer cannot convoy unrelated work)
_PER_ENDPOINT_LOCK = ("conn_lock", "ep_lock", "endpoint_lock")
_ALLOW_COMMENT = "lint: send-under-lock-ok"


def _is_register_op_call(node: ast.Call) -> bool:
    f = node.func
    return (isinstance(f, ast.Name) and f.id == "register_op") or (
        isinstance(f, ast.Attribute) and f.attr == "register_op")


def check_register_op_slots(tree: ast.AST, path: str):
    """Rule 1: register_op must bind `inputs` and `outputs`."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not _is_register_op_call(node):
            continue
        bound = {kw.arg for kw in node.keywords if kw.arg}
        # positional binding: register_op(type, inputs, outputs, ...)
        if len(node.args) >= 2:
            bound.add("inputs")
        if len(node.args) >= 3:
            bound.add("outputs")
        missing = [s for s in ("inputs", "outputs") if s not in bound]
        if missing:
            yield (path, node.lineno,
                   "register_op call does not declare "
                   + " or ".join(repr(m) for m in missing)
                   + " — declare every slot list explicitly (use "
                   "inputs=() / outputs=() for none) so the analysis "
                   "op-arity pass can validate op descs")


def check_silent_excepts(tree: ast.AST, path: str):
    """Rule 2 (core only): no `except [Exception]: pass`."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        broad = node.type is None or (
            isinstance(node.type, ast.Name)
            and node.type.id in ("Exception", "BaseException"))
        body_is_pass = (len(node.body) == 1
                        and isinstance(node.body[0], ast.Pass))
        if broad and body_is_pass:
            yield (path, node.lineno,
                   "bare `except Exception: pass` swallows failures "
                   "silently — narrow the exception type or surface it "
                   "(warn/log/report)")


def check_no_prints(tree: ast.AST, path: str):
    """Rule 3 (core + parallel): no `print(...)` calls."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Name) and node.func.id == "print":
            yield (path, node.lineno,
                   "bare print() in a runtime layer — use logging or "
                   "the observability registry/exporters "
                   "(docs/observability.md) so server processes stay "
                   "scrape-able")


def _lock_names(expr: ast.AST):
    """Identifier-ish names mentioned in a with-item's context expr."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute):
            yield node.attr
        elif isinstance(node, ast.Name):
            yield node.id


def _is_lock_expr(expr: ast.AST) -> bool:
    # token-wise match: `_cond` / `view_lock` are locks, but a name
    # merely CONTAINING the letters (`seconds`, `blockers`) is not
    import re as _re

    for n in _lock_names(expr):
        parts = [p for p in _re.split(r"[^a-z]+", n.lower()) if p]
        if any(p in ("lock", "cond", "cv", "mutex") for p in parts):
            return True
        if n.lower().endswith(("lock", "cond")):
            return True
    return False


def _is_allowed_lock(expr: ast.AST) -> bool:
    return any(n.lower().endswith(_PER_ENDPOINT_LOCK)
               for n in _lock_names(expr))


def _walk_executed(node: ast.AST):
    """ast.walk, but not into nested def/lambda bodies — code merely
    DEFINED under the lock runs later, after release."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            stack.extend(ast.iter_child_nodes(n))


def check_locked_io(tree: ast.AST, path: str, source_lines):
    """Rule 4 (parallel/cloud/serving): no blocking socket send*/recv*
    (or pserver frame helper) call while holding a lock."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.With):
            continue
        lockish = [i for i in node.items if _is_lock_expr(i.context_expr)]
        if not lockish:
            continue
        if any(_is_allowed_lock(i.context_expr) for i in lockish):
            continue  # per-endpoint worker pattern
        line = ""
        if 0 < node.lineno <= len(source_lines):
            line = source_lines[node.lineno - 1]
        if _ALLOW_COMMENT in line:
            continue
        for inner in _walk_executed(node):
            if not isinstance(inner, ast.Call):
                continue
            f = inner.func
            name = (f.attr if isinstance(f, ast.Attribute)
                    else f.id if isinstance(f, ast.Name) else "")
            if name in BLOCKING_IO_CALLS:
                yield (path, inner.lineno,
                       f"blocking wire call {name}() inside the "
                       f"`with` lock at line {node.lineno} — a stalled "
                       "peer holds the lock for the socket timeout and "
                       "every other thread convoys; move the IO outside "
                       "the lock (snapshot under it, send after), use a "
                       "per-endpoint `*_conn_lock`, or annotate the "
                       f"with-line `# {_ALLOW_COMMENT}` with a reason")


def iter_py_files(paths):
    for p in paths:
        if os.path.isfile(p):
            yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.join(root, f)


def lint(paths) -> int:
    violations = []
    for path in iter_py_files(paths):
        try:
            with open(path) as f:
                source = f.read()
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            violations.append((path, e.lineno or 0,
                               f"syntax error: {e.msg}"))
            continue
        violations.extend(check_register_op_slots(tree, path))
        abspath = os.path.abspath(path)
        if any(abspath.startswith(d + os.sep)
               for d in SILENT_EXCEPT_DIRS):
            violations.extend(check_silent_excepts(tree, path))
        if any(abspath.startswith(d + os.sep) for d in NO_PRINT_DIRS):
            violations.extend(check_no_prints(tree, path))
        if any(abspath.startswith(d + os.sep) for d in LOCKED_IO_DIRS):
            violations.extend(
                check_locked_io(tree, path, source.splitlines()))
    for path, line, msg in sorted(violations):
        rel = os.path.relpath(path, REPO_ROOT)
        print(f"{rel}:{line}: {msg}")
    if violations:
        print(f"lint: {len(violations)} violation(s)")
        return 1
    print("lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(lint(sys.argv[1:] or DEFAULT_PATHS))
