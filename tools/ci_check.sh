#!/usr/bin/env bash
# Fast correctness gate: repo lint + static program verification + the
# quick tier-1 subset, with the verifier armed (PADDLE_TPU_VERIFY=error)
# so every program the tests build must verify clean of error-severity
# diagnostics.  Full tier-1 stays the ROADMAP.md command; this script is
# the pre-push / CI smoke layer (a few minutes on a laptop CPU).
#
# Usage: tools/ci_check.sh
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export PADDLE_TPU_DATASET="${PADDLE_TPU_DATASET:-synthetic}"

echo "== [1/14] repo lint (tools/lint.py) =="
python tools/lint.py

echo "== [2/14] static verification of example programs =="
python -m paddle_tpu.cli verify \
    examples/transformer_lm.py \
    examples/pipeline_transformer_lm.py \
    examples/serve_image_classifier.py \
    examples/dist_ckpt_worker.py

echo "== [3/14] fast tier-1 subset with PADDLE_TPU_VERIFY=error =="
# (TestSoftmax::test_grad is back in: its constant-loss degeneracy — the
# old finite-difference flake — is fixed via grad_output_weights)
PADDLE_TPU_VERIFY=error python -m pytest \
    tests/test_analysis.py \
    tests/test_registry.py \
    tests/test_basic_ops.py \
    tests/test_control_flow.py \
    tests/test_io.py \
    tests/test_cli.py \
    tests/test_debugger.py \
    -q -m 'not slow' -p no:cacheprovider

echo "== [4/14] observability + comm subset with PADDLE_TPU_METRICS=on =="
# the instrumented hot paths must behave identically with the metric
# instruments armed (docs/observability.md); test_comm.py also pins the
# bucketed wire path's backward compatibility both directions
PADDLE_TPU_METRICS=on python -m pytest \
    tests/test_observability.py \
    tests/test_executor_cache.py \
    tests/test_serving.py \
    tests/test_pserver.py \
    tests/test_comm.py \
    -q -m 'not slow' -p no:cacheprovider

echo "== [5/14] memory layer: fast book subset + memory plan with the optimizer armed =="
# the whole-program memory layer (donation plan, dead-var freeing,
# rename pass — docs/performance.md 'Memory') must leave training
# semantics untouched with the verifier also armed: the book models
# still converge and every optimized program verifies clean
PADDLE_TPU_MEMORY_OPTIMIZE=on PADDLE_TPU_VERIFY=error python -m pytest \
    tests/book/test_fit_a_line.py \
    tests/book/test_recognize_digits.py \
    tests/book/test_recommender_system.py \
    tests/test_memory_optimize.py \
    tests/test_memory_plan.py \
    -q -p no:cacheprovider


echo "== [6/14] elastic cluster: fast subset under chaos + metrics =="
# the elastic runtime (docs/resilience.md "Elastic clusters") must hold
# with the fault injector armed and the metric instruments on: the
# injected first-rebalance failure is retried by the controller's watch
# loop, and every view change/migration still lands its telemetry
PADDLE_TPU_FAULTS="cluster.rebalance:error:1" PADDLE_TPU_METRICS=on \
    python -m pytest \
    tests/test_elastic.py \
    -q -m 'not slow' -p no:cacheprovider
# the rebalance counters must be visible in a Prometheus dump
PADDLE_TPU_METRICS=on python - <<'EOF'
import numpy as np
from paddle_tpu.cloud.cluster import ClusterController
from paddle_tpu.cloud.registry import Lease, RegistryClient
from paddle_tpu.observability import exporters
from paddle_tpu.parallel.distributed_spliter import VarDesc
from tests.test_elastic import _sgd_server

params = {"w": np.ones(8, np.float32)}
srv, ep = _sgd_server(params)
ctl = ClusterController(min_pservers=1, poll_s=0.05)
ctl.serve(0)
ctl.start()
ctl.define([VarDesc("w", (8,), "float32")])
lease = Lease(RegistryClient(ctl.registry_addr), "pserver", ep, ttl_s=2.0)
assert ctl.wait_view(1, timeout_s=15) is not None, "no stable view"
text = exporters.prometheus_text()
for series in ("paddle_tpu_cluster_view_epoch",
               "paddle_tpu_cluster_rebalances_total",
               "paddle_tpu_cluster_membership_changes_total",
               "paddle_tpu_cluster_rebalance_seconds"):
    assert series in text, f"missing {series} in Prometheus dump"
lease.release()
srv.stop()
ctl.close()
print("elastic telemetry visible in Prometheus dump")
EOF

echo "== [7/14] generation serving: fast subset + Prometheus series =="
# the continuous-batching serving layer (docs/serving.md) must behave
# identically with the metric instruments armed, and every serving
# process must expose the generation series a fleet dashboard scrapes
PADDLE_TPU_METRICS=on python -m pytest \
    tests/test_generation_serving.py \
    -q -m 'not slow' -p no:cacheprovider
PADDLE_TPU_METRICS=on python - <<'EOF'
import numpy as np
import paddle_tpu as fluid
import paddle_tpu.core.framework as fw
from paddle_tpu.models.transformer import build_lm_paged_decoder
from paddle_tpu.observability import exporters
from paddle_tpu.serving import GenerationServer

fw.reset_unique_names()
startup, dec = build_lm_paged_decoder(23, 4, 4, d_model=16, n_heads=2,
                                      n_layers=1)
scope = fluid.Scope()
fluid.Executor(fluid.CPUPlace()).run(startup, scope=scope)
states = {n: np.asarray(scope.find_var(n)) for n in dec.state_names}
# target doubles as its own draft: the speculative + prefix-cache
# paths run for real (proposals verified, prompt blocks hash-consed)
srv = GenerationServer(dec, states, slots=2, kv_blocks=8,
                       place=fluid.CPUPlace(),
                       draft_decoder=dec, draft_states=states,
                       spec_k=2)
assert srv.generate([1, 2, 3, 4], 6, timeout=60)
assert srv.generate([1, 2, 3, 4], 6, timeout=60)
st = srv.stats()
assert st["draft_proposed"] > 0, st
assert st["prefix_hits"] > 0, st
text = exporters.prometheus_text()
for series in ("paddle_tpu_serving_generation_requests_total",
               "paddle_tpu_serving_generated_tokens_total",
               "paddle_tpu_serving_decode_ticks_total",
               "paddle_tpu_serving_generation_shed_total",
               "paddle_tpu_serving_generation_seconds",
               "paddle_tpu_serving_first_token_seconds",
               "paddle_tpu_serving_kv_blocks_in_use",
               "paddle_tpu_serving_kv_pool_utilization",
               "paddle_tpu_serving_prefix_hits_total",
               "paddle_tpu_serving_prefix_misses_total",
               "paddle_tpu_serving_draft_proposed_total",
               "paddle_tpu_serving_draft_accepted_total",
               "paddle_tpu_serving_kv_bytes_resident"):
    assert series in text, f"missing {series} in Prometheus dump"
srv.close()
print("generation serving series visible in Prometheus dump "
      "(incl. prefix-cache + speculative-decoding series)")
EOF

echo "== [8/14] multichip sharding: spmd transpiler on the 8-device virtual mesh =="
# the mainline sharding path (docs/performance.md "Multichip sharding"):
# annotated Programs lower through ShardingTranspiler onto the proven
# dp/tp/pp executors, match serial + the composite.py oracle, and the
# sharding-consistency diagnostics verify clean with the verifier armed
XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" \
    PADDLE_TPU_VERIFY=error python -m pytest \
    tests/test_spmd_sharding.py \
    -q -m 'not slow' -p no:cacheprovider

echo "== [9/14] static cost analyzer: budget gate over the example configs =="
# the compile-free perf-regression gate (docs/analysis.md 'Budget
# gate'): every example config's static roofline / peak-HBM estimate
# must stay inside its checked-in budget, its bound verdict must not
# flip, and cost-metadata coverage must stay complete — with the
# verifier armed so the cost/comm/collective-safety passes run on
# every program the configs build
PADDLE_TPU_VERIFY=error python -m paddle_tpu.cli analyze \
    --budget tools/budgets.json \
    examples/transformer_lm.py \
    examples/pipeline_transformer_lm.py \
    examples/serve_image_classifier.py \
    examples/dist_ckpt_worker.py
# cli verify --json stays machine-parseable for editor/CI consumers
python -m paddle_tpu.cli verify --json examples/transformer_lm.py \
    | python -c "import json,sys; d=json.load(sys.stdin); \
assert not d['failed'] and d['programs'], d"


echo "== [10/14] concurrency analyzer: repo-wide lint + schedule-checked protocols =="
# the threaded runtimes (pserver wire protocol, elastic controller,
# serving scheduler, comm workers — docs/analysis.md 'Concurrency
# analysis') must stay free of unsuppressed error-severity concurrency
# findings (lock-order cycles, blocking-under-lock, thread hygiene),
# and the distributed protocols must hold their invariants (no
# deadlock, no lost shard copy, KV refcount balance, per-endpoint
# frame ordering) over every interleaving the schedule checker
# explores
python -m paddle_tpu.cli concurrency --sched

echo "== [11/14] fleet telemetry: mini-fleet federation + SLO gate =="
# the fleet telemetry plane (docs/observability.md "Fleet telemetry"):
# a real 1-trainer x 1-pserver + 1-replica fleet under
# PADDLE_TPU_METRICS=on, every member announcing its /metrics endpoint
# in the TTL-lease registry; the TelemetryCollector's federated dump
# must carry member-labeled series from all three kinds, the flight
# recorder must survive a SIGKILLed pserver, and the checked-in SLO
# baseline must hold against the dump
FLEET_PROM="$(mktemp -t paddle_fleet_XXXX.prom)"
PADDLE_TPU_METRICS=on python tools/mini_fleet.py --out "$FLEET_PROM"
python -m paddle_tpu.cli slo --check --spec tools/slo.json \
    --prom "$FLEET_PROM"
rm -f "$FLEET_PROM"



echo "== [12/14] autoscaling fleet: scale-out / SIGKILL / scale-in drill =="
# the ROADMAP-4 acceptance (docs/serving.md "Autoscaling"): an
# open-loop load ramp against a live router+autoscaler fleet triggers
# scale-out (warm-start replicas deserialize their executables), a
# SIGKILLed replica mid-ramp is absorbed by the router's resume
# contract, the ramp-down scales back in via graceful drain — with
# ZERO failed requests — and the fleet-size / crash-loop / zero-failed
# SLOs hold on the federated dump
DRILL_PROM="$(mktemp -t paddle_drill_XXXX.prom)"
PADDLE_TPU_METRICS=on python tools/mini_fleet.py --drill autoscale \
    --out "$DRILL_PROM"
python -m paddle_tpu.cli slo --check --spec tools/slo.json \
    --prom "$DRILL_PROM"
rm -f "$DRILL_PROM"



echo "== [13/14] time attribution: phase / exemplar / straggler drill =="
# the time-attribution acceptance (docs/observability.md "Time
# attribution"): phase() overhead stays under 5% when the stack is
# off, a decode-delay fault on one replica dominates the fleet
# why-table, a delayed pserver is flagged as a straggler from the
# comm-round histograms within one collector window, and a p99
# exemplar on the serving histogram joins to a tail-sampled Chrome
# trace via `cli trace-of`
ATTR_PROM="$(mktemp -t paddle_attr_XXXX.prom)"
PADDLE_TPU_METRICS=on python tools/mini_fleet.py --drill attribution \
    --out "$ATTR_PROM"
python -m paddle_tpu.cli slo --check --spec tools/slo.json \
    --prom "$ATTR_PROM"
rm -f "$ATTR_PROM"

echo "== [14/14] serving kernels: Pallas/XLA parity + fallback accounting =="
# the serving-kernel tier (docs/performance.md "Serving kernels"):
# greedy decode through the fused paged-attention path must be
# BIT-identical to the XLA oracle under interpret mode on CPU with
# runtime verification armed, and armed-but-unsupported selections
# must surface as the counted fallback series, reclaimed on close
JAX_PLATFORMS=cpu PADDLE_TPU_VERIFY=error python -m pytest \
    tests/test_serving_kernels.py \
    -q -m 'not slow' -p no:cacheprovider
JAX_PLATFORMS=cpu PADDLE_TPU_VERIFY=error PADDLE_TPU_METRICS=on \
    python - <<'EOF_KERNELS'
import numpy as np
import paddle_tpu as fluid
import paddle_tpu.core.framework as fw
from paddle_tpu.core.flags import get_flag, set_flags
from paddle_tpu.kernels import registry as kreg
from paddle_tpu.models.transformer import build_lm_paged_decoder
from paddle_tpu.observability import exporters
from paddle_tpu.serving import GenerationServer


def build(mode, kv_dtype=None):
    prev = get_flag("serving_kernels")
    set_flags({"serving_kernels": mode})
    try:
        fw.reset_unique_names()
        startup, dec = build_lm_paged_decoder(
            23, 4, 4, d_model=16, n_heads=2, n_layers=1,
            kv_dtype=kv_dtype)
    finally:
        set_flags({"serving_kernels": prev})
    return startup, dec


startup, dec_x = build("off")
scope = fluid.Scope()
fluid.Executor(fluid.CPUPlace()).run(startup, scope=scope)
states = {n: np.asarray(scope.find_var(n)) for n in dec_x.state_names}
_, dec_p = build("on")
assert dec_p.kernels["paged_attention_decode"] == "pallas", dec_p.kernels

outs = []
for dec in (dec_x, dec_p):
    srv = GenerationServer(dec, states, slots=2, kv_blocks=8,
                           place=fluid.CPUPlace())
    outs.append([srv.generate([1, 2, 3, 4], 8, timeout=120),
                 srv.generate([5, 1, 2], 6, timeout=120)])
    srv.close()
assert outs[0] == outs[1], "Pallas decode diverged from the XLA oracle"

# armed-but-unsupported: counted fallback series, reclaimed on close
prev = get_flag("serving_kernels")
set_flags({"serving_kernels": "on"})
try:
    sel = kreg.Selection()
    assert sel.pick("paged_attention_decode", d_model=64, n_heads=2,
                    block_size=64, max_blocks_per_seq=512,
                    kv_dtype="fp32") is None
    series = (kreg.FALLBACK_METRIC
              + '{kernel="paged_attention_decode",reason="vmem_scratch"}')
    assert series in exporters.prometheus_text(), "fallback not counted"
    sel.close()
    assert series not in exporters.prometheus_text(), "series leaked"
finally:
    set_flags({"serving_kernels": prev})
print("serving kernels: greedy decode bit-identical (fp32), "
      "fallback series counted and reclaimed")
EOF_KERNELS

echo "ci_check: all green"
