#!/usr/bin/env bash
# Fast correctness gate: repo lint + static program verification + the
# quick tier-1 subset, with the verifier armed (PADDLE_TPU_VERIFY=error)
# so every program the tests build must verify clean of error-severity
# diagnostics.  Full tier-1 stays the ROADMAP.md command; this script is
# the pre-push / CI smoke layer (a few minutes on a laptop CPU).
#
# Usage: tools/ci_check.sh
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export PADDLE_TPU_DATASET="${PADDLE_TPU_DATASET:-synthetic}"

echo "== [1/5] repo lint (tools/lint.py) =="
python tools/lint.py

echo "== [2/5] static verification of example programs =="
python -m paddle_tpu.cli verify \
    examples/transformer_lm.py \
    examples/pipeline_transformer_lm.py \
    examples/serve_image_classifier.py \
    examples/dist_ckpt_worker.py

echo "== [3/5] fast tier-1 subset with PADDLE_TPU_VERIFY=error =="
PADDLE_TPU_VERIFY=error python -m pytest \
    tests/test_analysis.py \
    tests/test_registry.py \
    tests/test_basic_ops.py \
    tests/test_control_flow.py \
    tests/test_io.py \
    tests/test_cli.py \
    tests/test_debugger.py \
    -q -m 'not slow' -p no:cacheprovider \
    --deselect tests/test_basic_ops.py::TestSoftmax::test_grad
# (TestSoftmax::test_grad is a pre-existing finite-difference tolerance
# flake — it fails identically on the pre-PR tree, unrelated to
# verification)

echo "== [4/5] observability + comm subset with PADDLE_TPU_METRICS=on =="
# the instrumented hot paths must behave identically with the metric
# instruments armed (docs/observability.md); test_comm.py also pins the
# bucketed wire path's backward compatibility both directions
PADDLE_TPU_METRICS=on python -m pytest \
    tests/test_observability.py \
    tests/test_executor_cache.py \
    tests/test_serving.py \
    tests/test_pserver.py \
    tests/test_comm.py \
    -q -m 'not slow' -p no:cacheprovider

echo "== [5/5] memory layer: fast book subset + memory plan with the optimizer armed =="
# the whole-program memory layer (donation plan, dead-var freeing,
# rename pass — docs/performance.md 'Memory') must leave training
# semantics untouched with the verifier also armed: the book models
# still converge and every optimized program verifies clean
PADDLE_TPU_MEMORY_OPTIMIZE=on PADDLE_TPU_VERIFY=error python -m pytest \
    tests/book/test_fit_a_line.py \
    tests/book/test_recognize_digits.py \
    tests/book/test_recommender_system.py \
    tests/test_memory_optimize.py \
    tests/test_memory_plan.py \
    -q -p no:cacheprovider

echo "ci_check: all green"
