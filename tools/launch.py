#!/usr/bin/env python
"""Cluster launcher — the reference's cluster_train scripts rebuilt.

Reference: /root/reference/paddle/scripts/cluster_train/paddle.py (ssh
fabric launcher setting PADDLE_INIT_* env), cluster_train_v2/{fabric,
openmpi}, and the book_distribute env-var convention
(tests/book_distribute/notest_dist_fit_a_line.py:43-60: PSERVERS /
TRAINING_ROLE / SERVER_ENDPOINT / PADDLE_INIT_TRAINER_ID).

Two modes:

1. pserver cluster (CPU hosts, DistributeTranspiler pserver mode):
       python tools/launch.py --pservers 2 --trainers 2 train.py [args...]
   Spawns the script once per role-instance with the reference's env-var
   convention; pserver endpoints are auto-assigned on localhost.  For a
   multi-host cluster, pass --endpoints with ALL pserver endpoints and run
   one launcher per host spawning only that host's share, using
   --pserver-offset to pick which endpoints this host serves:
       hostA$ launch.py --endpoints A:7164,B:7164 --pservers 1 \
                  --pserver-offset 0 --trainers 2 train.py
       hostB$ launch.py --endpoints A:7164,B:7164 --pservers 1 \
                  --pserver-offset 1 --trainers 2 train.py

2. multi-host SPMD (TPU pods, jax.distributed):
       python tools/launch.py --coordinator host0:1234 --num-processes 4 \
           --process-id 0 train.py [args...]
   Exports JAX coordination env (the etcd-membership analogue) and execs
   the script; paddle_tpu.parallel.init_distributed() picks it up.

3. registry-discovered pserver cluster (the reference's etcd flow):
       python tools/launch.py --registry --pservers 2 --trainers 2 train.py
   The launcher hosts a TTL-lease registry (cloud.registry); pservers
   bind their own ports, register under kept-alive leases, trainers
   discover — no static endpoint list, and a dead pserver's slot frees
   for a replacement.  The script resolves its role via
   cloud.registry.resolve_pserver_cluster() (see
   examples/dist_fit_a_line.py, which supports both modes).
"""
from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys

__all__ = ["launch_pserver_cluster", "launch_registry_cluster"]


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def launch_registry_cluster(script, script_args, n_pservers, n_trainers,
                            python=sys.executable):
    """Registry mode: NO static endpoint list.  The launcher hosts a
    TTL-lease registry (paddle_tpu.cloud.registry); pservers pick their
    own ports and register, trainers discover — the reference's etcd
    flow (go/cmd/pserver/pserver.go) instead of PSERVERS env plumbing.
    The script resolves its role via
    `cloud.registry.resolve_pserver_cluster()`.

    Returns (registry, [(role, proc)...]); stop the registry after the
    trainers exit."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from paddle_tpu.cloud.registry import Registry

    reg = Registry()
    rport = reg.serve(0)
    reg.set_desired("pserver", n_pservers)
    base = dict(os.environ,
                PADDLE_TPU_REGISTRY=f"127.0.0.1:{rport}",
                PADDLE_TPU_NUM_PSERVERS=str(n_pservers),
                PADDLE_INIT_NUM_GRADIENT_SERVERS=str(n_trainers))
    procs = []
    for _ in range(n_pservers):
        env = dict(base, TRAINING_ROLE="PSERVER")
        procs.append(("pserver",
                      subprocess.Popen([python, script] + script_args,
                                       env=env)))
    for i in range(n_trainers):
        env = dict(base, TRAINING_ROLE="TRAINER",
                   PADDLE_INIT_TRAINER_ID=str(i))
        procs.append(("trainer",
                      subprocess.Popen([python, script] + script_args,
                                       env=env)))
    return reg, procs


def launch_pserver_cluster(script, script_args, n_pservers, n_trainers,
                           endpoints=None, pserver_offset=0,
                           python=sys.executable, **trainer_popen_kwargs):
    """Spawn pserver + trainer processes with the book_distribute env-var
    convention; returns the list of (role, proc).

    `endpoints` lists the FULL cluster's pservers; this call serves
    eps[pserver_offset : pserver_offset+n_pservers] (multi-host: one call
    per host with its own offset).  `trainer_popen_kwargs` apply to the
    TRAINER Popen calls only (e.g. stdout=PIPE to harvest results);
    pservers deliberately inherit stdio — nobody drains their pipes, and
    a full unread pipe would block the server."""
    eps = (endpoints.split(",") if endpoints else
           [f"127.0.0.1:{_free_port()}" for _ in range(n_pservers)])
    if pserver_offset + n_pservers > len(eps):
        raise ValueError(
            f"--pservers {n_pservers} at offset {pserver_offset} exceeds "
            f"the {len(eps)} endpoints given")
    procs = []
    for i, ep in enumerate(eps[pserver_offset:pserver_offset + n_pservers]):
        env = dict(os.environ,
                   PSERVERS=",".join(eps),
                   TRAINING_ROLE="PSERVER",
                   SERVER_ENDPOINT=ep,
                   PADDLE_INIT_NUM_GRADIENT_SERVERS=str(n_trainers))
        procs.append(("pserver",
                      subprocess.Popen([python, script] + script_args,
                                       env=env)))
    for i in range(n_trainers):
        env = dict(os.environ,
                   PSERVERS=",".join(eps),
                   TRAINING_ROLE="TRAINER",
                   PADDLE_INIT_TRAINER_ID=str(i),
                   PADDLE_INIT_NUM_GRADIENT_SERVERS=str(n_trainers))
        procs.append(("trainer",
                      subprocess.Popen([python, script] + script_args,
                                       env=env, **trainer_popen_kwargs)))
    return procs


def main():
    ap = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--pservers", type=int, default=0)
    ap.add_argument("--trainers", type=int, default=1)
    ap.add_argument("--endpoints", default=None,
                    help="comma-separated pserver endpoints of the FULL "
                         "cluster (default: auto-assign localhost ports)")
    ap.add_argument("--pserver-offset", type=int, default=0,
                    help="index into --endpoints of this host's first "
                         "pserver (multi-host)")
    ap.add_argument("--registry", action="store_true",
                    help="host a TTL-lease registry instead of static "
                         "endpoints; pservers self-register, trainers "
                         "discover (script must use "
                         "cloud.registry.resolve_pserver_cluster)")
    ap.add_argument("--coordinator", default=None,
                    help="jax.distributed coordinator address host:port")
    ap.add_argument("--num-processes", type=int, default=None)
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument("script")
    ap.add_argument("script_args", nargs=argparse.REMAINDER)
    args = ap.parse_args()

    if args.coordinator:
        # multi-host SPMD: one process per host, env consumed by
        # parallel.mesh.init_distributed()
        if args.num_processes is None or args.process_id is None:
            ap.error("--coordinator requires --num-processes and "
                     "--process-id (otherwise each host silently runs an "
                     "independent single-host job)")
        env = dict(os.environ,
                   PADDLE_TPU_COORDINATOR=args.coordinator,
                   PADDLE_TPU_NUM_PROCESSES=str(args.num_processes),
                   PADDLE_TPU_PROCESS_ID=str(args.process_id))
        sys.exit(subprocess.call([sys.executable, args.script] +
                                 args.script_args, env=env))

    reg = None
    if args.registry:
        if args.endpoints or args.pserver_offset:
            ap.error("--registry discovers endpoints dynamically; "
                     "--endpoints/--pserver-offset only apply to the "
                     "static mode")
        reg, procs = launch_registry_cluster(
            args.script, args.script_args, args.pservers, args.trainers)
    else:
        procs = launch_pserver_cluster(args.script, args.script_args,
                                       args.pservers, args.trainers,
                                       args.endpoints, args.pserver_offset)
    rc = 0
    # trainers finishing ends the job; pservers are then terminated
    # (the reference's fabric launcher kills pservers the same way)
    for role, p in procs:
        if role == "trainer":
            rc |= p.wait()
    for role, p in procs:
        if role == "pserver" and p.poll() is None:
            p.terminate()
    for role, p in procs:
        if p.poll() is None:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
    if reg is not None:
        reg.close()
    sys.exit(rc)


if __name__ == "__main__":
    main()
