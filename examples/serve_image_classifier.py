"""Train a small CNN, export it, and serve it with the resident
InferenceServer — the deployment loop for vision models: per-bucket AOT
executables, dynamic request batching (numerics-identical to
one-request-at-a-time), transfer/compute overlap (docs/design/serving.md;
the reference's analogue is the capi resident process,
gradient_machine.cpp).

Run:  JAX_PLATFORMS=cpu python examples/serve_image_classifier.py
"""
import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS", "") == "cpu":
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.io import prune
from paddle_tpu.serving import InferenceServer

C, H, W, CLS = 3, 32, 32, 10


def build():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[C, H, W],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        conv = fluid.layers.conv2d(input=img, num_filters=16,
                                   filter_size=3, act="relu")
        pool = fluid.layers.pool2d(input=conv, pool_size=2, pool_stride=2)
        predict = fluid.layers.fc(input=pool, size=CLS, act="softmax")
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(input=predict, label=label))
        fluid.Momentum(learning_rate=0.05, momentum=0.9).minimize(loss)
    return main, startup, predict, loss


def main():
    main_p, startup, predict, loss = build()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)

    # quick training pass on synthetic class templates so the served
    # model actually predicts something
    r = np.random.RandomState(0)
    templates = r.rand(CLS, C, H, W).astype(np.float32)
    for step in range(30):
        lbl = r.randint(0, CLS, (64, 1))
        img = (templates[lbl[:, 0]]
               + 0.1 * r.randn(64, C, H, W)).astype(np.float32)
        lv, = exe.run(main_p, feed={"img": img, "label": lbl},
                      fetch_list=[loss], scope=scope)
        if step % 10 == 0:
            print(f"train step {step}: loss {float(np.asarray(lv)[0]):.3f}")

    infer_prog = prune(main_p, [predict], for_test=True)
    server = InferenceServer(infer_prog, "img", predict, scope,
                             place=fluid.CPUPlace(),
                             buckets=(1, 2, 4, 8), window_ms=2.0)
    try:
        # concurrent clients: each submits one image and checks the
        # argmax; the server coalesces them into few dispatches
        n, hits = 64, []

        def client(i):
            lbl = i % CLS
            img = templates[lbl] + 0.1 * np.random.RandomState(i) \
                .randn(C, H, W).astype(np.float32)
            probs = np.asarray(server.submit(img).result())[0]
            hits.append(int(np.argmax(probs)) == lbl)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stats = server.stats()
        print(f"served {stats['requests']} requests in "
              f"{stats['dispatches']} dispatches "
              f"(aggregation {stats['requests'] / stats['dispatches']:.1f}x), "
              f"accuracy {np.mean(hits):.2f}")
    finally:
        server.close()


if __name__ == "__main__":
    main()
