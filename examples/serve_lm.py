"""Train a tiny LM, then serve it three ways: greedy full-forward decode,
KV-cache incremental decode (the fast path, token-identical), and beam
search — all on-device, single-jit loops (docs/design/generation.md).

Run:  JAX_PLATFORMS=cpu python examples/serve_lm.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS", "") == "cpu":
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as fluid
import paddle_tpu.core.framework as fw
from paddle_tpu.models.transformer import (
    build_lm_beam_search,
    build_lm_generator,
    build_lm_kv_decoder,
    transformer_lm,
)

V, L, B = 16, 16, 32
ARCH = dict(d_model=48, n_heads=2, n_layers=1)


def train():
    fw.reset_unique_names()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data(name="ids", shape=[L], dtype="int64")
        nxt = fluid.layers.data(name="nxt", shape=[L, 1], dtype="int64")
        probs = transformer_lm(ids, V, max_len=L, **ARCH)
        loss = fluid.layers.mean(fluid.layers.cross_entropy(
            input=fluid.layers.reshape(probs, shape=[-1, V]),
            label=fluid.layers.reshape(nxt, shape=[-1, 1])))
        fluid.Adam(learning_rate=5e-3).minimize(loss)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    r = np.random.RandomState(0)
    for step in range(200):
        starts = r.randint(0, V, (B, 1))
        seq = (starts + np.arange(L + 1)) % V       # successor language
        out, = exe.run(main, feed={
            "ids": seq[:, :L].astype(np.int32),
            "nxt": seq[:, 1:, None].astype(np.int32)},
            fetch_list=[loss], scope=scope)
        if step % 50 == 0:
            print(f"train step {step:3d} "
                  f"loss {np.asarray(out).reshape(-1)[0].item():.3f}")
    return scope


def main():
    scope = train()
    prompt = np.array([[3, 4, 5, 6]], np.int32)

    fw.reset_unique_names()
    _, gen = build_lm_generator(V, L, **ARCH)
    states = {n: np.asarray(scope.find_var(n)) for n in gen.state_names}
    print("greedy (full forward):", np.asarray(
        gen(states, prompt, num_steps=8))[0, :12])

    fw.reset_unique_names()
    _, kv = build_lm_kv_decoder(V, L, **ARCH)
    print("greedy (KV cache):    ", np.asarray(
        kv(states, prompt, num_steps=8))[0, :12])

    fw.reset_unique_names()
    _, beam = build_lm_beam_search(V, L, beam_size=4, **ARCH)
    ids, scores = beam(states, prompt, num_steps=8)
    print("beam-4 best:          ", np.asarray(ids)[0, 0, :12],
          " score", float(np.asarray(scores)[0, 0]))


if __name__ == "__main__":
    main()
