"""Pipeline-parallel transformer LM from the Program DSL.

The r4 feature end-to-end: annotate the model's block stack with
`fluid.pipeline_stage(i)` (transformer_lm does it for you via
`pipeline_stages=S`), then run the SAME Program either serially
(Executor — the annotation is inert) or pipelined over a {dp, pp} mesh
(parallel.PipelineExecutor, GPipe schedule, the Program's own optimizer
ops applying the update).  Reference analogue: per-layer device
placement via the `parallel_nn` flag
(/root/reference/paddle/gserver/gradientmachines/ParallelNeuralNetwork.h,
/root/reference/paddle/utils/Flags.cpp:37) — here it is a context
manager in the DSL instead of a gconf flag.

Run on the 8-device virtual CPU mesh (no TPU pod needed):

    JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/pipeline_transformer_lm.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS", "") == "cpu":
    # the axon site hook overrides the env var; pin via config
    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import parallel
from paddle_tpu.models.transformer import transformer_lm

VOCAB, SEQ, D_MODEL, LAYERS, STAGES = 64, 16, 32, 4, 4
DP = max(1, len(jax.devices()) // STAGES)


def build():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data(name="ids", shape=[SEQ], dtype="int64")
        lbl = fluid.layers.data(name="lbl", shape=[SEQ, 1], dtype="int64")
        logits = transformer_lm(ids, VOCAB, d_model=D_MODEL, n_heads=4,
                                n_layers=LAYERS, max_len=SEQ,
                                return_logits=True,
                                pipeline_stages=STAGES)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(
                fluid.layers.reshape(logits, shape=[-1, VOCAB]),
                fluid.layers.reshape(lbl, shape=[-1, 1])))
        fluid.Momentum(learning_rate=0.05, momentum=0.9).minimize(loss)
    return main, startup, loss


def batch(r, n):
    ids = r.randint(0, VOCAB, (n, SEQ)).astype(np.int64)
    # learnable synthetic task: next token = (token + 1) mod vocab
    lbl = ((ids + 1) % VOCAB)[:, :, None]
    return {"ids": ids, "lbl": lbl}


def main():
    main_prog, startup, loss = build()
    pe = parallel.PipelineExecutor(
        main_prog, ["ids", "lbl"], [loss],
        mesh={"dp": DP, "pp": STAGES}, startup_program=startup,
        n_micro=2)
    r = np.random.RandomState(0)
    first = last = None
    for step in range(30):
        l, = pe.run(batch(r, 4 * DP))
        last = float(np.asarray(l).reshape(-1)[0])
        if first is None:
            first = last
        if step % 10 == 0:
            print(f"step {step:3d}  loss {last:.4f}")
    print(f"dp={DP} pp={STAGES}: {first:.4f} -> {last:.4f}")
    assert last < first, "pipelined training must reduce the loss"


if __name__ == "__main__":
    main()
