"""Train a decoder-only transformer LM with the TPU-first feature set
composed: bf16 amp, a rematerialized (jax.checkpoint) transformer body,
and data-parallel mesh execution.

Run (CPU demo, 8 virtual devices):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/transformer_lm.py

On a TPU pod slice, run one process per host with
`paddle_tpu.parallel.mesh.init_distributed()` (see tools/launch.py) and
the same script scales over ICI without changes.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# honor an explicit JAX_PLATFORMS=cpu even when a TPU-tunnel site hook
# force-set jax_platforms at interpreter boot (it overrides the env var)
if os.environ.get("JAX_PLATFORMS", "") == "cpu":
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import parallel
from paddle_tpu.models.transformer import transformer_lm

VOCAB, SEQ, BATCH, STEPS = 1000, 64, 32, 30


def build():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data(name="ids", shape=[SEQ], dtype="int64")
        nxt = fluid.layers.data(name="nxt", shape=[SEQ, 1], dtype="int64")
        # rematerialize the transformer body: its activations re-run in
        # backward instead of living in HBM (layers.recompute)
        probs = fluid.layers.recompute(
            lambda: transformer_lm(ids, VOCAB, d_model=128, n_heads=4,
                                   n_layers=2))
        probs2d = fluid.layers.reshape(probs, shape=[-1, VOCAB])
        lbl2d = fluid.layers.reshape(nxt, shape=[-1, 1])
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(input=probs2d, label=lbl2d))
        fluid.Adam(learning_rate=1e-3).minimize(loss)
    return main, startup, loss


def main():
    fluid.amp.enable_bf16()          # bf16 compute, f32 master weights
    main_prog, startup, loss = build()

    n = len(__import__("jax").devices())
    pe = parallel.ParallelExecutor(main_prog, ["ids", "nxt"], [loss],
                                   mesh={"dp": n},
                                   startup_program=startup)
    r = np.random.RandomState(0)
    # synthetic periodic data the model can actually learn
    base = np.arange(BATCH * SEQ).reshape(BATCH, SEQ) % 97
    for step in range(STEPS):
        ids = ((base + step) % 97).astype(np.int32)
        nxt = ((base + step + 1) % 97).astype(np.int32)[..., None]
        out, = pe.run({"ids": ids, "nxt": nxt})
        if step % 5 == 0:
            print(f"step {step:3d}  loss "
                  f"{np.asarray(out).reshape(-1)[0].item():.4f}")
    print("final loss", np.asarray(out).reshape(-1)[0].item())


if __name__ == "__main__":
    main()
