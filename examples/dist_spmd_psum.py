"""Multi-process SPMD worker: cross-process mesh + dp training step.

Run under tools/launch.py --coordinator mode (one process per "host"):
each process contributes its local CPU devices to one GLOBAL mesh, then

  1. a shard_map psum reduces across the process boundary (the DCN/ICI
     collective path the single-process virtual mesh cannot test), and
  2. a real paddle_tpu program (fit-a-line + SGD) trains one step with
     the batch sharded over the global dp axis — XLA inserts the
     cross-process grad psum — and the updated params are checked
     against a local numpy reference of the FULL global batch.

Exit code 0 on every process = pass (tests/test_multiprocess_spmd.py).
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # the device-tunnel site hook force-sets jax_platforms at boot; the
    # env var alone does not stick (see __graft_entry__.py)
    jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def main():
    from paddle_tpu.parallel import mesh as pmesh

    pmesh.init_distributed()
    nproc = jax.process_count()
    pid = jax.process_index()
    assert nproc >= 2, f"expected a multi-process run, got {nproc}"

    devs = np.array(jax.devices())
    n = devs.size
    mesh = Mesh(devs, ("dp",))

    # ---- 1. raw cross-process psum ---------------------------------------
    sharding = NamedSharding(mesh, P("dp"))
    gshape = (n, 4)

    def cb(idx):
        rows = np.arange(gshape[0], dtype=np.float32)[idx[0]]
        return rows.reshape(-1, 1) * np.ones((1, 4), np.float32)

    arr = jax.make_array_from_callback(gshape, sharding, cb)

    from jax.experimental.shard_map import shard_map

    summed = jax.jit(shard_map(
        lambda x: jax.lax.psum(x, "dp"), mesh=mesh,
        in_specs=P("dp"), out_specs=P("dp")))(arr)
    expect = float(sum(range(n)))
    for shard in summed.addressable_shards:
        np.testing.assert_allclose(np.asarray(shard.data), expect)
    print(f"[p{pid}] psum across {nproc} processes / {n} devices OK",
          flush=True)

    # ---- 2. dp-sharded train step of a real program -----------------------
    import paddle_tpu as fluid
    from paddle_tpu.core.executor import program_to_fn

    LR, BATCH, DIM = 0.1, 4 * n, 3
    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        x = fluid.layers.data(name="x", shape=[DIM], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(input=x, size=1,
                               param_attr=fluid.ParamAttr(name="w"),
                               bias_attr=fluid.ParamAttr(name="b"))
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y))
        fluid.SGD(learning_rate=LR).minimize(loss)

    fn = program_to_fn(main_p, ["x", "y"], [loss.name])
    scope = fluid.Scope()
    fluid.Executor(fluid.CPUPlace()).run(startup, scope=scope)
    states = {k: np.asarray(scope.find_var(k)) for k in fn.state_in_names}

    r = np.random.RandomState(0)  # same on every process
    xs = r.rand(BATCH, DIM).astype(np.float32)
    ys = (xs @ np.array([1.0, -2.0, 0.5], np.float32))[:, None]

    batch_shard = NamedSharding(mesh, P("dp"))
    repl = NamedSharding(mesh, P())
    feeds = {
        "x": jax.make_array_from_callback(
            xs.shape, batch_shard, lambda idx: xs[idx]),
        "y": jax.make_array_from_callback(
            ys.shape, batch_shard, lambda idx: ys[idx]),
    }
    dev_states = {k: jax.device_put(v, repl) for k, v in states.items()}

    step = jax.jit(fn, in_shardings=(
        {"x": batch_shard, "y": batch_shard},
        {k: repl for k in dev_states}, None))
    fetches, new_states = step(feeds, dev_states, jax.random.key(0))

    # numpy reference over the FULL global batch
    w = states["w"]
    b = states["b"]
    pred_np = xs @ w + b
    gw = 2 * xs.T @ (pred_np - ys) / BATCH
    gb = 2 * np.sum(pred_np - ys, axis=0) / BATCH
    np.testing.assert_allclose(
        np.asarray(jax.device_get(new_states["w"])), w - LR * gw,
        rtol=2e-5)
    np.testing.assert_allclose(
        np.asarray(jax.device_get(new_states["b"])), b - LR * gb,
        rtol=2e-5)
    print(f"[p{pid}] dp train step (global batch {BATCH}) matches the "
          "full-batch numpy reference OK", flush=True)


if __name__ == "__main__":
    main()
