"""Multi-process sharded-checkpoint worker (tests/test_multiprocess_spmd.py).

Launched by tools/launch.py --coordinator with N processes: trains a
dp-sharded classifier for STEPS_BEFORE steps on a GLOBAL device mesh
spanning the processes, then writes a sharded checkpoint — each process
saving only its addressable shards, process 0 publishing the
{uuid, md5, timestamp} meta (parallel/checkpoint.py; the reference
pserver's per-shard snapshot discipline, go/pserver/service.go:120-203).
The test then restores the snapshot in a SINGLE-process run on a
different mesh and checks the continued training matches the
uninterrupted serial oracle.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS", "") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import parallel

FEATS, CLS, HIDDEN = 16, 4, 32
STEPS_BEFORE = 5


def build():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[FEATS], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="int64")
        h = fluid.layers.fc(input=x, size=HIDDEN, act="relu")
        logits = fluid.layers.fc(input=h, size=CLS)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.Momentum(learning_rate=0.1, momentum=0.9).minimize(loss)
    return main, startup, loss


def batches(n):
    r = np.random.RandomState(17)
    return [(r.randn(32, FEATS).astype(np.float32),
             r.randint(0, CLS, (32, 1)).astype(np.int64))
            for _ in range(n)]


def main():
    ckpt_dir = sys.argv[1]
    parallel.init_distributed()
    n_dev = len(jax.devices())
    assert jax.process_count() > 1, "run via tools/launch.py --coordinator"
    main_p, startup, loss = build()
    pe = parallel.ParallelExecutor(
        main_p, ["x", "y"], [loss], mesh={"dp": n_dev},
        startup_program=startup, shard_optimizer_states=True)
    for x, y in batches(STEPS_BEFORE):
        out = pe.run({"x": x, "y": y})
    uuid = pe.save_checkpoint(ckpt_dir)
    print(f"proc {jax.process_index()}/{jax.process_count()}: trained "
          f"{STEPS_BEFORE} steps on dp-{n_dev}, saved shard of "
          f"checkpoint {uuid[:8]} OK, loss={float(np.asarray(out[0]))}")


if __name__ == "__main__":
    main()
