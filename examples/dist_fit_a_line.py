"""Distributed fit-a-line with the pserver transpiler (env-var roles).

Reference: tests/book_distribute/notest_dist_fit_a_line.py:43-78 — the
same program built on every node; PSERVERS / TRAINING_ROLE /
SERVER_ENDPOINT / PADDLE_INIT_TRAINER_ID (set by tools/launch.py) select
what each process runs.

    python tools/launch.py --pservers 2 --trainers 1 \
        examples/dist_fit_a_line.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import paddle_tpu as fluid


def main():
    role = os.environ["TRAINING_ROLE"]
    trainers = int(os.environ.get("PADDLE_INIT_NUM_GRADIENT_SERVERS", "1"))
    # static PSERVERS env OR TTL-lease discovery (launch.py --registry):
    # resolve_pserver_cluster registers this pserver / waits for the
    # cluster either way, returning an index-ordered endpoint list that
    # is identical on every process (the transpiler split is positional)
    from paddle_tpu.cloud.registry import resolve_pserver_cluster

    pservers, my_endpoint, lease = resolve_pserver_cluster()

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        x = fluid.layers.data(name="x", shape=[13], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(input=x, size=1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        opt_ops, params_grads = fluid.SGD(
            learning_rate=0.001).minimize(loss)

        t = fluid.DistributeTranspiler()
        t.transpile(optimize_ops=opt_ops, params_grads=params_grads,
                    trainers=trainers, pservers=pservers)

    exe = fluid.Executor(fluid.CPUPlace())
    if role == "PSERVER":
        endpoint = my_endpoint or os.environ["SERVER_ENDPOINT"]
        exe.run(t.get_startup_program(endpoint))
        exe.run(t.get_pserver_program(endpoint))  # serves until STOP
        if lease is not None:
            lease.release()
        return

    assert role == "TRAINER", role
    exe.run(startup)
    trainer_prog = t.get_trainer_program()
    rng = np.random.RandomState(0)
    w_true = rng.rand(13, 1).astype(np.float32)
    losses = []
    for step in range(30):
        xs = rng.rand(32, 13).astype(np.float32)
        ys = xs @ w_true
        lv, = exe.run(trainer_prog, feed={"x": xs, "y": ys},
                      fetch_list=[loss])
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    print(f"first loss {losses[0]:.5f} final loss {losses[-1]:.5f}")
    if not losses[-1] < losses[0]:
        raise SystemExit("loss did not decrease")
    # pserver shutdown is the LAUNCHER's job (it terminates pservers once
    # every trainer exits) — a trainer must never STOP the cluster itself,
    # or the fastest trainer would kill it under still-running peers


if __name__ == "__main__":
    main()
