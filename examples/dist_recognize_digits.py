"""Distributed recognize-digits (MLP) with the pserver transpiler.

Reference: tests/book_distribute/notest_dist_recognize_digits.py — the
same env-var role convention as dist_fit_a_line (PSERVERS /
TRAINING_ROLE / SERVER_ENDPOINT / PADDLE_INIT_TRAINER_ID, or TTL-lease
discovery under tools/launch.py --registry), with a real model on real
reader data: 784 -> 128 -> 64 -> softmax(10) over the mnist dataset
(real corpus when cached, synthetic fallback offline).

    python tools/launch.py --pservers 2 --trainers 2 \
        examples/dist_recognize_digits.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import paddle_tpu as fluid
from paddle_tpu import dataset, reader


def main():
    role = os.environ["TRAINING_ROLE"]
    trainers = int(os.environ.get("PADDLE_INIT_NUM_GRADIENT_SERVERS", "1"))
    from paddle_tpu.cloud.registry import resolve_pserver_cluster

    pservers, my_endpoint, lease = resolve_pserver_cluster()

    main_prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_prog, startup):
        img = fluid.layers.data(name="img", shape=[784], dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        h1 = fluid.layers.fc(input=img, size=128, act="relu")
        h2 = fluid.layers.fc(input=h1, size=64, act="relu")
        pred = fluid.layers.fc(input=h2, size=10, act="softmax")
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(input=pred, label=label))
        acc = fluid.layers.accuracy(input=pred, label=label)
        opt_ops, params_grads = fluid.Momentum(
            learning_rate=0.05, momentum=0.9).minimize(loss)

        t = fluid.DistributeTranspiler()
        t.transpile(optimize_ops=opt_ops, params_grads=params_grads,
                    trainers=trainers, pservers=pservers)

    exe = fluid.Executor(fluid.CPUPlace())
    if role == "PSERVER":
        endpoint = my_endpoint or os.environ["SERVER_ENDPOINT"]
        exe.run(t.get_startup_program(endpoint))
        exe.run(t.get_pserver_program(endpoint))  # serves until STOP
        if lease is not None:
            lease.release()
        return

    assert role == "TRAINER", role
    exe.run(startup)
    trainer_prog = t.get_trainer_program()
    batches = reader.batch(reader.shuffle(dataset.mnist.train(), 512),
                           batch_size=64, drop_last=True)
    accs = []
    losses = []
    for i, batch in enumerate(batches()):
        imgs = np.stack([s[0] for s in batch]).astype(np.float32)
        lbls = np.asarray([s[1] for s in batch], np.int64)[:, None]
        lv, av = exe.run(trainer_prog,
                         feed={"img": imgs, "label": lbls},
                         fetch_list=[loss, acc])
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
        accs.append(float(np.asarray(av).reshape(-1)[0]))
        if i >= 29:
            break
    first, last = np.mean(accs[:5]), np.mean(accs[-5:])
    print(f"acc {first:.3f} -> {last:.3f}  loss {losses[0]:.3f} -> "
          f"{losses[-1]:.3f}")
    if not (last > first or losses[-1] < losses[0]):
        raise SystemExit("did not learn")


if __name__ == "__main__":
    main()
