"""Autodiff by op-desc rewriting.

Reference: /root/reference/python/paddle/v2/fluid/backward.py —
`append_backward` (:338) walks the op list backwards, asks each op's
GradOpMaker for grad op descs, inserts `sum` ops where a forward var fans out
to several consumers (`_addup_repetitive_outputs_` :116) and prunes
no-grad branches (:166).

This implementation keeps that IR-level architecture (grad ops ARE ops in the
program, so transpilers/optimizers can rewrite them) but the default grad op
is the *generic VJP op* executed by core/execution.generic_grad_lower — no
per-op grad kernels needed.  Ops may still register custom grad makers
(registry.register_grad_maker) for cases where the VJP is wrong or wasteful
(dropout mask reuse, sparse lookup_table grads, control flow).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set

from .core import registry
from .core.framework import (
    EMPTY_VAR_NAMES,
    GRAD_SUFFIX,
    Parameter,
    Program,
    Variable,
    grad_var_name,
    unique_name,
)
from .core.types import is_float_dtype

__all__ = ["append_backward", "calc_gradient", "gradients"]


def _op_info(op):
    try:
        return registry.get_op_info(op.type)
    except KeyError:
        return None


def _relevant_ops(block, target_names: Set[str], stop_names: Set[str]):
    """Reverse reachability: indices of ops contributing to targets."""
    needed = set(target_names)
    relevant = []
    for i in reversed(range(len(block.ops))):
        op = block.ops[i]
        info = _op_info(op)
        outs = set(op.output_names())
        if not (outs & needed):
            continue
        if info is None or info.not_differentiable:
            continue
        relevant.append(i)
        for n in op.input_names():
            if n not in stop_names:
                needed.add(n)
    relevant.reverse()
    return relevant


def _var_needs_grad(block, name, no_grad: Set[str]) -> bool:
    if name in EMPTY_VAR_NAMES or name in no_grad:
        return False
    try:
        v = block.var(name)
    except KeyError:
        return False
    if v.stop_gradient:
        return False
    if v.dtype is not None and not is_float_dtype(v.dtype):
        return False
    return True


def _default_grad_op(op, block, out_grad_names: Dict[str, str],
                     no_grad: Set[str], partials: Dict[str, List[str]]):
    """Build the generic '<type>_grad' op desc for `op`.

    Grad-op I/O convention (consumed by generic_grad_lower):
      inputs  = forward input slots + forward output slots
                + '<out_slot>@GRAD' per differentiable output
      outputs = '<in_slot>@GRAD' per differentiable input, var names are
                partial-grad names registered into `partials`.
    """
    info = _op_info(op)
    g_inputs = {}
    for slot, names in op.inputs.items():
        g_inputs[slot] = list(names)
    for slot, names in op.outputs.items():
        g_inputs.setdefault(slot, list(names))
    # output cotangents
    diff_outs = (info.diff_outputs if info.diff_outputs is not None
                 else list(op.outputs.keys()))
    for slot in diff_outs:
        names = op.outputs.get(slot, [])
        if not names:
            continue
        g_names = []
        for n in names:
            gn = out_grad_names.get(n)
            if gn is None:
                # output with no path to the loss: zero cotangent
                gn = unique_name(grad_var_name(n) + "@ZERO")
                gv = block.create_var(name=gn, dtype=None)
                fv = block.var(n)
                gv.shape, gv.dtype = fv.shape, fv.dtype
                block.append_op("fill_zeros_like", {"X": [n]}, {"Out": [gn]})
            g_names.append(gn)
        g_inputs[slot + GRAD_SUFFIX] = g_names
    # input grads
    diff_ins = (info.diff_inputs if info.diff_inputs is not None
                else list(op.inputs.keys()))
    g_outputs = {}
    any_grad = False
    for slot in diff_ins:
        names = op.inputs.get(slot, [])
        if not names:
            continue
        out_names = []
        for n in names:
            if not _var_needs_grad(block, n, no_grad):
                out_names.append("@EMPTY@")
                continue
            plist = partials.setdefault(n, [])
            gn = (grad_var_name(n) if not plist
                  else unique_name(grad_var_name(n) + "@RENAME"))
            plist.append(gn)
            out_names.append(gn)
            any_grad = True
        g_outputs[slot + GRAD_SUFFIX] = out_names
    if not any_grad:
        return None
    grad_op = block.append_op(op.type + "_grad", g_inputs, g_outputs,
                              dict(op.attrs))
    # per-grad-op error clipping hook (reference backward.py invokes
    # error_clip_callback for every created grad op)
    from .clip import error_clip_callback

    error_clip_callback(block, grad_op)
    return True


def _resolve_total_grad(block, name, partials: Dict[str, List[str]]):
    """Collapse partial grads of `name` into one var (sum-insertion)."""
    plist = partials.get(name)
    if not plist:
        return None
    if len(plist) == 1:
        return plist[0]
    total = grad_var_name(name)
    if total in plist:
        # keep canonical name as the sum target; partials keep their renames
        out = unique_name(total + "@SUM")
    else:
        out = total
    block.append_op("sum", {"X": list(plist)}, {"Out": [out]})
    partials[name] = [out]
    return out


def append_backward(
    loss: Variable,
    parameter_list: Optional[Sequence[str]] = None,
    no_grad_set: Optional[Set[str]] = None,
):
    """Append grad ops for `loss` to its program; returns [(param, grad_var)]
    like reference backward.py:338."""
    block = loss.block
    program = block.program
    no_grad = set(no_grad_set or ())
    for v in program.list_vars():
        if v.stop_gradient:
            no_grad.add(v.name)

    relevant = _relevant_ops(block, {loss.name}, no_grad)

    # d(loss)/d(loss) = 1
    loss_grad = grad_var_name(loss.name)
    gv = block.create_var(name=loss_grad, dtype=loss.dtype)
    gv.shape = loss.shape
    block.append_op(
        "fill_constant",
        {},
        {"Out": [loss_grad]},
        {"shape": list(loss.shape or [1]), "value": 1.0,
         "dtype": loss.dtype or "float32"},
    )
    partials: Dict[str, List[str]] = {loss.name: [loss_grad]}

    for i in reversed(relevant):
        op = block.ops[i]
        info = _op_info(op)
        # total grads for this op's outputs
        out_grad_names = {}
        have_any = False
        for n in op.output_names():
            g = _resolve_total_grad(block, n, partials)
            if g is not None:
                out_grad_names[n] = g
                have_any = True
        if not have_any:
            continue
        if info.grad_maker is not None:
            info.grad_maker(op, block, out_grad_names, no_grad, partials)
        else:
            _default_grad_op(op, block, out_grad_names, no_grad, partials)

    # finalize parameter grads
    params_grads = []
    if parameter_list is not None:
        params = [block.var(p) if isinstance(p, str) else p
                  for p in parameter_list]
    else:
        params = [v for v in program.global_block().all_parameters()
                  if v.trainable]
    for p in params:
        g = _resolve_total_grad(block, p.name, partials)
        if g is None:
            continue
        gvar = block.var(g)
        if gvar.shape is None:
            gvar.shape, gvar.dtype = p.shape, p.dtype
        params_grads.append((p, gvar))
    return params_grads


def calc_gradient(targets, inputs, target_gradients=None, no_grad_set=None):
    """Gradients of `targets` wrt `inputs` (reference backward.py:464)."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    block = targets[0].block
    no_grad = set(no_grad_set or ())
    for v in block.program.list_vars():
        if v.stop_gradient:
            no_grad.add(v.name)
    relevant = _relevant_ops(block, {t.name for t in targets}, no_grad)

    partials: Dict[str, List[str]] = {}
    if target_gradients is None:
        target_gradients = [None] * len(targets)
    for t, tg in zip(targets, target_gradients):
        gname = grad_var_name(t.name)
        if tg is None:
            gv = block.create_var(name=gname, dtype=t.dtype)
            gv.shape = t.shape
            block.append_op(
                "fill_constant", {}, {"Out": [gname]},
                {"shape": list(t.shape or [1]), "value": 1.0,
                 "dtype": t.dtype or "float32"})
        else:
            block.append_op("assign", {"X": [tg.name]}, {"Out": [gname]})
        partials[t.name] = [gname]

    for i in reversed(relevant):
        op = block.ops[i]
        info = _op_info(op)
        out_grad_names = {}
        have_any = False
        for n in op.output_names():
            g = _resolve_total_grad(block, n, partials)
            if g is not None:
                out_grad_names[n] = g
                have_any = True
        if not have_any:
            continue
        if info.grad_maker is not None:
            info.grad_maker(op, block, out_grad_names, no_grad, partials)
        else:
            _default_grad_op(op, block, out_grad_names, no_grad, partials)

    outs = []
    for x in inputs:
        g = _resolve_total_grad(block, x.name, partials)
        outs.append(block.var(g) if g is not None else None)
    return outs


gradients = calc_gradient
