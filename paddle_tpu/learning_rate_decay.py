"""Learning-rate decay schedules built as ops in the program.

Reference: /root/reference/python/paddle/v2/fluid/learning_rate_decay.py:1-241
(exponential_decay, natural_exp_decay, inverse_time_decay, polynomial_decay,
piecewise_decay) — schedules are graph ops over a global step counter, so the
whole training step (including the LR math) stays inside one compiled XLA
executable; pass the returned variable as `learning_rate=` to an optimizer.
"""
from __future__ import annotations

from . import layers

__all__ = [
    "exponential_decay",
    "natural_exp_decay",
    "inverse_time_decay",
    "polynomial_decay",
    "piecewise_decay",
]


def float_global_step(global_step):
    return layers.cast(global_step, "float32")


def exponential_decay(learning_rate, global_step, decay_steps, decay_rate,
                      staircase=False):
    """lr * decay_rate ^ (global_step / decay_steps)"""
    step = float_global_step(global_step)
    div = layers.scale(step, scale=1.0 / decay_steps)
    if staircase:
        div = layers.floor(div)
    return layers.scale(
        layers.elementwise_pow(
            layers.fill_constant(shape=[1], dtype="float32",
                                 value=float(decay_rate)), div),
        scale=float(learning_rate))


def natural_exp_decay(learning_rate, global_step, decay_steps, decay_rate,
                      staircase=False):
    """lr * exp(-decay_rate * global_step / decay_steps)"""
    step = float_global_step(global_step)
    div = layers.scale(step, scale=1.0 / decay_steps)
    if staircase:
        div = layers.floor(div)
    return layers.scale(layers.exp(layers.scale(div, scale=-decay_rate)),
                        scale=float(learning_rate))


def inverse_time_decay(learning_rate, global_step, decay_steps, decay_rate,
                       staircase=False):
    """lr / (1 + decay_rate * global_step / decay_steps)"""
    step = float_global_step(global_step)
    div = layers.scale(step, scale=1.0 / decay_steps)
    if staircase:
        div = layers.floor(div)
    denom = layers.scale(div, scale=float(decay_rate), bias=1.0)
    return layers.scale(layers.reciprocal(denom), scale=float(learning_rate))


def polynomial_decay(learning_rate, global_step, decay_steps,
                     end_learning_rate=0.0001, power=1.0, cycle=False):
    """(lr - end_lr) * (1 - step/decay_steps)^power + end_lr"""
    step = float_global_step(global_step)
    if cycle:
        div = layers.ceil(layers.scale(step, scale=1.0 / decay_steps))
        # step == 0 -> div = 1 (reference zero_var/one_var dance)
        one = layers.fill_constant(shape=[1], dtype="float32", value=1.0)
        div = layers.elementwise_max(div, one)
        decay_steps_var = layers.scale(div, scale=float(decay_steps))
        frac = layers.elementwise_div(step, decay_steps_var)
    else:
        capped = layers.elementwise_min(
            step, layers.fill_constant(shape=[1], dtype="float32",
                                       value=float(decay_steps)))
        frac = layers.scale(capped, scale=1.0 / decay_steps)
    base = layers.scale(frac, scale=-1.0, bias=1.0)  # 1 - frac
    powed = layers.elementwise_pow(
        base, layers.fill_constant(shape=[1], dtype="float32",
                                   value=float(power)))
    return layers.scale(powed, scale=float(learning_rate - end_learning_rate),
                        bias=float(end_learning_rate))


def piecewise_decay(global_step, boundaries, values):
    """Step-function schedule (reference piecewise_decay): values[i] while
    global_step < boundaries[i], values[-1] after the last boundary."""
    assert len(values) == len(boundaries) + 1
    step = float_global_step(global_step)
    lr = layers.fill_constant(shape=[1], dtype="float32",
                              value=float(values[-1]))
    # build from the last interval backwards with where-style selects
    for b, v in zip(reversed(boundaries), reversed(values[:-1])):
        below = layers.cast(
            layers.less_than(
                step, layers.fill_constant(shape=[1], dtype="float32",
                                           value=float(b))),
            "float32")
        v_var = layers.fill_constant(shape=[1], dtype="float32",
                                     value=float(v))
        lr = layers.elementwise_add(
            layers.elementwise_mul(below, v_var),
            layers.elementwise_mul(
                layers.scale(below, scale=-1.0, bias=1.0), lr))
    return lr
