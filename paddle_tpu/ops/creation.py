"""Tensor creation / casting / random ops.

Reference kernels: /root/reference/paddle/fluid/operators/fill_constant_op.cc,
fill_constant_batch_size_like_op.cc, fill_zeros_like_op.cc, assign_op.cc,
assign_value_op.cc, cast_op.cc, uniform_random_op.cc, gaussian_random_op.cc,
increment_op.cc, one_hot_op.cc, shape-less host RNG replaced by jax PRNG keys
threaded through ExecContext (deterministic per op occurrence).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.execution import data_of, one
from ..core.registry import register_op
from ..core.types import np_dtype


def _host_seed(ctx, attrs) -> int:
    """Seed for the force_cpu numpy RNG path: a seed=0 attr means "fresh
    per op", so fold the (unique) output var name — otherwise every
    unseeded init would draw an identical stream and all same-shape
    params would come out bit-identical."""
    import zlib

    explicit = attrs.get("seed") or 0
    if explicit:
        return int(explicit)
    name = ctx.op.output("Out")[0]
    return zlib.crc32(name.encode()) & 0x7FFFFFFF


@register_op("fill_constant", inputs=(), outputs=("Out",),
             attrs={"shape": [1], "value": 0.0, "dtype": "float32",
                    "force_cpu": False},
             not_differentiable=True)
def fill_constant(ctx, ins, attrs):
    dt = np_dtype(attrs["dtype"])
    if attrs.get("force_cpu"):
        # init_on_cpu(): materialize in host memory (numpy); the value
        # moves to device only when a consumer needs it
        return {"Out": np.full(tuple(attrs["shape"]), attrs["value"],
                               dtype=dt)}
    return {"Out": jnp.full(tuple(attrs["shape"]), attrs["value"], dtype=dt)}


@register_op("fill_constant_batch_size_like", inputs=("Input",),
             outputs=("Out",),
             attrs={"shape": [1], "value": 0.0, "dtype": "float32",
                    "input_dim_idx": 0, "output_dim_idx": 0},
             not_differentiable=True)
def fill_constant_batch_size_like(ctx, ins, attrs):
    x = data_of(one(ins, "Input"))
    shape = list(attrs["shape"])
    shape[attrs["output_dim_idx"]] = x.shape[attrs["input_dim_idx"]]
    return {"Out": jnp.full(tuple(shape), attrs["value"],
                            dtype=np_dtype(attrs["dtype"]))}


@register_op("fill_zeros_like", inputs=("X",), outputs=("Out",),
             not_differentiable=True)
def fill_zeros_like(ctx, ins, attrs):
    x = one(ins, "X")
    return {"Out": jax.tree_util.tree_map(jnp.zeros_like, x)}


@register_op("assign", inputs=("X",), outputs=("Out",))
def assign(ctx, ins, attrs):
    return {"Out": one(ins, "X")}


@register_op("assign_value", inputs=(), outputs=("Out",),
             attrs={"shape": [1], "dtype": "float32", "values": []},
             not_differentiable=True)
def assign_value(ctx, ins, attrs):
    dt = np_dtype(attrs["dtype"])
    arr = np.asarray(attrs["values"], dtype=dt).reshape(tuple(attrs["shape"]))
    return {"Out": jnp.asarray(arr)}


@register_op("cast", inputs=("X",), outputs=("Out",),
             attrs={"out_dtype": "float32"})
def cast(ctx, ins, attrs):
    x = data_of(one(ins, "X"))
    return {"Out": x.astype(np_dtype(attrs["out_dtype"]))}


@register_op("increment", inputs=("X",), outputs=("Out",),
             attrs={"step": 1.0}, inplace={"Out": "X"})
def increment(ctx, ins, attrs):
    x = data_of(one(ins, "X"))
    return {"Out": x + jnp.asarray(attrs["step"], x.dtype)}


@register_op("uniform_random", inputs=(), outputs=("Out",),
             attrs={"shape": [1], "min": -1.0, "max": 1.0, "seed": 0,
                    "dtype": "float32", "force_cpu": False},
             random=True, not_differentiable=True)
def uniform_random(ctx, ins, attrs):
    dt = np_dtype(attrs["dtype"])
    if attrs.get("force_cpu"):
        # init_on_cpu(): host numpy RNG — keeps huge inits out of device
        # memory; the stream differs from the jax PRNG path
        rng = np.random.RandomState(_host_seed(ctx, attrs))
        return {"Out": rng.uniform(attrs["min"], attrs["max"],
                                   tuple(attrs["shape"])).astype(dt)}
    key = (jax.random.key(attrs["seed"]) if attrs.get("seed") else ctx.rng())
    return {"Out": jax.random.uniform(
        key, tuple(attrs["shape"]), dtype=jnp.float32,
        minval=attrs["min"], maxval=attrs["max"]).astype(dt)}


@register_op("gaussian_random", inputs=(), outputs=("Out",),
             attrs={"shape": [1], "mean": 0.0, "std": 1.0, "seed": 0,
                    "dtype": "float32", "force_cpu": False},
             random=True, not_differentiable=True)
def gaussian_random(ctx, ins, attrs):
    dt = np_dtype(attrs["dtype"])
    if attrs.get("force_cpu"):
        rng = np.random.RandomState(_host_seed(ctx, attrs))
        return {"Out": (rng.standard_normal(tuple(attrs["shape"]))
                        * attrs["std"] + attrs["mean"]).astype(dt)}
    key = (jax.random.key(attrs["seed"]) if attrs.get("seed") else ctx.rng())
    sample = jax.random.normal(key, tuple(attrs["shape"]), dtype=jnp.float32)
    return {"Out": (sample * attrs["std"] + attrs["mean"]).astype(dt)}


@register_op("uniform_random_batch_size_like", inputs=("Input",),
             outputs=("Out",),
             attrs={"shape": [1], "min": -1.0, "max": 1.0, "seed": 0,
                    "dtype": "float32", "input_dim_idx": 0,
                    "output_dim_idx": 0},
             random=True, not_differentiable=True)
def uniform_random_batch_size_like(ctx, ins, attrs):
    x = data_of(one(ins, "Input"))
    shape = list(attrs["shape"])
    shape[attrs["output_dim_idx"]] = x.shape[attrs["input_dim_idx"]]
    key = (jax.random.key(attrs["seed"]) if attrs.get("seed") else ctx.rng())
    return {"Out": jax.random.uniform(
        key, tuple(shape), dtype=jnp.float32,
        minval=attrs["min"], maxval=attrs["max"]
    ).astype(np_dtype(attrs["dtype"]))}


@register_op("one_hot", inputs=("X",), outputs=("Out",),
             attrs={"depth": 1, "dtype": "float32"},
             not_differentiable=True)
def one_hot(ctx, ins, attrs):
    x = data_of(one(ins, "X"))
    if x.ndim > 1 and x.shape[-1] == 1:
        x = x.squeeze(-1)
    return {"Out": jax.nn.one_hot(
        x, attrs["depth"], dtype=np_dtype(attrs["dtype"]))}


@register_op("shape", inputs=("Input",), outputs=("Out",),
             not_differentiable=True)
def shape_op(ctx, ins, attrs):
    x = data_of(one(ins, "Input"))
    return {"Out": jnp.asarray(x.shape, dtype=jnp.int64)}


@register_op("isfinite", inputs=("X",), outputs=("Out",),
             not_differentiable=True)
def isfinite(ctx, ins, attrs):
    xs = [data_of(v) for v in ins.get("X", []) if v is not None]
    ok = jnp.asarray(True)
    for x in xs:
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(x)))
    return {"Out": ok}
