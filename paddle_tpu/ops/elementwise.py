"""Elementwise binary ops with the reference's axis-broadcast semantics,
plus scale / sum / clip.

Reference: /root/reference/paddle/fluid/operators/elementwise_op_function.h —
Y's shape must be a contiguous sub-sequence of X's shape starting at `axis`
(axis == -1 means trailing alignment).  On XLA this is a reshape to a
broadcast-compatible rank followed by the fused elementwise op.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.execution import data_of, many, one, with_lod_of
from ..core.lod import SelectedRows
from ..core.registry import register_op


def _broadcast_y(x, y, axis):
    if x.shape == y.shape:
        return y
    axis = int(axis)
    if axis == -1:
        axis = x.ndim - y.ndim
    # trim trailing 1s of y (reference allows y shape (n,1) against axis dim n)
    yshape = list(y.shape)
    while yshape and yshape[-1] == 1 and len(yshape) > 1:
        yshape = yshape[:-1]
    target = [1] * axis + yshape + [1] * (x.ndim - axis - len(yshape))
    return y.reshape(target)


def _make_elementwise(name, fn):
    @register_op(name, inputs=("X", "Y"), outputs=("Out",),
                 attrs={"axis": -1})
    def lower(ctx, ins, attrs, _fn=fn):
        xv, yv = one(ins, "X"), one(ins, "Y")
        x, y = data_of(xv), data_of(yv)
        if _amp_mixed(x, y):
            # under amp, a bf16 activation meeting an f32 side (bias,
            # residual) computes in bf16 — keeps the activation chain in
            # bf16 instead of silently promoting back to f32
            x, y = x.astype(jnp.bfloat16), y.astype(jnp.bfloat16)
        out = _fn(x, _broadcast_y(x, y, attrs.get("axis", -1)))
        return {"Out": with_lod_of(xv, out)}

    return lower


def _amp_mixed(x, y) -> bool:
    from ..amp import is_bf16_enabled
    if not is_bf16_enabled():
        return False
    dts = {getattr(x, "dtype", None), getattr(y, "dtype", None)}
    return dts == {jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float32)}


_make_elementwise("elementwise_add", jnp.add)
_make_elementwise("elementwise_sub", jnp.subtract)
_make_elementwise("elementwise_mul", jnp.multiply)
_make_elementwise("elementwise_div", jnp.divide)
_make_elementwise("elementwise_max", jnp.maximum)
_make_elementwise("elementwise_min", jnp.minimum)
_make_elementwise("elementwise_pow", jnp.power)


@register_op("scale", inputs=("X",), outputs=("Out",),
             attrs={"scale": 1.0, "bias": 0.0, "bias_after_scale": True})
def scale(ctx, ins, attrs):
    xv = one(ins, "X")
    x = data_of(xv)
    s = jnp.asarray(attrs["scale"], x.dtype)
    b = jnp.asarray(attrs.get("bias", 0.0), x.dtype)
    if attrs.get("bias_after_scale", True):
        out = x * s + b
    else:
        out = (x + b) * s
    return {"Out": with_lod_of(xv, out)}


@register_op("clip", inputs=("X",), outputs=("Out",),
             attrs={"min": -1.0, "max": 1.0},
             inplace={"Out": "X"})
def clip(ctx, ins, attrs):
    xv = one(ins, "X")
    out = jnp.clip(data_of(xv), attrs["min"], attrs["max"])
    return {"Out": with_lod_of(xv, out)}


@register_op("clip_by_norm", inputs=("X",), outputs=("Out",),
             attrs={"max_norm": 1.0})
def clip_by_norm(ctx, ins, attrs):
    xv = one(ins, "X")
    x = data_of(xv)
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    factor = jnp.where(norm > attrs["max_norm"],
                       attrs["max_norm"] / jnp.maximum(norm, 1e-12), 1.0)
    return {"Out": with_lod_of(xv, x * factor.astype(x.dtype))}


@register_op("sum", inputs=("X",), outputs=("Out",),
             dup_inputs=("X",))
def sum_op(ctx, ins, attrs):
    """Fan-in accumulator.  Handles dense + SelectedRows mixtures exactly as
    the reference sum_op / math/selected_rows_functor do: all-sparse in,
    sparse out (rows concatenated); any dense in, dense out."""
    xs = [v for v in many(ins, "X") if v is not None]
    if not xs:
        return {"Out": None}
    sparse = [v for v in xs if isinstance(v, SelectedRows)]
    if len(sparse) == len(xs):
        rows = jnp.concatenate([s.rows for s in sparse])
        vals = jnp.concatenate([s.value for s in sparse])
        return {"Out": SelectedRows(rows, vals, sparse[0].height)}
    acc = None
    for v in xs:
        d = v.to_dense() if isinstance(v, SelectedRows) else data_of(v)
        acc = d if acc is None else acc + d
    first = next((v for v in xs if not isinstance(v, SelectedRows)), None)
    return {"Out": with_lod_of(first, acc) if first is not None else acc}


def _make_compare(name, fn):
    @register_op(name, inputs=("X", "Y"), outputs=("Out",),
                 attrs={"axis": -1}, not_differentiable=True)
    def lower(ctx, ins, attrs, _fn=fn):
        x, y = data_of(one(ins, "X")), data_of(one(ins, "Y"))
        return {"Out": _fn(x, _broadcast_y(x, y, attrs.get("axis", -1)))}

    return lower


_make_compare("less_than", jnp.less)
_make_compare("less_equal", jnp.less_equal)
_make_compare("greater_than", jnp.greater)
_make_compare("greater_equal", jnp.greater_equal)
_make_compare("equal", jnp.equal)
_make_compare("not_equal", jnp.not_equal)


def _make_logical(name, fn, unary=False):
    ins_slots = ("X",) if unary else ("X", "Y")

    @register_op(name, inputs=ins_slots, outputs=("Out",),
                 not_differentiable=True)
    def lower(ctx, ins, attrs, _fn=fn, _unary=unary):
        x = data_of(one(ins, "X"))
        if _unary:
            return {"Out": _fn(x)}
        return {"Out": _fn(x, data_of(one(ins, "Y")))}

    return lower


_make_logical("logical_and", jnp.logical_and)
_make_logical("logical_or", jnp.logical_or)
_make_logical("logical_xor", jnp.logical_xor)
_make_logical("logical_not", jnp.logical_not, unary=True)
