"""Optimizer update ops — optimizers-as-ops, exactly the reference scheme
(python optimizer.py appends these to the program).

Reference kernels: /root/reference/paddle/fluid/operators/{sgd,momentum,adam,
adamax,adagrad,adadelta,decayed_adagrad,rmsprop,ftrl,proximal_gd,
proximal_adagrad}_op.cc.  All write Param/accumulators in place
(ParamOut aliases Param); the compiled executor donates these buffers so the
update is in-place at the XLA level too.

Sparse (SelectedRows) gradients take the scatter path on sgd/adam/adagrad/
momentum, mirroring the reference's SelectedRows kernels.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.execution import data_of, one
from ..core.lod import SelectedRows
from ..core.registry import register_op


def _lr(ins):
    return data_of(one(ins, "LearningRate")).reshape(()).astype(jnp.float32)


def _dense_grad(g):
    return g.to_dense() if isinstance(g, SelectedRows) else data_of(g)


@register_op("sgd", inputs=("Param", "Grad", "LearningRate"),
             outputs=("ParamOut",), inplace={"ParamOut": "Param"},
             not_differentiable=True)
def sgd(ctx, ins, attrs):
    p = data_of(one(ins, "Param"))
    g = one(ins, "Grad")
    lr = _lr(ins).astype(p.dtype)
    if isinstance(g, SelectedRows):
        return {"ParamOut": p.at[g.rows].add(-lr * g.value)}
    return {"ParamOut": p - lr * data_of(g)}


@register_op("momentum",
             inputs=("Param", "Grad", "Velocity", "LearningRate"),
             outputs=("ParamOut", "VelocityOut"),
             attrs={"mu": 0.9, "use_nesterov": False},
             inplace={"ParamOut": "Param", "VelocityOut": "Velocity"},
             not_differentiable=True)
def momentum(ctx, ins, attrs):
    p = data_of(one(ins, "Param"))
    g = _dense_grad(one(ins, "Grad"))
    v = data_of(one(ins, "Velocity"))
    lr = _lr(ins).astype(p.dtype)
    mu = jnp.asarray(attrs["mu"], p.dtype)
    v_out = mu * v + g
    if attrs.get("use_nesterov"):
        p_out = p - (g + mu * v_out) * lr
    else:
        p_out = p - lr * v_out
    return {"ParamOut": p_out, "VelocityOut": v_out}


@register_op("adam",
             inputs=("Param", "Grad", "Moment1", "Moment2", "LearningRate",
                     "Beta1Pow", "Beta2Pow"),
             outputs=("ParamOut", "Moment1Out", "Moment2Out"),
             attrs={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8},
             inplace={"ParamOut": "Param", "Moment1Out": "Moment1",
                      "Moment2Out": "Moment2"},
             not_differentiable=True)
def adam(ctx, ins, attrs):
    p = data_of(one(ins, "Param"))
    m1 = data_of(one(ins, "Moment1"))
    m2 = data_of(one(ins, "Moment2"))
    b1p = data_of(one(ins, "Beta1Pow")).reshape(()).astype(p.dtype)
    b2p = data_of(one(ins, "Beta2Pow")).reshape(()).astype(p.dtype)
    lr = _lr(ins).astype(p.dtype)
    b1 = jnp.asarray(attrs["beta1"], p.dtype)
    b2 = jnp.asarray(attrs["beta2"], p.dtype)
    eps = jnp.asarray(attrs["epsilon"], p.dtype)
    # SelectedRows grads densify first (duplicate-row-safe; XLA scatter-add);
    # the dense-decay numerics match the reference's dense adam kernel.
    g = _dense_grad(one(ins, "Grad"))
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    m1_out = b1 * m1 + (1 - b1) * g
    m2_out = b2 * m2 + (1 - b2) * jnp.square(g)
    p_out = p - lr_t * m1_out / (jnp.sqrt(m2_out) + eps)
    return {"ParamOut": p_out, "Moment1Out": m1_out, "Moment2Out": m2_out}


@register_op("adamax",
             inputs=("Param", "Grad", "Moment", "InfNorm", "LearningRate",
                     "Beta1Pow"),
             outputs=("ParamOut", "MomentOut", "InfNormOut"),
             attrs={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8},
             inplace={"ParamOut": "Param", "MomentOut": "Moment",
                      "InfNormOut": "InfNorm"},
             not_differentiable=True)
def adamax(ctx, ins, attrs):
    p = data_of(one(ins, "Param"))
    g = _dense_grad(one(ins, "Grad"))
    m = data_of(one(ins, "Moment"))
    inf = data_of(one(ins, "InfNorm"))
    b1p = data_of(one(ins, "Beta1Pow")).reshape(()).astype(p.dtype)
    lr = _lr(ins).astype(p.dtype)
    b1 = jnp.asarray(attrs["beta1"], p.dtype)
    b2 = jnp.asarray(attrs["beta2"], p.dtype)
    eps = jnp.asarray(attrs["epsilon"], p.dtype)
    m_out = b1 * m + (1 - b1) * g
    inf_out = jnp.maximum(b2 * inf, jnp.abs(g) + eps)
    p_out = p - (lr / (1 - b1p)) * (m_out / inf_out)
    return {"ParamOut": p_out, "MomentOut": m_out, "InfNormOut": inf_out}


@register_op("adagrad",
             inputs=("Param", "Grad", "Moment", "LearningRate"),
             outputs=("ParamOut", "MomentOut"),
             attrs={"epsilon": 1e-6},
             inplace={"ParamOut": "Param", "MomentOut": "Moment"},
             not_differentiable=True)
def adagrad(ctx, ins, attrs):
    p = data_of(one(ins, "Param"))
    m = data_of(one(ins, "Moment"))
    lr = _lr(ins).astype(p.dtype)
    eps = jnp.asarray(attrs["epsilon"], p.dtype)
    g = _dense_grad(one(ins, "Grad"))
    m_out = m + jnp.square(g)
    p_out = p - lr * g / (jnp.sqrt(m_out) + eps)
    return {"ParamOut": p_out, "MomentOut": m_out}


@register_op("adadelta",
             inputs=("Param", "Grad", "AvgSquaredGrad", "AvgSquaredUpdate"),
             outputs=("ParamOut", "AvgSquaredGradOut", "AvgSquaredUpdateOut"),
             attrs={"rho": 0.95, "epsilon": 1e-6},
             inplace={"ParamOut": "Param",
                      "AvgSquaredGradOut": "AvgSquaredGrad",
                      "AvgSquaredUpdateOut": "AvgSquaredUpdate"},
             not_differentiable=True)
def adadelta(ctx, ins, attrs):
    p = data_of(one(ins, "Param"))
    g = _dense_grad(one(ins, "Grad"))
    asg = data_of(one(ins, "AvgSquaredGrad"))
    asu = data_of(one(ins, "AvgSquaredUpdate"))
    rho = jnp.asarray(attrs["rho"], p.dtype)
    eps = jnp.asarray(attrs["epsilon"], p.dtype)
    asg_out = rho * asg + (1 - rho) * jnp.square(g)
    update = -jnp.sqrt((asu + eps) / (asg_out + eps)) * g
    asu_out = rho * asu + (1 - rho) * jnp.square(update)
    return {"ParamOut": p + update, "AvgSquaredGradOut": asg_out,
            "AvgSquaredUpdateOut": asu_out}


@register_op("decayed_adagrad",
             inputs=("Param", "Grad", "Moment", "LearningRate"),
             outputs=("ParamOut", "MomentOut"),
             attrs={"decay": 0.95, "epsilon": 1e-6},
             inplace={"ParamOut": "Param", "MomentOut": "Moment"},
             not_differentiable=True)
def decayed_adagrad(ctx, ins, attrs):
    p = data_of(one(ins, "Param"))
    g = _dense_grad(one(ins, "Grad"))
    m = data_of(one(ins, "Moment"))
    lr = _lr(ins).astype(p.dtype)
    decay = jnp.asarray(attrs["decay"], p.dtype)
    eps = jnp.asarray(attrs["epsilon"], p.dtype)
    m_out = decay * m + (1 - decay) * jnp.square(g)
    return {"ParamOut": p - lr * g / (jnp.sqrt(m_out) + eps),
            "MomentOut": m_out}


@register_op("rmsprop",
             inputs=("Param", "Grad", "MeanSquare", "Moment", "LearningRate"),
             outputs=("ParamOut", "MeanSquareOut", "MomentOut"),
             attrs={"decay": 0.9, "momentum": 0.0, "epsilon": 1e-10},
             inplace={"ParamOut": "Param", "MeanSquareOut": "MeanSquare",
                      "MomentOut": "Moment"},
             not_differentiable=True)
def rmsprop(ctx, ins, attrs):
    p = data_of(one(ins, "Param"))
    g = _dense_grad(one(ins, "Grad"))
    ms = data_of(one(ins, "MeanSquare"))
    mom = data_of(one(ins, "Moment"))
    lr = _lr(ins).astype(p.dtype)
    decay = jnp.asarray(attrs["decay"], p.dtype)
    mu = jnp.asarray(attrs["momentum"], p.dtype)
    eps = jnp.asarray(attrs["epsilon"], p.dtype)
    ms_out = decay * ms + (1 - decay) * jnp.square(g)
    mom_out = mu * mom + lr * g / jnp.sqrt(ms_out + eps)
    return {"ParamOut": p - mom_out, "MeanSquareOut": ms_out,
            "MomentOut": mom_out}


@register_op("ftrl",
             inputs=("Param", "SquaredAccumulator", "LinearAccumulator",
                     "Grad", "LearningRate"),
             outputs=("ParamOut", "SquaredAccumOut", "LinearAccumOut"),
             attrs={"l1": 0.0, "l2": 0.0, "lr_power": -0.5},
             inplace={"ParamOut": "Param",
                      "SquaredAccumOut": "SquaredAccumulator",
                      "LinearAccumOut": "LinearAccumulator"},
             not_differentiable=True)
def ftrl(ctx, ins, attrs):
    p = data_of(one(ins, "Param"))
    sq = data_of(one(ins, "SquaredAccumulator"))
    lin = data_of(one(ins, "LinearAccumulator"))
    g = _dense_grad(one(ins, "Grad"))
    lr = _lr(ins).astype(p.dtype)
    l1 = jnp.asarray(attrs["l1"], p.dtype)
    l2 = jnp.asarray(attrs["l2"], p.dtype)
    power = attrs["lr_power"]
    sq_out = sq + jnp.square(g)
    sigma = (jnp.power(sq_out, -power) - jnp.power(sq, -power)) / lr
    lin_out = lin + g - sigma * p
    x = l1 * jnp.sign(lin_out) - lin_out
    y = jnp.power(sq_out, -power) / lr + 2 * l2
    p_out = jnp.where(jnp.abs(lin_out) > l1, x / y, jnp.zeros_like(p))
    return {"ParamOut": p_out, "SquaredAccumOut": sq_out,
            "LinearAccumOut": lin_out}


@register_op("proximal_gd", inputs=("Param", "Grad", "LearningRate"),
             outputs=("ParamOut",),
             attrs={"l1": 0.0, "l2": 0.0},
             inplace={"ParamOut": "Param"}, not_differentiable=True)
def proximal_gd(ctx, ins, attrs):
    p = data_of(one(ins, "Param"))
    g = _dense_grad(one(ins, "Grad"))
    lr = _lr(ins).astype(p.dtype)
    l1 = jnp.asarray(attrs["l1"], p.dtype)
    l2 = jnp.asarray(attrs["l2"], p.dtype)
    prox = p - lr * g
    p_out = (jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0)
             / (1.0 + lr * l2))
    return {"ParamOut": p_out}


@register_op("proximal_adagrad",
             inputs=("Param", "Moment", "Grad", "LearningRate"),
             outputs=("ParamOut", "MomentOut"),
             attrs={"l1": 0.0, "l2": 0.0},
             inplace={"ParamOut": "Param", "MomentOut": "Moment"},
             not_differentiable=True)
def proximal_adagrad(ctx, ins, attrs):
    p = data_of(one(ins, "Param"))
    m = data_of(one(ins, "Moment"))
    g = _dense_grad(one(ins, "Grad"))
    lr = _lr(ins).astype(p.dtype)
    l1 = jnp.asarray(attrs["l1"], p.dtype)
    l2 = jnp.asarray(attrs["l2"], p.dtype)
    m_out = m + jnp.square(g)
    lr_t = lr / jnp.sqrt(m_out)
    prox = p - lr_t * g
    p_out = (jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr_t * l1, 0.0)
             / (1.0 + lr_t * l2))
    return {"ParamOut": p_out, "MomentOut": m_out}


@register_op("average_accumulates",
             inputs=("Param", "InSum1", "InSum2", "InSum3",
                     "InNumAccumulates", "InOldNumAccumulates",
                     "InNumUpdates"),
             outputs=("OutSum1", "OutSum2", "OutSum3",
                      "OutNumAccumulates", "OutOldNumAccumulates",
                      "OutNumUpdates"),
             attrs={"average_window": 0.15, "min_average_window": 10000,
                    "max_average_window": 10000},
             inplace={"OutSum1": "InSum1", "OutSum2": "InSum2",
                      "OutSum3": "InSum3",
                      "OutNumAccumulates": "InNumAccumulates",
                      "OutOldNumAccumulates": "InOldNumAccumulates",
                      "OutNumUpdates": "InNumUpdates"},
             not_differentiable=True)
def average_accumulates(ctx, ins, attrs):
    """Windowed parameter-sum accumulation for Polyak averaging.

    Reference semantics: paddle/parameter/AverageOptimizer.cpp (legacy
    AverageOptimizer windowing — kMaxNumAccumulates chunked sums, window =
    min(max_average_window, num_updates * average_window) once past
    min_average_window).  All branch logic is jnp.where on scalars, so the
    op stays a single fused XLA kernel per parameter.
    """
    k_max_chunk = 16384
    p = data_of(one(ins, "Param"))
    s1 = data_of(one(ins, "InSum1"))
    s2 = data_of(one(ins, "InSum2"))
    s3 = data_of(one(ins, "InSum3"))
    num_acc = data_of(one(ins, "InNumAccumulates")).reshape(())
    old_num = data_of(one(ins, "InOldNumAccumulates")).reshape(())
    num_upd = data_of(one(ins, "InNumUpdates")).reshape(())

    num_upd = num_upd + 1
    num_acc = num_acc + 1
    s1 = s1 + p
    # fold a full chunk of step-sums into sum_2 to bound fp error growth
    fold = (num_upd % k_max_chunk) == 0
    s2 = jnp.where(fold, s2 + s1, s2)
    s1 = jnp.where(fold, jnp.zeros_like(s1), s1)
    # window rollover: snapshot the finished window into sum_3
    window = jnp.minimum(
        jnp.asarray(float(attrs["max_average_window"]), jnp.float32),
        num_upd.astype(jnp.float32) * float(attrs["average_window"]))
    roll = ((num_acc >= int(attrs["min_average_window"]))
            & (num_acc.astype(jnp.float32) >= window))
    s3 = jnp.where(roll, s1 + s2, s3)
    s1 = jnp.where(roll, jnp.zeros_like(s1), s1)
    s2 = jnp.where(roll, jnp.zeros_like(s2), s2)
    old_num = jnp.where(roll, num_acc, old_num)
    num_acc = jnp.where(roll, jnp.zeros_like(num_acc), num_acc)
    return {"OutSum1": s1, "OutSum2": s2, "OutSum3": s3,
            "OutNumAccumulates": num_acc.reshape(1),
            "OutOldNumAccumulates": old_num.reshape(1),
            "OutNumUpdates": num_upd.reshape(1)}


# ---------------------------------------------------------------------------
# f32 update arithmetic for sub-f32 storage
# ---------------------------------------------------------------------------

def _wrap_updates_in_f32():
    """Re-wrap every optimizer-op lowering to compute in float32 and cast
    results back to each output's stored dtype.

    Half-precision optimizer STATE arithmetic is numerically unsound (the
    motivating failure: a bf16 bias parameter's Adam state diverged within
    two steps; bf16 also rounds beta2=0.999 to exactly 1.0, which pins a
    bf16 beta2_pow accumulator at 1.0 — the beta pows are additionally
    forced to f32 storage in optimizer.py).  Under amp this never triggers
    (params/accumulators are f32 master copies), but models built
    explicitly in bf16/fp16 hit the optimizer ops with half-precision
    storage; the reference never faces this because its params are always
    f32 (optimizer.h kernels).
    """
    import jax.numpy as _jnp

    from ..core import registry
    from ..core.lod import SelectedRows as _SR

    def cast_val(v, dt):
        if v is None:
            return v
        if isinstance(v, _SR):
            if _jnp.issubdtype(_jnp.asarray(v.value).dtype, _jnp.floating):
                return _SR(v.rows, _jnp.asarray(v.value).astype(dt),
                           v.height)
            return v
        a = _jnp.asarray(v)
        return a.astype(dt) if _jnp.issubdtype(a.dtype, _jnp.floating) \
            else v

    def dtype_of(v):
        if isinstance(v, _SR):
            return _jnp.asarray(v.value).dtype
        return _jnp.asarray(v).dtype

    for name in ("sgd", "momentum", "adam", "adamax", "adagrad",
                 "adadelta", "decayed_adagrad", "rmsprop", "ftrl",
                 "proximal_gd", "proximal_adagrad"):
        info = registry.get_op_info(name)
        orig = info.lower

        def lower(ctx, ins, attrs, _orig=orig, _info=info):
            in_dtypes = {}
            cast_ins = {}
            for slot, vals in ins.items():
                in_dtypes[slot] = [None if v is None else dtype_of(v)
                                   for v in vals]
                cast_ins[slot] = [cast_val(v, _jnp.float32) for v in vals]
            outs = _orig(ctx, cast_ins, attrs)
            for oslot, islot in _info.inplace.items():
                if oslot in outs and islot in in_dtypes \
                        and in_dtypes[islot] and \
                        in_dtypes[islot][0] is not None:
                    dt = in_dtypes[islot][0]
                    if _jnp.issubdtype(dt, _jnp.floating):
                        outs[oslot] = cast_val(outs[oslot], dt)
            return outs

        info.lower = lower


_wrap_updates_in_f32()
