"""Op corpus: importing this package registers all op lowerings."""
from . import (  # noqa: F401
    activation,
    conv,
    creation,
    elementwise,
    embedding,
    io_ops,
    loss,
    manip,
    matmul,
    metrics,
    misc,
    norm,
    optimizer_ops,
    reduce,
    rnn,
    sequence,
)
