"""Distributed variable-transfer ops: send / recv / listen_and_serv.

Reference: /root/reference/paddle/fluid/operators/send_op.cc:44-94,
recv_op.cc:28-53, listen_and_serv_op.cc:56-185.  Host ops over the
parallel.pserver TCP transport (the gRPC layer's stand-in).  The
TPU-recommended data-parallel path remains psum over the mesh; these ops
serve the reference's multi-process pserver workflow and host-side
variable transfer.
"""
from __future__ import annotations

from ..core.execution import data_of, many, one
from ..core.registry import register_op

def reset_clients():
    from ..parallel.comm import reset_comm_pool

    reset_comm_pool()


def _data_scope(ctx):
    """The scope whose param copies back trainer-held shard recovery
    (comm.ensure_param_provider) — the executor's run scope, falling
    back to the global scope like listen_and_serv does."""
    scope = getattr(ctx, "scope", None)
    if scope is not None:
        return scope
    from ..core.executor import global_scope

    return global_scope()


@register_op("send", inputs=("X",), outputs=("Out",),
             attrs={"endpoints": [], "epmap": [], "out_epmap": [],
                    "bucket_bytes": -1},
             dup_inputs=("X",), dup_outputs=("Out",),
             not_differentiable=True, host=True)
def send(ctx, ins, attrs):
    """Push grads to their endpoints, barrier, pull updated params
    (send_op.cc:44-94: AsyncSendVariable / SendBatchBarrier /
    AsyncGetVariable).  Grads are packed into arrival-order buckets
    (SEND_BATCH frames, cap = `bucket_bytes` attr or the
    comm_bucket_bytes flag) and each endpoint's send→barrier→pull
    chain runs on its own pooled connection, so pservers are served
    concurrently instead of one serial round per endpoint.

    Under an elastic cluster subscription (comm.set_cluster /
    PADDLE_TPU_CONTROLLER) the transpile-time epmap becomes a fallback:
    each round maps every param through the controller's current view
    placement, and a round that dies mid-flight retries against the
    next stable view (comm.elastic_round)."""
    from ..parallel.comm import elastic_round

    xs = many(ins, "X")
    in_names = ctx.op.input("X")
    out_names = ctx.op.output("Out")
    epmap = attrs["epmap"] or [attrs["endpoints"][0]] * len(in_names)
    out_epmap = (attrs.get("out_epmap") or
                 [attrs["endpoints"][0]] * len(out_names))
    bucket = int(attrs.get("bucket_bytes", -1))
    # cluster views place PARAMS; the fused op aligns X grads with
    # their Out params positionally (DistributeTranspiler), so grad i's
    # placement key is out_names[i] — with a grad-only tail (or a
    # legacy non-fused op) fall back to stripping the @GRAD suffix
    def param_key(i):
        if i < len(out_names):
            return out_names[i]
        n = in_names[i]
        return n[:-len("@GRAD")] if n.endswith("@GRAD") else n

    outs = elastic_round(
        [(param_key(i), n, data_of(v), ep)
         for i, (n, v, ep) in enumerate(zip(in_names, xs, epmap))],
        [(n, n, ep) for n, ep in zip(out_names, out_epmap)],
        bucket_bytes=None if bucket < 0 else bucket,
        scope=_data_scope(ctx))
    return {"Out": outs}


@register_op("recv", inputs=("X",), outputs=("Out",),
             attrs={"endpoint": ""},
             dup_inputs=("X",), dup_outputs=("Out",),
             not_differentiable=True, host=True)
def recv(ctx, ins, attrs):
    """Standalone param fetch (recv_op.cc:28-53), batched into
    GET_BATCH frames; under an elastic cluster subscription each name
    resolves through the current view placement."""
    from ..parallel.comm import elastic_round

    out_names = ctx.op.output("Out")
    ep = attrs["endpoint"]
    outs = elastic_round([], [(n, n, ep) for n in out_names])
    return {"Out": outs}


@register_op("listen_and_serv", inputs=("X",), outputs=(),
             attrs={"endpoint": "127.0.0.1:0", "Fanin": 1,
                    "sync_mode": True},
             dup_inputs=("X",),
             not_differentiable=True, host=True)
def listen_and_serv(ctx, ins, attrs):
    """Run a VariableServer over this op's sub-block as the optimize
    program (listen_and_serv_op.cc:56-185).  Blocks until a client sends
    STOP — run it from a dedicated thread/process like the reference's
    send_recv_op_test.cc does."""
    import time

    from ..core.framework import Program
    from ..core.executor import CPUPlace, Executor, global_scope
    from ..parallel.pserver import VariableServer

    # wrap the optimize sub-block into a standalone single-block program
    # (the reference hands the block to a nested Executor, :160)
    sub = ctx.op.sub_block()
    prog = Program()
    blk = prog.global_block()
    for v in sub.vars.values():
        if not blk.has_var(v.name):
            blk.create_var(name=v.name, shape=v.shape, dtype=v.dtype,
                           persistable=True)
    for op_ in sub.ops:
        blk.append_op(op_.type, dict(op_.inputs), dict(op_.outputs),
                      dict(op_.attrs))
    scope = getattr(ctx, "scope", None) or global_scope()
    server = VariableServer(prog if sub.ops else None, scope,
                            Executor(CPUPlace()),
                            fan_in=attrs.get("Fanin", 1),
                            sync=attrs.get("sync_mode", True))
    endpoint = attrs["endpoint"]
    port = int(endpoint.rsplit(":", 1)[1])
    server.serve(port)
    ctx.env.set("__listen_and_serv_port__", server.port)
    while not server._stopping:
        time.sleep(0.05)
    return {}
