"""Loss ops.

Reference: /root/reference/paddle/fluid/operators/{cross_entropy,
softmax_with_cross_entropy,sigmoid_cross_entropy_with_logits,hinge_loss,
huber_loss,log_loss,margin_rank_loss,modified_huber_loss,rank_loss,
smooth_l1_loss,squared_l2_distance}_op.cc and math/cross_entropy.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..amp import amp_upcast
from ..core.execution import data_of, one, with_lod_of
from ..core.registry import register_op


def _take_label(x, label):
    """x: [N, D] probabilities/logits; label: [N] or [N,1] int -> x[i, label[i]]."""
    label = data_of(label)
    if label.ndim == 2 and label.shape[-1] == 1:
        label = label.squeeze(-1)
    return jnp.take_along_axis(x, label[:, None].astype(jnp.int32),
                               axis=1), label


@register_op("cross_entropy", inputs=("X", "Label"), outputs=("Y",),
             attrs={"soft_label": False}, diff_inputs=("X",))
def cross_entropy(ctx, ins, attrs):
    xv = one(ins, "X")
    # numerically sensitive tail: bf16 probabilities upcast to f32
    x = amp_upcast(data_of(xv))
    # additive eps (not clamp): keeps a finite, recovery-capable gradient
    # -1/(p+eps) when the softmax saturates to p≈0 on the true class
    eps = jnp.asarray(1e-10 if x.dtype == jnp.float32 else 1e-20, x.dtype)
    if attrs.get("soft_label"):
        lbl = data_of(one(ins, "Label"))
        y = -jnp.sum(lbl * jnp.log(x + eps), axis=-1, keepdims=True)
    else:
        picked, _ = _take_label(x, one(ins, "Label"))
        y = -jnp.log(picked + eps)
    return {"Y": with_lod_of(xv, y)}


@register_op("softmax_with_cross_entropy", inputs=("Logits", "Label"),
             outputs=("Softmax", "Loss"),
             attrs={"soft_label": False},
             diff_inputs=("Logits",), diff_outputs=("Loss",))
def softmax_with_cross_entropy(ctx, ins, attrs):
    logits = amp_upcast(data_of(one(ins, "Logits")))
    log_p = jax.nn.log_softmax(logits, axis=-1)
    if attrs.get("soft_label"):
        lbl = data_of(one(ins, "Label"))
        loss = -jnp.sum(lbl * log_p, axis=-1, keepdims=True)
    else:
        picked, _ = _take_label(log_p, one(ins, "Label"))
        loss = -picked
    return {"Softmax": jnp.exp(log_p), "Loss": loss}


@register_op("sigmoid_cross_entropy_with_logits", inputs=("X", "Label"),
             outputs=("Out",), diff_inputs=("X",))
def sigmoid_cross_entropy_with_logits(ctx, ins, attrs):
    x = data_of(one(ins, "X"))
    lbl = data_of(one(ins, "Label")).astype(x.dtype)
    out = jnp.maximum(x, 0) - x * lbl + jnp.log1p(jnp.exp(-jnp.abs(x)))
    return {"Out": out}


@register_op("hinge_loss", inputs=("Logits", "Labels"), outputs=("Loss",),
             diff_inputs=("Logits",))
def hinge_loss(ctx, ins, attrs):
    x = data_of(one(ins, "Logits"))
    y = data_of(one(ins, "Labels")).astype(x.dtype)
    return {"Loss": jnp.maximum(1.0 - (2.0 * y - 1.0) * x, 0.0)}


@register_op("huber_loss", inputs=("X", "Y"), outputs=("Residual", "Out"),
             attrs={"delta": 1.0}, diff_outputs=("Out",))
def huber_loss(ctx, ins, attrs):
    x = data_of(one(ins, "X"))
    y = data_of(one(ins, "Y"))
    d = jnp.asarray(attrs["delta"], x.dtype)
    r = y - x
    out = jnp.where(jnp.abs(r) <= d, 0.5 * jnp.square(r),
                    d * (jnp.abs(r) - 0.5 * d))
    return {"Residual": r, "Out": out}


@register_op("log_loss", inputs=("Predicted", "Labels"), outputs=("Loss",),
             attrs={"epsilon": 1e-4}, diff_inputs=("Predicted",))
def log_loss(ctx, ins, attrs):
    p = data_of(one(ins, "Predicted"))
    y = data_of(one(ins, "Labels")).astype(p.dtype)
    eps = jnp.asarray(attrs["epsilon"], p.dtype)
    return {"Loss": -y * jnp.log(p + eps) - (1 - y) * jnp.log(1 - p + eps)}


@register_op("margin_rank_loss", inputs=("X1", "X2", "Label"),
             outputs=("Out", "Activated"),
             attrs={"margin": 0.0},
             diff_inputs=("X1", "X2"), diff_outputs=("Out",))
def margin_rank_loss(ctx, ins, attrs):
    x1 = data_of(one(ins, "X1"))
    x2 = data_of(one(ins, "X2"))
    lbl = data_of(one(ins, "Label")).astype(x1.dtype)
    m = jnp.asarray(attrs["margin"], x1.dtype)
    out = jnp.maximum(-lbl * (x1 - x2) + m, 0.0)
    return {"Out": out, "Activated": (out > 0).astype(x1.dtype)}


@register_op("modified_huber_loss", inputs=("X", "Y"),
             outputs=("IntermediateVal", "Out"),
             diff_inputs=("X",), diff_outputs=("Out",))
def modified_huber_loss(ctx, ins, attrs):
    x = data_of(one(ins, "X"))
    y = data_of(one(ins, "Y")).astype(x.dtype)
    z = (2.0 * y - 1.0) * x
    out = jnp.where(z < -1.0, -4.0 * z,
                    jnp.where(z < 1.0, jnp.square(1.0 - z),
                              jnp.zeros_like(z)))
    return {"IntermediateVal": z, "Out": out}


@register_op("rank_loss", inputs=("Label", "Left", "Right"), outputs=("Out",),
             diff_inputs=("Left", "Right"))
def rank_loss(ctx, ins, attrs):
    lbl = data_of(one(ins, "Label"))
    left = data_of(one(ins, "Left"))
    right = data_of(one(ins, "Right"))
    d = left - right
    return {"Out": jnp.log1p(jnp.exp(d)) - lbl.astype(d.dtype) * d}


@register_op("smooth_l1_loss", inputs=("X", "Y", "InsideWeight",
                                       "OutsideWeight"),
             outputs=("Diff", "Out"),
             attrs={"sigma": 1.0},
             diff_inputs=("X",), diff_outputs=("Out",))
def smooth_l1_loss(ctx, ins, attrs):
    x = data_of(one(ins, "X"))
    y = data_of(one(ins, "Y"))
    iw = one(ins, "InsideWeight")
    ow = one(ins, "OutsideWeight")
    sigma2 = attrs["sigma"] ** 2
    diff = x - y
    if iw is not None:
        diff = diff * data_of(iw)
    ad = jnp.abs(diff)
    val = jnp.where(ad < 1.0 / sigma2, 0.5 * sigma2 * jnp.square(diff),
                    ad - 0.5 / sigma2)
    if ow is not None:
        val = val * data_of(ow)
    return {"Diff": diff,
            "Out": jnp.sum(val, axis=tuple(range(1, val.ndim))).reshape(-1, 1)}


# -- explicit build-time shape inference -------------------------------------

from ..core.registry import register_infer_shape  # noqa: E402
from ..core.shape_inference import input_var, set_output_shape  # noqa: E402


@register_infer_shape("cross_entropy")
def _infer_cross_entropy(op, block):
    """One loss value per row: [..., C] -> [..., 1].  Default inference
    trips when X and Label carry DIFFERENT -1 row sentinels (both map to
    the same placeholder size only if the dims really agree)."""
    x = input_var(op, block, "X")
    if x is None or x.shape is None:
        return
    set_output_shape(op, block, "Y", tuple(x.shape[:-1]) + (1,), x.dtype)
