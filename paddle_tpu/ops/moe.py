"""Mixture-of-Experts FFN op — the DSL surface of the MoE subsystem.

No reference analogue (SURVEY.md §2.5: expert parallelism absent there);
the op lowers to the mesh-free GShard math in parallel/moe.py
(`moe_dense`: top-1/top-2 gating, static capacity, one-hot
dispatch/combine einsums, batched expert matmuls).  Under
ParallelExecutor the expert dim shards with
`param_shardings={"<w_in name>": P("ep"), ...}` and the XLA partitioner
inserts the ep collectives; the shard_map / all_to_all forms stay
available for raw-JAX use (parallel.moe_ffn / moe_ffn_a2a).

The auxiliary load-balance loss is a real output: add
`aux_weight * AuxLoss` to the training loss and the router trains
toward balance (pinned in tests/test_moe.py).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.execution import data_of, one
from ..core.registry import register_op


@register_op("moe_ffn",
             inputs=("X", "GateW", "WIn", "WOut"),
             outputs=("Out", "AuxLoss"),
             attrs={"top_k": 1, "capacity_factor": 1.25},
             diff_inputs=("X", "GateW", "WIn", "WOut"),
             diff_outputs=("Out", "AuxLoss"),
             cost="moe")
def moe_ffn(ctx, ins, attrs):
    from ..parallel.moe import moe_dense

    xv = one(ins, "X")
    x = data_of(xv)
    gate_w = data_of(one(ins, "GateW"))
    w_in = data_of(one(ins, "WIn"))
    w_out = data_of(one(ins, "WOut"))
    lead = x.shape[:-1]
    flat = x.reshape(-1, x.shape[-1])
    y, aux = moe_dense(flat, gate_w, w_in, w_out,
                       capacity_factor=float(attrs["capacity_factor"]),
                       top_k=int(attrs["top_k"]))
    return {"Out": y.reshape(*lead, y.shape[-1]),
            "AuxLoss": jnp.reshape(aux, (1,))}
