"""Normalization ops: batch_norm, layer_norm, lrn.

Reference: /root/reference/paddle/fluid/operators/batch_norm_op.cc(+cu),
layer_norm_op.cc, lrn_op.cc.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.execution import data_of, one
from ..core.registry import register_op


@register_op("batch_norm",
             inputs=("X", "Scale", "Bias", "Mean", "Variance"),
             outputs=("Y", "MeanOut", "VarianceOut", "SavedMean",
                      "SavedVariance"),
             attrs={"momentum": 0.9, "epsilon": 1e-5, "is_test": False,
                    "data_layout": "NCHW"},
             diff_inputs=("X", "Scale", "Bias"), diff_outputs=("Y",),
             inplace={"MeanOut": "Mean", "VarianceOut": "Variance"})
def batch_norm(ctx, ins, attrs):
    from ..amp import is_bf16_enabled
    x = data_of(one(ins, "X"))
    # under amp, stats compute in f32 (bf16 mean/var is too coarse) and Y
    # returns in x's dtype; outside amp the user's dtype is honored as-is
    out_dtype = x.dtype
    if is_bf16_enabled() and x.dtype == jnp.bfloat16:
        x = x.astype(jnp.float32)
    scale = data_of(one(ins, "Scale"))
    bias = data_of(one(ins, "Bias"))
    mean = data_of(one(ins, "Mean"))
    var = data_of(one(ins, "Variance"))
    eps = attrs["epsilon"]
    mom = attrs["momentum"]
    layout = attrs.get("data_layout", "NCHW")
    c_axis = 1 if (layout == "NCHW" and x.ndim > 1) else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i != c_axis)
    bshape = [1] * x.ndim
    bshape[c_axis] = x.shape[c_axis]

    if attrs.get("is_test"):
        use_mean, use_var = mean, var
        mean_out, var_out = mean, var
        saved_mean, saved_var = mean, var
    else:
        use_mean = jnp.mean(x, axis=axes)
        use_var = jnp.mean(jnp.square(x - use_mean.reshape(bshape)),
                           axis=axes)
        mean_out = mom * mean + (1.0 - mom) * use_mean
        var_out = mom * var + (1.0 - mom) * use_var
        saved_mean = use_mean
        saved_var = 1.0 / jnp.sqrt(use_var + eps)
    inv_std = 1.0 / jnp.sqrt(use_var + eps)
    y = ((x - use_mean.reshape(bshape)) * inv_std.reshape(bshape)
         * scale.reshape(bshape) + bias.reshape(bshape))
    # running stats keep the state var's dtype: a dtype flip here would
    # change the train-step state avals and force a recompile every step
    return {"Y": y.astype(out_dtype),
            "MeanOut": mean_out.astype(mean.dtype),
            "VarianceOut": var_out.astype(var.dtype),
            "SavedMean": saved_mean, "SavedVariance": saved_var}


@register_op("layer_norm", inputs=("X", "Scale", "Bias"),
             outputs=("Y", "Mean", "Variance"),
             attrs={"epsilon": 1e-5, "begin_norm_axis": 1},
             diff_inputs=("X", "Scale", "Bias"), diff_outputs=("Y",))
def layer_norm(ctx, ins, attrs):
    x = data_of(one(ins, "X"))
    a = attrs["begin_norm_axis"]
    axes = tuple(range(a, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=axes, keepdims=True)
    y = (x - mean) / jnp.sqrt(var + attrs["epsilon"])
    scale = one(ins, "Scale")
    bias = one(ins, "Bias")
    norm_shape = [1] * a + list(x.shape[a:])
    if scale is not None:
        y = y * data_of(scale).reshape(norm_shape)
    if bias is not None:
        y = y + data_of(bias).reshape(norm_shape)
    return {"Y": y, "Mean": mean.reshape(x.shape[:a]),
            "Variance": var.reshape(x.shape[:a])}


@register_op("lrn", inputs=("X",), outputs=("Out", "MidOut"),
             attrs={"n": 5, "k": 2.0, "alpha": 1e-4, "beta": 0.75},
             diff_outputs=("Out",))
def lrn(ctx, ins, attrs):
    """Cross-channel local response normalization (reference lrn_op.cc)."""
    x = data_of(one(ins, "X"))  # [N, C, H, W]
    n = attrs["n"]
    half = n // 2
    sq = jnp.square(x)
    pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    window = sum(pad[:, i:i + x.shape[1]] for i in range(n))
    mid = attrs["k"] + attrs["alpha"] * window
    return {"Out": x / jnp.power(mid, attrs["beta"]), "MidOut": mid}
