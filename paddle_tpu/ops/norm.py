"""Normalization ops: batch_norm, layer_norm, lrn.

Reference: /root/reference/paddle/fluid/operators/batch_norm_op.cc(+cu),
layer_norm_op.cc, lrn_op.cc.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.execution import data_of, one
from ..core.registry import register_op


@register_op("batch_norm",
             inputs=("X", "Scale", "Bias", "Mean", "Variance"),
             outputs=("Y", "MeanOut", "VarianceOut", "SavedMean",
                      "SavedVariance"),
             attrs={"momentum": 0.9, "epsilon": 1e-5, "is_test": False,
                    "data_layout": "NCHW"},
             diff_inputs=("X", "Scale", "Bias"), diff_outputs=("Y",),
             inplace={"MeanOut": "Mean", "VarianceOut": "Variance"})
def batch_norm(ctx, ins, attrs):
    """HBM-traffic-minimal batch norm (the dominant cost on TPU, where
    conv nets run memory-bound — see benchmark/README.md roofline):

      * statistics accumulate in f32 IN-REGISTER over the input
        (``jnp.mean(x, dtype=f32)``) — no materialized f32 copy of a
        bf16 activation, full f32 accuracy even for bf16 inputs;
      * the normalize collapses to ONE affine pass ``y = x*a + b`` with
        per-channel f32 ``a = scale/sqrt(var+eps)``,
        ``b = bias - mean*a``, whose backward needs only ``x`` (already
        materialized as the producing conv's output) — no xhat/centered
        residual tensor is ever written.

    Measured on v5e: 86.3 -> 75.0 GB HBM traffic per ResNet-50 bs256
    train step vs the two-pass f32-cast form, identical convergence."""
    x = data_of(one(ins, "X"))
    scale = data_of(one(ins, "Scale"))
    bias = data_of(one(ins, "Bias"))
    mean = data_of(one(ins, "Mean"))
    var = data_of(one(ins, "Variance"))
    eps = attrs["epsilon"]
    mom = attrs["momentum"]
    layout = attrs.get("data_layout", "NCHW")
    c_axis = 1 if (layout == "NCHW" and x.ndim > 1) else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i != c_axis)
    bshape = [1] * x.ndim
    bshape[c_axis] = x.shape[c_axis]
    f32 = jnp.float32

    if attrs.get("is_test"):
        use_mean = mean.astype(f32)
        use_var = var.astype(f32)
        mean_out, var_out = mean, var
        saved_mean, saved_var = mean, var
        inv = jax.lax.rsqrt(use_var + eps)
        a = inv * scale.astype(f32)
        b = bias.astype(f32) - use_mean * a
        y = x * a.astype(x.dtype).reshape(bshape) + \
            b.astype(x.dtype).reshape(bshape)
        return {"Y": y, "MeanOut": mean_out, "VarianceOut": var_out,
                "SavedMean": saved_mean, "SavedVariance": saved_var}

    use_mean = jnp.mean(x, axis=axes, dtype=f32)
    if x.dtype in (jnp.float32, jnp.float64):
        # full-precision input: two-pass centered variance (E[x^2]-m^2
        # cancels catastrophically when |mean| >> std); the extra read
        # pass only affects the already-full-traffic f32 path
        use_var = jnp.mean(
            jax.lax.square(x - use_mean.astype(x.dtype).reshape(bshape)),
            axis=axes, dtype=f32)
    else:
        # low-precision input (bf16/f16): ONE read pass, f32 in-register
        # accumulation — the input's own quantization (~3 digits for
        # bf16) dwarfs any E[x^2]-m^2 cancellation, so this loses
        # nothing while halving the stats traffic
        ex2 = jnp.mean(jax.lax.square(x.astype(f32)), axis=axes)
        use_var = jnp.maximum(ex2 - jax.lax.square(use_mean), 0.0)
    mean_out = mom * mean.astype(f32) + (1.0 - mom) * use_mean
    var_out = mom * var.astype(f32) + (1.0 - mom) * use_var
    inv = jax.lax.rsqrt(use_var + eps)
    a = inv * scale.astype(f32)
    b = bias.astype(f32) - use_mean * a
    y = x * a.astype(x.dtype).reshape(bshape) + \
        b.astype(x.dtype).reshape(bshape)
    # running stats keep the state var's dtype: a dtype flip here would
    # change the train-step state avals and force a recompile every step
    return {"Y": y,
            "MeanOut": mean_out.astype(mean.dtype),
            "VarianceOut": var_out.astype(var.dtype),
            "SavedMean": use_mean, "SavedVariance": inv}


@register_op("layer_norm", inputs=("X", "Scale", "Bias"),
             outputs=("Y", "Mean", "Variance"),
             attrs={"epsilon": 1e-5, "begin_norm_axis": 1},
             diff_inputs=("X", "Scale", "Bias"), diff_outputs=("Y",))
def layer_norm(ctx, ins, attrs):
    x = data_of(one(ins, "X"))
    a = attrs["begin_norm_axis"]
    axes = tuple(range(a, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=axes, keepdims=True)
    y = (x - mean) / jnp.sqrt(var + attrs["epsilon"])
    scale = one(ins, "Scale")
    bias = one(ins, "Bias")
    norm_shape = [1] * a + list(x.shape[a:])
    if scale is not None:
        y = y * data_of(scale).reshape(norm_shape)
    if bias is not None:
        y = y + data_of(bias).reshape(norm_shape)
    return {"Y": y, "Mean": mean.reshape(x.shape[:a]),
            "Variance": var.reshape(x.shape[:a])}


@register_op("lrn", inputs=("X",), outputs=("Out", "MidOut"),
             attrs={"n": 5, "k": 2.0, "alpha": 1e-4, "beta": 0.75},
             diff_outputs=("Out",))
def lrn(ctx, ins, attrs):
    """Cross-channel local response normalization (reference lrn_op.cc)."""
    x = data_of(one(ins, "X"))  # [N, C, H, W]
    n = attrs["n"]
    half = n // 2
    sq = jnp.square(x)
    pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    window = sum(pad[:, i:i + x.shape[1]] for i in range(n))
    mid = attrs["k"] + attrs["alpha"] * window
    return {"Out": x / jnp.power(mid, attrs["beta"]), "MidOut": mid}
