"""Metric ops (metrics-as-ops, reference scheme).

Reference: /root/reference/paddle/fluid/operators/{accuracy,auc,
precision_recall,edit_distance}_op.cc.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.execution import data_of, one
from ..core.registry import register_op


@register_op("accuracy", inputs=("Out", "Indices", "Label"),
             outputs=("Accuracy", "Correct", "Total"),
             not_differentiable=True)
def accuracy(ctx, ins, attrs):
    """Top-k accuracy from top_k outputs (reference accuracy_op.cc)."""
    idx = data_of(one(ins, "Indices"))  # [N, k]
    label = data_of(one(ins, "Label"))
    if label.ndim == 2 and label.shape[-1] == 1:
        label = label.squeeze(-1)
    hit = jnp.any(idx == label[:, None].astype(idx.dtype), axis=1)
    correct = jnp.sum(hit.astype(jnp.int32))
    total = jnp.asarray(idx.shape[0], jnp.int32)
    acc = correct.astype(jnp.float32) / total.astype(jnp.float32)
    return {"Accuracy": acc.reshape(1), "Correct": correct.reshape(1),
            "Total": total.reshape(1)}


@register_op("auc",
             inputs=("Out", "Indices", "Label"),
             outputs=("AUC",),
             attrs={"curve": "ROC", "num_thresholds": 200},
             not_differentiable=True)
def auc(ctx, ins, attrs):
    """Single-batch AUC via threshold sweep (reference auc_op.cc)."""
    probs = data_of(one(ins, "Out"))
    if probs.ndim == 2:
        pos = probs[:, -1] if probs.shape[1] > 1 else probs[:, 0]
    else:
        pos = probs
    label = data_of(one(ins, "Label")).reshape(-1)
    n_thr = attrs["num_thresholds"]
    thr = jnp.linspace(0.0, 1.0, n_thr)
    is_pos = (label > 0)
    pred = pos[None, :] > thr[:, None]          # [T, N]
    tp = jnp.sum(pred & is_pos[None, :], axis=1).astype(jnp.float32)
    fp = jnp.sum(pred & ~is_pos[None, :], axis=1).astype(jnp.float32)
    p = jnp.maximum(jnp.sum(is_pos).astype(jnp.float32), 1.0)
    n = jnp.maximum(jnp.sum(~is_pos).astype(jnp.float32), 1.0)
    tpr = tp / p
    fpr = fp / n
    # trapezoidal area over decreasing fpr
    area = jnp.sum((fpr[:-1] - fpr[1:]) * (tpr[:-1] + tpr[1:]) / 2.0)
    return {"AUC": area.reshape(1)}


@register_op("precision_recall",
             inputs=("MaxProbs", "Indices", "Labels", "Weights",
                     "StatesInfo"),
             outputs=("BatchMetrics", "AccumMetrics", "AccumStatesInfo"),
             attrs={"class_number": 2},
             not_differentiable=True)
def precision_recall(ctx, ins, attrs):
    c = attrs["class_number"]
    idx = data_of(one(ins, "Indices")).reshape(-1)
    labels = data_of(one(ins, "Labels")).reshape(-1)
    wv = one(ins, "Weights")
    w = (jnp.ones(idx.shape[0], jnp.float32) if wv is None
         else data_of(wv).reshape(-1).astype(jnp.float32))
    onehot_pred = jnp.eye(c, dtype=jnp.float32)[idx]
    onehot_lbl = jnp.eye(c, dtype=jnp.float32)[labels]
    tp = jnp.sum(w[:, None] * onehot_pred * onehot_lbl, axis=0)
    fp = jnp.sum(w[:, None] * onehot_pred * (1 - onehot_lbl), axis=0)
    fn = jnp.sum(w[:, None] * (1 - onehot_pred) * onehot_lbl, axis=0)
    # TN per class = weight of samples that neither predicted nor carried
    # the class (reference precision_recall_op.h:71-81 increments all
    # classes then subtracts the predicted/true ones)
    tn = jnp.sum(w) - tp - fp - fn
    states = jnp.stack([tp, fp, tn, fn], axis=1)
    prev = one(ins, "StatesInfo")
    acc = states if prev is None else states + data_of(prev)

    def metrics(s):
        tp_, fp_, _, fn_ = s[:, 0], s[:, 1], s[:, 2], s[:, 3]
        prec = jnp.where(tp_ + fp_ > 0, tp_ / jnp.maximum(tp_ + fp_, 1e-9), 0)
        rec = jnp.where(tp_ + fn_ > 0, tp_ / jnp.maximum(tp_ + fn_, 1e-9), 0)
        f1 = jnp.where(prec + rec > 0,
                       2 * prec * rec / jnp.maximum(prec + rec, 1e-9), 0)
        macro = jnp.stack([jnp.mean(prec), jnp.mean(rec), jnp.mean(f1)])
        micro_p = jnp.sum(tp_) / jnp.maximum(jnp.sum(tp_ + fp_), 1e-9)
        micro_r = jnp.sum(tp_) / jnp.maximum(jnp.sum(tp_ + fn_), 1e-9)
        micro_f = jnp.where(micro_p + micro_r > 0,
                            2 * micro_p * micro_r /
                            jnp.maximum(micro_p + micro_r, 1e-9), 0)
        return jnp.concatenate([macro, jnp.stack([micro_p, micro_r, micro_f])])

    return {"BatchMetrics": metrics(states), "AccumMetrics": metrics(acc),
            "AccumStatesInfo": acc}


@register_op("edit_distance", inputs=("Hyps", "Refs"),
             outputs=("Out", "SequenceNum"),
             attrs={"normalized": False}, not_differentiable=True, host=True)
def edit_distance(ctx, ins, attrs):
    """Levenshtein distance over LoD sequences — host op (dynamic lengths)."""
    import numpy as np

    hyps = one(ins, "Hyps")
    refs = one(ins, "Refs")

    def seqs(t):
        d = np.asarray(data_of(t)).reshape(-1)
        if hasattr(t, "lod") and t.lod:
            offs = t.lod[0]
            return [d[offs[i]:offs[i + 1]] for i in range(len(offs) - 1)]
        return [d]

    H, R = seqs(hyps), seqs(refs)
    outs = []
    for h, r in zip(H, R):
        m, n = len(h), len(r)
        dp = np.arange(n + 1, dtype=np.float32)
        for i in range(1, m + 1):
            prev = dp.copy()
            dp[0] = i
            for j in range(1, n + 1):
                cost = 0 if h[i - 1] == r[j - 1] else 1
                dp[j] = min(prev[j] + 1, dp[j - 1] + 1, prev[j - 1] + cost)
        d = dp[n]
        if attrs.get("normalized") and n > 0:
            d /= n
        outs.append(d)
    return {"Out": np.asarray(outs, np.float32).reshape(-1, 1),
            "SequenceNum": np.asarray([len(outs)], np.int64)}


@register_op("positive_negative_pair",
             inputs=("Score", "Label", "QueryID", "Weight",
                     "AccumulatePositivePair", "AccumulateNegativePair",
                     "AccumulateNeutralPair"),
             outputs=("PositivePair", "NegativePair", "NeutralPair"),
             attrs={"column": -1},
             not_differentiable=True, host=True)
def positive_negative_pair(ctx, ins, attrs):
    """Per-query correctly/incorrectly-ordered pair counts (reference
    positive_negative_pair_op.h).  Host op: rows are grouped by QueryID
    and pairs are vectorized WITHIN each query, so memory is O(max query
    size squared), matching the reference's per-query loop rather than
    O(total rows squared).

    Keeps the reference's exact edge semantics: pairs with equal scores add
    their weight to BOTH NeutralPair and NegativePair (the kernel's ternary
    falls through to `neg` when the score delta is zero)."""
    import numpy as np

    from ..core.execution import many

    score = np.asarray(data_of(one(ins, "Score")))
    label = np.asarray(data_of(one(ins, "Label"))).reshape(-1)
    query = np.asarray(data_of(one(ins, "QueryID"))).reshape(-1)
    col = attrs.get("column", -1)
    s = (score[:, col] if score.ndim == 2 else score.reshape(-1)
         ).astype(np.float64)
    wv = many(ins, "Weight")
    w = (np.asarray(data_of(wv[0])).reshape(-1).astype(np.float64) if wv
         else np.ones_like(s))

    pos = neg = neu = 0.0
    for q in np.unique(query):
        idx = np.flatnonzero(query == q)
        sq, lq, wq = s[idx], label[idx].astype(np.float64), w[idx]
        k = len(idx)
        if k < 2:
            continue
        iu = np.triu(np.ones((k, k), bool), k=1)
        ldiff = lq[:, None] - lq[None, :]
        sdiff = sq[:, None] - sq[None, :]
        vw = np.where(iu & (ldiff != 0), (wq[:, None] + wq[None, :]) * 0.5,
                      0.0)
        correct = sdiff * ldiff > 0
        pos += float(np.sum(np.where(correct, vw, 0.0)))
        neg += float(np.sum(np.where(correct, 0.0, vw)))
        neu += float(np.sum(np.where(sdiff == 0, vw, 0.0)))

    # accumulators apply only when all three are wired, matching the
    # reference's combined nullptr check (positive_negative_pair_op.h:81)
    accs = [many(ins, k) for k in ("AccumulatePositivePair",
                                   "AccumulateNegativePair",
                                   "AccumulateNeutralPair")]
    if all(accs):
        pos += float(np.asarray(data_of(accs[0][0])).reshape(-1)[0])
        neg += float(np.asarray(data_of(accs[1][0])).reshape(-1)[0])
        neu += float(np.asarray(data_of(accs[2][0])).reshape(-1)[0])
    return {"PositivePair": np.asarray([pos], np.float32),
            "NegativePair": np.asarray([neg], np.float32),
            "NeutralPair": np.asarray([neu], np.float32)}
