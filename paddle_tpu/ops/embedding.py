"""Embedding / sparse ops: lookup_table (+SelectedRows grad), nce.

Reference: /root/reference/paddle/fluid/operators/lookup_table_op.cc
(`is_sparse` attr switches the grad var type to SelectedRows via
VarTypeInference, :114-131), nce_op.cc,
math/selected_rows_functor.

TPU design: dense grads are segment-sum scatters (XLA scatter-add);
sparse grads keep the SelectedRows representation so sharded-embedding /
pserver-equivalent paths can ship only touched rows.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.execution import data_of, one, with_lod_of
from ..core.lod import LoDTensor, SelectedRows
from ..core.registry import register_grad_maker, register_op


@register_op("lookup_table", inputs=("Ids", "W"), outputs=("Out",),
             attrs={"is_sparse": False, "padding_idx": -1},
             diff_inputs=("W",))
def lookup_table(ctx, ins, attrs):
    ids_v = one(ins, "Ids")
    ids = data_of(ids_v)
    w = data_of(one(ins, "W"))
    flat = ids.reshape(-1).astype(jnp.int32)
    out = jnp.take(w, flat, axis=0)
    pad = attrs.get("padding_idx", -1)
    if pad is not None and pad >= 0:
        out = jnp.where((flat == pad)[:, None], jnp.zeros_like(out), out)
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        out_shape = ids.shape[:-1] + (w.shape[1],)
    else:
        out_shape = ids.shape + (w.shape[1],)
    return {"Out": with_lod_of(ids_v, out.reshape(out_shape))}


@register_op("lookup_table_grad", inputs=("Ids", "W", "Out@GRAD"),
             outputs=("W@GRAD",))
def lookup_table_grad(ctx, ins, attrs):
    ids = data_of(one(ins, "Ids"))
    w = data_of(one(ins, "W"))
    og = data_of(one(ins, "Out@GRAD"))
    flat = ids.reshape(-1).astype(jnp.int32)
    og2 = og.reshape(-1, w.shape[1])
    pad = attrs.get("padding_idx", -1)
    if pad is not None and pad >= 0:
        og2 = jnp.where((flat == pad)[:, None], jnp.zeros_like(og2), og2)
    if attrs.get("is_sparse"):
        return {"W@GRAD": SelectedRows(flat, og2, w.shape[0])}
    return {"W@GRAD": jnp.zeros_like(w).at[flat].add(
        og2.astype(w.dtype))}


@register_op("nce",
             inputs=("Input", "Label", "Weight", "Bias", "SampleWeight"),
             outputs=("Cost", "SampleLogits", "SampleLabels"),
             attrs={"num_total_classes": 2, "num_neg_samples": 10,
                    "seed": 0},
             diff_inputs=("Input", "Weight", "Bias"),
             diff_outputs=("Cost",), random=True)
def nce(ctx, ins, attrs):
    """Noise-contrastive estimation (reference nce_op.cc): uniform negative
    sampling, logistic loss over true + sampled classes."""
    x = data_of(one(ins, "Input"))          # [B, D]
    label = data_of(one(ins, "Label"))      # [B, T]
    w = data_of(one(ins, "Weight"))         # [C, D]
    b = one(ins, "Bias")                    # [C] or None
    num_classes = attrs["num_total_classes"]
    k = attrs["num_neg_samples"]
    bsz = x.shape[0]
    if label.ndim == 1:
        label = label[:, None]
    n_true = label.shape[1]
    key = (jax.random.key(attrs["seed"]) if attrs.get("seed")
           else ctx.rng())
    neg = jax.random.randint(key, (bsz, k), 0, num_classes)
    samples = jnp.concatenate([label.astype(jnp.int32),
                               neg.astype(jnp.int32)], axis=1)  # [B, T+k]
    w_s = jnp.take(w, samples.reshape(-1), axis=0).reshape(
        bsz, n_true + k, -1)
    logits = jnp.einsum("bd,btd->bt", x, w_s)
    if b is not None:
        logits = logits + jnp.take(data_of(b), samples.reshape(-1)
                                   ).reshape(bsz, n_true + k)
    p_true = 1.0 / num_classes  # uniform sampler
    # NCE logistic loss: P(D=1|x) for true, P(D=0|x) for noise
    logit_adj = logits - jnp.log(jnp.asarray(k * p_true, logits.dtype))
    lbl_mat = jnp.concatenate(
        [jnp.ones((bsz, n_true), logits.dtype),
         jnp.zeros((bsz, k), logits.dtype)], axis=1)
    per = (jnp.maximum(logit_adj, 0) - logit_adj * lbl_mat +
           jnp.log1p(jnp.exp(-jnp.abs(logit_adj))))
    cost = jnp.sum(per, axis=1, keepdims=True)
    return {"Cost": cost, "SampleLogits": logits,
            "SampleLabels": samples.astype(jnp.int64)}
