"""Activation ops — the full functor set of the reference's
/root/reference/paddle/fluid/operators/activation_op.h (30 activations in one
template file; python registry list python/paddle/v2/fluid/layers/ops.py:16-46)
plus softmax, prelu and dropout.

Gradients come from the generic VJP (core/execution.py), matching the
reference's hand-written grad functors analytically.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.execution import data_of, one, with_lod_of
from ..core.registry import register_op


def _unary(name, fn, attrs=None):
    @register_op(name, inputs=("X",), outputs=("Out",), attrs=attrs or {})
    def lower(ctx, ins, attrs, _fn=fn):
        xv = one(ins, "X")
        return {"Out": with_lod_of(xv, _fn(data_of(xv), attrs))}

    return lower


_unary("sigmoid", lambda x, a: jax.nn.sigmoid(x))
_unary("logsigmoid", lambda x, a: jax.nn.log_sigmoid(x))
_unary("exp", lambda x, a: jnp.exp(x))
_unary("relu", lambda x, a: jax.nn.relu(x))
_unary("tanh", lambda x, a: jnp.tanh(x))
_unary("tanh_shrink", lambda x, a: x - jnp.tanh(x))
_unary("softshrink",
       lambda x, a: jnp.where(x > a["lambda"], x - a["lambda"],
                              jnp.where(x < -a["lambda"], x + a["lambda"],
                                        jnp.zeros_like(x))),
       attrs={"lambda": 0.5})
_unary("sqrt", lambda x, a: jnp.sqrt(x))
_unary("abs", lambda x, a: jnp.abs(x))
_unary("ceil", lambda x, a: jnp.ceil(x))
_unary("floor", lambda x, a: jnp.floor(x))
_unary("round", lambda x, a: jnp.round(x))
_unary("reciprocal", lambda x, a: 1.0 / x)
_unary("log", lambda x, a: jnp.log(x))
_unary("square", lambda x, a: jnp.square(x))
_unary("softplus", lambda x, a: jax.nn.softplus(x))
_unary("softsign", lambda x, a: x / (1 + jnp.abs(x)))
_unary("brelu", lambda x, a: jnp.clip(x, a["t_min"], a["t_max"]),
       attrs={"t_min": 0.0, "t_max": 24.0})
_unary("leaky_relu", lambda x, a: jnp.where(x > 0, x, a["alpha"] * x),
       attrs={"alpha": 0.02})
_unary("soft_relu",
       lambda x, a: jnp.log1p(jnp.exp(jnp.clip(x, -a["threshold"],
                                               a["threshold"]))),
       attrs={"threshold": 40.0})
_unary("elu", lambda x, a: jnp.where(x > 0, x, a["alpha"] * jnp.expm1(x)),
       attrs={"alpha": 1.0})
_unary("relu6", lambda x, a: jnp.clip(x, 0.0, a["threshold"]),
       attrs={"threshold": 6.0})
_unary("pow", lambda x, a: jnp.power(x, a["factor"]), attrs={"factor": 1.0})
_unary("stanh",
       lambda x, a: a["scale_b"] * jnp.tanh(a["scale_a"] * x),
       attrs={"scale_a": 2.0 / 3.0, "scale_b": 1.7159})
_unary("hard_shrink",
       lambda x, a: jnp.where(jnp.abs(x) > a["threshold"], x,
                              jnp.zeros_like(x)),
       attrs={"threshold": 0.5})
_unary("thresholded_relu",
       lambda x, a: jnp.where(x > a["threshold"], x, jnp.zeros_like(x)),
       attrs={"threshold": 1.0})
_unary("hard_sigmoid",
       lambda x, a: jnp.clip(a["slope"] * x + a["offset"], 0.0, 1.0),
       attrs={"slope": 0.2, "offset": 0.5})
_unary("swish", lambda x, a: x * jax.nn.sigmoid(a["beta"] * x),
       attrs={"beta": 1.0})


@register_op("softmax", inputs=("X",), outputs=("Out",))
def softmax(ctx, ins, attrs):
    """Reference softmax_op.cc: softmax over the last dim of a 2D input.
    bf16 inputs upcast to f32 (numerically sensitive amp blacklist)."""
    from ..amp import amp_upcast
    xv = one(ins, "X")
    return {"Out": with_lod_of(
        xv, jax.nn.softmax(amp_upcast(data_of(xv)), axis=-1))}


@register_op("prelu", inputs=("X", "Alpha"), outputs=("Out",))
def prelu(ctx, ins, attrs):
    xv = one(ins, "X")
    x = data_of(xv)
    alpha = data_of(one(ins, "Alpha")).reshape(())
    return {"Out": with_lod_of(xv, jnp.where(x > 0, x, alpha * x))}


@register_op("dropout", inputs=("X",), outputs=("Out", "Mask"),
             attrs={"dropout_prob": 0.5, "is_test": False, "seed": 0,
                    "fix_seed": False},
             diff_inputs=("X",), diff_outputs=("Out",), random=True)
def dropout(ctx, ins, attrs):
    """Batch-position-keyed masks: row i's mask depends only on (op key,
    global row index), never on the batch's partitioning — so a
    microbatched / dp-sharded / pipelined execution reproduces the
    serial masks bit-for-bit.  PipelineExecutor's staged trunk supplies
    the global row offset (and, under sequence parallelism, a seq-block
    fold) on the ExecContext; the serial executor supplies neither, which
    is exactly offset 0 on the full batch."""
    xv = one(ins, "X")
    x = data_of(xv)
    if attrs.get("is_test"):
        keep = jnp.asarray(1.0 - attrs["dropout_prob"], x.dtype)
        return {"Out": with_lod_of(xv, x * keep),
                "Mask": jnp.ones_like(x)}
    key = (jax.random.key(attrs["seed"]) if attrs.get("fix_seed")
           else ctx.rng())
    root = getattr(ctx, "root", None)
    rows = getattr(root, "row_offset", 0) + jnp.arange(x.shape[0])
    seq_block = getattr(root, "rng_seq_block", None)

    def row_u(i):
        k = jax.random.fold_in(key, i)
        if seq_block is not None:
            # sp: each rank draws its own seq block independently
            # (distribution-equivalent to serial, not bit-equal)
            k = jax.random.fold_in(k, seq_block)
        return jax.random.uniform(k, x.shape[1:])

    mask = (jax.vmap(row_u)(rows) >= attrs["dropout_prob"])
    mask = mask.astype(x.dtype)
    return {"Out": with_lod_of(xv, x * mask), "Mask": mask}


@register_op("dropout_grad", inputs=("Mask", "Out@GRAD"),
             outputs=("X@GRAD",))
def dropout_grad(ctx, ins, attrs):
    """Custom grad: reuse the saved mask (generic VJP would re-sample)."""
    mask = data_of(one(ins, "Mask"))
    og = data_of(one(ins, "Out@GRAD"))
    return {"X@GRAD": og * mask}
