"""Convolution / pooling ops.

Reference: /root/reference/paddle/fluid/operators/conv_op.cc (GEMM im2col
path), conv_cudnn_op.cu.cc, conv_transpose_op.cc, pool_op.cc,
pool_with_index, math/depthwise_conv.cu, spp_op, unpool_op.

TPU design: all lower to `lax.conv_general_dilated` / `lax.reduce_window`,
which XLA maps onto the MXU with its own im2col/winograd-free tiling — the
`use_cudnn`-vs-GEMM kernel choice of the reference (conv_op.cc:72-91
GetExpectedKernelType) has no analogue; the compiler owns algorithm choice.
Layout is kept NCHW at the IR level (reference default); XLA relayouts
internally for the hardware.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..amp import amp_cast
from ..core.execution import data_of, one
from ..core.flags import get_flag
from ..core.registry import register_op


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


@register_op("conv2d", inputs=("Input", "Filter"), outputs=("Output",),
             attrs={"strides": [1, 1], "paddings": [0, 0],
                    "dilations": [1, 1], "groups": 1, "use_cudnn": True,
                    "data_format": "NCHW"},
             cost="conv")
def conv2d(ctx, ins, attrs):
    """data_format "NHWC" keeps activations channels-last — the TPU's
    native conv layout (vector lanes = channels); weights stay OIHW at the
    IR level either way (lax handles the rhs spec).

    The `conv_layout` flag (PADDLE_TPU_CONV_LAYOUT=NHWC, trace-time)
    opt-in overrides NCHW-declared convs to run channels-last inside the
    lowering: transpose in, NHWC conv, transpose out.  XLA cancels the
    adjacent transpose pairs between consecutive convs, so a whole conv
    trunk runs natively channels-last without touching the program IR —
    the layout half of the memory knobs (docs/performance.md 'Memory');
    combine with amp_bf16 for the bf16-native NHWC path."""
    x = data_of(one(ins, "Input"))        # [N, C, H, W] or [N, H, W, C]
    w = data_of(one(ins, "Filter"))       # [M, C/groups, kh, kw]
    x, w = amp_cast(x, w)
    s, p, d = (_pair(attrs["strides"]), _pair(attrs["paddings"]),
               _pair(attrs["dilations"]))
    df = attrs.get("data_format", "NCHW")
    relayout = (df == "NCHW" and x.ndim == 4
                and str(get_flag("conv_layout")).upper() == "NHWC")
    if relayout:
        x, df = jnp.transpose(x, (0, 2, 3, 1)), "NHWC"
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=s,
        padding=[(p[0], p[0]), (p[1], p[1])],
        rhs_dilation=d,
        dimension_numbers=(df, "OIHW", df),
        feature_group_count=int(attrs.get("groups") or 1),
        preferred_element_type=jnp.float32
        if x.dtype == jnp.float32 else None)
    if relayout:
        out = jnp.transpose(out, (0, 3, 1, 2))
    return {"Output": out.astype(x.dtype)}


@register_op("depthwise_conv2d", inputs=("Input", "Filter"),
             outputs=("Output",),
             attrs={"strides": [1, 1], "paddings": [0, 0],
                    "dilations": [1, 1], "groups": 1})
def depthwise_conv2d(ctx, ins, attrs):
    x = data_of(one(ins, "Input"))
    groups = attrs.get("groups") or x.shape[1]
    return conv2d(ctx, ins, {**attrs, "groups": groups})


@register_op("conv3d", inputs=("Input", "Filter"), outputs=("Output",),
             attrs={"strides": [1, 1, 1], "paddings": [0, 0, 0],
                    "dilations": [1, 1, 1], "groups": 1})
def conv3d(ctx, ins, attrs):
    x = data_of(one(ins, "Input"))        # [N, C, D, H, W]
    w = data_of(one(ins, "Filter"))
    x, w = amp_cast(x, w)
    s = _pair(attrs["strides"], 3)
    p = _pair(attrs["paddings"], 3)
    d = _pair(attrs["dilations"], 3)
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=s, padding=[(pi, pi) for pi in p],
        rhs_dilation=d, dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        feature_group_count=int(attrs.get("groups") or 1))
    return {"Output": out}


@register_op("conv2d_transpose", inputs=("Input", "Filter"),
             outputs=("Output",),
             attrs={"strides": [1, 1], "paddings": [0, 0],
                    "dilations": [1, 1]})
def conv2d_transpose(ctx, ins, attrs):
    x = data_of(one(ins, "Input"))        # [N, C, H, W]
    w = data_of(one(ins, "Filter"))       # [C, M, kh, kw] (reference layout)
    x, w = amp_cast(x, w)
    s, p = _pair(attrs["strides"]), _pair(attrs["paddings"])
    d = _pair(attrs.get("dilations", [1, 1]))
    kh, kw = w.shape[2], w.shape[3]
    # effective (dilated) kernel extents
    ekh, ekw = (kh - 1) * d[0] + 1, (kw - 1) * d[1] + 1
    # gradient-of-conv formulation: lhs-dilate input by stride, full-pad conv
    # with the spatially-flipped, IO-swapped, rhs-dilated kernel
    out = jax.lax.conv_general_dilated(
        x, jnp.flip(w, axis=(2, 3)).swapaxes(0, 1),
        window_strides=(1, 1),
        padding=[(ekh - 1 - p[0], ekh - 1 - p[0]),
                 (ekw - 1 - p[1], ekw - 1 - p[1])],
        lhs_dilation=s,
        rhs_dilation=d,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return {"Output": out}


def _pool_window(x, attrs, rank):
    """(window, strides, pads) for an N-spatial-dim pool; channels-last
    supported for rank 2 via data_format."""
    k = _pair(attrs.get("ksize", [2] * rank), rank)
    s = _pair(attrs.get("strides", [1] * rank), rank)
    p = _pair(attrs.get("paddings", [0] * rank), rank)
    nhwc = attrs.get("data_format", "NCHW") == "NHWC"
    sp_axes = (tuple(range(1, 1 + rank)) if nhwc
               else tuple(range(2, 2 + rank)))
    if attrs.get("global_pooling"):
        k = tuple(x.shape[a] for a in sp_axes)
        s, p = (1,) * rank, (0,) * rank
    sp_pads = tuple((pi, pi) for pi in p)
    if nhwc:
        return (1,) + k + (1,), (1,) + s + (1,), \
            ((0, 0),) + sp_pads + ((0, 0),)
    return (1, 1) + k, (1, 1) + s, ((0, 0), (0, 0)) + sp_pads


def _pool(x, attrs, rank):
    ptype = attrs.get("pooling_type", "max")
    window, strides, pads = _pool_window(x, attrs, rank)
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
            jnp.iinfo(x.dtype).min
        return jax.lax.reduce_window(x, init, jax.lax.max, window, strides,
                                     pads)
    ssum = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides,
                                 pads)
    cnt = jax.lax.reduce_window(jnp.ones_like(x), 0.0, jax.lax.add,
                                window, strides, pads)
    return ssum / cnt


def _pool2d(x, attrs):
    return _pool(x, attrs, 2)


@register_op("pool2d", inputs=("X",), outputs=("Out",),
             attrs={"pooling_type": "max", "ksize": [2, 2],
                    "strides": [1, 1], "paddings": [0, 0],
                    "global_pooling": False, "use_cudnn": True,
                    "data_format": "NCHW"})
def pool2d(ctx, ins, attrs):
    return {"Out": _pool2d(data_of(one(ins, "X")), attrs)}


@register_op("max_pool2d_with_index", inputs=("X",),
             outputs=("Out", "Mask"),
             attrs={"ksize": [2, 2], "strides": [1, 1], "paddings": [0, 0],
                    "global_pooling": False},
             diff_outputs=("Out",))
def max_pool2d_with_index(ctx, ins, attrs):
    """Max pool + flat-spatial argmax per window in one variadic pass
    (reference pool_with_index); int32 iota so indices stay exact."""
    x = data_of(one(ins, "X"))
    h, w = x.shape[2:]
    flat_idx = jnp.arange(h * w, dtype=jnp.int32).reshape(1, 1, h, w)
    flat_idx = jnp.broadcast_to(flat_idx, x.shape)
    window, strides, pads = _pool_window(x, attrs, 2)

    def sel(a, b):
        av, ai = a
        bv, bi = b
        take_b = bv > av
        return (jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai))

    init = (jnp.asarray(-jnp.inf, x.dtype), jnp.asarray(-1, jnp.int32))
    vals, idxs = jax.lax.reduce_window((x, flat_idx), init, sel,
                                       window, strides, pads)
    return {"Out": vals, "Mask": idxs.astype(jnp.int64)}


@register_op("spp", inputs=("X",), outputs=("Out",),
             attrs={"pyramid_height": 2, "pooling_type": "max"})
def spp(ctx, ins, attrs):
    """Spatial pyramid pooling (reference spp_op.cc)."""
    x = data_of(one(ins, "X"))
    n, c, h, w = x.shape
    outs = []
    for level in range(attrs["pyramid_height"]):
        bins = 2 ** level
        kh, kw = int(np.ceil(h / bins)), int(np.ceil(w / bins))
        ph, pw = (kh * bins - h + 1) // 2, (kw * bins - w + 1) // 2
        pooled = _pool2d(x, {"pooling_type": attrs["pooling_type"],
                             "ksize": [kh, kw], "strides": [kh, kw],
                             "paddings": [ph, pw]})
        outs.append(pooled.reshape(n, -1))
    return {"Out": jnp.concatenate(outs, axis=1)}


@register_op("unpool", inputs=("X", "Indices"), outputs=("Out",),
             attrs={"ksize": [2, 2], "strides": [2, 2], "paddings": [0, 0],
                    "unpooling_type": "max"},
             diff_inputs=("X",))
def unpool(ctx, ins, attrs):
    """Max-unpool via the saved flat indices (reference unpool_op.cc)."""
    x = data_of(one(ins, "X"))
    idx = data_of(one(ins, "Indices"))
    n, c, h, w = x.shape
    oh = (h - 1) * attrs["strides"][0] - 2 * attrs["paddings"][0] + \
        attrs["ksize"][0]
    ow = (w - 1) * attrs["strides"][1] - 2 * attrs["paddings"][1] + \
        attrs["ksize"][1]
    flat = jnp.zeros((n, c, oh * ow), x.dtype)
    out = jax.vmap(jax.vmap(
        lambda o, i, v: o.at[i].set(v)))(flat, idx.reshape(n, c, -1),
                                         x.reshape(n, c, -1))
    return {"Out": out.reshape(n, c, oh, ow)}


@register_op("conv_shift", inputs=("X", "Y"), outputs=("Out",))
def conv_shift(ctx, ins, attrs):
    """Circular correlation (reference conv_shift_op.cc): out[i,j] =
    sum_k x[i, (j+k-M/2) mod N] * y[i,k]."""
    x = data_of(one(ins, "X"))  # [B, N]
    y = data_of(one(ins, "Y"))  # [B, M], M odd
    m = y.shape[1]
    half = m // 2
    shifted = jnp.stack(
        [jnp.roll(x, shift=half - k, axis=1) for k in range(m)], axis=2)
    return {"Out": jnp.einsum("bnm,bm->bn", shifted, y)}


@register_op("row_conv", inputs=("X", "Filter"), outputs=("Out",))
def row_conv(ctx, ins, attrs):
    """Lookahead row convolution (reference row_conv_op.cc) over a batched
    [B, T, D] input; Filter is [future_context, D]."""
    from ..core.lod import LoDTensor

    xv = one(ins, "X")
    x = data_of(xv)
    w = data_of(one(ins, "Filter"))  # [K, D]
    k = w.shape[0]
    batched = x.ndim == 3
    if not batched:
        x3 = x[None]  # single sequence
    else:
        x3 = x
    pad = jnp.pad(x3, ((0, 0), (0, k - 1), (0, 0)))
    out = sum(pad[:, i:i + x3.shape[1], :] * w[i] for i in range(k))
    out = out if batched else out[0]
    if isinstance(xv, LoDTensor):
        return {"Out": LoDTensor(out, xv.lod)}
    return {"Out": out}


# ---------------------------------------------------------------------------
# 3D pooling + transposed conv3d (reference pool_op.cc REGISTER pool3d,
# pool_with_index_op.cc max_pool3d_with_index, conv_transpose_op.cc
# conv3d_transpose)
# ---------------------------------------------------------------------------


@register_op("pool3d", inputs=("X",), outputs=("Out",),
             attrs={"pooling_type": "max", "ksize": [2, 2, 2],
                    "strides": [1, 1, 1], "paddings": [0, 0, 0],
                    "global_pooling": False})
def pool3d(ctx, ins, attrs):
    return {"Out": _pool(data_of(one(ins, "X")), attrs, 3)}


@register_op("max_pool3d_with_index", inputs=("X",),
             outputs=("Out", "Mask"),
             attrs={"ksize": [2, 2, 2], "strides": [1, 1, 1],
                    "paddings": [0, 0, 0], "global_pooling": False},
             diff_outputs=("Out",))
def max_pool3d_with_index(ctx, ins, attrs):
    """Max pool + flat-spatial argmax index per window in ONE variadic
    reduce_window pass (reference pool_with_index_op.cc, 3D
    registration).  The index iota is int32 — float32 iotas collapse
    above 2^24 voxels."""
    x = data_of(one(ins, "X"))
    d, h, w = x.shape[2:]
    flat = jnp.arange(d * h * w, dtype=jnp.int32).reshape(1, 1, d, h, w)
    flat = jnp.broadcast_to(flat, x.shape)
    window, strides, pads = _pool_window(x, attrs, 3)

    def sel(a, b):
        av, ai = a
        bv, bi = b
        take_b = bv > av
        return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)

    init = (jnp.asarray(-jnp.inf, x.dtype), jnp.asarray(-1, jnp.int32))
    out, idx = jax.lax.reduce_window((x, flat), init, sel, window, strides,
                                     pads)
    return {"Out": out, "Mask": idx}


@register_op("conv3d_transpose", inputs=("Input", "Filter"),
             outputs=("Output",),
             attrs={"strides": [1, 1, 1], "paddings": [0, 0, 0],
                    "dilations": [1, 1, 1]})
def conv3d_transpose(ctx, ins, attrs):
    """Gradient-of-conv formulation, 3D (reference conv_transpose_op.cc
    conv3d_transpose registration); filter layout [C, M, kd, kh, kw]."""
    x = data_of(one(ins, "Input"))        # [N, C, D, H, W]
    w = data_of(one(ins, "Filter"))
    x, w = amp_cast(x, w)
    s = _pair(attrs["strides"], 3)
    p = _pair(attrs["paddings"], 3)
    d = _pair(attrs.get("dilations", [1, 1, 1]), 3)
    ks = w.shape[2:]
    ek = tuple((ks[i] - 1) * d[i] + 1 for i in range(3))
    out = jax.lax.conv_general_dilated(
        x, jnp.flip(w, axis=(2, 3, 4)).swapaxes(0, 1),
        window_strides=(1, 1, 1),
        padding=[(ek[i] - 1 - p[i], ek[i] - 1 - p[i]) for i in range(3)],
        lhs_dilation=s, rhs_dilation=d,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))
    return {"Output": out}
