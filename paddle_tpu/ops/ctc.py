"""CTC ops: warpctc (native CTC loss), ctc_align (greedy decode collapse).

Reference: /root/reference/paddle/fluid/operators/warpctc_op.{cc,h} (dynload
wrapper around Baidu warp-ctc + sequence_padding/sequence_scale plumbing) and
ctc_align_op.{cc,h}.

TPU design: instead of dynloading a CUDA library, CTC is computed natively —
the standard log-space alpha recursion over the blank-extended label sequence,
batched as ONE `lax.scan` over padded time (mask from the LoD, built host-side
per bucket).  It is differentiable by construction through the generic VJP
grad op (the reference needs warp-ctc's hand-written gradient)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.execution import data_of, one
from ..core.lod import LoDTensor, lod_from_seq_lens
from ..core.registry import register_op
from .sequence import lod_to_padded_index

NEG_INF = -1e30


def _logsumexp2(a, b):
    """Numerically-safe log(e^a + e^b) for values that may be NEG_INF.
    Differences are clipped so no exp(-inf)/log(0) appears even on the
    untaken `where` branch (whose NaNs would poison the VJP)."""
    m = jnp.maximum(a, b)
    m_safe = jnp.where(m <= NEG_INF / 2, 0.0, m)
    da = jnp.clip(a - m_safe, -80.0, 0.0)
    db = jnp.clip(b - m_safe, -80.0, 0.0)
    out = m_safe + jnp.log(jnp.exp(da) + jnp.exp(db))
    return jnp.where(m <= NEG_INF / 2, NEG_INF, out)


def _logsumexp3(a, b, c):
    return _logsumexp2(_logsumexp2(a, b), c)


@register_op("warpctc", inputs=("Logits", "Label"),
             outputs=("Loss", "WarpCTCGrad"),
             attrs={"blank": 0, "norm_by_times": False},
             diff_inputs=("Logits",), diff_outputs=("Loss",))
def warpctc(ctx, ins, attrs):
    """CTC negative log-likelihood per sequence.

    Logits: LoD rows [sum(T_i), C] of UNNORMALIZED activations (the reference
    applies softmax internally via warp-ctc); Label: LoD rows [sum(L_i), 1]
    int; blank index = attrs["blank"].  Loss: [num_seqs, 1]."""
    lv = one(ins, "Logits")
    labv = one(ins, "Label")
    blank = int(attrs.get("blank", 0))
    logits_lod = lv.lod[-1]
    label_lod = labv.lod[-1]

    idx, mask = lod_to_padded_index(logits_lod)     # [B, Tmax]
    B, Tmax = idx.shape
    logp_rows = jax.nn.log_softmax(data_of(lv), axis=-1)
    logp = logp_rows[idx]                            # [B, Tmax, C]
    tmask = jnp.asarray(mask)                        # [B, Tmax]

    # label VALUES are traced under jit; only the LoD layout is host-static
    lab_lens = [label_lod[i + 1] - label_lod[i] for i in range(B)]
    Lmax = max(lab_lens) if lab_lens else 0
    S = 2 * Lmax + 1
    lab_idx, lab_mask = lod_to_padded_index(label_lod)   # [B, Lmax] static
    labels_flat = data_of(labv).reshape(-1).astype(jnp.int32)
    lab_pad = jnp.where(jnp.asarray(lab_mask) > 0,
                        labels_flat[jnp.asarray(lab_idx)], blank)
    # blank-extended label sequences [B, S]: blank l1 blank l2 ... blank
    ext_j = jnp.full((B, S), blank, jnp.int32)
    ext_j = ext_j.at[:, 1::2].set(lab_pad)
    ext_len = np.asarray([2 * ln + 1 for ln in lab_lens], np.int64)
    # allow skip transition s-2 -> s when ext[s] != blank and != ext[s-2];
    # S may be 1 (all-empty labels) -> no skips at all
    skip_j = jnp.concatenate(
        [jnp.zeros((B, min(2, S))),
         ((ext_j[:, 2:] != blank) &
          (ext_j[:, 2:] != ext_j[:, :-2])).astype(jnp.float32)], axis=1)

    # alpha init: t=0 can start at s=0 (blank) or s=1 (first label)
    lp0 = jnp.take_along_axis(logp[:, 0, :], ext_j, axis=1)  # [B, S]
    start_mask = np.full((B, S), NEG_INF, np.float32)
    start_mask[:, 0] = 0.0
    for b in range(B):
        if lab_lens[b] > 0:
            start_mask[b, 1] = 0.0
    alpha0 = lp0 + jnp.asarray(start_mask)

    def step(alpha, xs):
        logp_t, m_t = xs                             # [B, C], [B]
        lp = jnp.take_along_axis(logp_t, ext_j, axis=1)   # [B, S]
        # pad-then-slice keeps shapes right even when S < 2
        a_shift1 = jnp.pad(alpha, ((0, 0), (1, 0)),
                           constant_values=NEG_INF)[:, :S]
        a_shift2 = jnp.pad(alpha, ((0, 0), (2, 0)),
                           constant_values=NEG_INF)[:, :S]
        a_skip = jnp.where(skip_j > 0, a_shift2, NEG_INF)
        nxt = _logsumexp3(alpha, a_shift1, a_skip) + lp
        return jnp.where(m_t[:, None] > 0, nxt, alpha), None

    if Tmax > 1:
        alpha_last, _ = jax.lax.scan(
            step, alpha0,
            (jnp.swapaxes(logp, 0, 1)[1:], tmask.T[1:]))
    else:
        alpha_last = alpha0
    # p = alpha[ext_len-1] + alpha[ext_len-2]
    last1 = jnp.take_along_axis(
        alpha_last, jnp.asarray(ext_len - 1)[:, None], axis=1)[:, 0]
    idx2 = np.maximum(ext_len - 2, 0)
    last2_raw = jnp.take_along_axis(
        alpha_last, jnp.asarray(idx2)[:, None], axis=1)[:, 0]
    last2 = jnp.where(jnp.asarray(ext_len) >= 2, last2_raw, NEG_INF)
    loss = -_logsumexp2(last1, last2)                 # [B]
    if attrs.get("norm_by_times"):
        lens = jnp.asarray(
            [logits_lod[i + 1] - logits_lod[i] for i in range(B)],
            loss.dtype)
        loss = loss / lens
    return {"Loss": loss[:, None],
            "WarpCTCGrad": LoDTensor(jnp.zeros_like(data_of(lv)), lv.lod)}


@register_op("ctc_align", inputs=("Input",), outputs=("Output",),
             attrs={"blank": 0, "merge_repeated": True},
             not_differentiable=True, host=True)
def ctc_align(ctx, ins, attrs):
    """Greedy CTC decode: merge repeats then drop blanks (reference
    ctc_align_op.h) — dynamic output size, so a host op."""
    xv = one(ins, "Input")
    x = np.asarray(data_of(xv)).reshape(-1)
    lod = xv.lod[-1]
    blank = int(attrs["blank"])
    merge = bool(attrs.get("merge_repeated", True))
    out_rows, out_lens = [], []
    for i in range(len(lod) - 1):
        seq = x[lod[i]:lod[i + 1]]
        prev = None
        kept = []
        for t in seq:
            t = int(t)
            if merge and prev is not None and t == prev:
                prev = t
                continue
            if t != blank:
                kept.append(t)
            prev = t
        out_rows.extend(kept)
        out_lens.append(len(kept))
    data = np.asarray(out_rows, np.int64).reshape(-1, 1) if out_rows \
        else np.zeros((0, 1), np.int64)
    return {"Output": LoDTensor(data, [lod_from_seq_lens(out_lens)])}


# -- explicit build-time shape inference (LoD-dependent) ---------------------

from ..core.registry import register_infer_shape  # noqa: E402
from ..core.shape_inference import input_var, set_output_shape  # noqa: E402


@register_infer_shape("warpctc")
def _infer_warpctc(op, block):
    logits = input_var(op, block, "Logits")
    if logits is None or logits.shape is None:
        return
    # one loss row per sequence; the count lives in the LoD
    set_output_shape(op, block, "Loss", (-1, 1), logits.dtype)
    set_output_shape(op, block, "WarpCTCGrad", logits.shape, logits.dtype)
