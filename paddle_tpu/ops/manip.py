"""Tensor manipulation ops: reshape/transpose/concat/split/expand/pad/crop/
gather/scatter/top_k/sequence-agnostic reorderings.

Reference: /root/reference/paddle/fluid/operators/{reshape,transpose,concat,
split,expand,pad,crop,gather,scatter,top_k}_op.cc.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.execution import data_of, many, one, with_lod_of
from ..core.lod import LoDTensor
from ..core.registry import register_op


@register_op("reshape", inputs=("X",), outputs=("Out",),
             attrs={"shape": []})
def reshape(ctx, ins, attrs):
    xv = one(ins, "X")
    x = data_of(xv)
    shape = list(attrs["shape"])
    # reference reshape_op: 0 keeps the original dim, -1 infers
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = x.shape[i]
    return {"Out": with_lod_of(xv, x.reshape(shape))}


@register_op("transpose", inputs=("X",), outputs=("Out",),
             attrs={"axis": []})
def transpose(ctx, ins, attrs):
    x = data_of(one(ins, "X"))
    return {"Out": jnp.transpose(x, attrs["axis"] or None)}


@register_op("concat", inputs=("X",), outputs=("Out",),
             dup_inputs=("X",),
             attrs={"axis": 0})
def concat(ctx, ins, attrs):
    vs = many(ins, "X")
    out = jnp.concatenate([data_of(v) for v in vs], axis=attrs["axis"])
    if attrs["axis"] != 0 and isinstance(vs[0], LoDTensor):
        # feature-axis concat keeps the row structure: share Ins[0]'s lod
        # specifically (reference concat_op.cc) — not whichever input
        # happens to carry one
        return {"Out": LoDTensor(out, list(vs[0].lod))}
    return {"Out": out}


@register_op("split", inputs=("X",), outputs=("Out",),
             dup_outputs=("Out",),
             attrs={"axis": 0, "num": 0, "sections": []})
def split(ctx, ins, attrs):
    x = data_of(one(ins, "X"))
    axis = attrs["axis"]
    if attrs.get("sections"):
        idx = np.cumsum(attrs["sections"])[:-1].tolist()
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, attrs["num"], axis=axis)
    return {"Out": list(outs)}


@register_op("expand", inputs=("X",), outputs=("Out",),
             attrs={"expand_times": []})
def expand(ctx, ins, attrs):
    x = data_of(one(ins, "X"))
    return {"Out": jnp.tile(x, attrs["expand_times"])}


@register_op("pad", inputs=("X",), outputs=("Out",),
             attrs={"paddings": [], "pad_value": 0.0})
def pad(ctx, ins, attrs):
    x = data_of(one(ins, "X"))
    p = attrs["paddings"]
    pairs = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return {"Out": jnp.pad(x, pairs, constant_values=attrs["pad_value"])}


@register_op("crop", inputs=("X", "Y"), outputs=("Out",),
             attrs={"offsets": [], "shape": []})
def crop(ctx, ins, attrs):
    x = data_of(one(ins, "X"))
    y = one(ins, "Y")
    shape = tuple(data_of(y).shape) if y is not None else tuple(attrs["shape"])
    offsets = attrs.get("offsets") or [0] * x.ndim
    return {"Out": jax.lax.dynamic_slice(x, offsets, shape)}


@register_op("gather", inputs=("X", "Index"), outputs=("Out",),
             diff_inputs=("X",))
def gather(ctx, ins, attrs):
    x = data_of(one(ins, "X"))
    idx = data_of(one(ins, "Index")).reshape(-1)
    return {"Out": jnp.take(x, idx, axis=0)}


@register_op("scatter", inputs=("X", "Ids", "Updates"), outputs=("Out",),
             diff_inputs=("X", "Updates"))
def scatter(ctx, ins, attrs):
    """Reference scatter_op: Out = X; Out[Ids] = Updates (overwrite)."""
    x = data_of(one(ins, "X"))
    ids = data_of(one(ins, "Ids")).reshape(-1)
    upd = data_of(one(ins, "Updates"))
    return {"Out": x.at[ids].set(upd)}


@register_op("top_k", inputs=("X",), outputs=("Out", "Indices"),
             attrs={"k": 1}, diff_outputs=())
def top_k(ctx, ins, attrs):
    xv = one(ins, "X")
    x = data_of(xv)
    vals, idx = jax.lax.top_k(x, attrs["k"])
    return {"Out": with_lod_of(xv, vals),
            "Indices": with_lod_of(xv, idx.astype(jnp.int64))}


@register_op("unsqueeze", inputs=("X",), outputs=("Out",),
             attrs={"axes": []})
def unsqueeze(ctx, ins, attrs):
    x = data_of(one(ins, "X"))
    for a in sorted(attrs["axes"]):
        x = jnp.expand_dims(x, a)
    return {"Out": x}


@register_op("squeeze", inputs=("X",), outputs=("Out",),
             attrs={"axes": []})
def squeeze(ctx, ins, attrs):
    x = data_of(one(ins, "X"))
    axes = attrs.get("axes")
    return {"Out": jnp.squeeze(x, axis=tuple(axes) if axes else None)}


@register_op("stack", inputs=("X",), outputs=("Out",), attrs={"axis": 0},
             dup_inputs=("X",))
def stack(ctx, ins, attrs):
    xs = [data_of(v) for v in many(ins, "X")]
    return {"Out": jnp.stack(xs, axis=attrs["axis"])}


@register_op("slice", inputs=("Input",), outputs=("Out",),
             attrs={"axes": [], "starts": [], "ends": []})
def slice_op(ctx, ins, attrs):
    x = data_of(one(ins, "Input"))
    idx = [slice(None)] * x.ndim
    for a, s, e in zip(attrs["axes"], attrs["starts"], attrs["ends"]):
        idx[a] = slice(s, e)
    return {"Out": x[tuple(idx)]}


@register_op("flatten", inputs=("X",), outputs=("Out",), attrs={"axis": 1})
def flatten(ctx, ins, attrs):
    x = data_of(one(ins, "X"))
    a = attrs["axis"]
    lead = int(np.prod(x.shape[:a], dtype=np.int64)) if a else 1
    return {"Out": x.reshape(lead, -1)}


@register_op("reverse", inputs=("X",), outputs=("Out",), attrs={"axis": [0]})
def reverse(ctx, ins, attrs):
    x = data_of(one(ins, "X"))
    ax = attrs["axis"]
    ax = ax if isinstance(ax, (list, tuple)) else [ax]
    return {"Out": jnp.flip(x, axis=tuple(ax))}
