"""Attention op backed by the Pallas flash-attention kernel.

The reference has no attention operator — attention is composed from
matmul/softmax ops (/root/reference/python/paddle/v2/fluid/nets.py:162-219).
The rebuild promotes it to a first-class op so the hot path runs the
Pallas kernel (kernels/flash_attention.py) instead of materializing the
score matrix; the generic-VJP grad machinery picks up the kernel's
custom_vjp automatically.
"""
from __future__ import annotations

from ..core.execution import data_of, one
from ..core.registry import register_op
from ..kernels import flash_attention as _flash


@register_op("flash_attention", inputs=("Q", "K", "V"), outputs=("Out",),
             attrs={"causal": False, "scale": 1.0, "default_scale": True,
                    "min_seq_k": -1},
             cost="attention")
def flash_attention_op(ctx, ins, attrs):
    """Q/K/V: [batch, seq, heads, head_dim].  default_scale=True ->
    1/sqrt(head_dim); otherwise the explicit `scale` attr (0.0 included).
    min_seq_k: -1 = kernel policy default (XLA composition below ~2k K/V
    length, where it measures faster); 0 forces the Pallas kernel."""
    q = data_of(one(ins, "Q"))
    k = data_of(one(ins, "K"))
    v = data_of(one(ins, "V"))
    scale = None if attrs.get("default_scale", True) else attrs["scale"]
    # sequence parallelism: when the executor runs this op inside a
    # shard_map whose ExecContext carries sp_axis (PipelineExecutor's
    # staged trunk with sp), q/k/v arrive as LOCAL sequence blocks and
    # attention must ring the K/V shards over that manual axis
    root = getattr(ctx, "root", None)
    sp_axis = getattr(root, "sp_axis", None) if root is not None else None
    if sp_axis:
        from ..parallel.ring_attention import ring_attention_local
        out = ring_attention_local(
            q, k, v, sp_axis, int(root.sp_size),
            causal=bool(attrs.get("causal", False)), scale=scale)
        return {"Out": out}
    kw = {}
    msk = int(attrs.get("min_seq_k", -1))
    if msk < 0:
        # per-op attr unset: the process-wide flag may override the
        # kernel's crossover policy (see core/flags.py flash_min_seq_k)
        from ..core.flags import get_flag
        msk = int(get_flag("flash_min_seq_k"))
    if msk >= 0:
        kw["min_seq_k"] = msk
    out = _flash(q, k, v, causal=bool(attrs.get("causal", False)),
                 scale=scale, **kw)
    return {"Out": out}
