"""Detection (CV) op family: prior_box, box_coder, iou_similarity,
bipartite_match, target_assign, mine_hard_examples, multiclass_nms,
roi_pool, detection_map.

Reference: /root/reference/paddle/fluid/operators/{prior_box_op.h,
box_coder_op.h, iou_similarity_op.h, bipartite_match_op.cc,
target_assign_op.h, mine_hard_examples_op.cc, multiclass_nms_op.cc,
roi_pool_op.h, detection_map_op.h}.

TPU split: dense geometry (prior_box constants, box encode/decode, IoU
matrices, target gathering, ROI pooling via masked reductions) lowers to
jax and stays on device; the intrinsically sequential/dynamic-output
algorithms (greedy bipartite matching, hard-example mining, NMS, mAP) are
host ops — exactly the ops that are CPU-only kernels in the reference too.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.execution import data_of, one
from ..core.lod import LoDTensor, lod_from_seq_lens
from ..core.registry import register_op


# ---------------------------------------------------------------------------
# prior_box
# ---------------------------------------------------------------------------


def _expand_aspect_ratios(aspect_ratios, flip):
    """prior_box_op.h ExpandAspectRatios: start from 1.0, dedupe, add 1/ar
    when flip."""
    out = [1.0]
    for ar in aspect_ratios:
        if any(abs(ar - o) < 1e-6 for o in out):
            continue
        out.append(float(ar))
        if flip:
            out.append(1.0 / float(ar))
    return out


@register_op("prior_box", inputs=("Input", "Image"),
             outputs=("Boxes", "Variances"),
             attrs={"min_sizes": [], "max_sizes": [], "aspect_ratios": [],
                    "variances": [0.1, 0.1, 0.2, 0.2], "flip": True,
                    "clip": True, "step_w": 0.0, "step_h": 0.0,
                    "offset": 0.5},
             not_differentiable=True)
def prior_box(ctx, ins, attrs):
    """SSD prior boxes [H, W, num_priors, 4] (prior_box_op.h kernel).  Boxes
    depend only on static shapes + attrs, so they are computed host-side and
    enter the graph as constants."""
    x = data_of(one(ins, "Input"))
    img = data_of(one(ins, "Image"))
    fh, fw = int(x.shape[2]), int(x.shape[3])
    ih, iw = int(img.shape[2]), int(img.shape[3])
    min_sizes = [float(s) for s in attrs["min_sizes"]]
    max_sizes = [float(s) for s in attrs["max_sizes"]]
    ars = _expand_aspect_ratios(attrs["aspect_ratios"], attrs["flip"])
    variances = [float(v) for v in attrs["variances"]]
    offset = float(attrs["offset"])
    step_w = float(attrs["step_w"]) or iw / fw
    step_h = float(attrs["step_h"]) or ih / fh

    num_priors = len(ars) * len(min_sizes) + len(max_sizes)
    boxes = np.zeros((fh, fw, num_priors, 4), np.float32)
    for h in range(fh):
        for w in range(fw):
            cx = (w + offset) * step_w
            cy = (h + offset) * step_h
            k = 0

            def put(bw, bh, k):
                boxes[h, w, k] = [(cx - bw / 2) / iw, (cy - bh / 2) / ih,
                                  (cx + bw / 2) / iw, (cy + bh / 2) / ih]
                return k + 1

            for s, ms in enumerate(min_sizes):
                k = put(ms, ms, k)
                if max_sizes:
                    sz = math.sqrt(ms * max_sizes[s])
                    k = put(sz, sz, k)
                for ar in ars:
                    if abs(ar - 1.0) < 1e-6:
                        continue
                    k = put(ms * math.sqrt(ar), ms / math.sqrt(ar), k)
    if attrs["clip"]:
        boxes = np.clip(boxes, 0.0, 1.0)
    var = np.tile(np.asarray(variances, np.float32),
                  (fh, fw, num_priors, 1))
    return {"Boxes": jnp.asarray(boxes), "Variances": jnp.asarray(var)}


# ---------------------------------------------------------------------------
# box_coder / iou_similarity
# ---------------------------------------------------------------------------


def _center_size(box):
    """[..., 4] xyxy -> (cx, cy, w, h)"""
    w = box[..., 2] - box[..., 0]
    h = box[..., 3] - box[..., 1]
    cx = (box[..., 2] + box[..., 0]) / 2
    cy = (box[..., 3] + box[..., 1]) / 2
    return cx, cy, w, h


@register_op("box_coder", inputs=("PriorBox", "PriorBoxVar", "TargetBox"),
             outputs=("OutputBox",),
             attrs={"code_type": "encode_center_size"},
             diff_inputs=("TargetBox",))
def box_coder(ctx, ins, attrs):
    """Encode/decode boxes against priors (box_coder_op.h).  Output
    [row, col, 4] where row indexes target boxes, col indexes priors."""
    prior = data_of(one(ins, "PriorBox"))          # [col, 4]
    pvar = data_of(one(ins, "PriorBoxVar"))        # [col, 4]
    tb_v = one(ins, "TargetBox")
    target = data_of(tb_v)                          # [row, 4] / [row, col, 4]
    pcx, pcy, pw, ph = _center_size(prior)          # [col]
    if attrs["code_type"] == "encode_center_size":
        tcx, tcy, tw, th = _center_size(target)     # [row]
        ox = (tcx[:, None] - pcx[None, :]) / pw[None, :] / pvar[None, :, 0]
        oy = (tcy[:, None] - pcy[None, :]) / ph[None, :] / pvar[None, :, 1]
        ow = jnp.log(jnp.abs(tw[:, None] / pw[None, :])) / pvar[None, :, 2]
        oh = jnp.log(jnp.abs(th[:, None] / ph[None, :])) / pvar[None, :, 3]
        out = jnp.stack([ox, oy, ow, oh], axis=-1)  # [row, col, 4]
    else:  # decode_center_size: target [row, col, 4] deltas
        dcx = pvar[None, :, 0] * target[..., 0] * pw[None, :] + pcx[None, :]
        dcy = pvar[None, :, 1] * target[..., 1] * ph[None, :] + pcy[None, :]
        dw = jnp.exp(pvar[None, :, 2] * target[..., 2]) * pw[None, :]
        dh = jnp.exp(pvar[None, :, 3] * target[..., 3]) * ph[None, :]
        out = jnp.stack([dcx - dw / 2, dcy - dh / 2,
                         dcx + dw / 2, dcy + dh / 2], axis=-1)
    return {"OutputBox": out}


@register_op("iou_similarity", inputs=("X", "Y"), outputs=("Out",),
             diff_inputs=())
def iou_similarity(ctx, ins, attrs):
    """Pairwise IoU matrix [N, M] (iou_similarity_op.h)."""
    xv = one(ins, "X")
    x = data_of(xv)                                 # [N, 4]
    y = data_of(one(ins, "Y"))                      # [M, 4]
    ix1 = jnp.maximum(x[:, None, 0], y[None, :, 0])
    iy1 = jnp.maximum(x[:, None, 1], y[None, :, 1])
    ix2 = jnp.minimum(x[:, None, 2], y[None, :, 2])
    iy2 = jnp.minimum(x[:, None, 3], y[None, :, 3])
    iw = jnp.maximum(ix2 - ix1, 0.0)
    ih = jnp.maximum(iy2 - iy1, 0.0)
    inter = iw * ih
    ax = (x[:, 2] - x[:, 0]) * (x[:, 3] - x[:, 1])
    ay = (y[:, 2] - y[:, 0]) * (y[:, 3] - y[:, 1])
    union = ax[:, None] + ay[None, :] - inter
    out = jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)
    if isinstance(xv, LoDTensor) and xv.lod:
        return {"Out": LoDTensor(out, xv.lod)}
    return {"Out": out}


# ---------------------------------------------------------------------------
# bipartite_match (host greedy, bipartite_match_op.cc)
# ---------------------------------------------------------------------------


@register_op("bipartite_match", inputs=("DistMat",),
             outputs=("ColToRowMatchIndices", "ColToRowMatchDist"),
             not_differentiable=True, host=True)
def bipartite_match(ctx, ins, attrs):
    dv = one(ins, "DistMat")
    dist_all = np.asarray(data_of(dv))
    if isinstance(dv, LoDTensor) and dv.lod:
        offs = dv.lod[-1]
    else:
        offs = (0, dist_all.shape[0])
    n = len(offs) - 1
    col = dist_all.shape[1]
    match_idx = -np.ones((n, col), np.int32)
    match_dist = np.zeros((n, col), np.float32)
    eps = 1e-6
    for b in range(n):
        dist = dist_all[offs[b]:offs[b + 1]]
        row_pool = list(range(dist.shape[0]))
        while row_pool:
            best = (-1, -1, -1.0)  # (col, row, dist)
            for j in range(col):
                if match_idx[b, j] != -1:
                    continue
                for m in row_pool:
                    d = dist[m, j]
                    if d < eps:
                        continue
                    if d > best[2]:
                        best = (j, m, float(d))
            if best[0] == -1:
                break
            match_idx[b, best[0]] = best[1]
            match_dist[b, best[0]] = best[2]
            row_pool.remove(best[1])
    return {"ColToRowMatchIndices": match_idx,
            "ColToRowMatchDist": match_dist}


# ---------------------------------------------------------------------------
# target_assign (device gather, target_assign_op.h)
# ---------------------------------------------------------------------------


@register_op("target_assign",
             inputs=("X", "MatchIndices", "NegIndices"),
             outputs=("Out", "OutWeight"),
             attrs={"mismatch_value": 0}, not_differentiable=True)
def target_assign(ctx, ins, attrs):
    """out[n, m] = X[lod[n] + match[n, m], m % P] when matched, else
    mismatch_value; weight 1/0; negative indices get weight 1."""
    xv = one(ins, "X")
    x = data_of(xv)
    if x.ndim == 2:
        x = x[:, None, :]
    lod = xv.lod[-1] if isinstance(xv, LoDTensor) and xv.lod else None
    match = data_of(one(ins, "MatchIndices")).astype(jnp.int32)  # [N, M]
    N, M = match.shape
    P, K = x.shape[1], x.shape[2]
    if lod is None:
        lod = tuple(range(N + 1))
    off = jnp.asarray(np.asarray(lod[:-1], np.int32))[:, None]   # [N, 1]
    rows = off + jnp.maximum(match, 0)                           # [N, M]
    cols = jnp.asarray(np.arange(M, dtype=np.int32) % P)[None, :]
    gathered = x[rows, jnp.broadcast_to(cols, rows.shape)]       # [N, M, K]
    matched = (match > -1)
    mismatch = jnp.asarray(float(attrs["mismatch_value"]), x.dtype)
    out = jnp.where(matched[:, :, None], gathered, mismatch)
    wt = matched.astype(jnp.float32)
    neg = one(ins, "NegIndices")
    if neg is not None:
        neg_rows = data_of(neg).reshape(-1).astype(jnp.int32)
        neg_lod = neg.lod[-1] if isinstance(neg, LoDTensor) and neg.lod \
            else (0, neg_rows.shape[0])
        img_of_row = np.zeros(neg_lod[-1], np.int32)
        for i in range(len(neg_lod) - 1):
            img_of_row[neg_lod[i]:neg_lod[i + 1]] = i
        flat = jnp.asarray(img_of_row) * M + neg_rows
        wt = wt.reshape(-1).at[flat].set(1.0).reshape(N, M)
        out = out.reshape(N * M, K).at[flat].set(mismatch).reshape(N, M, K)
    return {"Out": out, "OutWeight": wt[:, :, None]}


# ---------------------------------------------------------------------------
# mine_hard_examples (host, mine_hard_examples_op.cc)
# ---------------------------------------------------------------------------


@register_op("mine_hard_examples",
             inputs=("ClsLoss", "LocLoss", "MatchIndices", "MatchDist"),
             outputs=("NegIndices", "UpdatedMatchIndices"),
             attrs={"neg_pos_ratio": 3.0, "neg_dist_threshold": 0.5,
                    "mining_type": "max_negative", "sample_size": 0},
             not_differentiable=True, host=True)
def mine_hard_examples(ctx, ins, attrs):
    cls_loss = np.asarray(data_of(one(ins, "ClsLoss")))
    loc = one(ins, "LocLoss")
    loc_loss = np.asarray(data_of(loc)) if loc is not None else None
    match = np.asarray(data_of(one(ins, "MatchIndices"))).copy()
    mdist = np.asarray(data_of(one(ins, "MatchDist")))
    ratio = float(attrs["neg_pos_ratio"])
    thresh = float(attrs["neg_dist_threshold"])
    mtype = attrs["mining_type"]
    sample_size = int(attrs.get("sample_size") or 0)
    N, M = match.shape
    neg_rows, neg_lens = [], []
    for n in range(N):
        cands = []
        for m in range(M):
            if mtype == "max_negative":
                ok = match[n, m] == -1 and mdist[n, m] < thresh
            else:
                ok = True
            if ok:
                loss = cls_loss[n, m]
                if mtype == "hard_example" and loc_loss is not None:
                    loss = loss + loc_loss[n, m]
                cands.append((float(loss), m))
        if mtype == "max_negative":
            num_pos = int((match[n] != -1).sum())
            neg_sel = min(int(num_pos * ratio), len(cands))
        else:
            neg_sel = min(sample_size, len(cands))
        cands.sort(key=lambda t: -t[0])
        sel = sorted(m for _, m in cands[:neg_sel])
        if mtype == "hard_example":
            keep = {m for _, m in cands[:neg_sel]}
            for m in range(M):
                if match[n, m] > -1 and m not in keep:
                    match[n, m] = -1
        neg_rows.extend(sel)
        neg_lens.append(len(sel))
    neg = np.asarray(neg_rows, np.int32).reshape(-1, 1) if neg_rows \
        else np.zeros((0, 1), np.int32)
    return {"NegIndices": LoDTensor(neg, [lod_from_seq_lens(neg_lens)]),
            "UpdatedMatchIndices": match}


# ---------------------------------------------------------------------------
# multiclass_nms (host, multiclass_nms_op.cc)
# ---------------------------------------------------------------------------


def _nms_single(boxes, scores, score_threshold, nms_threshold, eta, top_k):
    """multiclass_nms_op.cc NMSFast: greedy IoU suppression."""
    idx = [i for i in range(len(scores)) if scores[i] > score_threshold]
    idx.sort(key=lambda i: -scores[i])
    if top_k > -1:
        idx = idx[:top_k]
    kept = []
    adaptive_threshold = nms_threshold
    for i in idx:
        keep = True
        for k in kept:
            iou = _iou_np(boxes[i], boxes[k])
            if iou > adaptive_threshold:
                keep = False
                break
        if keep:
            kept.append(i)
            if eta < 1 and adaptive_threshold > 0.5:
                adaptive_threshold *= eta
    return kept


def _iou_np(a, b):
    ix1, iy1 = max(a[0], b[0]), max(a[1], b[1])
    ix2, iy2 = min(a[2], b[2]), min(a[3], b[3])
    inter = max(ix2 - ix1, 0.0) * max(iy2 - iy1, 0.0)
    ua = (a[2] - a[0]) * (a[3] - a[1]) + (b[2] - b[0]) * (b[3] - b[1]) - inter
    return inter / ua if ua > 0 else 0.0


@register_op("multiclass_nms", inputs=("BBoxes", "Scores"),
             outputs=("Out",),
             attrs={"background_label": 0, "score_threshold": 0.01,
                    "nms_top_k": 400, "nms_threshold": 0.3, "nms_eta": 1.0,
                    "keep_top_k": 200},
             not_differentiable=True, host=True)
def multiclass_nms(ctx, ins, attrs):
    """BBoxes [N, M, 4] (shared across classes), Scores [N, C, M] ->
    LoD output [num_kept, 6]: label, score, xmin, ymin, xmax, ymax."""
    bboxes = np.asarray(data_of(one(ins, "BBoxes")))
    scores = np.asarray(data_of(one(ins, "Scores")))
    if bboxes.ndim == 2:
        bboxes = bboxes[None]
        scores = scores[None]
    N, C, M = scores.shape
    bg = int(attrs["background_label"])
    rows, lens = [], []
    for n in range(N):
        dets = []
        for c in range(C):
            if c == bg:
                continue
            kept = _nms_single(bboxes[n], scores[n, c],
                               attrs["score_threshold"],
                               attrs["nms_threshold"], attrs["nms_eta"],
                               attrs["nms_top_k"])
            for i in kept:
                dets.append([float(c), float(scores[n, c, i])] +
                            [float(v) for v in bboxes[n, i]])
        keep_top_k = int(attrs["keep_top_k"])
        if keep_top_k > -1 and len(dets) > keep_top_k:
            dets.sort(key=lambda d: -d[1])
            dets = dets[:keep_top_k]
        rows.extend(dets)
        lens.append(len(dets))
    data = np.asarray(rows, np.float32) if rows \
        else np.zeros((0, 6), np.float32)
    return {"Out": LoDTensor(data, [lod_from_seq_lens(lens)])}


# ---------------------------------------------------------------------------
# roi_pool (device: masked max over bins, roi_pool_op.h)
# ---------------------------------------------------------------------------


@register_op("roi_pool", inputs=("X", "ROIs"), outputs=("Out", "Argmax"),
             attrs={"spatial_scale": 1.0, "pooled_height": 1,
                    "pooled_width": 1},
             diff_inputs=("X",), diff_outputs=("Out",))
def roi_pool(ctx, ins, attrs):
    """Max-pool each ROI into a pooled_h x pooled_w grid.  The reference
    loops bins with dynamic extents; here each bin is a masked max over the
    full feature map (bin membership computed from traced ROI coords), which
    keeps shapes static for XLA."""
    x = data_of(one(ins, "X"))                     # [N, C, H, W]
    roi_v = one(ins, "ROIs")
    rois = data_of(roi_v)                          # [R, 4]
    scale = float(attrs["spatial_scale"])
    ph, pw = int(attrs["pooled_height"]), int(attrs["pooled_width"])
    N, C, H, W = x.shape
    R = rois.shape[0]
    if isinstance(roi_v, LoDTensor) and roi_v.lod:
        lod = roi_v.lod[-1]
        batch_of_roi = np.zeros(R, np.int32)
        for i in range(len(lod) - 1):
            batch_of_roi[lod[i]:lod[i + 1]] = i
    else:
        batch_of_roi = np.zeros(R, np.int32)
    b_idx = jnp.asarray(batch_of_roi)

    r = jnp.round(rois * scale)
    x1, y1, x2, y2 = r[:, 0], r[:, 1], r[:, 2], r[:, 3]
    roi_h = jnp.maximum(y2 - y1 + 1, 1.0)          # [R]
    roi_w = jnp.maximum(x2 - x1 + 1, 1.0)
    bin_h = roi_h / ph
    bin_w = roi_w / pw

    hs = jnp.arange(H, dtype=x.dtype)
    ws = jnp.arange(W, dtype=x.dtype)
    # bin start/end per (roi, bin_index): [R, ph]
    i_idx = jnp.arange(ph, dtype=x.dtype)
    j_idx = jnp.arange(pw, dtype=x.dtype)
    hstart = jnp.floor(i_idx[None, :] * bin_h[:, None]) + y1[:, None]
    hend = jnp.ceil((i_idx[None, :] + 1) * bin_h[:, None]) + y1[:, None]
    wstart = jnp.floor(j_idx[None, :] * bin_w[:, None]) + x1[:, None]
    wend = jnp.ceil((j_idx[None, :] + 1) * bin_w[:, None]) + x1[:, None]
    mask_h = ((hs[None, None, :] >= hstart[:, :, None]) &
              (hs[None, None, :] < hend[:, :, None]))   # [R, ph, H]
    mask_w = ((ws[None, None, :] >= wstart[:, :, None]) &
              (ws[None, None, :] < wend[:, :, None]))   # [R, pw, W]
    feats = x[b_idx]                                    # [R, C, H, W]
    masked = jnp.where(
        mask_h[:, None, :, None, :, None] & mask_w[:, None, None, :, None, :],
        feats[:, :, None, None, :, :], -jnp.inf)        # [R,C,ph,pw,H,W]
    masked_r = masked.reshape(R, C, ph, pw, H * W)
    # route the max through the Argmax indices the op already computes
    # (reference roi_pool backward does exactly this, roi_pool_op.cu) —
    # index routing is also immune to the TPU fusion false-tie hazard
    # of equality-based max VJPs (see ops/reduce.py)
    arg = jax.lax.stop_gradient(jnp.argmax(masked_r, axis=-1))
    out = jnp.take_along_axis(masked_r, arg[..., None], axis=-1)[..., 0]
    out = jnp.where(jnp.isfinite(out), out, 0.0)
    return {"Out": out, "Argmax": arg.astype(jnp.int64)}


# ---------------------------------------------------------------------------
# detection_map (host metric, detection_map_op.h)
# ---------------------------------------------------------------------------


@register_op("detection_map", inputs=("DetectRes", "Label"),
             outputs=("MAP",),
             attrs={"overlap_threshold": 0.5, "evaluate_difficult": True,
                    "ap_type": "integral"},
             not_differentiable=True, host=True)
def detection_map(ctx, ins, attrs):
    """mean Average Precision over a batch.  DetectRes: LoD [Nd, 6]
    (label, score, box); Label: LoD [Ng, 6] (label, xmin, ymin, xmax, ymax,
    difficult) or [Ng, 5]."""
    det_v = one(ins, "DetectRes")
    gt_v = one(ins, "Label")
    det = np.asarray(data_of(det_v))
    gt = np.asarray(data_of(gt_v))
    d_lod = det_v.lod[-1]
    g_lod = gt_v.lod[-1]
    thresh = float(attrs["overlap_threshold"])
    ap_type = attrs["ap_type"]
    n = len(d_lod) - 1

    # gather per-class (score, tp) pairs and gt counts; matching is greedy
    # per image in descending score order, but the PR curve must rank ALL
    # detections of a class globally by score
    cls_entries = {}  # class -> [(score, tp)]
    gt_count = {}
    for b in range(n):
        dets = det[d_lod[b]:d_lod[b + 1]]
        gts = gt[g_lod[b]:g_lod[b + 1]]
        used = np.zeros(len(gts), bool)
        for c in set(int(g[0]) for g in gts):
            gt_count[c] = gt_count.get(c, 0) + sum(
                1 for g in gts if int(g[0]) == c)
        for d in sorted(dets, key=lambda d: -d[1]):
            c = int(d[0])
            best_iou, best_j = 0.0, -1
            for j, g in enumerate(gts):
                if int(g[0]) != c or used[j]:
                    continue
                iou = _iou_np(d[2:6], g[1:5])
                if iou > best_iou:
                    best_iou, best_j = iou, j
            tp = best_iou > thresh and best_j >= 0
            if tp:
                used[best_j] = True
            cls_entries.setdefault(c, []).append((float(d[1]),
                                                  1 if tp else 0))

    aps = []
    for c, count in gt_count.items():
        if count == 0:
            continue
        entries = sorted(cls_entries.get(c, []), key=lambda e: -e[0])
        if not entries:
            aps.append(0.0)
            continue
        tps = np.asarray([tp for _, tp in entries], np.float64)
        cum_tp = np.cumsum(tps)
        cum_fp = np.cumsum(1 - tps)
        recall = cum_tp / count
        precision = cum_tp / np.maximum(cum_tp + cum_fp, 1e-10)
        if ap_type == "11point":
            ap = 0.0
            for t in np.linspace(0, 1, 11):
                p = precision[recall >= t].max() if (recall >= t).any() \
                    else 0.0
                ap += p / 11
        else:  # integral
            ap = 0.0
            prev_r = 0.0
            for p, rc in zip(precision, recall):
                ap += p * (rc - prev_r)
                prev_r = rc
        aps.append(float(ap))
    mAP = float(np.mean(aps)) if aps else 0.0
    return {"MAP": np.asarray([mAP], np.float32)}
