"""Recurrent ops: dynamic_lstm(p), dynamic_gru, lstm_unit, gru_unit.

Reference: /root/reference/paddle/fluid/operators/{lstm,lstmp,gru,lstm_unit,
gru_unit}_op.cc + math/{lstm,gru}_compute and the sequence2batch dynamic
batching machinery (math/sequence2batch.h, LoDRankTable length-bucketing).

TPU design: instead of the reference's shrinking-batch reorganization
(sort-by-length + per-timestep variable batch), sequences are padded to
[B, T, ·] with a static index/mask built from the LoD (host-side, compile
cached) and the recurrence is ONE `lax.scan` over time with masked state
updates — XLA fuses the per-step gate math into a few MXU matmuls; no
dynamic shapes, grads come from scan's native VJP through the generic
grad op.

Gate layouts (self-consistent; documented for checkpoint portability):
  lstm Input/Weight 4D blocks: [i, f, c(candidate), o]
  gru  Input 3D blocks: [u(update), r(reset), c(candidate)];
       Weight = [D, 2D] (u,r) concat [D, D] (candidate)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.execution import data_of, one
from ..core.lod import LoDTensor
from ..core.registry import register_op
from .sequence import lod_to_padded_index, padded_to_lod_index

_ACT = {
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "identity": lambda x: x,
}


def _padded(xv: LoDTensor, reverse=False):
    lod = xv.lod[-1]
    idx, mask = lod_to_padded_index(lod)
    if reverse:
        # reverse each sequence's rows in the gather index (time runs
        # backwards within the valid region; padding stays at the tail)
        for i in range(idx.shape[0]):
            ln = int(mask[i].sum())
            idx[i, :ln] = idx[i, :ln][::-1]
    data = jnp.take(xv.data, jnp.asarray(idx).reshape(-1), axis=0)
    data = data.reshape(idx.shape + xv.data.shape[1:])
    return data, jnp.asarray(mask), lod


def _repack(padded, lod, reverse=False):
    b, t = padded.shape[:2]
    if reverse:
        lens = [lod[i + 1] - lod[i] for i in range(len(lod) - 1)]
        flat_idx = []
        for i, ln in enumerate(lens):
            flat_idx.extend(i * t + (ln - 1 - k) for k in range(ln))
        flat_idx = np.asarray(flat_idx, np.int32)
    else:
        flat_idx = padded_to_lod_index(lod)
    flat = padded.reshape((b * t,) + padded.shape[2:])
    return jnp.take(flat, jnp.asarray(flat_idx), axis=0)


@register_op("lstm",
             inputs=("Input", "H0", "C0", "Weight", "Bias"),
             outputs=("Hidden", "Cell", "BatchGate", "BatchCellPreAct"),
             attrs={"use_peepholes": True, "is_reverse": False,
                    "gate_activation": "sigmoid",
                    "cell_activation": "tanh",
                    "candidate_activation": "tanh"},
             diff_inputs=("Input", "H0", "C0", "Weight", "Bias"),
             diff_outputs=("Hidden", "Cell"))
def lstm(ctx, ins, attrs):
    xv = one(ins, "Input")                   # LoDTensor [N, 4D]
    w = data_of(one(ins, "Weight"))          # [D, 4D]
    bias = one(ins, "Bias")                  # [1, 4D] or [1, 7D] w/ peepholes
    d = w.shape[0]
    gact = _ACT[attrs["gate_activation"]]
    cact = _ACT[attrs["cell_activation"]]
    candact = _ACT[attrs["candidate_activation"]]
    peep = attrs.get("use_peepholes", True)

    x_pad, mask, lod = _padded(xv, attrs.get("is_reverse", False))
    bsz = x_pad.shape[0]
    if bias is not None:
        b = data_of(bias).reshape(-1)
        x_pad = x_pad + b[:4 * d]
        if peep and b.shape[0] >= 7 * d:
            w_ic, w_fc, w_oc = (b[4 * d:5 * d], b[5 * d:6 * d],
                                b[6 * d:7 * d])
        else:
            w_ic = w_fc = w_oc = jnp.zeros((d,), x_pad.dtype)
    else:
        w_ic = w_fc = w_oc = jnp.zeros((d,), x_pad.dtype)

    h0 = one(ins, "H0")
    c0 = one(ins, "C0")
    h_init = (data_of(h0) if h0 is not None
              else jnp.zeros((bsz, d), x_pad.dtype))
    c_init = (data_of(c0) if c0 is not None
              else jnp.zeros((bsz, d), x_pad.dtype))

    xs = jnp.swapaxes(x_pad, 0, 1)           # [T, B, 4D]
    ms = jnp.swapaxes(mask, 0, 1)[:, :, None]  # [T, B, 1]

    def step(carry, inp):
        h_prev, c_prev = carry
        x_t, m_t = inp
        gates = x_t + h_prev @ w             # [B, 4D]
        gi, gf, gc, go = jnp.split(gates, 4, axis=1)
        i = gact(gi + w_ic * c_prev)
        f = gact(gf + w_fc * c_prev)
        cand = candact(gc)
        c = f * c_prev + i * cand
        o = gact(go + w_oc * c)
        h = o * cact(c)
        h = m_t * h + (1 - m_t) * h_prev
        c = m_t * c + (1 - m_t) * c_prev
        return (h, c), (h, c, gates)

    (_, _), (hs, cs, gs) = jax.lax.scan(step, (h_init, c_init), (xs, ms))
    rev = attrs.get("is_reverse", False)
    hidden = _repack(jnp.swapaxes(hs, 0, 1), lod, rev)
    cell = _repack(jnp.swapaxes(cs, 0, 1), lod, rev)
    batch_gate = _repack(jnp.swapaxes(gs, 0, 1), lod, rev)
    return {"Hidden": LoDTensor(hidden, xv.lod),
            "Cell": LoDTensor(cell, xv.lod),
            "BatchGate": LoDTensor(batch_gate, xv.lod),
            "BatchCellPreAct": LoDTensor(cell, xv.lod)}


@register_op("lstmp",
             inputs=("Input", "H0", "C0", "Weight", "ProjWeight", "Bias"),
             outputs=("Projection", "Cell", "BatchGate",
                      "BatchHidden", "BatchCellPreAct"),
             attrs={"use_peepholes": True, "is_reverse": False,
                    "gate_activation": "sigmoid",
                    "cell_activation": "tanh",
                    "candidate_activation": "tanh",
                    "proj_activation": "tanh"},
             diff_inputs=("Input", "Weight", "ProjWeight", "Bias"),
             diff_outputs=("Projection",))
def lstmp(ctx, ins, attrs):
    """LSTM with a recurrent projection layer (reference lstmp_op.cc):
    r_t = proj_act(h_t @ P); the recurrent input is r, not h."""
    xv = one(ins, "Input")                    # [N, 4D]
    w = data_of(one(ins, "Weight"))           # [P, 4D]
    pw = data_of(one(ins, "ProjWeight"))      # [D, P]
    bias = one(ins, "Bias")
    d = pw.shape[0]
    p_dim = pw.shape[1]
    gact = _ACT[attrs["gate_activation"]]
    cact = _ACT[attrs["cell_activation"]]
    candact = _ACT[attrs["candidate_activation"]]
    pact = _ACT[attrs["proj_activation"]]
    x_pad, mask, lod = _padded(xv, attrs.get("is_reverse", False))
    bsz = x_pad.shape[0]
    if bias is not None:
        x_pad = x_pad + data_of(bias).reshape(-1)[:4 * d]
    r_init = jnp.zeros((bsz, p_dim), x_pad.dtype)
    c_init = jnp.zeros((bsz, d), x_pad.dtype)
    xs = jnp.swapaxes(x_pad, 0, 1)
    ms = jnp.swapaxes(mask, 0, 1)[:, :, None]

    def step(carry, inp):
        r_prev, c_prev = carry
        x_t, m_t = inp
        gates = x_t + r_prev @ w
        gi, gf, gc, go = jnp.split(gates, 4, axis=1)
        i, f = gact(gi), gact(gf)
        c = f * c_prev + i * candact(gc)
        h = gact(go) * cact(c)
        r = pact(h @ pw)
        r = m_t * r + (1 - m_t) * r_prev
        c = m_t * c + (1 - m_t) * c_prev
        return (r, c), (r, c)

    _, (rs, cs) = jax.lax.scan(step, (r_init, c_init), (xs, ms))
    rev = attrs.get("is_reverse", False)
    proj = _repack(jnp.swapaxes(rs, 0, 1), lod, rev)
    cell = _repack(jnp.swapaxes(cs, 0, 1), lod, rev)
    return {"Projection": LoDTensor(proj, xv.lod),
            "Cell": LoDTensor(cell, xv.lod),
            "BatchGate": LoDTensor(proj, xv.lod),
            "BatchHidden": LoDTensor(proj, xv.lod),
            "BatchCellPreAct": LoDTensor(cell, xv.lod)}


@register_op("gru",
             inputs=("Input", "H0", "Weight", "Bias"),
             outputs=("Hidden", "BatchGate", "BatchResetHiddenPrev",
                      "BatchHidden"),
             attrs={"is_reverse": False, "gate_activation": "sigmoid",
                    "activation": "tanh"},
             diff_inputs=("Input", "H0", "Weight", "Bias"),
             diff_outputs=("Hidden",))
def gru(ctx, ins, attrs):
    xv = one(ins, "Input")                    # [N, 3D]
    w = data_of(one(ins, "Weight"))           # [D, 3D]: [u,r | cand]
    bias = one(ins, "Bias")
    d = w.shape[0]
    gact = _ACT[attrs["gate_activation"]]
    act = _ACT[attrs["activation"]]
    w_ur = w[:, :2 * d]
    w_c = w[:, 2 * d:]
    x_pad, mask, lod = _padded(xv, attrs.get("is_reverse", False))
    bsz = x_pad.shape[0]
    if bias is not None:
        x_pad = x_pad + data_of(bias).reshape(-1)
    h0 = one(ins, "H0")
    h_init = (data_of(h0) if h0 is not None
              else jnp.zeros((bsz, d), x_pad.dtype))
    xs = jnp.swapaxes(x_pad, 0, 1)
    ms = jnp.swapaxes(mask, 0, 1)[:, :, None]

    def step(h_prev, inp):
        x_t, m_t = inp
        x_ur = x_t[:, :2 * d]
        x_c = x_t[:, 2 * d:]
        ur = gact(x_ur + h_prev @ w_ur)
        u, r = jnp.split(ur, 2, axis=1)
        cand = act(x_c + (r * h_prev) @ w_c)
        # reference gru_compute: h = h_prev + u * (cand - h_prev)
        h = h_prev + u * (cand - h_prev)
        h = m_t * h + (1 - m_t) * h_prev
        return h, h

    _, hs = jax.lax.scan(step, h_init, (xs, ms))
    rev = attrs.get("is_reverse", False)
    hidden = _repack(jnp.swapaxes(hs, 0, 1), lod, rev)
    return {"Hidden": LoDTensor(hidden, xv.lod),
            "BatchGate": LoDTensor(hidden, xv.lod),
            "BatchResetHiddenPrev": LoDTensor(hidden, xv.lod),
            "BatchHidden": LoDTensor(hidden, xv.lod)}


@register_op("lstm_unit", inputs=("X", "C_prev"), outputs=("C", "H"),
             attrs={"forget_bias": 0.0})
def lstm_unit(ctx, ins, attrs):
    """Single LSTM step on dense tensors (reference lstm_unit_op.cc;
    gate order i, f, o, c to match its kernel)."""
    x = data_of(one(ins, "X"))                # [B, 4D]
    c_prev = data_of(one(ins, "C_prev"))      # [B, D]
    gi, gf, go, gc = jnp.split(x, 4, axis=1)
    i = jax.nn.sigmoid(gi)
    f = jax.nn.sigmoid(gf + attrs.get("forget_bias", 0.0))
    o = jax.nn.sigmoid(go)
    c = f * c_prev + i * jnp.tanh(gc)
    h = o * jnp.tanh(c)
    return {"C": c, "H": h}


@register_op("gru_unit",
             inputs=("Input", "HiddenPrev", "Weight", "Bias"),
             outputs=("Gate", "ResetHiddenPrev", "Hidden"),
             attrs={"activation": "tanh", "gate_activation": "sigmoid"},
             diff_outputs=("Hidden",))
def gru_unit(ctx, ins, attrs):
    x = data_of(one(ins, "Input"))            # [B, 3D]
    h_prev = data_of(one(ins, "HiddenPrev"))  # [B, D]
    w = data_of(one(ins, "Weight"))           # [D, 3D]
    d = h_prev.shape[1]
    bias = one(ins, "Bias")
    if bias is not None:
        x = x + data_of(bias).reshape(-1)
    gact = _ACT[attrs["gate_activation"]]
    act = _ACT[attrs["activation"]]
    ur = gact(x[:, :2 * d] + h_prev @ w[:, :2 * d])
    u, r = jnp.split(ur, 2, axis=1)
    rh = r * h_prev
    cand = act(x[:, 2 * d:] + rh @ w[:, 2 * d:])
    h = h_prev + u * (cand - h_prev)
    gate = jnp.concatenate([u, r, cand], axis=1)
    return {"Gate": gate, "ResetHiddenPrev": rh, "Hidden": h}


# ---------------------------------------------------------------------------
# explicit build-time shape inference (LoD-driven recurrences)
# ---------------------------------------------------------------------------
# The fused recurrences consume LoDTensors (per-sequence scan boundaries),
# which eval_shape-based default inference cannot model.  Row counts follow
# the input rows; widths come from the weight shapes.

from ..core.registry import register_infer_shape  # noqa: E402
from ..core.shape_inference import input_var, set_output_shape  # noqa: E402


@register_infer_shape("lstm")
def _infer_lstm(op, block):
    x = input_var(op, block, "Input")
    w = input_var(op, block, "Weight")
    if x is None or x.shape is None or w is None or w.shape is None:
        return
    n, d = x.shape[0], w.shape[0]
    set_output_shape(op, block, "Hidden", (n, d), x.dtype)
    set_output_shape(op, block, "Cell", (n, d), x.dtype)
    set_output_shape(op, block, "BatchGate", (n, 4 * d), x.dtype)
    set_output_shape(op, block, "BatchCellPreAct", (n, d), x.dtype)


@register_infer_shape("lstmp")
def _infer_lstmp(op, block):
    x = input_var(op, block, "Input")
    w = input_var(op, block, "Weight")          # [P, 4D]
    pw = input_var(op, block, "ProjWeight")     # [D, P]
    if any(v is None or v.shape is None for v in (x, w, pw)):
        return
    n, d, p = x.shape[0], w.shape[1] // 4, pw.shape[1]
    set_output_shape(op, block, "Projection", (n, p), x.dtype)
    set_output_shape(op, block, "Cell", (n, d), x.dtype)
    set_output_shape(op, block, "BatchGate", (n, 4 * d), x.dtype)
    set_output_shape(op, block, "BatchHidden", (n, d), x.dtype)
    set_output_shape(op, block, "BatchCellPreAct", (n, d), x.dtype)


@register_infer_shape("gru")
def _infer_gru(op, block):
    x = input_var(op, block, "Input")
    w = input_var(op, block, "Weight")          # [D, 3D]
    if x is None or x.shape is None or w is None or w.shape is None:
        return
    n, d = x.shape[0], w.shape[0]
    set_output_shape(op, block, "Hidden", (n, d), x.dtype)
    set_output_shape(op, block, "BatchGate", (n, 3 * d), x.dtype)
    set_output_shape(op, block, "BatchResetHiddenPrev", (n, d), x.dtype)
    set_output_shape(op, block, "BatchHidden", (n, d), x.dtype)
