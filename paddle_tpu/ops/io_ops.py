"""Checkpoint ops: save / load / save_combine / load_combine.

Reference: /root/reference/paddle/fluid/operators/save_op.cc:99 (tensor
serialized as uint32 version header + TensorDesc + raw bytes + LoD;
`SerializeToStream` lod_tensor.cc:236-267), load_op.cc, save_combine_op.cc,
load_combine_op.cc, tested by save_load_op_test.cc.

TPU-native format: same layering (version header, self-describing tensor
desc, raw little-endian buffer, LoD offsets) but the desc is JSON instead of
a protobuf TensorDesc — there is no C++ executor on the other side that
needs proto.  These are `host` ops: the executor runs the enclosing block in
interpreter mode and the op does host file IO, exactly like the reference's
save/load kernels which always run on CPU after a device->host copy.
"""
from __future__ import annotations

import json
import os
import struct

import jax.numpy as jnp
import numpy as np

from ..core.execution import data_of, many, one
from ..core.lod import LoDTensor
from ..core.registry import register_op

MAGIC = b"PTP0"
VERSION = 0


def _tensor_payload(value):
    """-> (header dict, raw bytes) for one tensor value."""
    lod = ()
    if isinstance(value, LoDTensor):
        lod = value.lod
        value = value.data
    arr = np.ascontiguousarray(np.asarray(value))
    header = {
        "dtype": str(arr.dtype),
        "shape": list(arr.shape),
        "lod": [list(level) for level in lod],
    }
    return header, arr.tobytes()


def _write_tensor(f, value, name=None):
    header, raw = _tensor_payload(value)
    if name is not None:
        header["name"] = name
    hb = json.dumps(header).encode("utf-8")
    f.write(struct.pack("<I", VERSION))
    f.write(struct.pack("<I", len(hb)))
    f.write(hb)
    f.write(struct.pack("<Q", len(raw)))
    f.write(raw)


def _read_tensor(f):
    ver_bytes = f.read(4)
    if len(ver_bytes) < 4:
        return None  # EOF
    (ver,) = struct.unpack("<I", ver_bytes)
    if ver != VERSION:
        raise ValueError(f"unsupported tensor file version {ver}")
    (hlen,) = struct.unpack("<I", f.read(4))
    header = json.loads(f.read(hlen).decode("utf-8"))
    (rlen,) = struct.unpack("<Q", f.read(8))
    arr = np.frombuffer(f.read(rlen), dtype=np.dtype(header["dtype"]))
    arr = arr.reshape(header["shape"]).copy()
    if header.get("lod"):
        return header, LoDTensor(arr, header["lod"])
    return header, arr


def save_tensor_to_file(path, value):
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "wb") as f:
        f.write(MAGIC)
        _write_tensor(f, value)


def load_tensor_from_file(path):
    with open(path, "rb") as f:
        if f.read(4) != MAGIC:
            raise ValueError(f"{path} is not a paddle_tpu tensor file")
        _, value = _read_tensor(f)
        return value


@register_op(
    "save",
    inputs=("X",),
    outputs=(),
    attrs={"file_path": "", "overwrite": True},
    not_differentiable=True,
    host=True,
)
def save_lower(ctx, ins, attrs):
    path = attrs["file_path"]
    if os.path.exists(path) and not attrs.get("overwrite", True):
        raise IOError(f"{path} exists; overwrite=False (save_op.cc:45)")
    value = one(ins, "X")
    if value is None:
        raise ValueError(
            f"save: variable {ctx.op.input('X')} is not initialized "
            "(reference save_op.cc enforce)")
    save_tensor_to_file(path, value)
    return {}


@register_op(
    "load",
    inputs=(),
    outputs=("Out",),
    attrs={"file_path": ""},
    not_differentiable=True,
    host=True,
)
def load_lower(ctx, ins, attrs):
    return {"Out": load_tensor_from_file(attrs["file_path"])}


@register_op(
    "save_combine",
    inputs=("X",),
    outputs=(),
    attrs={"file_path": "", "overwrite": True},
    dup_inputs=("X",),
    not_differentiable=True,
    host=True,
)
def save_combine_lower(ctx, ins, attrs):
    path = attrs["file_path"]
    if os.path.exists(path) and not attrs.get("overwrite", True):
        raise IOError(f"{path} exists; overwrite=False")
    names = ctx.op.input("X")
    values = many(ins, "X")
    missing = [n for n, v in zip(names, values) if v is None]
    if missing:
        raise ValueError(
            f"save_combine: variables {missing} are not initialized")
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "wb") as f:
        f.write(MAGIC)
        for name, v in zip(names, values):
            _write_tensor(f, v, name=name)
    return {}


@register_op(
    "load_combine",
    inputs=(),
    outputs=("Out",),
    attrs={"file_path": ""},
    dup_outputs=("Out",),
    not_differentiable=True,
    host=True,
)
def load_combine_lower(ctx, ins, attrs):
    path = attrs["file_path"]
    out_names = ctx.op.output("Out")
    by_name = {}
    order = []
    with open(path, "rb") as f:
        if f.read(4) != MAGIC:
            raise ValueError(f"{path} is not a paddle_tpu tensor file")
        while True:
            rec = _read_tensor(f)
            if rec is None:
                break
            header, value = rec
            by_name[header.get("name")] = value
            order.append(value)
    if all(n in by_name for n in out_names):
        return {"Out": [by_name[n] for n in out_names]}
    # fall back to positional order (reference load_combine semantics)
    if len(order) < len(out_names):
        raise ValueError(
            f"{path} holds {len(order)} tensors; program expects "
            f"{len(out_names)}"
        )
    return {"Out": order[: len(out_names)]}


# ---------------------------------------------------------------------------
# C++-side reader pipeline + feed/fetch ops (reference framework/reader.h
# ReaderBase/DecoratedReader, operators/create_reader_op.cc, read_op.cc,
# feed_op.cc, fetch_op.cc).  Readers are host objects held in scope vars;
# decorators wrap them like the reference's DecoratedReader chain.  The
# executor normally feeds/fetches directly (no injected ops), but the ops
# exist for program-level parity with reference-generated programs.
# ---------------------------------------------------------------------------


class _RandomDataReader:
    """Uniform-random reader (create_random_data_generator_op.cc)."""

    def __init__(self, shapes, low, high, seed=0):
        self.shapes = shapes
        self.low, self.high = low, high
        self.rng = np.random.RandomState(seed)

    def read_next(self):
        return [self.rng.uniform(self.low, self.high, s).astype(np.float32)
                for s in self.shapes]

    def reset(self):
        pass


class _ShuffleReader:
    def __init__(self, reader, buffer_size, seed=0):
        self.reader = reader
        self.buffer_size = buffer_size
        self.rng = np.random.RandomState(seed)
        self._buf = []

    def read_next(self):
        if not self._buf:
            for _ in range(self.buffer_size):
                item = self.reader.read_next()
                if item is None:
                    break
                self._buf.append(item)
            order = self.rng.permutation(len(self._buf))
            self._buf = [self._buf[i] for i in order]
        return self._buf.pop() if self._buf else None

    def reset(self):
        self._buf = []
        self.reader.reset()


class _BatchReader:
    def __init__(self, reader, batch_size):
        self.reader = reader
        self.batch_size = batch_size

    def read_next(self):
        rows = []
        for _ in range(self.batch_size):
            item = self.reader.read_next()
            if item is None:
                break
            rows.append(item)
        if not rows:
            return None
        return [np.stack([r[i] for r in rows]) for i in range(len(rows[0]))]

    def reset(self):
        self.reader.reset()


def _split_shapes(attrs):
    concat = list(attrs["shape_concat"])
    ranks = list(attrs["ranks"])
    shapes, off = [], 0
    for r in ranks:
        shapes.append(tuple(int(d) for d in concat[off:off + r]))
        off += r
    return shapes


@register_op("create_random_data_generator", inputs=(), outputs=("Out",),
             attrs={"shape_concat": [], "ranks": [], "lod_levels": [],
                    "min": 0.0, "max": 1.0, "seed": 0},
             not_differentiable=True, host=True)
def create_random_data_generator(ctx, ins, attrs):
    return {"Out": _RandomDataReader(_split_shapes(attrs), attrs["min"],
                                     attrs["max"], attrs.get("seed", 0))}


@register_op("create_shuffle_reader", inputs=("UnderlyingReader",),
             outputs=("Out",), attrs={"buffer_size": 64},
             not_differentiable=True, host=True)
def create_shuffle_reader(ctx, ins, attrs):
    return {"Out": _ShuffleReader(one(ins, "UnderlyingReader"),
                                  attrs["buffer_size"])}


@register_op("create_batch_reader", inputs=("UnderlyingReader",),
             outputs=("Out",), attrs={"batch_size": 1},
             not_differentiable=True, host=True)
def create_batch_reader(ctx, ins, attrs):
    return {"Out": _BatchReader(one(ins, "UnderlyingReader"),
                                attrs["batch_size"])}


@register_op("read", inputs=("Reader",), outputs=("Out",),
             dup_outputs=("Out",),
             not_differentiable=True, host=True)
def read(ctx, ins, attrs):
    """Pull the next item from a reader into the output vars
    (reference read_op.cc).  Exhaustion raises EOFError — catchable by
    drivers without PEP-479 StopIteration/generator interference."""
    item = one(ins, "Reader").read_next()
    if item is None:
        raise EOFError("reader exhausted")
    return {"Out": [jnp.asarray(x) for x in item]}


@register_op("feed", inputs=("X",), outputs=("Out",),
             attrs={"col": 0}, not_differentiable=True, host=True)
def feed(ctx, ins, attrs):
    """Copy feed-list column `col` into the output var (reference
    feed_op.cc; the executor's direct feed path normally replaces this)."""
    item = one(ins, "X")
    if isinstance(item, (list, tuple)):
        item = item[attrs.get("col", 0)]
    return {"Out": item}


@register_op("fetch", inputs=("X",), outputs=("Out",),
             attrs={"col": 0}, not_differentiable=True, host=True)
def fetch(ctx, ins, attrs):
    """Copy a var into the fetch list, LoD intact (reference fetch_op.cc
    copies the full LoDTensor)."""
    return {"Out": one(ins, "X")}
