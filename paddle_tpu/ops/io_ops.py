"""Checkpoint ops: save / load / save_combine / load_combine.

Reference: /root/reference/paddle/fluid/operators/save_op.cc:99 (tensor
serialized as uint32 version header + TensorDesc + raw bytes + LoD;
`SerializeToStream` lod_tensor.cc:236-267), load_op.cc, save_combine_op.cc,
load_combine_op.cc, tested by save_load_op_test.cc.

TPU-native format: same layering (version header, self-describing tensor
desc, raw little-endian buffer, LoD offsets) but the desc is JSON instead of
a protobuf TensorDesc — there is no C++ executor on the other side that
needs proto.  These are `host` ops: the executor runs the enclosing block in
interpreter mode and the op does host file IO, exactly like the reference's
save/load kernels which always run on CPU after a device->host copy.
"""
from __future__ import annotations

import json
import os
import struct

import numpy as np

from ..core.execution import many, one
from ..core.lod import LoDTensor
from ..core.registry import register_op

MAGIC = b"PTP0"
VERSION = 0


def _tensor_payload(value):
    """-> (header dict, raw bytes) for one tensor value."""
    lod = ()
    if isinstance(value, LoDTensor):
        lod = value.lod
        value = value.data
    arr = np.ascontiguousarray(np.asarray(value))
    header = {
        "dtype": str(arr.dtype),
        "shape": list(arr.shape),
        "lod": [list(level) for level in lod],
    }
    return header, arr.tobytes()


def _write_tensor(f, value, name=None):
    header, raw = _tensor_payload(value)
    if name is not None:
        header["name"] = name
    hb = json.dumps(header).encode("utf-8")
    f.write(struct.pack("<I", VERSION))
    f.write(struct.pack("<I", len(hb)))
    f.write(hb)
    f.write(struct.pack("<Q", len(raw)))
    f.write(raw)


def _read_tensor(f):
    ver_bytes = f.read(4)
    if len(ver_bytes) < 4:
        return None  # EOF
    (ver,) = struct.unpack("<I", ver_bytes)
    if ver != VERSION:
        raise ValueError(f"unsupported tensor file version {ver}")
    (hlen,) = struct.unpack("<I", f.read(4))
    header = json.loads(f.read(hlen).decode("utf-8"))
    (rlen,) = struct.unpack("<Q", f.read(8))
    arr = np.frombuffer(f.read(rlen), dtype=np.dtype(header["dtype"]))
    arr = arr.reshape(header["shape"]).copy()
    if header.get("lod"):
        return header, LoDTensor(arr, header["lod"])
    return header, arr


def save_tensor_to_file(path, value):
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "wb") as f:
        f.write(MAGIC)
        _write_tensor(f, value)


def load_tensor_from_file(path):
    with open(path, "rb") as f:
        if f.read(4) != MAGIC:
            raise ValueError(f"{path} is not a paddle_tpu tensor file")
        _, value = _read_tensor(f)
        return value


@register_op(
    "save",
    inputs=("X",),
    outputs=(),
    attrs={"file_path": "", "overwrite": True},
    not_differentiable=True,
    host=True,
)
def save_lower(ctx, ins, attrs):
    path = attrs["file_path"]
    if os.path.exists(path) and not attrs.get("overwrite", True):
        raise IOError(f"{path} exists; overwrite=False (save_op.cc:45)")
    value = one(ins, "X")
    if value is None:
        raise ValueError(
            f"save: variable {ctx.op.input('X')} is not initialized "
            "(reference save_op.cc enforce)")
    save_tensor_to_file(path, value)
    return {}


@register_op(
    "load",
    inputs=(),
    outputs=("Out",),
    attrs={"file_path": ""},
    not_differentiable=True,
    host=True,
)
def load_lower(ctx, ins, attrs):
    return {"Out": load_tensor_from_file(attrs["file_path"])}


@register_op(
    "save_combine",
    inputs=("X",),
    outputs=(),
    attrs={"file_path": "", "overwrite": True},
    not_differentiable=True,
    host=True,
)
def save_combine_lower(ctx, ins, attrs):
    path = attrs["file_path"]
    if os.path.exists(path) and not attrs.get("overwrite", True):
        raise IOError(f"{path} exists; overwrite=False")
    names = ctx.op.input("X")
    values = many(ins, "X")
    missing = [n for n, v in zip(names, values) if v is None]
    if missing:
        raise ValueError(
            f"save_combine: variables {missing} are not initialized")
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "wb") as f:
        f.write(MAGIC)
        for name, v in zip(names, values):
            _write_tensor(f, v, name=name)
    return {}


@register_op(
    "load_combine",
    inputs=(),
    outputs=("Out",),
    attrs={"file_path": ""},
    not_differentiable=True,
    host=True,
)
def load_combine_lower(ctx, ins, attrs):
    path = attrs["file_path"]
    out_names = ctx.op.output("Out")
    by_name = {}
    order = []
    with open(path, "rb") as f:
        if f.read(4) != MAGIC:
            raise ValueError(f"{path} is not a paddle_tpu tensor file")
        while True:
            rec = _read_tensor(f)
            if rec is None:
                break
            header, value = rec
            by_name[header.get("name")] = value
            order.append(value)
    if all(n in by_name for n in out_names):
        return {"Out": [by_name[n] for n in out_names]}
    # fall back to positional order (reference load_combine semantics)
    if len(order) < len(out_names):
        raise ValueError(
            f"{path} holds {len(order)} tensors; program expects "
            f"{len(out_names)}"
        )
    return {"Out": order[: len(out_names)]}
