"""Linear-chain CRF ops: linear_chain_crf, crf_decoding, chunk_eval.

Reference: /root/reference/paddle/fluid/operators/linear_chain_crf_op.{cc,h}
(forward alpha recursion + hand-written backward), crf_decoding_op.{cc,h}
(Viterbi), chunk_eval_op.{cc,h} (segment extraction + P/R/F1).

TPU-native design: sequences are packed LoD rows; the LoD offsets are host
metadata (static under trace — see core/lod.py), so each batch is padded to
its max length with statically-built gather indices, and the alpha/Viterbi
recursions run as `lax.scan` over the time axis — MXU-friendly [S, D] x
[D, D] steps instead of the reference's per-sequence C++ loops.  The
backward pass is the generic VJP of the forward scan (no hand-written
gradient needed).

Transition layout matches the reference exactly (linear_chain_crf_op.h):
row 0 = start weights, row 1 = end weights, rows 2..D+1 = transition
matrix [D, D].
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.execution import data_of, one
from ..core.lod import LoDTensor
from ..core.registry import register_op


def _pad_layout(lod):
    """Static (numpy) padding layout from LoD offsets:
    -> (idx [S,T], mask [S,T] bool, lens [S]); shares the builder with
    ops/sequence.py."""
    from .sequence import lod_to_padded_index

    offs = lod[0]
    idx, maskf = lod_to_padded_index(offs)
    lens = np.diff(np.asarray(offs, np.int64)).astype(np.int32)
    return idx, maskf.astype(bool), lens


def _split_transition(transition):
    start, end, trans = transition[0], transition[1], transition[2:]
    return start, end, trans


@register_op("linear_chain_crf", inputs=("Emission", "Transition", "Label"),
             outputs=("Alpha", "EmissionExps", "TransitionExps",
                      "LogLikelihood"),
             diff_inputs=("Emission", "Transition"),
             diff_outputs=("LogLikelihood",))
def linear_chain_crf(ctx, ins, attrs):
    ev = one(ins, "Emission")
    if not (isinstance(ev, LoDTensor) and ev.lod):
        raise ValueError("linear_chain_crf requires a LoD emission input")
    emission = data_of(ev)
    transition = data_of(one(ins, "Transition"))
    label = data_of(one(ins, "Label"))
    if label.ndim == 2:
        label = label[:, 0]
    idx, mask, lens = _pad_layout(ev.lod)
    S, T = idx.shape
    D = emission.shape[-1]
    start, end, trans = _split_transition(transition)

    em = emission[idx]                       # [S, T, D]
    lab = label[idx].astype(jnp.int32)       # [S, T]
    maskf = jnp.asarray(mask, emission.dtype)

    # --- partition function: alpha recursion as lax.scan over time -------
    a0 = start[None, :] + em[:, 0, :]        # [S, D]

    def step(alpha, xs):
        em_t, m_t = xs                       # [S, D], [S]
        nxt = jax.scipy.special.logsumexp(
            alpha[:, :, None] + trans[None, :, :], axis=1) + em_t
        alpha = jnp.where(m_t[:, None] > 0, nxt, alpha)
        return alpha, alpha

    alpha_last, alphas = jax.lax.scan(
        step, a0, (jnp.swapaxes(em, 0, 1)[1:], maskf.T[1:]))
    log_z = jax.scipy.special.logsumexp(alpha_last + end[None, :], axis=1)

    # --- gold path score -------------------------------------------------
    em_path = jnp.take_along_axis(em, lab[:, :, None], axis=2)[:, :, 0]
    em_score = jnp.sum(em_path * maskf, axis=1)
    tr_path = trans[lab[:, :-1], lab[:, 1:]] if T > 1 else jnp.zeros((S, 0))
    tr_score = jnp.sum(tr_path * maskf[:, 1:], axis=1)
    last_lab = lab[np.arange(S), lens - 1]
    score = em_score + tr_score + start[lab[:, 0]] + end[last_lab]

    nll = (log_z - score)[:, None]           # [S, 1] negative log-likelihood

    # Alpha per packed row (parity output; the reference caches it for its
    # hand-written backward — here it is informational)
    all_alphas = jnp.concatenate([a0[:, None, :],
                                  jnp.swapaxes(alphas, 0, 1)], axis=1) \
        if T > 1 else a0[:, None, :]
    # padded slots scatter out-of-bounds and are dropped
    scatter_idx = np.where(mask, idx, emission.shape[0]).reshape(-1)
    alpha_rows = jnp.zeros_like(emission).at[scatter_idx].set(
        all_alphas.reshape(-1, D), mode="drop")

    return {
        "Alpha": LoDTensor(alpha_rows, ev.lod),
        "EmissionExps": LoDTensor(jax.nn.softmax(emission, axis=-1), ev.lod),
        "TransitionExps": jnp.exp(transition),
        "LogLikelihood": nll,
    }


@register_op("crf_decoding", inputs=("Emission", "Transition", "Label"),
             outputs=("ViterbiPath",), not_differentiable=True)
def crf_decoding(ctx, ins, attrs):
    """Viterbi decode.  Without Label: per-token best tag ids.  With Label:
    1 where the decoded tag equals the label, else 0 (crf_decoding_op.cc
    semantics, feeding chunk_eval/error counts)."""
    ev = one(ins, "Emission")
    emission = data_of(ev)
    transition = data_of(one(ins, "Transition"))
    idx, mask, lens = _pad_layout(ev.lod)
    S, T = idx.shape
    start, end, trans = _split_transition(transition)
    em = emission[idx]

    a0 = start[None, :] + em[:, 0, :]

    def fwd(alpha, xs):
        em_t, m_t = xs
        scores = alpha[:, :, None] + trans[None, :, :]   # [S, D, D]
        best = jnp.max(scores, axis=1) + em_t
        ptr = jnp.argmax(scores, axis=1)                 # [S, D]
        nxt = jnp.where(m_t[:, None] > 0, best, alpha)
        return nxt, ptr

    maskf = jnp.asarray(mask, emission.dtype)
    alpha_last, ptrs = jax.lax.scan(
        fwd, a0, (jnp.swapaxes(em, 0, 1)[1:], maskf.T[1:]))
    # best final tag per sequence (end weights applied at each seq's last
    # real step: since padding froze alpha, alpha_last IS the last real one)
    last_tag = jnp.argmax(alpha_last + end[None, :], axis=1)  # [S]

    # backtrack (reverse scan over stored argmax pointers)
    def back(tag, xs):
        ptr_t, m_t = xs                                   # [S, D], [S]
        prev = jnp.take_along_axis(ptr_t, tag[:, None], axis=1)[:, 0]
        tag_prev = jnp.where(m_t > 0, prev, tag)
        return tag_prev, tag_prev

    _, rev_tags = jax.lax.scan(back, last_tag,
                               (ptrs[::-1], maskf.T[1:][::-1]))
    tags = jnp.concatenate([rev_tags[::-1], last_tag[None, :]], axis=0) \
        if T > 1 else last_tag[None, :]
    tags = jnp.swapaxes(tags, 0, 1)                       # [S, T]

    # scatter back to packed rows (padded slots dropped out-of-bounds)
    scatter_idx = np.where(mask, idx, emission.shape[0]).reshape(-1)
    path = jnp.zeros((emission.shape[0],), jnp.int32).at[
        scatter_idx].set(tags.reshape(-1).astype(jnp.int32), mode="drop")
    label = one(ins, "Label")
    if label is not None:
        lab = data_of(label)
        if lab.ndim == 2:
            lab = lab[:, 0]
        path = (path == lab.astype(jnp.int32)).astype(jnp.int32)
    return {"ViterbiPath": LoDTensor(path[:, None], ev.lod)}


# ---------------------------------------------------------------------------
# chunk_eval (host metric op — reference chunk_eval_op.h GetSegments)
# ---------------------------------------------------------------------------


# (num_tag_types, tag_begin, tag_inside, tag_end, tag_single) per scheme —
# chunk_eval_op.h Compute's scheme table
_SCHEMES = {
    "IOB": (2, 0, 1, -1, -1),
    "IOE": (2, -1, 0, 1, -1),
    "IOBES": (4, 0, 1, 2, 3),
    "plain": (1, -1, -1, -1, -1),
}


def _chunk_end(prev_tag, prev_type, tag, type_, other, tb, ti, te, ts):
    """Faithful port of chunk_eval_op.h ChunkEnd."""
    if prev_type == other:
        return False
    if type_ == other:
        return True
    if type_ != prev_type:
        return True
    if prev_tag == tb:
        return tag in (tb, ts)
    if prev_tag == ti:
        return tag in (tb, ts)
    if prev_tag in (te, ts):
        return True
    return False


def _chunk_begin(prev_tag, prev_type, tag, type_, other, tb, ti, te, ts):
    """Faithful port of chunk_eval_op.h ChunkBegin."""
    if prev_type == other:
        return type_ != other
    if type_ == other:
        return False
    if type_ != prev_type:
        return True
    if tag == tb:
        return True
    if tag in (ti, te):
        return prev_tag in (te, ts)
    if tag == ts:
        return True
    return False


def _extract_chunks(tags, scheme, num_chunk_types, excluded):
    """-> set of (begin, end_inclusive, type) segments in one sequence
    (port of chunk_eval_op.h GetSegments)."""
    n_tag, tb, ti, te, ts = _SCHEMES[scheme]
    other = num_chunk_types
    chunks = []
    in_chunk = False
    chunk_start = 0
    tag, type_ = -1, other
    for i, t in enumerate(tags):
        prev_tag, prev_type = tag, type_
        t = int(t)
        tag = t % n_tag
        type_ = t // n_tag
        if in_chunk and _chunk_end(prev_tag, prev_type, tag, type_, other,
                                   tb, ti, te, ts):
            chunks.append((chunk_start, i - 1, prev_type))
            in_chunk = False
        if _chunk_begin(prev_tag, prev_type, tag, type_, other,
                        tb, ti, te, ts):
            chunk_start = i
            in_chunk = True
    if in_chunk:
        chunks.append((chunk_start, len(tags) - 1, type_))
    return {c for c in chunks if c[2] not in excluded}


@register_op("chunk_eval", inputs=("Inference", "Label"),
             outputs=("Precision", "Recall", "F1-Score", "NumInferChunks",
                      "NumLabelChunks", "NumCorrectChunks"),
             attrs={"chunk_scheme": "IOB", "num_chunk_types": 1,
                    "excluded_chunk_types": []},
             not_differentiable=True, host=True)
def chunk_eval(ctx, ins, attrs):
    inf_v = one(ins, "Inference")
    lab_v = one(ins, "Label")
    inf = np.asarray(data_of(inf_v)).reshape(-1)
    lab = np.asarray(data_of(lab_v)).reshape(-1)
    lod = inf_v.lod if isinstance(inf_v, LoDTensor) and inf_v.lod \
        else ((0, len(inf)),)
    offs = lod[0] if isinstance(lod[0], (tuple, list)) else lod
    scheme = attrs["chunk_scheme"]
    n_types = int(attrs["num_chunk_types"])
    excluded = set(attrs.get("excluded_chunk_types") or [])
    n_inf = n_lab = n_cor = 0
    for s in range(len(offs) - 1):
        lo, hi = offs[s], offs[s + 1]
        ci = _extract_chunks(inf[lo:hi], scheme, n_types, excluded)
        cl = _extract_chunks(lab[lo:hi], scheme, n_types, excluded)
        n_inf += len(ci)
        n_lab += len(cl)
        n_cor += len(ci & cl)
    p = n_cor / n_inf if n_inf else 0.0
    r = n_cor / n_lab if n_lab else 0.0
    f1 = 2 * p * r / (p + r) if p + r else 0.0
    return {
        "Precision": np.float32(p), "Recall": np.float32(r),
        "F1-Score": np.float32(f1),
        "NumInferChunks": np.int64(n_inf),
        "NumLabelChunks": np.int64(n_lab),
        "NumCorrectChunks": np.int64(n_cor),
    }


# -- explicit build-time shape inference (LoD-dependent) ---------------------

from ..core.registry import register_infer_shape  # noqa: E402
from ..core.shape_inference import input_var, set_output_shape  # noqa: E402


@register_infer_shape("linear_chain_crf")
def _infer_linear_chain_crf(op, block):
    e = input_var(op, block, "Emission")
    t = input_var(op, block, "Transition")
    if e is None or e.shape is None:
        return
    set_output_shape(op, block, "Alpha", e.shape, e.dtype)
    set_output_shape(op, block, "EmissionExps", e.shape, e.dtype)
    if t is not None and t.shape is not None:
        set_output_shape(op, block, "TransitionExps", t.shape, e.dtype)
    # one log-likelihood row per sequence (count in the LoD)
    set_output_shape(op, block, "LogLikelihood", (-1, 1), e.dtype)


@register_infer_shape("crf_decoding")
def _infer_crf_decoding(op, block):
    e = input_var(op, block, "Emission")
    if e is None or e.shape is None:
        return
    set_output_shape(op, block, "ViterbiPath", (e.shape[0], 1), "int64")
