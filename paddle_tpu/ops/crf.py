"""Linear-chain CRF ops: linear_chain_crf, crf_decoding, chunk_eval.

Reference: /root/reference/paddle/fluid/operators/linear_chain_crf_op.{cc,h}
(forward alpha recursion + hand-written backward), crf_decoding_op.{cc,h}
(Viterbi), chunk_eval_op.{cc,h} (segment extraction + P/R/F1).

TPU-native design: sequences are packed LoD rows; the LoD offsets are host
metadata (static under trace — see core/lod.py), so each batch is padded to
its max length with statically-built gather indices, and the alpha/Viterbi
recursions run as `lax.scan` over the time axis — MXU-friendly [S, D] x
[D, D] steps instead of the reference's per-sequence C++ loops.  The
backward pass is the generic VJP of the forward scan (no hand-written
gradient needed).

Transition layout matches the reference exactly (linear_chain_crf_op.h):
row 0 = start weights, row 1 = end weights, rows 2..D+1 = transition
matrix [D, D].
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.execution import data_of, one
from ..core.lod import LoDTensor
from ..core.registry import register_op


def _pad_layout(lod):
    """Static (numpy) padding layout from LoD offsets:
    -> (idx [S,T], mask [S,T], lens [S])."""
    offs = lod[0]
    lens = np.diff(np.asarray(offs, np.int64))
    S, T = len(lens), int(lens.max()) if len(lens) else 0
    idx = np.zeros((S, T), np.int32)
    mask = np.zeros((S, T), bool)
    for s in range(S):
        idx[s, : lens[s]] = np.arange(offs[s], offs[s + 1], dtype=np.int32)
        mask[s, : lens[s]] = True
    return idx, mask, lens.astype(np.int32)


def _split_transition(transition):
    start, end, trans = transition[0], transition[1], transition[2:]
    return start, end, trans


@register_op("linear_chain_crf", inputs=("Emission", "Transition", "Label"),
             outputs=("Alpha", "EmissionExps", "TransitionExps",
                      "LogLikelihood"),
             diff_inputs=("Emission", "Transition"),
             diff_outputs=("LogLikelihood",))
def linear_chain_crf(ctx, ins, attrs):
    ev = one(ins, "Emission")
    if not (isinstance(ev, LoDTensor) and ev.lod):
        raise ValueError("linear_chain_crf requires a LoD emission input")
    emission = data_of(ev)
    transition = data_of(one(ins, "Transition"))
    label = data_of(one(ins, "Label"))
    if label.ndim == 2:
        label = label[:, 0]
    idx, mask, lens = _pad_layout(ev.lod)
    S, T = idx.shape
    D = emission.shape[-1]
    start, end, trans = _split_transition(transition)

    em = emission[idx]                       # [S, T, D]
    lab = label[idx].astype(jnp.int32)       # [S, T]
    maskf = jnp.asarray(mask, emission.dtype)

    # --- partition function: alpha recursion as lax.scan over time -------
    a0 = start[None, :] + em[:, 0, :]        # [S, D]

    def step(alpha, xs):
        em_t, m_t = xs                       # [S, D], [S]
        nxt = jax.scipy.special.logsumexp(
            alpha[:, :, None] + trans[None, :, :], axis=1) + em_t
        alpha = jnp.where(m_t[:, None] > 0, nxt, alpha)
        return alpha, alpha

    alpha_last, alphas = jax.lax.scan(
        step, a0, (jnp.swapaxes(em, 0, 1)[1:], maskf.T[1:]))
    log_z = jax.scipy.special.logsumexp(alpha_last + end[None, :], axis=1)

    # --- gold path score -------------------------------------------------
    em_path = jnp.take_along_axis(em, lab[:, :, None], axis=2)[:, :, 0]
    em_score = jnp.sum(em_path * maskf, axis=1)
    tr_path = trans[lab[:, :-1], lab[:, 1:]] if T > 1 else jnp.zeros((S, 0))
    tr_score = jnp.sum(tr_path * maskf[:, 1:], axis=1)
    last_lab = lab[np.arange(S), lens - 1]
    score = em_score + tr_score + start[lab[:, 0]] + end[last_lab]

    nll = (log_z - score)[:, None]           # [S, 1] negative log-likelihood

    # Alpha per packed row (parity output; the reference caches it for its
    # hand-written backward — here it is informational)
    all_alphas = jnp.concatenate([a0[:, None, :],
                                  jnp.swapaxes(alphas, 0, 1)], axis=1) \
        if T > 1 else a0[:, None, :]
    # padded slots scatter out-of-bounds and are dropped
    scatter_idx = np.where(mask, idx, emission.shape[0]).reshape(-1)
    alpha_rows = jnp.zeros_like(emission).at[scatter_idx].set(
        all_alphas.reshape(-1, D), mode="drop")

    return {
        "Alpha": LoDTensor(alpha_rows, ev.lod),
        "EmissionExps": LoDTensor(jax.nn.softmax(emission, axis=-1), ev.lod),
        "TransitionExps": jnp.exp(transition),
        "LogLikelihood": nll,
    }


@register_op("crf_decoding", inputs=("Emission", "Transition", "Label"),
             outputs=("ViterbiPath",), not_differentiable=True)
def crf_decoding(ctx, ins, attrs):
    """Viterbi decode.  Without Label: per-token best tag ids.  With Label:
    1 where the decoded tag equals the label, else 0 (crf_decoding_op.cc
    semantics, feeding chunk_eval/error counts)."""
    ev = one(ins, "Emission")
    emission = data_of(ev)
    transition = data_of(one(ins, "Transition"))
    idx, mask, lens = _pad_layout(ev.lod)
    S, T = idx.shape
    start, end, trans = _split_transition(transition)
    em = emission[idx]

    a0 = start[None, :] + em[:, 0, :]

    def fwd(alpha, xs):
        em_t, m_t = xs
        scores = alpha[:, :, None] + trans[None, :, :]   # [S, D, D]
        best = jnp.max(scores, axis=1) + em_t
        ptr = jnp.argmax(scores, axis=1)                 # [S, D]
        nxt = jnp.where(m_t[:, None] > 0, best, alpha)
        return nxt, ptr

    maskf = jnp.asarray(mask, emission.dtype)
    alpha_last, ptrs = jax.lax.scan(
        fwd, a0, (jnp.swapaxes(em, 0, 1)[1:], maskf.T[1:]))
    # best final tag per sequence (end weights applied at each seq's last
    # real step: since padding froze alpha, alpha_last IS the last real one)
    last_tag = jnp.argmax(alpha_last + end[None, :], axis=1)  # [S]

    # backtrack (reverse scan over stored argmax pointers)
    def back(tag, xs):
        ptr_t, m_t = xs                                   # [S, D], [S]
        prev = jnp.take_along_axis(ptr_t, tag[:, None], axis=1)[:, 0]
        tag_prev = jnp.where(m_t > 0, prev, tag)
        return tag_prev, tag_prev

    _, rev_tags = jax.lax.scan(back, last_tag,
                               (ptrs[::-1], maskf.T[1:][::-1]))
    tags = jnp.concatenate([rev_tags[::-1], last_tag[None, :]], axis=0) \
        if T > 1 else last_tag[None, :]
    tags = jnp.swapaxes(tags, 0, 1)                       # [S, T]

    # scatter back to packed rows (padded slots dropped out-of-bounds)
    scatter_idx = np.where(mask, idx, emission.shape[0]).reshape(-1)
    path = jnp.zeros((emission.shape[0],), jnp.int32).at[
        scatter_idx].set(tags.reshape(-1).astype(jnp.int32), mode="drop")
    label = one(ins, "Label")
    if label is not None:
        lab = data_of(label)
        if lab.ndim == 2:
            lab = lab[:, 0]
        path = (path == lab.astype(jnp.int32)).astype(jnp.int32)
    return {"ViterbiPath": LoDTensor(path[:, None], ev.lod)}


# ---------------------------------------------------------------------------
# chunk_eval (host metric op — reference chunk_eval_op.h GetSegments)
# ---------------------------------------------------------------------------


def _extract_chunks(tags, scheme, num_chunk_types, excluded):
    """-> set of (begin, end_exclusive, type) segments in one sequence."""
    chunks = []
    if scheme == "plain":
        cur_type, cur_start = None, None
        for i, t in enumerate(list(tags) + [-1]):
            ty = int(t) if 0 <= t < num_chunk_types else None
            if ty != cur_type:
                if cur_type is not None:
                    chunks.append((cur_start, i, cur_type))
                cur_type, cur_start = ty, i
        return {c for c in chunks if c[2] not in excluded}
    n_tag = {"IOB": 2, "IOE": 2, "IOBES": 4}[scheme]
    begin_tag = {"IOB": 0, "IOE": None, "IOBES": 0}[scheme]
    cur = None  # (start, type)
    for i, t in enumerate(tags):
        t = int(t)
        inside = 0 <= t < num_chunk_types * n_tag
        ty = t // n_tag if inside else None
        tag = t % n_tag if inside else None
        if scheme == "IOB":
            starts = inside and (tag == 0)
            cont = inside and (tag == 1)
        elif scheme == "IOE":
            starts = inside and cur is None
            cont = inside
        else:  # IOBES: B=0 I=1 E=2 S=3
            starts = inside and tag in (0, 3)
            cont = inside and tag in (1, 2)
        if cur is not None and (not cont or ty != cur[1] or starts):
            chunks.append((cur[0], i, cur[1]))
            cur = None
        if cur is None and starts:
            cur = (i, ty)
        elif cur is None and cont and scheme == "IOE":
            cur = (i, ty)
        # sequence enders
        if cur is not None:
            if scheme == "IOBES" and tag in (2, 3):
                chunks.append((cur[0], i + 1, cur[1]))
                cur = None
            elif scheme == "IOE" and tag == 1:
                chunks.append((cur[0], i + 1, cur[1]))
                cur = None
    if cur is not None and scheme not in ("IOE", "IOBES"):
        chunks.append((cur[0], len(tags), cur[1]))
    return {c for c in chunks if c[2] not in excluded}


@register_op("chunk_eval", inputs=("Inference", "Label"),
             outputs=("Precision", "Recall", "F1-Score", "NumInferChunks",
                      "NumLabelChunks", "NumCorrectChunks"),
             attrs={"chunk_scheme": "IOB", "num_chunk_types": 1,
                    "excluded_chunk_types": []},
             not_differentiable=True, host=True)
def chunk_eval(ctx, ins, attrs):
    inf_v = one(ins, "Inference")
    lab_v = one(ins, "Label")
    inf = np.asarray(data_of(inf_v)).reshape(-1)
    lab = np.asarray(data_of(lab_v)).reshape(-1)
    lod = inf_v.lod if isinstance(inf_v, LoDTensor) and inf_v.lod \
        else ((0, len(inf)),)
    offs = lod[0] if isinstance(lod[0], (tuple, list)) else lod
    scheme = attrs["chunk_scheme"]
    n_types = int(attrs["num_chunk_types"])
    excluded = set(attrs.get("excluded_chunk_types") or [])
    n_inf = n_lab = n_cor = 0
    for s in range(len(offs) - 1):
        lo, hi = offs[s], offs[s + 1]
        ci = _extract_chunks(inf[lo:hi], scheme, n_types, excluded)
        cl = _extract_chunks(lab[lo:hi], scheme, n_types, excluded)
        n_inf += len(ci)
        n_lab += len(cl)
        n_cor += len(ci & cl)
    p = n_cor / n_inf if n_inf else 0.0
    r = n_cor / n_lab if n_lab else 0.0
    f1 = 2 * p * r / (p + r) if p + r else 0.0
    return {
        "Precision": np.float32(p), "Recall": np.float32(r),
        "F1-Score": np.float32(f1),
        "NumInferChunks": np.int64(n_inf),
        "NumLabelChunks": np.int64(n_lab),
        "NumCorrectChunks": np.int64(n_cor),
    }
