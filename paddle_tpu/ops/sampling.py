"""Sampled-softmax family: nce (noise-contrastive estimation).

Reference: /root/reference/paddle/fluid/operators/nce_op.{cc,h} —
SampleLabels = [true labels | uniform negative samples]; per sampled class
o = sigmoid(x·w_label + b_label); with b = num_neg_samples/num_total_classes:
cost = Σ_true -log(o/(o+b)) + Σ_neg -log(b/(o+b)).

The VJP grad op re-traces this lowering with the SAME per-op PRNG key
(core/execution._op_rng_tag), so forward and backward see identical negative
samples — the reference instead re-reads its materialized SampleLabels
output in a hand-written grad kernel.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.execution import data_of, one
from ..core.registry import register_op


@register_op("hsigmoid",
             inputs=("X", "W", "Label", "Bias"),
             outputs=("Out", "PreOut"),
             attrs={"num_classes": 2},
             diff_inputs=("X", "W", "Bias"),
             diff_outputs=("Out",))
def hsigmoid(ctx, ins, attrs):
    """Hierarchical sigmoid over a complete binary tree of classes.

    Reference: /root/reference/paddle/gserver/layers/HierarchicalSigmoidLayer.cpp
    and paddle/math/MatrixBitCode.cpp (SimpleCode: c = label + num_classes;
    node index at bit j = (c >> (j+1)) - 1; branch bit = (c >> j) & 1; path
    length = bit_length(c) - 1).  Per-sample cost is the sum of
    sigmoid-cross-entropies along the label's root-to-leaf path:
        cost = Σ_j softplus(pre_j) - bit_j · pre_j,  pre clipped to ±40.
    Unlike the reference (which also softplus-es zero-padded lanes, adding a
    constant log 2 per padding lane), padding lanes are fully masked out.

    The whole path is gathered at once (W[idx] is one XLA gather feeding a
    batched dot), so the tree walk costs two MXU-friendly ops, not a scalar
    loop; grads (scatter-add into W) come from the generic VJP.
    """
    x = data_of(one(ins, "X"))                  # [B, D]
    w = data_of(one(ins, "W"))                  # [K-1, D]
    label = data_of(one(ins, "Label")).reshape(-1)  # [B] int
    bias = one(ins, "Bias")
    K = int(attrs["num_classes"])
    max_len = max((K - 1).bit_length(), 1)

    c = label.astype(jnp.int32) + K             # codes in [K, 2K)
    j = jnp.arange(max_len, dtype=jnp.int32)    # [L]
    idx = (c[:, None] >> (j + 1)) - 1           # [B, L] internal-node ids
    bit = ((c[:, None] >> j) & 1).astype(x.dtype)
    # path length = bit_length(c) - 1, computed without float log2
    length = jnp.zeros_like(c)
    for k in range(1, (2 * K).bit_length() + 1):
        length = length + (c >= (1 << k)).astype(c.dtype)
    valid = (j[None, :] < length[:, None])      # [B, L]
    idx = jnp.clip(idx, 0, K - 2)

    pre = jnp.einsum("bd,bld->bl", x, w[idx])   # [B, L]
    if bias is not None:
        pre = pre + data_of(bias).reshape(-1)[idx]
    pre = jnp.clip(pre, -40.0, 40.0)
    pre = jnp.where(valid, pre, 0.0)
    cost = jnp.sum(jnp.where(valid, jax.nn.softplus(pre) - bit * pre, 0.0),
                   axis=1)
    return {"Out": cost[:, None], "PreOut": pre}


@register_op("nce",
             inputs=("Input", "Label", "Weight", "Bias", "SampleWeight"),
             outputs=("Cost", "SampleLogits", "SampleLabels"),
             attrs={"num_total_classes": 2, "num_neg_samples": 10},
             diff_inputs=("Input", "Weight", "Bias"),
             diff_outputs=("Cost",), random=True)
def nce(ctx, ins, attrs):
    x = data_of(one(ins, "Input"))              # [B, D]
    label = data_of(one(ins, "Label"))          # [B, num_true] int
    w = data_of(one(ins, "Weight"))             # [num_total, D]
    bias = one(ins, "Bias")                     # [num_total] or None
    sw = one(ins, "SampleWeight")
    num_total = int(attrs["num_total_classes"])
    num_neg = int(attrs["num_neg_samples"])
    B = x.shape[0]
    num_true = label.shape[1] if label.ndim == 2 else 1
    label = label.reshape(B, num_true)

    negs = jax.random.randint(ctx.rng(), (B, num_neg), 0, num_total)
    sample_labels = jnp.concatenate([label.astype(jnp.int32),
                                     negs.astype(jnp.int32)], axis=1)

    w_s = w[sample_labels]                      # [B, T+N, D]
    logits = jnp.einsum("bd,bsd->bs", x, w_s)
    if bias is not None:
        logits = logits + data_of(bias).reshape(-1)[sample_labels]
    o = jax.nn.sigmoid(logits)
    b = float(num_neg) / float(num_total)
    cost_true = -jnp.log(o[:, :num_true] / (o[:, :num_true] + b))
    cost_neg = -jnp.log(b / (o[:, num_true:] + b))
    cost = jnp.sum(cost_true, axis=1) + jnp.sum(cost_neg, axis=1)
    if sw is not None:
        cost = cost * data_of(sw).reshape(-1)
    return {"Cost": cost[:, None], "SampleLogits": o,
            "SampleLabels": sample_labels.astype(jnp.int64)}
