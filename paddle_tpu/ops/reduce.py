"""Reductions: mean, reduce_{sum,mean,max,min,prod}, cumsum, norms, argmax.

Reference: /root/reference/paddle/fluid/operators/mean_op.cc (scalar mean,
shape {1}), reduce_op.cc (dim/keep_dim/reduce_all attrs), cum_op.h.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.execution import data_of, one
from ..core.registry import register_op


def _index_routed_extreme(plain_fn, arg_fn):
    """Max/min reduction whose VJP routes the cotangent by ARGMAX INDEX
    (scatter at one position), not by float equality.  jnp.max's VJP
    tests `x == broadcast(max)`, and under whole-program XLA:TPU fusion
    the two sides can recompute at different effective precisions —
    false ties then duplicate the cotangent into many elements (the
    sequence_pool MAX bug, see ops/sequence.py).  Also matches the
    reference kernels' single-index tie routing (reduce_op.h keeps one
    position).

    custom_vjp keeps the two costs separate: a forward-only (inference)
    graph runs the plain fused reduction; only a differentiated graph
    pays the transpose+argmax residual computation.
    Returns fn(x, axis=axes_tuple_or_None, keepdims=bool)."""

    def reduce(x, axis=None, keepdims=False):
        nd = x.ndim
        axes = (tuple(range(nd)) if axis is None
                else tuple(sorted(a if a >= 0 else a + nd for a in axis)))
        keep = tuple(a for a in range(nd) if a not in axes)
        perm = keep + axes
        inv_perm = tuple(int(p) for p in
                         sorted(range(nd), key=perm.__getitem__))
        flatlen = 1
        for a in axes:
            flatlen *= x.shape[a]

        @jax.custom_vjp
        def _r(x):
            return plain_fn(x, axis=axes, keepdims=keepdims)

        def _fwd(x):
            xt = jnp.transpose(x, perm)
            kshape = xt.shape[:len(keep)]
            xf = xt.reshape(kshape + (-1,))
            i = arg_fn(xf, axis=-1)
            out = jnp.take_along_axis(xf, i[..., None], axis=-1)[..., 0]
            if keepdims:
                for a in axes:
                    out = jnp.expand_dims(out, a)
            return out, (i, kshape, xt.shape)

        def _bwd(res, g):
            i, kshape, tshape = res
            gf = g.reshape(kshape)
            scat = (jax.nn.one_hot(i, flatlen, dtype=gf.dtype)
                    * gf[..., None])
            return (jnp.transpose(scat.reshape(tshape), inv_perm),)

        _r.defvjp(_fwd, _bwd)
        return _r(x)

    return reduce


_max_by_index = _index_routed_extreme(jnp.max, jnp.argmax)
_min_by_index = _index_routed_extreme(jnp.min, jnp.argmin)


@register_op("mean", inputs=("X",), outputs=("Out",))
def mean(ctx, ins, attrs):
    x = data_of(one(ins, "X"))
    # reference mean_op outputs a {1}-shaped tensor (mean_op.cc InferShape)
    return {"Out": jnp.mean(x).reshape(1)}


def _make_reduce(name, fn):
    @register_op(name, inputs=("X",), outputs=("Out",),
                 attrs={"dim": [0], "keep_dim": False, "reduce_all": False})
    def lower(ctx, ins, attrs, _fn=fn):
        x = data_of(one(ins, "X"))
        if attrs.get("reduce_all"):
            out = _fn(x, axis=None, keepdims=attrs["keep_dim"])
            if not attrs["keep_dim"]:
                out = out.reshape(1)
        else:
            dim = attrs["dim"]
            axes = tuple(dim) if isinstance(dim, (list, tuple)) else (int(dim),)
            axes = tuple(a if a >= 0 else a + x.ndim for a in axes)
            out = _fn(x, axis=axes, keepdims=attrs["keep_dim"])
            if out.ndim == 0:
                out = out.reshape(1)
        return {"Out": out}

    return lower


_make_reduce("reduce_sum", jnp.sum)
_make_reduce("reduce_mean", jnp.mean)
_make_reduce("reduce_max", _max_by_index)
_make_reduce("reduce_min", _min_by_index)
_make_reduce("reduce_prod", jnp.prod)


@register_op("cumsum", inputs=("X",), outputs=("Out",),
             attrs={"axis": -1, "exclusive": False, "reverse": False})
def cumsum(ctx, ins, attrs):
    x = data_of(one(ins, "X"))
    axis = attrs["axis"]
    if attrs.get("reverse"):
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis=axis, dtype=x.dtype)
    if attrs.get("exclusive"):
        out = out - x
    if attrs.get("reverse"):
        out = jnp.flip(out, axis)
    return {"Out": out}


@register_op("l1_norm", inputs=("X",), outputs=("Out",))
def l1_norm(ctx, ins, attrs):
    x = data_of(one(ins, "X"))
    return {"Out": jnp.sum(jnp.abs(x)).reshape(1)}


@register_op("squared_l2_norm", inputs=("X",), outputs=("Out",))
def squared_l2_norm(ctx, ins, attrs):
    x = data_of(one(ins, "X"))
    return {"Out": jnp.sum(jnp.square(x)).reshape(1)}


@register_op("squared_l2_distance", inputs=("X", "Y"),
             outputs=("Out", "sub_result"))
def squared_l2_distance(ctx, ins, attrs):
    x = data_of(one(ins, "X"))
    y = data_of(one(ins, "Y"))
    sub = x - y.reshape((1,) + y.shape[1:] if y.shape[0] == 1 else y.shape)
    return {"sub_result": sub,
            "Out": jnp.sum(jnp.square(sub), axis=tuple(range(1, sub.ndim)),
                           keepdims=False).reshape(-1, 1)}


@register_op("norm", inputs=("X", "Scale"), outputs=("Out",),
             attrs={"epsilon": 1e-10})
def norm(ctx, ins, attrs):
    """Cross-channel L2 norm scaling (reference norm_op.cc)."""
    x = data_of(one(ins, "X"))          # [N, C, H, W]
    scale = data_of(one(ins, "Scale"))  # [C]
    l2 = jnp.sqrt(jnp.sum(jnp.square(x), axis=1, keepdims=True)
                  + attrs["epsilon"])
    return {"Out": x / l2 * scale.reshape(1, -1, 1, 1)}


@register_op("argmax", inputs=("X",), outputs=("Out",),
             attrs={"axis": -1}, not_differentiable=True)
def argmax(ctx, ins, attrs):
    x = data_of(one(ins, "X"))
    return {"Out": jnp.argmax(x, axis=attrs["axis"]).astype(jnp.int64)}


@register_op("maxout", inputs=("X",), outputs=("Out",),
             attrs={"groups": 1})
def maxout(ctx, ins, attrs):
    """Channel maxout (reference maxout_op.cc): NCHW, C split into groups."""
    x = data_of(one(ins, "X"))
    n, c, h, w = x.shape
    g = attrs["groups"]
    # index-routed max: fusion-safe VJP (see _index_routed_extreme)
    return {"Out": _max_by_index(x.reshape(n, c // g, g, h, w),
                                 axis=(2,))}
