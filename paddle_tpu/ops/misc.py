"""Miscellaneous ops: cos_sim, is_empty, print.

Reference: /root/reference/paddle/fluid/operators/cos_sim_op.{cc,h},
is_empty_op.cc, print_op.cc.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.execution import data_of, one, with_lod_of
from ..core.registry import register_op


@register_op("cos_sim", inputs=("X", "Y"),
             outputs=("Out", "XNorm", "YNorm"),
             diff_outputs=("Out",))
def cos_sim(ctx, ins, attrs):
    """Row-wise cosine similarity; Y may have 1 row (broadcast against every
    row of X), matching cos_sim_op.h."""
    xv = one(ins, "X")
    x = data_of(xv)
    y = data_of(one(ins, "Y"))
    xn = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), axis=-1, keepdims=True))
    out = jnp.sum(x * y, axis=-1, keepdims=True) / (xn * yn)
    return {"Out": with_lod_of(xv, out), "XNorm": xn, "YNorm": yn}


@register_op("is_empty", inputs=("X",), outputs=("Out",),
             not_differentiable=True)
def is_empty(ctx, ins, attrs):
    x = data_of(one(ins, "X"))
    return {"Out": jnp.asarray(x.size == 0)}


_PRINT_COUNTS: dict = {}


@register_op("print", inputs=("In",), outputs=("Out",),
             attrs={"first_n": -1, "message": "", "summarize": 20,
                    "print_tensor_name": True, "print_tensor_type": True,
                    "print_tensor_shape": True, "print_tensor_lod": True,
                    "print_phase": "BOTH"},
             not_differentiable=True, host=True)
def print_op(ctx, ins, attrs):
    """Debug print (reference print_op.cc); identity pass-through.
    `first_n` > 0 prints only the first n executions of this op instance;
    `print_phase` BACKWARD suppresses forward printing (there is no
    separate backward print here — the op is not differentiated)."""
    v = one(ins, "In")
    if attrs.get("print_phase", "BOTH").upper() == "BACKWARD":
        return {"Out": v}
    first_n = int(attrs.get("first_n", -1))
    if first_n > 0:
        key = id(ctx.op)
        _PRINT_COUNTS[key] = _PRINT_COUNTS.get(key, 0) + 1
        if _PRINT_COUNTS[key] > first_n:
            return {"Out": v}
    x = np.asarray(data_of(v))
    parts = [attrs.get("message") or ""]
    if attrs.get("print_tensor_name", True):
        parts.append(f"name={ctx.op.input('In')[0]}")
    if attrs.get("print_tensor_shape", True):
        parts.append(f"shape={tuple(x.shape)}")
    if attrs.get("print_tensor_type", True):
        parts.append(f"dtype={x.dtype}")
    if attrs.get("print_tensor_lod", True) and hasattr(v, "lod"):
        parts.append(f"lod={v.lod}")
    n = int(attrs.get("summarize", 20))
    flat = x.reshape(-1)
    data = flat if (n < 0 or flat.size <= n) else flat[:n]
    print(" ".join(p for p in parts if p), "data:", data)
    return {"Out": v}


# ---------------------------------------------------------------------------
# small parity ops (reference fill_op.cc, sign_op.cc, minus_op.cc,
# label_smooth_op.cc/.h, multiplex_op.cc/.h, rnn_memory_helper_op.cc,
# get_places_op.cc, cond_op.cc, split_selected_rows_op.cc)
# ---------------------------------------------------------------------------


@register_op("sign", inputs=("X",), outputs=("Out",))
def sign(ctx, ins, attrs):
    xv = one(ins, "X")
    return {"Out": with_lod_of(xv, jnp.sign(data_of(xv)))}


@register_op("minus", inputs=("X", "Y"), outputs=("Out",))
def minus(ctx, ins, attrs):
    """Out = X - Y (reference minus_op.cc; no broadcast, unlike
    elementwise_sub)."""
    xv = one(ins, "X")
    return {"Out": with_lod_of(xv, data_of(xv) - data_of(one(ins, "Y")))}


@register_op("fill", inputs=(), outputs=("Out",),
             attrs={"shape": [], "value": [], "dtype": "float32",
                    "force_cpu": False},
             not_differentiable=True)
def fill(ctx, ins, attrs):
    """Fill Out with the flat `value` list reshaped to `shape`
    (reference fill_op.cc — the data-carrying cousin of fill_constant)."""
    from ..core.types import np_dtype

    data = np.asarray(attrs["value"], np_dtype(attrs.get("dtype",
                                                         "float32")))
    return {"Out": jnp.asarray(data.reshape(attrs["shape"]))}


@register_op("label_smooth", inputs=("X", "PriorDist"), outputs=("Out",),
             attrs={"epsilon": 0.0}, diff_inputs=("X",))
def label_smooth(ctx, ins, attrs):
    """(1-eps)*X + eps*prior (uniform 1/num_classes when PriorDist is
    absent) — reference label_smooth_op.h:26-46."""
    from ..core.execution import many

    xv = one(ins, "X")
    x = data_of(xv)
    eps = attrs["epsilon"]
    prior = many(ins, "PriorDist")
    if prior:
        out = (1.0 - eps) * x + eps * data_of(prior[0]).reshape(
            (1,) * (x.ndim - 1) + (-1,))
    else:
        out = (1.0 - eps) * x + eps / x.shape[-1]
    return {"Out": with_lod_of(xv, out)}


@register_op("multiplex", inputs=("Ids", "X"), outputs=("Out",),
             dup_inputs=("X",),
             diff_inputs=("X",))
def multiplex(ctx, ins, attrs):
    """Out[i] = X[Ids[i]][i] — per-row gather across candidate tensors
    (reference multiplex_op.h)."""
    from ..core.execution import many

    ids = data_of(one(ins, "Ids")).reshape(-1).astype(jnp.int32)
    xs = jnp.stack([data_of(x) for x in many(ins, "X")])  # [K, N, ...]
    rows = jnp.arange(xs.shape[1])
    return {"Out": xs[ids, rows]}


@register_op("rnn_memory_helper", inputs=("X",), outputs=("Out",))
def rnn_memory_helper(ctx, ins, attrs):
    """Identity pass-through (reference rnn_memory_helper_op.cc — exists
    so RNN memories always have a grad slot; the generic VJP gives the
    identity grad here for free)."""
    return {"Out": data_of(one(ins, "X"))}


@register_op("get_places", inputs=(), outputs=("Out",),
             attrs={"device_count": 0, "device_type": ""},
             not_differentiable=True, host=True)
def get_places_op(ctx, ins, attrs):
    """Materialize the device list as a host value (reference
    get_places_op.cc)."""
    from ..parallel.mesh import get_places

    n = attrs.get("device_count") or None
    return {"Out": get_places(n)}


@register_op("cond", inputs=("Cond",), outputs=(),
             not_differentiable=True, host=True)
def cond(ctx, ins, attrs):
    """Scalar-condition branch: run `sub_block` when Cond is true, else
    `else_block` if given (reference cond_op.cc, the scope-based
    predecessor of conditional_block)."""
    from .control_flow import _truthy
    from ..core.execution import run_op as _run_op

    take = _truthy(one(ins, "Cond"))
    sub = ctx.op.sub_block("sub_block" if take else "else_block")
    if sub is None:
        return {}
    for op_ in sub.ops:
        _run_op(ctx.root, op_, ctx.env)
    return {}


@register_op("split_selected_rows", inputs=("X",), outputs=("Out",),
             dup_outputs=("Out",),
             attrs={"height_sections": []},
             not_differentiable=True, host=True)
def split_selected_rows(ctx, ins, attrs):
    """Route SelectedRows rows into per-section outputs by row range
    (reference split_selected_rows_op.h FindOutIdx) — the sparse-grad
    sharding step of the pserver transpiler."""
    from ..core.lod import SelectedRows

    x = one(ins, "X")
    sections = [int(s) for s in attrs["height_sections"]]
    rows = np.asarray(x.rows).reshape(-1)
    value = np.asarray(x.value)
    offsets = np.cumsum([0] + sections)
    outs = []
    for k, h in enumerate(sections):
        m = (rows >= offsets[k]) & (rows < offsets[k] + h)
        outs.append(SelectedRows(
            jnp.asarray(rows[m] - offsets[k]),
            jnp.asarray(value[m]), h))
    return {"Out": outs}


@register_op("pruning_mask", inputs=("Param",), outputs=("Mask",),
             attrs={"sparsity_ratio": 0.6}, not_differentiable=True)
def pruning_mask(ctx, ins, attrs):
    """0/1 mask keeping the largest-magnitude (1-ratio) fraction of the
    parameter (reference parameter/ParameterUpdaterHook.cpp
    StaticPruningHook::generateMask — sorts |param| and zeroes the bottom
    sparsity_ratio quantile)."""
    p = data_of(one(ins, "Param"))
    ratio = float(attrs["sparsity_ratio"])
    a = jnp.abs(p.astype(jnp.float32)).reshape(-1)
    thr = jnp.quantile(a, jnp.clip(ratio, 0.0, 1.0))
    return {"Mask": (jnp.abs(p.astype(jnp.float32)) >= thr)
            .astype(p.dtype).reshape(p.shape)}
