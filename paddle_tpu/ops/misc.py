"""Miscellaneous ops: cos_sim, is_empty, print.

Reference: /root/reference/paddle/fluid/operators/cos_sim_op.{cc,h},
is_empty_op.cc, print_op.cc.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.execution import data_of, one, with_lod_of
from ..core.registry import register_op


@register_op("cos_sim", inputs=("X", "Y"),
             outputs=("Out", "XNorm", "YNorm"),
             diff_outputs=("Out",))
def cos_sim(ctx, ins, attrs):
    """Row-wise cosine similarity; Y may have 1 row (broadcast against every
    row of X), matching cos_sim_op.h."""
    xv = one(ins, "X")
    x = data_of(xv)
    y = data_of(one(ins, "Y"))
    xn = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), axis=-1, keepdims=True))
    out = jnp.sum(x * y, axis=-1, keepdims=True) / (xn * yn)
    return {"Out": with_lod_of(xv, out), "XNorm": xn, "YNorm": yn}


@register_op("is_empty", inputs=("X",), outputs=("Out",),
             not_differentiable=True)
def is_empty(ctx, ins, attrs):
    x = data_of(one(ins, "X"))
    return {"Out": jnp.asarray(x.size == 0)}


@register_op("print", inputs=("In",), outputs=("Out",),
             attrs={"first_n": -1, "message": "", "summarize": 20,
                    "print_tensor_name": True, "print_tensor_type": True,
                    "print_tensor_shape": True, "print_tensor_lod": True,
                    "print_phase": "BOTH"},
             not_differentiable=True, host=True)
def print_op(ctx, ins, attrs):
    """Debug print (reference print_op.cc); identity pass-through."""
    v = one(ins, "In")
    x = np.asarray(data_of(v))
    parts = [attrs.get("message") or ""]
    if attrs.get("print_tensor_name", True):
        parts.append(f"name={ctx.op.input('In')[0]}")
    if attrs.get("print_tensor_shape", True):
        parts.append(f"shape={tuple(x.shape)}")
    if attrs.get("print_tensor_type", True):
        parts.append(f"dtype={x.dtype}")
    if attrs.get("print_tensor_lod", True) and hasattr(v, "lod"):
        parts.append(f"lod={v.lod}")
    n = int(attrs.get("summarize", 20))
    flat = x.reshape(-1)
    data = flat if (n < 0 or flat.size <= n) else flat[:n]
    print(" ".join(p for p in parts if p), "data:", data)
    return {"Out": v}
