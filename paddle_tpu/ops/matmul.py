"""Dense linear algebra: mul, matmul, bilinear_tensor_product.

Reference: /root/reference/paddle/fluid/operators/mul_op.cc (flatten-to-2D
GEMM with x_num_col_dims / y_num_col_dims), matmul_op.h (batched matmul with
transpose flags, wrapping math/matmul.h -> cuBLAS).  Here both map straight
onto jnp.matmul / lax.dot_general, which XLA tiles onto the MXU — batched and
bf16-friendly by construction.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..amp import amp_cast
from ..core.execution import data_of, one, with_lod_of
from ..core.registry import register_op


def _flatten2d(x, num_col_dims):
    lead = int(np.prod(x.shape[:num_col_dims], dtype=np.int64)) if num_col_dims else 1
    return x.reshape(lead, -1)


@register_op("mul", inputs=("X", "Y"), outputs=("Out",),
             attrs={"x_num_col_dims": 1, "y_num_col_dims": 1},
             cost="matmul")
def mul(ctx, ins, attrs):
    xv = one(ins, "X")
    x = data_of(xv)
    y = data_of(one(ins, "Y"))
    xd, yd = attrs["x_num_col_dims"], attrs["y_num_col_dims"]
    x2 = _flatten2d(x, xd)
    y2 = y.reshape(int(np.prod(y.shape[:yd], dtype=np.int64)), -1)
    x2, y2 = amp_cast(x2, y2)
    out = jnp.matmul(x2, y2)
    out_shape = x.shape[:xd] + y.shape[yd:]
    # rows map 1:1 -> sequence structure survives a projection
    return {"Out": with_lod_of(xv, out.reshape(out_shape))}


@register_op("matmul", inputs=("X", "Y"), outputs=("Out",),
             attrs={"transpose_X": False, "transpose_Y": False,
                    "alpha": 1.0},
             cost="matmul")
def matmul(ctx, ins, attrs):
    """Reference matmul_op.h semantics: 1-D operands get vector treatment;
    leading batch dims broadcast."""
    x = data_of(one(ins, "X"))
    y = data_of(one(ins, "Y"))
    x, y = amp_cast(x, y)
    tx, ty = attrs["transpose_X"], attrs["transpose_Y"]
    squeeze_first = squeeze_last = False
    if x.ndim == 1:
        x = x[None, :] if not tx else x[:, None]
        x, tx, squeeze_first = x, False, True
    if y.ndim == 1:
        y = y[:, None] if not ty else y[None, :]
        y, ty, squeeze_last = y, False, True
    if tx:
        x = jnp.swapaxes(x, -1, -2)
    if ty:
        y = jnp.swapaxes(y, -1, -2)
    out = jnp.matmul(x, y)
    alpha = attrs.get("alpha", 1.0)
    if alpha != 1.0:
        out = out * jnp.asarray(alpha, out.dtype)
    if squeeze_first:
        out = out.squeeze(-2)
    if squeeze_last:
        out = out.squeeze(-1)
    return {"Out": out}


@register_op("bilinear_tensor_product", inputs=("X", "Y", "Weight", "Bias"),
             outputs=("Out",))
def bilinear_tensor_product(ctx, ins, attrs):
    """out[b, k] = x[b] @ W[k] @ y[b] (+ bias) — reference
    bilinear_tensor_product_op.cc."""
    x = data_of(one(ins, "X"))       # [B, M]
    y = data_of(one(ins, "Y"))       # [B, N]
    w = data_of(one(ins, "Weight"))  # [K, M, N]
    out = jnp.einsum("bm,kmn,bn->bk", x, w, y)
    b = one(ins, "Bias")
    if b is not None:
        out = out + data_of(b)
    return {"Out": out}
