"""Control-flow ops: while, conditional_block, tensor arrays, LoD rank
tables, beam search, and the scan-based `dynamic_rnn`.

Reference: /root/reference/paddle/fluid/operators/while_op.cc:35,
conditional_block_op.cc, tensor_array_read_write_op.cc, lod_rank_table_op.cc,
lod_tensor_to_array_op.cc, array_to_lod_tensor_op.cc, shrink_rnn_memory_op.cc,
max_sequence_len_op.cc, reorder_lod_tensor_by_rank_op.cc, beam_search_op.cc,
beam_search_decode_op.h, recurrent_op.cc.

TPU design split (SURVEY.md §5.7, §7 "hard parts" 1-2):

  * **Training-time recurrence** is the `dynamic_rnn` op: the user's step
    sub-block is traced once per time step inside ONE `jax.lax.scan` over a
    padded+masked [T, B, ...] view built from the (host-side, static) LoD —
    the recurrence stays fully on-device, XLA fuses the step body, and
    gradients come from scan's native VJP through the generic grad op.  This
    replaces the reference's while_op + lod_tensor_to_array shrinking-batch
    machinery for the differentiable path.
  * **Decode-time control flow** (`while`, tensor arrays, beam search) runs
    host-side through the interpreter: beam pruning genuinely changes shapes
    and LoD every step, which is exactly the case static-shape XLA should not
    be forced through.  Encoder/scoring segments inside the loop still hit
    compiled device code via the segmented executor.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.execution import (
    DictEnv,
    data_of,
    many,
    one,
    run_op,
)
from ..core.lod import LoDTensor, TensorArray, lod_from_seq_lens
from ..core.registry import register_op
from .sequence import lod_to_padded_index, padded_to_lod_index


def _scalar_int(v) -> int:
    return int(np.asarray(data_of(v)).reshape(-1)[0])


def _truthy(v) -> bool:
    return bool(np.asarray(data_of(v)).reshape(-1)[0])


# ---------------------------------------------------------------------------
# while / conditional_block (host interpreters over sub-blocks)
# ---------------------------------------------------------------------------


@register_op("while", inputs=("Condition", "X"), outputs=("Out",),
             attrs={"max_iters": 100000},
             dup_inputs=("X",), dup_outputs=("Out",),
             not_differentiable=True, host=True)
def while_op(ctx, ins, attrs):
    """Run the sub-block until the condition var becomes false (reference
    while_op.cc:35).  The body shares the surrounding env (the reference's
    step-scope parent lookup), so array writes and condition updates
    persist across iterations."""
    sub = ctx.op.sub_block()
    env = ctx.env
    cond_name = ctx.op.input("Condition")[0]
    it = 0
    while _truthy(env.get(cond_name)):
        if it >= attrs["max_iters"]:
            raise RuntimeError(
                f"while op exceeded max_iters={attrs['max_iters']}")
        # fold the iteration index into the rng so random ops draw fresh
        # samples each trip
        it_ctx = ctx.root.child(it)
        for op_ in sub.ops:
            run_op(it_ctx, op_, env)
        it += 1
    return {}


@register_op("conditional_block", inputs=("X", "Params"), outputs=("Out",),
             attrs={"is_scalar_condition": False},
             dup_inputs=("X", "Params"), dup_outputs=("Out",),
             not_differentiable=True, host=True)
def conditional_block(ctx, ins, attrs):
    """Run the sub-block iff the condition input is true / non-empty
    (reference conditional_block_op.cc)."""
    xs = many(ins, "X")
    if attrs.get("is_scalar_condition"):
        go = _truthy(xs[0])
    else:
        go = all(np.asarray(data_of(x)).size > 0 for x in xs)
    if go:
        sub = ctx.op.sub_block()
        for op_ in sub.ops:
            run_op(ctx.root, op_, ctx.env)
    return {}


# ---------------------------------------------------------------------------
# split/merge by mask — the IfElse machinery (reference
# split_lod_tensor_op.cc, merge_lod_tensor_op.cc).  Host ops: the mask is
# data-dependent so row counts are only known at run time; ops downstream of
# the split run inside compiled segments keyed by the realized shapes.
# ---------------------------------------------------------------------------


def _mask_bools(mask) -> np.ndarray:
    return np.asarray(data_of(mask)).reshape(-1).astype(bool)


def _branch_rows(xv, m: np.ndarray, level: int):
    """-> (true_rows, false_rows, true_lens, false_lens); lens are None for
    dense inputs.  For LoD inputs the mask entries select whole level-`level`
    sequences (reference split_lod_tensor_op.cc CopyTensorAndLod)."""
    if isinstance(xv, LoDTensor) and xv.lod:
        lod = xv.lod[level]
        if len(m) != len(lod) - 1:
            raise ValueError(
                f"split_lod_tensor: mask has {len(m)} entries but input has "
                f"{len(lod) - 1} level-{level} sequences")
        t_rows, f_rows, t_lens, f_lens = [], [], [], []
        for s, take in enumerate(m):
            rows = range(lod[s], lod[s + 1])
            if take:
                t_rows.extend(rows)
                t_lens.append(len(rows))
            else:
                f_rows.extend(rows)
                f_lens.append(len(rows))
        return t_rows, f_rows, t_lens, f_lens
    n = np.asarray(data_of(xv)).shape[0]
    if len(m) != n:
        raise ValueError(
            f"split_lod_tensor: mask has {len(m)} entries for {n} rows")
    idx = np.arange(n)
    return idx[m].tolist(), idx[~m].tolist(), None, None


def _branch_out(xv, x: np.ndarray, rows, lens):
    out = jnp.asarray(x[rows] if rows else
                      np.zeros((0,) + x.shape[1:], x.dtype))
    if lens is None:
        return out
    return LoDTensor(out, [lod_from_seq_lens(lens)])


@register_op("split_lod_tensor", inputs=("X", "Mask"),
             outputs=("OutTrue", "OutFalse"),
             attrs={"level": 0}, diff_inputs=("X",), host=True)
def split_lod_tensor(ctx, ins, attrs):
    xv = one(ins, "X")
    m = _mask_bools(one(ins, "Mask"))
    t_rows, f_rows, t_lens, f_lens = _branch_rows(xv, m, attrs["level"])
    x = np.asarray(data_of(xv))
    return {"OutTrue": _branch_out(xv, x, t_rows, t_lens),
            "OutFalse": _branch_out(xv, x, f_rows, f_lens)}


@register_op("split_lod_tensor_grad",
             inputs=("X", "Mask", "OutTrue@GRAD", "OutFalse@GRAD"),
             outputs=("X@GRAD",), attrs={"level": 0}, host=True)
def split_lod_tensor_grad(ctx, ins, attrs):
    """Scatter the branch grads back to the original rows."""
    xv = one(ins, "X")
    m = _mask_bools(one(ins, "Mask"))
    t_rows, f_rows, _, _ = _branch_rows(xv, m, attrs["level"])
    x = np.asarray(data_of(xv))
    gx = np.zeros(x.shape, x.dtype)
    gt = many(ins, "OutTrue@GRAD")
    gf = many(ins, "OutFalse@GRAD")
    if gt and t_rows:
        gx[t_rows] = np.asarray(data_of(gt[0])).reshape(
            (len(t_rows),) + x.shape[1:])
    if gf and f_rows:
        gx[f_rows] = np.asarray(data_of(gf[0])).reshape(
            (len(f_rows),) + x.shape[1:])
    out = jnp.asarray(gx)
    if isinstance(xv, LoDTensor) and xv.lod:
        out = LoDTensor(out, xv.lod)
    return {"X@GRAD": out}


@register_op("merge_lod_tensor", inputs=("X", "Mask", "InTrue", "InFalse"),
             outputs=("Out",), attrs={"level": 0},
             diff_inputs=("InTrue", "InFalse"), host=True)
def merge_lod_tensor(ctx, ins, attrs):
    """Interleave the two branches back into X's sequence order (reference
    merge_lod_tensor_op.cc).  X supplies the LoD frame the split used."""
    xv = one(ins, "X")
    m = _mask_bools(one(ins, "Mask"))
    t_rows, f_rows, t_lens, f_lens = _branch_rows(xv, m, attrs["level"])
    tv, fv = one(ins, "InTrue"), one(ins, "InFalse")
    t = np.asarray(data_of(tv))
    f = np.asarray(data_of(fv))
    feat = t.shape[1:] if t.size or not f.size else f.shape[1:]
    n = len(t_rows) + len(f_rows)
    out = np.zeros((n,) + feat, t.dtype if t.size or not f.size else f.dtype)
    if len(t_rows):
        out[t_rows] = t.reshape((len(t_rows),) + feat)
    if len(f_rows):
        out[f_rows] = f.reshape((len(f_rows),) + feat)
    res = jnp.asarray(out)
    if isinstance(xv, LoDTensor) and xv.lod:
        res = LoDTensor(res, xv.lod)
    return {"Out": res}


@register_op("merge_lod_tensor_grad",
             inputs=("X", "Mask", "InTrue", "InFalse", "Out@GRAD"),
             outputs=("InTrue@GRAD", "InFalse@GRAD"),
             attrs={"level": 0}, host=True)
def merge_lod_tensor_grad(ctx, ins, attrs):
    """Split the merged grad back into the two branch grads."""
    xv = one(ins, "X")
    m = _mask_bools(one(ins, "Mask"))
    t_rows, f_rows, t_lens, f_lens = _branch_rows(xv, m, attrs["level"])
    g = np.asarray(data_of(one(ins, "Out@GRAD")))
    return {"InTrue@GRAD": _branch_out(xv, g, t_rows, t_lens),
            "InFalse@GRAD": _branch_out(xv, g, f_rows, f_lens)}


# ---------------------------------------------------------------------------
# tensor arrays (reference tensor_array_read_write_op.cc)
# ---------------------------------------------------------------------------


@register_op("write_to_array", inputs=("X", "I"), outputs=("Out",),
             not_differentiable=True, host=True)
def write_to_array(ctx, ins, attrs):
    x = one(ins, "X")
    i = _scalar_int(one(ins, "I"))
    name = ctx.op.output("Out")[0]
    arr = ctx.env.get(name)
    arr = TensorArray(list(arr.tensors)) if isinstance(arr, TensorArray) \
        else TensorArray()
    while len(arr) <= i:
        arr.append(None)
    arr.tensors[i] = x
    return {"Out": arr}


@register_op("read_from_array", inputs=("X", "I"), outputs=("Out",),
             not_differentiable=True, host=True)
def read_from_array(ctx, ins, attrs):
    arr = one(ins, "X")
    i = _scalar_int(one(ins, "I"))
    return {"Out": arr[i]}


@register_op("lod_array_length", inputs=("X",), outputs=("Out",),
             not_differentiable=True, host=True)
def lod_array_length(ctx, ins, attrs):
    arr = one(ins, "X")
    return {"Out": np.asarray([len(arr)], np.int64)}


# ---------------------------------------------------------------------------
# LoD rank table machinery (reference lod_rank_table_op.cc and friends) —
# the length-bucketed dynamic-RNN path, kept for API parity; the TPU-native
# recurrence is `dynamic_rnn` below.
# ---------------------------------------------------------------------------


class LoDRankTable:
    """Sequences of one LoD level sorted by descending length:
    items[i] = (original_seq_index, length)."""

    __slots__ = ("items",)

    def __init__(self, items):
        self.items = list(items)

    def __repr__(self):
        return f"LoDRankTable({self.items})"


@register_op("lod_rank_table", inputs=("X",), outputs=("Out",),
             attrs={"level": 0}, not_differentiable=True, host=True)
def lod_rank_table(ctx, ins, attrs):
    xv = one(ins, "X")
    lvl = attrs["level"]
    lens = xv.seq_lens(lvl)
    items = sorted(
        [(i, ln) for i, ln in enumerate(lens)],
        key=lambda t: (-t[1], t[0]),
    )
    return {"Out": LoDRankTable(items)}


@register_op("max_sequence_len", inputs=("RankTable",), outputs=("Out",),
             not_differentiable=True, host=True)
def max_sequence_len(ctx, ins, attrs):
    table = one(ins, "RankTable")
    mx = table.items[0][1] if table.items else 0
    return {"Out": np.asarray([mx], np.int64)}


@register_op("lod_tensor_to_array", inputs=("X", "RankTable"),
             outputs=("Out",), not_differentiable=True, host=True)
def lod_tensor_to_array(ctx, ins, attrs):
    """Split a LoD tensor into per-timestep tensors with shrinking batch,
    sequences ordered by the rank table (reference lod_tensor_to_array_op.cc)."""
    xv = one(ins, "X")
    table = one(ins, "RankTable")
    lod = xv.lod[-1]
    x = np.asarray(xv.data)
    max_len = table.items[0][1] if table.items else 0
    arr = TensorArray()
    for t in range(max_len):
        rows = [lod[idx] + t for idx, ln in table.items if ln > t]
        arr.append(jnp.asarray(x[rows]))
    return {"Out": arr}


@register_op("array_to_lod_tensor", inputs=("X", "RankTable"),
             outputs=("Out",), not_differentiable=True, host=True)
def array_to_lod_tensor(ctx, ins, attrs):
    """Inverse of lod_tensor_to_array: reassemble the original sequence
    order (reference array_to_lod_tensor_op.cc)."""
    arr = one(ins, "X")
    table = one(ins, "RankTable")
    lens = {idx: ln for idx, ln in table.items}
    nseq = len(table.items)
    feature_shape = None
    steps = [np.asarray(data_of(t)) for t in arr.tensors]
    for s in steps:
        if s.size:
            feature_shape = s.shape[1:]
            break
    rows_per_seq = {i: [] for i in range(nseq)}
    for t, step in enumerate(steps):
        active = [idx for idx, ln in table.items if ln > t]
        for k, idx in enumerate(active):
            rows_per_seq[idx].append(step[k])
    out_rows, out_lens = [], []
    for i in range(nseq):
        out_rows.extend(rows_per_seq[i])
        out_lens.append(lens.get(i, 0))
    data = (np.stack(out_rows) if out_rows
            else np.zeros((0,) + (feature_shape or (1,)), np.float32))
    return {"Out": LoDTensor(jnp.asarray(data),
                             [lod_from_seq_lens(out_lens)])}


@register_op("shrink_rnn_memory", inputs=("X", "RankTable", "I"),
             outputs=("Out",), host=True)
def shrink_rnn_memory(ctx, ins, attrs):
    """Keep the first k rows of the memory, where k = number of sequences
    still active at step I (reference shrink_rnn_memory_op.cc)."""
    x = data_of(one(ins, "X"))
    table = one(ins, "RankTable")
    i = _scalar_int(one(ins, "I"))
    k = sum(1 for _, ln in table.items if ln > i)
    return {"Out": x[:k]}


@register_op("reorder_lod_tensor_by_rank", inputs=("X", "RankTable"),
             outputs=("Out",), not_differentiable=True, host=True)
def reorder_lod_tensor_by_rank(ctx, ins, attrs):
    xv = one(ins, "X")
    table = one(ins, "RankTable")
    if isinstance(xv, LoDTensor) and xv.lod:
        lod = xv.lod[-1]
        x = np.asarray(xv.data)
        rows, out_lens = [], []
        for idx, ln in table.items:
            rows.extend(range(lod[idx], lod[idx + 1]))
            out_lens.append(ln)
        return {"Out": LoDTensor(jnp.asarray(x[rows]),
                                 [lod_from_seq_lens(out_lens)])}
    x = np.asarray(data_of(xv))
    order = [idx for idx, _ in table.items]
    return {"Out": jnp.asarray(x[order])}


# ---------------------------------------------------------------------------
# beam search (reference beam_search_op.cc — a host/CPU op there too)
# ---------------------------------------------------------------------------


def _abs_offsets(lod, level):
    """LoD offsets of `level` converted to absolute row offsets
    (reference framework::ToAbsOffset)."""
    off = list(lod[level])
    for lower in lod[level + 1:]:
        off = [lower[o] for o in off]
    return off


@register_op("beam_search",
             inputs=("pre_ids", "ids", "scores"),
             outputs=("selected_ids", "selected_scores"),
             attrs={"level": 0, "beam_size": 1, "end_id": 0},
             not_differentiable=True, host=True)
def beam_search(ctx, ins, attrs):
    """Select top beam_size candidates per source sentence and prune ended
    prefixes — numpy re-expression of beam_search_op.cc:24-116.

    ids/scores: [n_prefix_rows, K] with a 2-level LoD whose level-`level`
    abs offsets split prefix rows by source sentence.  Output LoD:
    level 0 = those abs offsets, level 1 = per-prefix selected-candidate
    offsets."""
    pre_ids = np.asarray(data_of(one(ins, "pre_ids"))).reshape(-1)
    idsv = one(ins, "ids")
    ids = np.asarray(data_of(idsv))
    scores = np.asarray(data_of(one(ins, "scores")))
    level = attrs["level"]
    beam_size = attrs["beam_size"]
    end_id = attrs["end_id"]

    high = _abs_offsets(idsv.lod, level)
    n_rows = high[-1]
    ids2 = ids.reshape(n_rows, -1)
    scores2 = scores.reshape(n_rows, -1)

    # per source sentence: top beam_size (row, id, score) items
    per_row = [[] for _ in range(n_rows)]
    for s in range(len(high) - 1):
        items = [
            (r, int(ids2[r, d]), float(scores2[r, d]))
            for r in range(high[s], high[s + 1])
            for d in range(ids2.shape[1])
        ]
        items.sort(key=lambda t: -t[2])
        for it in items[:beam_size]:
            per_row[it[0]].append(it)

    # prune candidates of prefixes that already ended
    for r in range(n_rows):
        if r < len(pre_ids) and int(pre_ids[r]) == end_id:
            per_row[r] = []

    sel_ids, sel_scores, low = [], [], [0]
    for r in range(n_rows):
        row_items = sorted(per_row[r], key=lambda t: (t[0], t[1]))
        for _, i, sc in row_items:
            sel_ids.append(i)
            sel_scores.append(sc)
        low.append(len(sel_ids))
    out_lod = (tuple(high), tuple(low))
    return {
        "selected_ids": LoDTensor(
            jnp.asarray(np.asarray(sel_ids, np.int64).reshape(-1, 1)),
            out_lod),
        "selected_scores": LoDTensor(
            jnp.asarray(np.asarray(sel_scores, np.float32).reshape(-1, 1)),
            out_lod),
    }


@register_op("beam_search_decode", inputs=("Ids", "Scores"),
             outputs=("SentenceIds", "SentenceScores"),
             not_differentiable=True, host=True)
def beam_search_decode(ctx, ins, attrs):
    """Back-track the per-step beam arrays into full candidate sentences —
    python re-expression of beam_search_decode_op.h PackAllSteps."""
    step_ids = one(ins, "Ids")
    step_scores = one(ins, "Scores")
    steps = [
        (np.asarray(data_of(i)).reshape(-1),
         np.asarray(data_of(s)).reshape(-1),
         i.lod)
        for i, s in zip(step_ids.tensors, step_scores.tensors)
        if i is not None
    ]
    assert steps, "beam_search_decode needs at least one step"
    src_num = len(steps[0][2][0]) - 1

    # node = (word_id, score, parent_node_or_None)
    prefixes = []  # per source: list of leaf nodes
    sentences = [[] for _ in range(src_num)]

    for t, (ids, scores, lod) in enumerate(steps):
        src_off, cand_off = lod[0], lod[1]
        new_prefixes = []
        for s in range(src_num):
            nodes = []
            if not prefixes:  # first step: every id starts a sentence
                for r in range(src_off[s], src_off[s + 1]):
                    nodes.append((int(ids[r]), float(scores[r]), None))
            else:
                prev = prefixes[s]
                for p_idx, prefix in enumerate(prev):
                    row = src_off[s] + p_idx
                    lo, hi = cand_off[row], cand_off[row + 1]
                    if lo == hi:  # no continuation: sentence complete
                        sentences[s].append(_make_sentence(prefix))
                    else:
                        for c in range(lo, hi):
                            nodes.append(
                                (int(ids[c]), float(scores[c]), prefix))
            new_prefixes.append(nodes)
        prefixes = new_prefixes

    for s in range(src_num):
        for node in prefixes[s]:
            sentences[s].append(_make_sentence(node))

    id_data, score_data = [], []
    src_lod, sent_lod = [0], [0]
    for s in range(src_num):
        for words, scs in sentences[s]:
            id_data.extend(words)
            score_data.extend(scs)
            sent_lod.append(sent_lod[-1] + len(words))
        src_lod.append(src_lod[-1] + len(sentences[s]))
    lod = (tuple(src_lod), tuple(sent_lod))
    return {
        "SentenceIds": LoDTensor(
            jnp.asarray(np.asarray(id_data, np.int64)), lod),
        "SentenceScores": LoDTensor(
            jnp.asarray(np.asarray(score_data, np.float32)), lod),
    }


def _make_sentence(node):
    words, scores = [], []
    while node is not None:
        words.append(node[0])
        scores.append(node[1])
        node = node[2]
    return words[::-1], scores[::-1]


# ---------------------------------------------------------------------------
# parallel_do — single-host data parallelism (reference parallel_do_op.cc:113)
#
# The reference splits the batch into per-place scopes, runs the sub-block on
# worker threads, and sums partial grads back to place 0 (:249-267).  Here
# data parallelism is a *sharding annotation*: inputs get a
# with_sharding_constraint over a 'dp' device mesh, the sub-block is traced
# inline, and XLA partitions the whole computation (compute AND the generic
# VJP backward) across devices — no threads, no scope copies, grads arrive
# pre-summed by XLA's partitioner.
# ---------------------------------------------------------------------------


def _dp_shardings(num_places: int):
    """(batch-sharded, replicated) NamedShardings over a 'dp' mesh built
    by the shared parallel.mesh helpers (one mesh-construction path
    framework-wide)."""
    from ..parallel.mesh import data_sharding, make_mesh, replicated
    mesh = make_mesh({"dp": num_places})
    return data_sharding(mesh), replicated(mesh)


def _dp_constrain(d, row_shard, repl, num_places):
    if d.ndim >= 1 and d.shape[0] % num_places == 0:
        return jax.lax.with_sharding_constraint(d, row_shard)
    return jax.lax.with_sharding_constraint(d, repl)


@register_op(
    "parallel_do",
    inputs=("Inputs", "Captured", "CapturedNoGrad"),
    outputs=("Outs",),
    attrs={"use_nccl": False},
    dup_inputs=("Inputs", "Captured", "CapturedNoGrad"),
    dup_outputs=("Outs",),
    diff_inputs=("Inputs", "Captured"),
    diff_outputs=("Outs",))
def parallel_do(ctx, ins, attrs):
    in_vals = many(ins, "Inputs")
    cap_vals = many(ins, "Captured")
    capng_vals = many(ins, "CapturedNoGrad")
    num_places = min(attrs["num_places"], len(jax.devices()))
    row_shard, repl = _dp_shardings(num_places)

    env = _ChainEnv({}, {})
    env.outer = dict(zip(ctx.op.input("Captured"), cap_vals))
    env.outer.update(zip(ctx.op.input("CapturedNoGrad"), capng_vals))
    for name, v in zip(attrs["input_names"], in_vals):
        env.set(name, _dp_constrain(data_of(v), row_shard, repl,
                                    num_places))
    sub = ctx.op.sub_block()
    for op_ in sub.ops:
        run_op(ctx, op_, env)
    outs = [_dp_constrain(data_of(env.get(n)), row_shard, repl, num_places)
            for n in attrs["output_names"]]
    return {"Outs": outs}


# ---------------------------------------------------------------------------
# dynamic_rnn — the TPU-native recurrence over a user-defined step block
# ---------------------------------------------------------------------------


class _ChainEnv(DictEnv):
    """Dict env with read-through to a fixed outer mapping."""

    def __init__(self, inner, outer):
        super().__init__(inner)
        self.outer = outer

    def get(self, name):
        if name in self.d:
            return self.d[name]
        return self.outer.get(name)

    def has(self, name):
        return name in self.d or name in self.outer


@register_op(
    "dynamic_rnn",
    inputs=("StepInputs", "InitMemories", "StaticInputs", "Captured",
            "CapturedNoGrad"),
    outputs=("Outs",),
    attrs={"is_dynamic": True},
    dup_inputs=("StepInputs", "InitMemories", "StaticInputs", "Captured",
                "CapturedNoGrad"),
    dup_outputs=("Outs",),
    diff_inputs=("StepInputs", "InitMemories", "StaticInputs", "Captured"),
    diff_outputs=("Outs",))
def dynamic_rnn(ctx, ins, attrs):
    """Run the step sub-block under ONE lax.scan over time.

    Dynamic mode (`is_dynamic=True`): step inputs are LoDTensors sharing one
    LoD; they are padded to [B, T, ...] with a mask built host-side from the
    LoD, memories are masked so finished sequences hold their last state, and
    outputs are repacked to LoD rows (original batch order — no rank-table
    reordering needed, unlike the reference's lod_tensor_to_array path).

    Static mode: step inputs are dense tensors iterated along axis 0
    (reference recurrent_op.cc semantics)."""
    sub = ctx.op.sub_block()
    a = attrs
    step_vals = many(ins, "StepInputs")
    init_vals = many(ins, "InitMemories")
    static_vals = many(ins, "StaticInputs")
    cap_vals = many(ins, "Captured")
    capng_vals = many(ins, "CapturedNoGrad")
    dynamic = a.get("is_dynamic", True)

    if dynamic:
        lod = step_vals[0].lod[-1]
        for sv in step_vals[1:]:
            assert sv.lod[-1] == step_vals[0].lod[-1], (
                "dynamic_rnn: all step inputs must share one LoD, got "
                f"{sv.lod[-1]} vs {step_vals[0].lod[-1]}")
        idx, mask_np = lod_to_padded_index(lod)
        bsz, tmax = idx.shape
        xs = []
        for xv in step_vals:
            d = jnp.take(xv.data, jnp.asarray(idx).reshape(-1), axis=0)
            d = d.reshape((bsz, tmax) + xv.data.shape[1:])
            xs.append(jnp.swapaxes(d, 0, 1))  # [T, B, ...]
        mask = jnp.swapaxes(jnp.asarray(mask_np), 0, 1)  # [T, B]
    else:
        xs = [data_of(x) for x in step_vals]
        tmax = xs[0].shape[0]
        bsz = None
        mask = jnp.ones((tmax,), jnp.float32)

    # initial memory values
    mems0 = []
    init_iter = iter(init_vals)
    for spec in a["memory_specs"]:
        if spec["init"]:
            mems0.append(data_of(next(init_iter)))
        else:
            shape = tuple(spec["shape"])
            if dynamic and spec.get("batch_ref", True):
                shape = (bsz,) + shape
            mems0.append(jnp.full(shape, spec.get("value", 0.0),
                                  spec.get("dtype", "float32")))

    outer = {}
    outer.update(zip(a["static_input_names"],
                     [data_of(v) for v in static_vals]))
    # captured vars have no placeholders: the input-slot names ARE the names
    # the sub-block ops reference (works for the grad op too — its input
    # slots are copied from the forward op)
    outer.update(zip(ctx.op.input("Captured"), cap_vals))
    outer.update(zip(ctx.op.input("CapturedNoGrad"), capng_vals))

    step_names = a["step_input_names"]
    mem_names = a["memory_names"]
    upd_names = a["memory_update_names"]
    out_names = a["output_names"]
    sub_ops = tuple(sub.ops)

    def body(carry, inp):
        xt, m_t, t_idx = inp
        env = _ChainEnv({}, outer)
        for n, v in zip(step_names, xt):
            env.set(n, v)
        for n, v in zip(mem_names, carry):
            env.set(n, v)
        # per-timestep rng: fold the (traced) step index so random ops
        # (dropout) draw fresh samples each step, matching while_op
        step_ctx = ctx.child(t_idx)
        for op_ in sub_ops:
            run_op(step_ctx, op_, env)
        new_mems = []
        for old, n in zip(carry, upd_names):
            new = data_of(env.get(n))
            if dynamic:
                m = m_t.reshape((-1,) + (1,) * (new.ndim - 1))
                new = m * new + (1 - m) * old
            new_mems.append(new)
        outs = tuple(data_of(env.get(n)) for n in out_names)
        return tuple(new_mems), outs

    _, ys = jax.lax.scan(body, tuple(mems0),
                         (tuple(xs), mask, jnp.arange(tmax)))

    outs = []
    if dynamic:
        flat_idx = jnp.asarray(padded_to_lod_index(lod))
        for y in ys:  # [T, B, ...] -> LoD rows
            yb = jnp.swapaxes(y, 0, 1)
            flat = yb.reshape((bsz * tmax,) + yb.shape[2:])
            outs.append(LoDTensor(jnp.take(flat, flat_idx, axis=0),
                                  step_vals[0].lod))
    else:
        outs = list(ys)
    return {"Outs": outs}


# `recurrent` (reference recurrent_op.cc, the static RNN) is the same
# lowering as dynamic_rnn with is_dynamic=False — registered under both
# names so reference-shaped programs resolve
register_op("recurrent",
            inputs=("StepInputs", "InitMemories", "StaticInputs",
                    "Captured", "CapturedNoGrad"),
            outputs=("Outs",), attrs={"is_dynamic": False},
            dup_inputs=("StepInputs", "InitMemories", "StaticInputs",
                        "Captured", "CapturedNoGrad"),
            dup_outputs=("Outs",),
            diff_inputs=("StepInputs", "InitMemories", "StaticInputs",
                         "Captured"),
            diff_outputs=("Outs",))(dynamic_rnn)


# ---------------------------------------------------------------------------
# recompute (rematerialization) — TPU-native memory/FLOPs trade
# ---------------------------------------------------------------------------


@register_op("recompute", inputs=("X",), outputs=("Out",),
             attrs={"output_names": []},
             dup_inputs=("X",), dup_outputs=("Out",),
             diff_inputs=("X",), diff_outputs=("Out",))
def recompute(ctx, ins, attrs):
    """Run the sub-block under `jax.checkpoint`: activations inside the
    segment are NOT saved for backward — the segment re-runs during the
    grad pass.  No reference analogue (its memory tool is the liveness
    transpiler, memory_optimization_transpiler.py, which this framework
    also has); this is the HBM-side lever SURVEY.md's TPU notes call for
    ("use jax.checkpoint / rematerialisation to trade FLOPs for memory").

    Inputs X are every outer var the segment reads (params included, so
    the generic VJP yields their grads); Out mirrors the sub-block vars
    named in `output_names`.
    """
    sub = ctx.op.sub_block()
    in_names = list(ctx.op.input("X"))
    out_names = list(attrs["output_names"])
    sub_ops = tuple(sub.ops)
    in_vals = many(ins, "X")

    def fn(*vals):
        env = DictEnv(dict(zip(in_names, vals)))
        sctx = ctx.child(0)
        for op_ in sub_ops:
            run_op(sctx, op_, env)
        return tuple(env.get(n) for n in out_names)

    outs = jax.checkpoint(fn)(*in_vals)
    return {"Out": list(outs)}


# ---------------------------------------------------------------------------
# explicit build-time shape inference: sub-block ops
# ---------------------------------------------------------------------------
# Ops executing a sub-block (scan bodies, device fan-out, remat segments)
# cannot be abstractly evaluated without binding the sub-block's captured
# environment; their outputs' shapes are declared by the layer builders
# that create them.  Explicit no-op inference documents that and keeps the
# analysis shape pass from reporting spurious failures.

from ..core.registry import register_infer_shape  # noqa: E402


def _infer_via_builder(op, block):
    """Output shapes already declared at construction (layers/*)."""


for _t in ("dynamic_rnn", "recurrent", "parallel_do", "recompute"):
    register_infer_shape(_t)(_infer_via_builder)
