"""Sequence (LoD) ops.

Reference: /root/reference/paddle/fluid/operators/sequence_*.cc,
lod_reset_op.cc, im2sequence_op.cc and the math/ sequence kernels
(sequence2batch.h, sequence_pooling.cc, context_project.h).

TPU lowering strategy (SURVEY.md §5.7): the LoD offset table is host-side
static metadata (part of the compile cache key), so ragged reductions become
XLA segment ops over precomputed constant segment-id / index arrays, and
recurrences become padded+masked `lax.scan` (ops/rnn.py).  No per-step
dynamic shapes — each length bucket compiles once.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.execution import data_of, many, one
from ..core.lod import LoDTensor, lod_from_seq_lens
from ..core.registry import register_op


def _seq_lens(lod_level):
    return [lod_level[i + 1] - lod_level[i] for i in range(len(lod_level) - 1)]


def _segment_ids(lod_level) -> np.ndarray:
    n = lod_level[-1]
    out = np.zeros(n, dtype=np.int32)
    for i in range(len(lod_level) - 1):
        out[lod_level[i]:lod_level[i + 1]] = i
    return out


def lod_to_padded_index(lod_level):
    """Static (rows->padded) scatter/gather indices.

    Returns (index [B, T] int32 into the packed row axis — 0-padded past each
    sequence's length, mask [B, T] float32)."""
    lens = _seq_lens(lod_level)
    bsz = len(lens)
    tmax = max(lens) if lens else 0
    idx = np.zeros((bsz, tmax), dtype=np.int32)
    mask = np.zeros((bsz, tmax), dtype=np.float32)
    for i, ln in enumerate(lens):
        idx[i, :ln] = np.arange(lod_level[i], lod_level[i] + ln)
        mask[i, :ln] = 1.0
    return idx, mask


def padded_to_lod_index(lod_level):
    """Static flat gather indices mapping padded [B, T] back to packed rows."""
    lens = _seq_lens(lod_level)
    tmax = max(lens) if lens else 0
    out = []
    for i, ln in enumerate(lens):
        out.extend(i * tmax + t for t in range(ln))
    return np.asarray(out, dtype=np.int32)


# ---------------------------------------------------------------------------
# pooling / softmax
# ---------------------------------------------------------------------------


@register_op("sequence_pool", inputs=("X",), outputs=("Out", "MaxIndex"),
             attrs={"pooltype": "AVERAGE"}, diff_outputs=("Out",))
def sequence_pool(ctx, ins, attrs):
    xv = one(ins, "X")
    assert isinstance(xv, LoDTensor) and xv.lod, \
        "sequence_pool requires a LoDTensor input"
    lod = xv.lod[-1]
    x = xv.data
    nseq = len(lod) - 1
    seg = jnp.asarray(_segment_ids(lod))
    lens = jnp.asarray(_seq_lens(lod), x.dtype).reshape(-1, 1)
    pt = attrs["pooltype"].upper()
    if pt == "SUM":
        out = jax.ops.segment_sum(x, seg, nseq)
    elif pt == "AVERAGE":
        out = jax.ops.segment_sum(x, seg, nseq) / jnp.maximum(lens, 1)
    elif pt == "SQRT":
        out = jax.ops.segment_sum(x, seg, nseq) / jnp.sqrt(
            jnp.maximum(lens, 1))
    elif pt == "MAX":
        # NOT segment_max: its VJP routes gradient by float equality
        # (x == max[seg]), and under whole-program XLA:TPU fusion the two
        # sides can be recomputed at different effective precisions —
        # false ties then scatter the cotangent into MANY rows (measured:
        # grads inflated ~100x, an upstream LSTM never learns).  Padded
        # argmax + take_along_axis keeps the backward a pure integer
        # gather/scatter, immune to recomputation precision.
        idx, mask = lod_to_padded_index(lod)
        feat_dims = x.ndim - 1
        neg = jnp.asarray(
            jnp.finfo(x.dtype).min
            if jnp.issubdtype(x.dtype, jnp.floating)
            else jnp.iinfo(x.dtype).min, x.dtype)
        seq_lens_np = np.asarray(_seq_lens(lod))
        if idx.shape[1] == 0:  # every sequence empty
            out = jnp.full((nseq,) + x.shape[1:], neg, x.dtype)
        else:
            xp = x[jnp.asarray(idx)]                  # [B, T, ...]
            m = jnp.asarray(mask).reshape(mask.shape + (1,) * feat_dims)
            am = jax.lax.stop_gradient(
                jnp.argmax(jnp.where(m > 0, xp, neg), axis=1))  # [B, ...]
            out = jnp.take_along_axis(xp, am[:, None], axis=1)[:, 0]
            if (seq_lens_np == 0).any():
                # empty sequences: the pad gather aliases row 0 of the
                # packed tensor — mask to the max identity (segment_max
                # semantics); where() keeps their gradient exactly zero
                empty = jnp.asarray(seq_lens_np == 0).reshape(
                    (-1,) + (1,) * feat_dims)
                out = jnp.where(empty, neg, out)
    elif pt == "LAST":
        out = x[jnp.asarray([o - 1 for o in lod[1:]])]
    elif pt == "FIRST":
        out = x[jnp.asarray(lod[:-1])]
    else:
        raise ValueError(f"unknown pooltype {pt}")
    new_lod = xv.lod[:-1]
    if new_lod:
        return {"Out": LoDTensor(out, new_lod), "MaxIndex": None}
    return {"Out": out, "MaxIndex": None}


@register_op("sequence_softmax", inputs=("X",), outputs=("Out",))
def sequence_softmax(ctx, ins, attrs):
    xv = one(ins, "X")
    lod = xv.lod[-1]
    x = xv.data.reshape(-1)
    nseq = len(lod) - 1
    seg = jnp.asarray(_segment_ids(lod))
    # stop_gradient: softmax is shift-invariant so the max's gradient
    # cancels exactly — and segment_max's equality-based VJP is unsafe
    # under TPU fusion (see sequence_pool MAX above)
    smax = jax.lax.stop_gradient(jax.ops.segment_max(x, seg, nseq))
    e = jnp.exp(x - smax[seg])
    ssum = jax.ops.segment_sum(e, seg, nseq)
    return {"Out": LoDTensor((e / ssum[seg]).reshape(xv.data.shape),
                             xv.lod)}


# ---------------------------------------------------------------------------
# expand / concat / reshape / erase / slice / lod_reset
# ---------------------------------------------------------------------------


@register_op("sequence_expand", inputs=("X", "Y"), outputs=("Out",),
             diff_inputs=("X",))
def sequence_expand(ctx, ins, attrs):
    """Row-wise expansion (exact reference sequence_expand_op.h kernel
    semantics): X row i is repeated len(Y.lod[-1] sequence i) times —
    requires len(Y.lod[-1]) - 1 == X rows; Out.lod = Y.lod.  X's own LoD
    does not influence the expansion (also the beam-search decode idiom:
    one state row per prefix, Y's inner LoD maps prefixes -> candidates)."""
    xv = one(ins, "X")
    yv = one(ins, "Y")
    x = data_of(xv)
    y_lod = yv.lod[-1]
    y_lens = _seq_lens(y_lod)
    assert len(y_lens) == x.shape[0], (
        f"sequence_expand: X has {x.shape[0]} rows but Y's last LoD level "
        f"has {len(y_lens)} sequences")
    reps = []
    for i, yl in enumerate(y_lens):
        reps.extend([i] * yl)
    out = jnp.take(x, jnp.asarray(np.asarray(reps, np.int32)), axis=0)
    return {"Out": LoDTensor(out, list(yv.lod))}


@register_op("sequence_concat", inputs=("X",), outputs=("Out",),
             dup_inputs=("X",),
             attrs={"axis": 0, "level": 0})
def sequence_concat(ctx, ins, attrs):
    """Concatenate corresponding sequences from each input (reference
    sequence_concat_op.cc, axis=0 path)."""
    xs = many(ins, "X")
    lods = [x.lod[-1] for x in xs]
    nseq = len(lods[0]) - 1
    order = []
    offset = [0]
    for x in xs:
        offset.append(offset[-1] + int(x.data.shape[0]))
    out_lens = []
    for i in range(nseq):
        total = 0
        for k, x in enumerate(xs):
            lo, hi = lods[k][i], lods[k][i + 1]
            order.extend(range(offset[k] + lo, offset[k] + hi))
            total += hi - lo
        out_lens.append(total)
    data = jnp.concatenate([x.data for x in xs], axis=0)
    out = jnp.take(data, jnp.asarray(np.asarray(order, np.int32)), axis=0)
    return {"Out": LoDTensor(out, [lod_from_seq_lens(out_lens)])}


@register_op("sequence_reshape", inputs=("X",), outputs=("Out",),
             attrs={"new_dim": 1})
def sequence_reshape(ctx, ins, attrs):
    xv = one(ins, "X")
    x = xv.data
    new_dim = attrs["new_dim"]
    old_dim = x.shape[-1]
    lod = xv.lod[-1]
    out_lens = [ln * old_dim // new_dim for ln in _seq_lens(lod)]
    out = x.reshape(-1, new_dim)
    return {"Out": LoDTensor(out, [lod_from_seq_lens(out_lens)])}


@register_op("sequence_erase", inputs=("X",), outputs=("Out",),
             attrs={"tokens": []}, not_differentiable=True, host=True)
def sequence_erase(ctx, ins, attrs):
    """Remove given tokens (dynamic output size -> host op, reference
    sequence_erase_op.cc)."""
    xv = one(ins, "X")
    x = np.asarray(xv.data)
    tokens = set(attrs["tokens"])
    lod = xv.lod[-1]
    keep_rows, out_lens = [], []
    for i in range(len(lod) - 1):
        cnt = 0
        for r in range(lod[i], lod[i + 1]):
            if int(x[r].reshape(-1)[0]) not in tokens:
                keep_rows.append(r)
                cnt += 1
        out_lens.append(cnt)
    out = x[keep_rows] if keep_rows else x[:0]
    return {"Out": LoDTensor(out, [lod_from_seq_lens(out_lens)])}


@register_op("sequence_slice", inputs=("X", "Offset", "Length"),
             outputs=("Out",), diff_inputs=("X",), host=True)
def sequence_slice(ctx, ins, attrs):
    xv = one(ins, "X")
    off = np.asarray(data_of(one(ins, "Offset"))).reshape(-1)
    length = np.asarray(data_of(one(ins, "Length"))).reshape(-1)
    lod = xv.lod[-1]
    rows, out_lens = [], []
    for i in range(len(lod) - 1):
        start = lod[i] + int(off[i])
        rows.extend(range(start, start + int(length[i])))
        out_lens.append(int(length[i]))
    out = jnp.take(xv.data, jnp.asarray(np.asarray(rows, np.int32)), axis=0)
    return {"Out": LoDTensor(out, [lod_from_seq_lens(out_lens)])}


@register_op("lod_reset", inputs=("X", "Y"), outputs=("Out",),
             attrs={"target_lod": []})
def lod_reset(ctx, ins, attrs):
    xv = one(ins, "X")
    x = data_of(xv)
    y = one(ins, "Y")
    if y is not None and isinstance(y, LoDTensor) and y.lod:
        lod = y.lod[-1]
    elif y is not None:
        lod = tuple(int(v) for v in np.asarray(data_of(y)).reshape(-1))
    else:
        lod = tuple(int(v) for v in attrs["target_lod"])
    return {"Out": LoDTensor(x, [lod])}


@register_op("im2sequence", inputs=("X",), outputs=("Out",),
             attrs={"kernels": [1, 1], "strides": [1, 1],
                    "paddings": [0, 0, 0, 0]})
def im2sequence(ctx, ins, attrs):
    """Image -> sequence of flattened patches (reference
    im2sequence_op.cc): output rows are sliding windows, one sequence per
    image."""
    x = data_of(one(ins, "X"))  # [N, C, H, W]
    kh, kw = attrs["kernels"]
    sh, sw = attrs["strides"]
    pu, pl, pd, pr = (attrs["paddings"] + [0, 0, 0, 0])[:4]
    x = jnp.pad(x, ((0, 0), (0, 0), (pu, pd), (pl, pr)))
    n, c, h, w = x.shape
    oh = (h - kh) // sh + 1
    ow = (w - kw) // sw + 1
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), padding="VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"))  # [N, C*kh*kw, oh, ow]
    out = patches.transpose(0, 2, 3, 1).reshape(n * oh * ow, c * kh * kw)
    lod = lod_from_seq_lens([oh * ow] * n)
    return {"Out": LoDTensor(out, [lod])}


# ---------------------------------------------------------------------------
# sequence_conv (context projection)
# ---------------------------------------------------------------------------


@register_op("sequence_conv", inputs=("X", "Filter", "PaddingData"),
             outputs=("Out",),
             attrs={"contextLength": 3, "contextStart": -1,
                    "contextStride": 1},
             diff_inputs=("X", "Filter"))
def sequence_conv(ctx, ins, attrs):
    """Context-window projection per sequence (reference sequence_conv_op.cc
    + math/context_project.h): gather [ctx_len] neighbor rows (zero outside
    the sequence), flatten, matmul with Filter [ctx_len*D, M]."""
    xv = one(ins, "X")
    w = data_of(one(ins, "Filter"))
    lod = xv.lod[-1]
    x = xv.data
    n, d = x.shape
    ctx_len = attrs["contextLength"]
    ctx_start = attrs["contextStart"]
    # static gather index + validity mask per (row, context offset)
    idx = np.zeros((n, ctx_len), np.int32)
    mask = np.zeros((n, ctx_len), np.float32)
    for i in range(len(lod) - 1):
        lo, hi = lod[i], lod[i + 1]
        for r in range(lo, hi):
            for j in range(ctx_len):
                src = r + ctx_start + j
                if lo <= src < hi:
                    idx[r, j] = src
                    mask[r, j] = 1.0
    gathered = jnp.take(x, jnp.asarray(idx), axis=0)  # [N, ctx, D]
    gathered = gathered * jnp.asarray(mask)[:, :, None]
    out = gathered.reshape(n, ctx_len * d) @ w
    return {"Out": LoDTensor(out, xv.lod)}


# ---------------------------------------------------------------------------
# padding helpers exposed as ops (reference math/sequence_padding)
# ---------------------------------------------------------------------------


@register_op("sequence_pad", inputs=("X",), outputs=("Out", "Length"),
             attrs={"pad_value": 0.0, "padded_length": -1},
             diff_outputs=("Out",))
def sequence_pad(ctx, ins, attrs):
    """padded_length=-1 pads to the batch max (reference
    sequence_padding.h); a positive value fixes the time axis — the
    static-shape handle attention-over-padded-states needs under jit."""
    xv = one(ins, "X")
    lod = xv.lod[-1]
    idx, mask = lod_to_padded_index(lod)
    want = int(attrs.get("padded_length", -1))
    if want > 0:
        t = idx.shape[1]
        assert want >= t, (
            f"sequence_pad: padded_length {want} < longest sequence {t}")
        idx = np.pad(idx, ((0, 0), (0, want - t)))
        mask = np.pad(mask, ((0, 0), (0, want - t)))
    out = jnp.take(xv.data, jnp.asarray(idx).reshape(-1), axis=0)
    out = out.reshape(idx.shape + xv.data.shape[1:])
    m = jnp.asarray(mask).reshape(mask.shape + (1,) * (out.ndim - 2))
    pad = jnp.asarray(attrs["pad_value"], out.dtype)
    out = out * m + pad * (1 - m)
    return {"Out": out,
            "Length": jnp.asarray(_seq_lens(lod), jnp.int32)}


@register_op("sequence_unpad", inputs=("X", "Length"), outputs=("Out",),
             diff_inputs=("X",), host=True)
def sequence_unpad(ctx, ins, attrs):
    x = data_of(one(ins, "X"))  # [B, T, ...]
    lens = [int(v) for v in np.asarray(data_of(one(ins, "Length")))]
    lod = lod_from_seq_lens(lens)
    flat_idx = padded_to_lod_index(lod)
    flat = x.reshape((-1,) + x.shape[2:])
    out = jnp.take(flat, jnp.asarray(flat_idx), axis=0)
    return {"Out": LoDTensor(out, [lod])}


@register_op("sequence_mask", inputs=("X",), outputs=("Y",),
             attrs={"maxlen": -1, "out_dtype": "float32"},
             not_differentiable=True)
def sequence_mask(ctx, ins, attrs):
    """[N] lengths -> [N, maxlen] 0/1 mask (the standard companion of
    sequence_pad for attention masking; maxlen=-1 uses max(lengths),
    which requires interpreter mode — pass a static maxlen under jit)."""
    lens = data_of(one(ins, "X")).reshape(-1)
    maxlen = int(attrs.get("maxlen", -1))
    if maxlen <= 0:
        maxlen = int(np.asarray(lens).max())
    j = jnp.arange(maxlen)
    return {"Y": (j[None, :] < lens[:, None]).astype(
        attrs.get("out_dtype", "float32"))}


# ---------------------------------------------------------------------------
# explicit build-time shape inference (LoD-dependent ops)
# ---------------------------------------------------------------------------
# The default eval_shape-based inference only sees abstract arrays; these
# lowerings require real LoD metadata, so they would otherwise be reported
# by the analysis shape-inference pass as inference failures.  Row counts
# that depend on the LoD are declared as -1 (data-dependent).

from ..core.registry import register_infer_shape  # noqa: E402
from ..core.shape_inference import input_var, set_output_shape  # noqa: E402


@register_infer_shape("sequence_pool")
def _infer_sequence_pool(op, block):
    x = input_var(op, block, "X")
    if x is None or x.shape is None:
        return
    # one pooled row per sequence; the sequence count lives in the LoD
    set_output_shape(op, block, "Out", (-1,) + tuple(x.shape[1:]), x.dtype)


@register_infer_shape("sequence_softmax")
def _infer_sequence_softmax(op, block):
    x = input_var(op, block, "X")
    if x is None or x.shape is None:
        return
    set_output_shape(op, block, "Out", x.shape, x.dtype)


@register_infer_shape("sequence_expand")
def _infer_sequence_expand(op, block):
    x = input_var(op, block, "X")
    if x is None or x.shape is None:
        return
    set_output_shape(op, block, "Out", (-1,) + tuple(x.shape[1:]), x.dtype)


@register_infer_shape("sequence_conv")
def _infer_sequence_conv(op, block):
    x = input_var(op, block, "X")
    f = input_var(op, block, "Filter")
    if x is None or x.shape is None or f is None or f.shape is None:
        return
    set_output_shape(op, block, "Out", (x.shape[0], f.shape[1]), x.dtype)


@register_infer_shape("sequence_reshape")
def _infer_sequence_reshape(op, block):
    x = input_var(op, block, "X")
    if x is None or x.shape is None:
        return
    new_dim = int(op.attrs.get("new_dim", 1))
    set_output_shape(op, block, "Out", (-1, new_dim), x.dtype)
