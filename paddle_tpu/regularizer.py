"""Weight-decay regularizers appended onto gradients.

Reference: /root/reference/python/paddle/v2/fluid/regularizer.py:1-188.
"""
from __future__ import annotations

from .core.framework import unique_name

__all__ = ["L1Decay", "L2Decay", "L1DecayRegularizer", "L2DecayRegularizer",
           "append_regularization_ops"]


class WeightDecayRegularizer:
    def append_regularization_op(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def append_regularization_op(self, param, grad, block):
        decay = block.create_var(name=unique_name(param.name + "_l2decay"),
                                 dtype=param.dtype)
        block.append_op("scale", {"X": [param.name]},
                        {"Out": [decay.name]}, {"scale": self._coeff})
        return decay


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def append_regularization_op(self, param, grad, block):
        sign = block.create_var(name=unique_name(param.name + "_sign"),
                                dtype=param.dtype)
        # sign(x) = x / |x|; implemented as clip(x*1e9, -1, 1) for stability
        block.append_op("scale", {"X": [param.name]}, {"Out": [sign.name]},
                        {"scale": 1e9})
        clipped = block.create_var(name=unique_name(param.name + "_signc"),
                                   dtype=param.dtype)
        block.append_op("clip", {"X": [sign.name]}, {"Out": [clipped.name]},
                        {"min": -1.0, "max": 1.0})
        decay = block.create_var(name=unique_name(param.name + "_l1decay"),
                                 dtype=param.dtype)
        block.append_op("scale", {"X": [clipped.name]},
                        {"Out": [decay.name]}, {"scale": self._coeff})
        return decay


def append_regularization_ops(params_grads, regularization=None):
    """grad += decay(param) for each param with a regularizer
    (reference regularizer.py append_regularization_ops)."""
    out = []
    for param, grad in params_grads:
        regularizer = getattr(param, "regularizer", None) or regularization
        if grad is None or regularizer is None:
            out.append((param, grad))
            continue
        block = grad.block
        decay = regularizer.append_regularization_op(param, grad, block)
        new_grad = block.create_var(
            name=unique_name(grad.name + "_reg"), dtype=param.dtype)
        block.append_op("sum", {"X": [grad.name, decay.name]},
                        {"Out": [new_grad.name]})
        out.append((param, new_grad))
    return out


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer
