"""Gradient / error clipping.

Reference: /root/reference/python/paddle/v2/fluid/clip.py:1-236 —
ErrorClipByValue (clips activation error "@GRAD" vars during backward),
GradientClipByValue / GradientClipByNorm / GradientClipByGlobalNorm
(rewrite (param, grad) pairs before the optimizer ops), `set_gradient_clip`
and `append_gradient_clip_ops` called from Optimizer.minimize.
"""
from __future__ import annotations

from . import layers
from .core.framework import Parameter, unique_name

__all__ = [
    "ErrorClipByValue",
    "GradientClipByValue",
    "GradientClipByNorm",
    "GradientClipByGlobalNorm",
    "set_gradient_clip",
    "append_gradient_clip_ops",
    "error_clip_callback",
]


class BaseErrorClipAttr:
    def append_clip_op(self, block, grad_name):
        raise NotImplementedError


class ErrorClipByValue(BaseErrorClipAttr):
    def __init__(self, max, min=None):
        max = float(max)
        self.min = float(min) if min is not None else -max
        self.max = max

    def append_clip_op(self, block, grad_name):
        block.append_op("clip", {"X": [grad_name]}, {"Out": [grad_name]},
                        {"min": self.min, "max": self.max})


def error_clip_callback(block, op):
    """Apply each output var's error_clip attr to its @GRAD var (reference
    clip.py error_clip_callback, invoked per grad op in backward)."""
    for grad_n in op.output_names():
        if not grad_n.endswith("@GRAD"):
            continue
        fwd_name = grad_n[: -len("@GRAD")]
        if not block.has_var(fwd_name):
            continue
        fwd_var = block.var(fwd_name)
        clip_attr = getattr(fwd_var, "error_clip", None)
        if clip_attr is not None:
            clip_attr.append_clip_op(block, grad_n)


class BaseGradientClipAttr:
    def process_context(self, context, param, grad):
        pass

    def create_operators(self, param, grad):
        raise NotImplementedError


class NullGradientClipAttr(BaseGradientClipAttr):
    def create_operators(self, param, grad):
        return param, grad


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        max = float(max)
        self.min = float(min) if min is not None else -max
        self.max = max

    def create_operators(self, param, grad):
        new_grad = layers.clip(grad, min=self.min, max=self.max)
        return param, new_grad


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def create_operators(self, param, grad):
        new_grad = layers.clip_by_norm(grad, max_norm=self.clip_norm)
        return param, new_grad


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    """Scale all grads by clip_norm / max(global_norm, clip_norm)
    (reference clip.py:120-180)."""

    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def process_context(self, context, param, grad):
        grads = context.setdefault(self.group_name, [])
        grads.append(grad)

    def create_operators(self, param, grad, context):
        # scale var computed once per group by finalize_group; looked up in
        # the SHARED context so distinct instances with one group_name work
        scale_var = context[self.group_name + "@scale"]
        new_grad = layers.elementwise_mul(grad, scale_var)
        return param, new_grad

    def finalize_group(self, context):
        grads = context.get(self.group_name, [])
        sq_sums = []
        for g in grads:
            sq = layers.reduce_sum(layers.square(g))
            sq_sums.append(sq)
        global_norm = layers.sqrt(layers.sums(sq_sums))
        clip_var = layers.fill_constant(shape=[1], dtype="float32",
                                        value=self.clip_norm)
        denom = layers.elementwise_max(global_norm, clip_var)
        context[self.group_name + "@scale"] = layers.elementwise_div(
            clip_var, denom)


_GRADIENT_CLIP_ATTR = "gradient_clip_attr"


def set_gradient_clip(clip, param_list=None, program=None):
    """Attach a clip attr to parameters (all trainable ones by default)."""
    from .core.framework import default_main_program

    program = program or default_main_program()
    if param_list is None:
        params = program.global_block().all_parameters()
    else:
        params = [
            program.global_block().var(p) if isinstance(p, str) else p
            for p in param_list
        ]
    for p in params:
        p.gradient_clip_attr = clip


def append_gradient_clip_ops(param_grad):
    """Rewrite (param, grad) pairs through each param's clip attr
    (reference clip.py append_gradient_clip_ops)."""
    context = {}
    attrs = []
    for p, g in param_grad:
        clip_attr = getattr(p, _GRADIENT_CLIP_ATTR, None) or \
            NullGradientClipAttr()
        attrs.append(clip_attr)
        clip_attr.process_context(context, p, g)
    finalized = set()
    for a in attrs:
        if isinstance(a, GradientClipByGlobalNorm) and \
                a.group_name not in finalized:
            a.finalize_group(context)
            finalized.add(a.group_name)
    res = []
    for (p, g), a in zip(param_grad, attrs):
        if isinstance(a, GradientClipByGlobalNorm):
            res.append(a.create_operators(p, g, context))
        else:
            res.append(a.create_operators(p, g))
    return res
