"""Image preprocessing utilities (reference python/paddle/v2/image.py).

The reference implements these over cv2; here they are PIL + numpy (cv2
is not a dependency of the TPU build).  Channel conventions match the
reference: HWC uint8 RGB in, `to_chw` for the CHW training layout.
"""
from __future__ import annotations

import io
import os
import tarfile

import numpy as np

__all__ = [
    "batch_images_from_tar",
    "load_image_bytes",
    "load_image",
    "resize_short",
    "to_chw",
    "center_crop",
    "random_crop",
    "left_right_flip",
    "simple_transform",
    "load_and_transform",
]


def _pil():
    from PIL import Image
    return Image


def load_image_bytes(bytes_, is_color=True):
    """Decode raw encoded image bytes -> HWC (or HW for gray) uint8 array
    (reference image.py:111)."""
    im = _pil().open(io.BytesIO(bytes_))
    im = im.convert("RGB" if is_color else "L")
    return np.asarray(im)


def load_image(file, is_color=True):
    """Load an image file (reference image.py:135)."""
    with open(file, "rb") as f:
        return load_image_bytes(f.read(), is_color=is_color)


def _resize(im: np.ndarray, w: int, h: int) -> np.ndarray:
    pil_im = _pil().fromarray(im)
    return np.asarray(pil_im.resize((w, h), _pil().BILINEAR))


def resize_short(im, size):
    """Resize so the SHORTER edge equals `size`, keeping aspect ratio
    (reference image.py:163)."""
    h, w = im.shape[:2]
    if h > w:
        h_new, w_new = size * h // w, size
    else:
        h_new, w_new = size, size * w // h
    return _resize(im, w_new, h_new)


def to_chw(im, order=(2, 0, 1)):
    """HWC -> CHW (reference image.py:189)."""
    assert len(im.shape) == len(order)
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    """Crop the center size×size patch (reference image.py:213)."""
    h, w = im.shape[:2]
    h_start = (h - size) // 2
    w_start = (w - size) // 2
    return im[h_start:h_start + size, w_start:w_start + size]


def random_crop(im, size, is_color=True):
    """Crop a random size×size patch (reference image.py:241)."""
    h, w = im.shape[:2]
    h_start = np.random.randint(0, h - size + 1)
    w_start = np.random.randint(0, w - size + 1)
    return im[h_start:h_start + size, w_start:w_start + size]


def left_right_flip(im, is_color=True):
    """Mirror horizontally (reference image.py:269)."""
    return im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None):
    """resize_short -> (random crop + random flip | center crop) ->
    CHW float32 -> optional mean subtraction (reference image.py:291)."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, is_color=is_color)
        if np.random.randint(2) == 0:
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size, is_color=is_color)
    if len(im.shape) == 3:
        im = to_chw(im)
    im = im.astype(np.float32)
    if mean is not None:
        mean = np.array(mean, dtype=np.float32)
        if mean.ndim == 1 and len(im.shape) == 3:
            mean = mean[:, np.newaxis, np.newaxis]
        im -= mean
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    """load_image + simple_transform (reference image.py:348)."""
    return simple_transform(load_image(filename, is_color), resize_size,
                            crop_size, is_train, is_color, mean)


def batch_images_from_tar(data_file, dataset_name, img2label,
                          num_per_batch=1024):
    """Pack images from a tar file into pickled numpy batches
    (reference image.py:48): each batch file holds {'data': [flattened
    uint8 arrays], 'label': [...]}.  Returns the batch-list meta file."""
    import pickle

    out_path = f"{data_file}_{dataset_name}_batch"
    meta_file = os.path.join(out_path, "batch_list")
    if os.path.exists(meta_file):
        return meta_file
    os.makedirs(out_path, exist_ok=True)

    tf = tarfile.open(data_file)
    data, labels, file_id, names = [], [], 0, []
    for mem in tf.getmembers():
        if mem.name not in img2label:
            continue
        data.append(load_image_bytes(tf.extractfile(mem).read()).flatten())
        labels.append(img2label[mem.name])
        if len(data) == num_per_batch:
            output = {"label": labels, "data": data}
            name = f"batch_{file_id}"
            with open(os.path.join(out_path, name), "wb") as f:
                pickle.dump(output, f, protocol=2)
            names.append(name)
            file_id += 1
            data, labels = [], []
    if data:
        name = f"batch_{file_id}"
        with open(os.path.join(out_path, name), "wb") as f:
            pickle.dump({"label": labels, "data": data}, f, protocol=2)
        names.append(name)
    with open(meta_file, "w") as f:
        f.write("\n".join(names))
    return meta_file
