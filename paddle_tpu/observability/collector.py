"""Central telemetry collection: scrape the fleet, federate one dump.

PR 4 gave every process its own registry and a localhost ``/metrics``
endpoint; a production fleet (trainers, pservers, `cli serve` replicas,
a router) is only legible when those endpoints merge into ONE view.
This module is the collection plane:

  * **announce** — each member process calls
    :func:`announce(registry_addr, kind)`: it starts a localhost
    Prometheus endpoint over its process registry and registers the
    endpoint in the fleet's TTL-lease registry (cloud/registry.py)
    under the shared ``telemetry`` kind, encoded ``kind|host:port``.
    Lease expiry IS member death — the same liveness contract pservers
    and replicas already live by.
  * **TelemetryCollector** — discovers members from the registry,
    scrapes each endpoint on a period, and merges the samples into a
    fleet-level store with ``member``/``kind`` labels: a
    :class:`~paddle_tpu.observability.timeseries.TimeSeriesStore`
    (windowed rate/p99 queries for `cli top`, the SLO layer and the
    router's autoscaler signal) plus a latest-scrape table rendered as
    **Prometheus federation output** (``federation_text()``).  A member
    that dies mid-scrape times out, never wedges the loop, and its
    series are reclaimed (registry delisting, or ``fail_limit``
    consecutive scrape failures).
  * **push path** — ``collector.serve(port)`` exposes the federated
    dump over HTTP (``GET /metrics``) and accepts pushes
    (``POST /push?kind=K&member=M`` with Prometheus text body) from
    short-lived processes that cannot wait to be scraped;
    :func:`push_metrics` is the client half.
  * **trace assembly** — spans already carry wire-propagated trace
    ids; :func:`assemble_traces` joins the per-process Chrome-trace
    files (and flight-recorder dumps) of a trace dir into one Chrome
    trace PER TRACE ID, so a cross-process request reads as a single
    timeline.

See docs/observability.md "Fleet telemetry" for the topology runbook.
"""
from __future__ import annotations

import glob
import json
import logging
import os
import threading
import time
import urllib.request
from typing import Dict, List, Optional, Tuple

from . import exemplars as exemplars_mod
from . import exporters
from . import metrics as metrics_mod
from .exporters import _fmt_labels, _fmt_value
from .timeseries import TimeSeriesStore, cum_to_per_bucket

__all__ = [
    "TELEMETRY_KIND",
    "announce",
    "Announcement",
    "TelemetryCollector",
    "parse_prometheus_text",
    "push_metrics",
    "assemble_traces",
    "merge_traces",
]

# every member publishes under ONE registry kind — the collector's
# discovery is a single LIST, and member kinds ride inside the address
# string, so adding a new process kind needs no registry change
TELEMETRY_KIND = "telemetry"
_DESIRED_SLOTS = 256


def _encode_member(kind: str, addr: str, member: str = "") -> str:
    if "|" in kind or "|" in member:
        raise ValueError("member kind/name cannot contain '|'")
    return f"{kind}|{addr}|{member}" if member else f"{kind}|{addr}"


def _decode_member(index: int, raw: str) -> Tuple[str, str, str]:
    """-> (kind, scrape addr, member id).  Addresses that predate the
    encoding (bare host:port) fall back to kind 'unknown'."""
    parts = raw.split("|")
    if len(parts) == 1:
        return "unknown", parts[0], f"unknown-{index}"
    kind, addr = parts[0], parts[1]
    member = parts[2] if len(parts) > 2 and parts[2] else \
        f"{kind}-{index}"
    return kind, addr, member


class Announcement:
    """A member's live telemetry publication: the localhost endpoint +
    the registry lease keeping it discoverable."""

    def __init__(self, http_server, lease, kind: str, member: str):
        self.http = http_server
        self.lease = lease
        self.kind = kind
        self.member = member

    @property
    def url(self) -> str:
        return self.http.url()

    def close(self):
        if self.lease is not None:
            self.lease.release()
        self.http.close()


def announce(registry_addr: str, kind: str, member: str = "",
             port: int = 0, ttl_s: float = 2.0,
             registry=None, metrics_registry=None) -> Announcement:
    """Publish THIS process's /metrics endpoint in the fleet registry
    so a TelemetryCollector discovers and scrapes it.  `registry_addr`
    is the TTL-lease registry (a ClusterController's
    ``registry_addr``, a router-hosted one, or a standalone
    ``Registry.serve()``); pass an in-process ``registry`` object to
    skip TCP, and ``metrics_registry`` to expose a registry other than
    the process-wide one.  Returns the Announcement — close() on clean
    shutdown (the lease TTL reclaims the slot after a crash)."""
    from ..cloud.registry import Lease, RegistryClient

    srv = exporters.start_http_server(port=port,
                                      registry=metrics_registry)
    try:
        reg = registry if registry is not None \
            else RegistryClient(registry_addr)
        # every announcer pins the same generous slot cap: members may
        # race the collector to the registry, and DESIRE is idempotent
        reg.set_desired(TELEMETRY_KIND, _DESIRED_SLOTS)
        lease = Lease(reg, TELEMETRY_KIND,
                      _encode_member(kind, f"{srv.addr}:{srv.port}",
                                     member),
                      ttl_s=ttl_s)
    except Exception:
        srv.close()  # no half-announced member: endpoint without lease
        raise
    m = member or f"{kind}-{lease.index}"
    return Announcement(srv, lease, kind, m)


_ENV_ANNOUNCE_LOCK = threading.Lock()
_ENV_ANNOUNCEMENT: Optional[Announcement] = None
_ENV_TRIED = False


def maybe_announce(kind: str, member: str = "") -> Optional[Announcement]:
    """Announce once per process when PADDLE_TPU_TELEMETRY_REGISTRY is
    set — the hook trainer/pserver/replica entrypoints call so a fleet
    launched with the env var self-assembles under the collector.  The
    first caller's kind wins (one process, one member)."""
    global _ENV_ANNOUNCEMENT, _ENV_TRIED
    addr = os.environ.get("PADDLE_TPU_TELEMETRY_REGISTRY", "")
    if not addr:
        return None
    with _ENV_ANNOUNCE_LOCK:
        if _ENV_TRIED:
            return _ENV_ANNOUNCEMENT
        _ENV_TRIED = True
        try:
            _ENV_ANNOUNCEMENT = announce(
                addr, kind,
                member or os.environ.get("PADDLE_TPU_TELEMETRY_MEMBER",
                                         ""))
        except Exception as e:
            # telemetry must never block boot — but a member silently
            # missing from every `cli top` needs SOME breadcrumb
            _ENV_ANNOUNCEMENT = None
            logging.getLogger("paddle_tpu.telemetry").warning(
                "telemetry announce to %s failed (%r): this process "
                "will not appear in the fleet view", addr, e)
        return _ENV_ANNOUNCEMENT


# ---------------------------------------------------------------------------
# Prometheus text parsing (the exposition exporters.py produces)
# ---------------------------------------------------------------------------


def _unescape_label(v: str) -> str:
    # left-to-right over escape PAIRS — chained str.replace corrupts
    # values like 'C:\\net' (the collapsed backslash re-matches '\n')
    out = []
    i = 0
    while i < len(v):
        c = v[i]
        if c == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            out.append({"n": "\n", '"': '"', "\\": "\\"}.get(nxt,
                                                            c + nxt))
            i += 2
            continue
        out.append(c)
        i += 1
    return "".join(out)


def _parse_labels(s: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    i = 0
    while i < len(s):
        eq = s.index("=", i)
        key = s[i:eq].strip().lstrip(",").strip()
        j = eq + 2  # skip ="
        buf = []
        while j < len(s):
            c = s[j]
            if c == "\\" and j + 1 < len(s):
                buf.append(s[j:j + 2])
                j += 2
                continue
            if c == '"':
                break
            buf.append(c)
            j += 1
        out[key] = _unescape_label("".join(buf))
        i = j + 1
    return out


def _parse_value(s: str) -> float:
    s = s.strip()
    if s == "+Inf":
        return float("inf")
    if s == "-Inf":
        return float("-inf")
    return float(s)


def parse_prometheus_text(text: str) -> Dict[str, dict]:
    """Parse Prometheus text exposition back into the registry-snapshot
    shape: ``{name: {"type", "help", "samples": [{"labels", "value"}]}}``
    with histogram families reassembled (value = ``{"buckets": [[le,
    cumulative]...], "sum", "count"}`` plus, when bucket lines carry
    OpenMetrics exemplars, ``"exemplars": {le: parsed exemplar}``).
    Tolerant of unknown types and of series lacking a # TYPE line
    (treated as untyped gauges)."""
    types: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    raw: List[Tuple[str, Dict[str, str], float, Optional[dict]]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3].strip()
            elif len(parts) >= 4 and parts[1] == "HELP":
                helps[parts[2]] = parts[3]
            continue
        # the exemplar splits off FIRST: its `# {...}` suffix carries
        # braces that would otherwise confuse the label-set scan
        line, ex = exemplars_mod.split_sample_line(line)
        if "{" in line:
            name = line[:line.index("{")]
            rest = line[line.index("{") + 1:]
            close = rest.rindex("}")
            labels = _parse_labels(rest[:close])
            value = _parse_value(rest[close + 1:])
        else:
            name, _, v = line.partition(" ")
            labels = {}
            value = _parse_value(v)
        raw.append((name, labels, value, ex))

    out: Dict[str, dict] = {}
    hist_parts: Dict[str, dict] = {}
    hist_names = {n for n, t in types.items() if t == "histogram"}

    def _hist_slot(base: str, labels: Dict[str, str]) -> dict:
        fam = hist_parts.setdefault(base, {})
        key = tuple(sorted((k, v) for k, v in labels.items()
                           if k != "le"))
        return fam.setdefault(key, {"labels": {k: v for k, v in labels.items() if k != "le"},  # noqa: E501
                                    "buckets": [], "sum": 0.0,
                                    "count": 0})

    for name, labels, value, ex in raw:
        base = None
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and \
                    name[: -len(suffix)] in hist_names:
                base = name[: -len(suffix)]
                if suffix == "_bucket":
                    le = _parse_value(labels.get("le", "+Inf"))
                    slot = _hist_slot(base, labels)
                    slot["buckets"].append([le, int(value)])
                    if ex is not None:
                        slot.setdefault("exemplars", {})[le] = ex
                elif suffix == "_sum":
                    _hist_slot(base, labels)["sum"] = value
                else:
                    _hist_slot(base, labels)["count"] = int(value)
                break
        if base is not None:
            continue
        fam = out.setdefault(name, {
            "type": types.get(name, "gauge"),
            "help": helps.get(name, ""), "samples": []})
        fam["samples"].append({"labels": labels, "value": value})

    for base, slots in hist_parts.items():
        fam = out.setdefault(base, {
            "type": "histogram", "help": helps.get(base, ""),
            "samples": []})
        for slot in slots.values():
            slot["buckets"].sort(key=lambda b: b[0])
            value = {"buckets": slot["buckets"],
                     "sum": slot["sum"],
                     "count": slot["count"]}
            if slot.get("exemplars"):
                # keyed by le so federation can re-attach each to its
                # bucket line; absent entirely for exemplar-free dumps
                value["exemplars"] = slot["exemplars"]
            fam["samples"].append({
                "labels": slot["labels"], "value": value})
    return out


# ---------------------------------------------------------------------------
# the collector
# ---------------------------------------------------------------------------


class _Member:
    __slots__ = ("member", "kind", "addr", "fails", "up", "last_ts",
                 "parsed")

    def __init__(self, member: str, kind: str, addr: str):
        self.member = member
        self.kind = kind
        self.addr = addr
        self.fails = 0
        self.up = False
        self.last_ts = 0.0
        self.parsed: Dict[str, dict] = {}


class TelemetryCollector:
    """Fleet-level scrape-and-merge over a TTL-lease registry.

    ``registry_addr`` joins an existing registry; ``registry=`` an
    in-process one; neither hosts a fresh Registry over TCP (members
    then announce at ``collector.registry_addr``)."""

    def __init__(self, registry_addr: Optional[str] = None,
                 registry=None, period_s: float = 1.0,
                 scrape_timeout_s: float = 1.0, fail_limit: int = 2,
                 capacity: int = 720):
        self._owned_registry = None
        if registry is None and registry_addr is None:
            from ..cloud.registry import Registry

            self._owned_registry = registry = Registry()
            port = registry.serve(0)
            registry_addr = f"127.0.0.1:{port}"
        elif registry is None:
            from ..cloud.registry import RegistryClient

            registry = RegistryClient(registry_addr)
        self._reg = registry
        self.registry_addr = registry_addr
        try:
            self._reg.set_desired(TELEMETRY_KIND, _DESIRED_SLOTS)
        except Exception:
            pass  # a read-only registry client still discovers
        self.period_s = float(period_s)
        self.scrape_timeout_s = float(scrape_timeout_s)
        self.fail_limit = int(fail_limit)
        # the fleet time-series: fed by scrapes/pushes, never
        # self-sampling (its registry would be the COLLECTOR's, not the
        # fleet's)
        self.series = TimeSeriesStore(capacity=capacity,
                                      period_s=period_s)
        self._lock = threading.Lock()
        self._members: Dict[str, _Member] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._http = None
        self.scrapes = 0
        self.scrape_failures = 0
        # detector window: wide enough that a single scrape's lifetime
        # stats already produce a verdict (mean() treats a one-point
        # series as in-window), tight enough to track live drift
        self.detector_window_s = max(60.0, 10 * self.period_s)
        # collector-synthesized families (straggler scores, calibration
        # ratios), parsed-snapshot shaped, merged into federation_text
        self._synth: Dict[str, dict] = {}

    # -- discovery + scrape -------------------------------------------------
    def _discover(self) -> Dict[str, Tuple[str, str]]:
        """member id -> (kind, addr) from the registry (empty on a
        registry hiccup: keep the current table, never wedge)."""
        try:
            listed = self._reg.list(TELEMETRY_KIND)
        except Exception:
            with self._lock:
                return {m.member: (m.kind, m.addr)
                        for m in self._members.values()}
        out = {}
        for idx, rawaddr in listed.items():
            kind, addr, member = _decode_member(idx, rawaddr)
            out[member] = (kind, addr)
        return out

    def _drop_member_locked(self, member: str) -> None:
        self._members.pop(member, None)
        self.series.drop({"member": member})

    def scrape_once(self) -> Dict[str, bool]:
        """Discover + scrape every member once; returns
        {member: scrape_ok}.  All network I/O runs outside the
        collector lock with a per-member timeout — one dying member
        costs at most `scrape_timeout_s`, never the loop."""
        listing = self._discover()
        with self._lock:
            for member, (kind, addr) in listing.items():
                m = self._members.get(member)
                if m is None or m.addr != addr or m.kind != kind:
                    if m is not None:
                        # same member id, new incarnation (a restarted
                        # process can reclaim the lowest free lease
                        # index, and its /metrics port — baked into
                        # addr — changes): the old points must go, or
                        # the new process's reset counters append
                        # after the old high values and every rate()
                        # in the window reads NEGATIVE
                        self.series.drop({"member": member})
                    self._members[member] = _Member(member, kind, addr)
            for member in list(self._members):
                if member not in listing \
                        and self._members[member].addr != "push":
                    # lease expired / released: the member is gone and
                    # so are its series.  Push members never held a
                    # lease — they persist until restarted pushes
                    # replace them
                    self._drop_member_locked(member)
            targets = [m for m in self._members.values()
                       if m.addr != "push"]
        results: Dict[str, bool] = {}
        for m in targets:
            ok = self._scrape_member(m)
            results[m.member] = ok
        self.run_detectors()
        return results

    def run_detectors(self) -> Dict[str, dict]:
        """Recompute the collector-side detectors (comm stragglers,
        static-vs-measured calibration drift) over the fleet series and
        publish their synthetic gauges: ingested into the time-series
        store (SLO-able, `cli top`) and merged into federation_text.
        Runs after every scrape pass; cheap (label scans + window
        means).  Detection must never wedge collection."""
        try:
            from . import attribution

            synth = attribution.run_detectors(
                self.series, window_s=self.detector_window_s)
        except Exception:
            return dict(self._synth)
        with self._lock:
            self._synth = synth
        for name, fam in synth.items():
            for s in fam["samples"]:
                self.series.ingest_value(name, fam["type"],
                                         s["labels"], s["value"])
        return synth

    def _scrape_member(self, m: _Member) -> bool:
        ts = time.monotonic()
        try:
            with urllib.request.urlopen(
                    f"http://{m.addr}/metrics",
                    timeout=self.scrape_timeout_s) as resp:
                text = resp.read().decode()
            parsed = parse_prometheus_text(text)
        except Exception:
            with self._lock:
                self.scrape_failures += 1
                if self._members.get(m.member) is not m:
                    # delisted (or replaced by a new incarnation) while
                    # this scrape was in flight: its series are already
                    # dropped — writing anything back would resurrect a
                    # ghost no future discovery pass can reclaim
                    return False
                m.fails += 1
                m.up = False
                if m.fails >= self.fail_limit:
                    # still lease-listed but unscrapeable (wedged or
                    # firewalled): reclaim its series — a dashboard
                    # must not keep rendering a ghost
                    self.series.drop({"member": m.member})
                    m.parsed = {}
                # member_up goes in AFTER any fail-limit drop: a wedged
                # member must read DOWN in the store, not no-data
                # (no-data passes SLO checks)
                self.series.ingest_value(
                    "paddle_tpu_member_up", "gauge",
                    {"member": m.member, "kind": m.kind}, 0.0)
            return False
        self._ingest(m, parsed, ts)
        return True

    def _ingest(self, m: _Member, parsed: Dict[str, dict],
                ts: float) -> None:
        with self._lock:
            if self._members.get(m.member) is not m:
                # a concurrent discovery pass delisted this member (or
                # replaced it with a new incarnation) after we snapshot
                # our targets: its series were dropped, and ingesting
                # this in-flight scrape would leak them forever
                return
            self.scrapes += 1
            m.fails = 0
            m.up = True
            m.last_ts = ts
            m.parsed = parsed
            extra = {"member": m.member, "kind": m.kind}
            self.series.ingest_value("paddle_tpu_member_up", "gauge",
                                     extra, 1.0, ts=ts)
            for name, fam in parsed.items():
                for s in fam["samples"]:
                    labels = {**s["labels"], **extra}
                    if fam["type"] == "histogram":
                        les, counts = cum_to_per_bucket(
                            s["value"]["buckets"])
                        if not les:
                            continue
                        self.series.ingest_histogram(
                            name, labels, les, counts,
                            s["value"]["count"], s["value"]["sum"],
                            ts=ts)
                    else:
                        self.series.ingest_value(
                            name, fam["type"], labels, s["value"],
                            ts=ts)

    def ingest_push(self, kind: str, member: str, text: str) -> None:
        """The push path: one Prometheus text body from a short-lived
        process that will not live to be scraped."""
        member = member or f"{kind}-push"
        with self._lock:
            m = self._members.get(member)
            if m is None:
                m = self._members[member] = _Member(member, kind,
                                                    "push")
        self._ingest(m, parse_prometheus_text(text), time.monotonic())
        self.run_detectors()

    # -- outputs ------------------------------------------------------------
    def members(self) -> List[dict]:
        with self._lock:
            return [{"member": m.member, "kind": m.kind,
                     "addr": m.addr, "up": m.up, "fails": m.fails}
                    for m in sorted(self._members.values(),
                                    key=lambda m: m.member)]

    def federation_text(self) -> str:
        """The whole fleet's latest scrape as ONE Prometheus text dump,
        every series labeled ``member``/``kind`` — what a real
        Prometheus would produce from a /federate pull."""
        merged: Dict[str, dict] = {}
        with self._lock:
            snapshot = [(m.member, m.kind, dict(m.parsed), m.up)
                        for m in sorted(self._members.values(),
                                        key=lambda m: m.member)]
            synth = {n: {"type": f["type"], "help": f["help"],
                         "samples": [(dict(s["labels"]), s["value"])
                                     for s in f["samples"]]}
                     for n, f in self._synth.items()}
        lines = []
        for member, kind, parsed, up in snapshot:
            for name, fam in parsed.items():
                slot = merged.setdefault(
                    name, {"type": fam["type"], "help": fam["help"],
                           "samples": []})
                for s in fam["samples"]:
                    slot["samples"].append(
                        ({**s["labels"], "member": member,
                          "kind": kind}, s["value"]))
        up_fam = {"type": "gauge",
                  "help": "1 when the member's last scrape succeeded",
                  "samples": [({"member": member, "kind": kind},
                               1.0 if up else 0.0)
                              for member, kind, _, up in snapshot]}
        merged["paddle_tpu_member_up"] = up_fam
        for name, fam in synth.items():
            slot = merged.setdefault(
                name, {"type": fam["type"], "help": fam["help"],
                       "samples": []})
            slot["samples"].extend(fam["samples"])
        for name in sorted(merged):
            fam = merged[name]
            if fam["help"]:
                lines.append(f"# HELP {name} {fam['help']}")
            lines.append(f"# TYPE {name} {fam['type']}")
            for labels, value in fam["samples"]:
                if fam["type"] == "histogram":
                    exs = value.get("exemplars") or {}
                    for le, cum in value["buckets"]:
                        line = (
                            f"{name}_bucket"
                            f"{_fmt_labels(labels, {'le': _fmt_value(le)})}"  # noqa: E501
                            f" {cum}")
                        if le in exs:
                            # federation preserves member exemplars, so
                            # `cli trace-of` can resolve a fleet-level
                            # p99 straight to a member's trace id
                            line += " " + exemplars_mod.render_exemplar(
                                exs[le])
                        lines.append(line)
                    lines.append(f"{name}_sum{_fmt_labels(labels)} "
                                 f"{_fmt_value(value['sum'])}")
                    lines.append(f"{name}_count{_fmt_labels(labels)} "
                                 f"{value['count']}")
                else:
                    lines.append(f"{name}{_fmt_labels(labels)} "
                                 f"{_fmt_value(value)}")
        return "\n".join(lines) + "\n"

    def write_federation(self, path: str) -> str:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            f.write(self.federation_text())
        return path

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "TelemetryCollector":
        with self._lock:
            if self._thread is not None:
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name="paddle-tpu-collector")
            self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.period_s):
            try:
                self.scrape_once()
            except Exception:
                pass  # the scrape loop must survive anything

    def serve(self, port: int = 0, addr: str = "127.0.0.1") -> int:
        """Expose the federated dump + the push endpoint over HTTP:
        GET /metrics (or /federate) and POST /push?kind=K&member=M."""
        import http.server
        import urllib.parse

        coll = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def _send(self, code: int, body: bytes,
                      ctype: str = "text/plain; version=0.0.4"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 (stdlib casing)
                path = self.path.split("?")[0]
                if path in ("/metrics", "/federate", "/"):
                    self._send(200, coll.federation_text().encode())
                elif path == "/members":
                    self._send(200,
                               json.dumps(coll.members()).encode(),
                               "application/json")
                else:
                    self.send_error(404)

            def do_POST(self):  # noqa: N802
                parsed = urllib.parse.urlparse(self.path)
                if parsed.path != "/push":
                    self.send_error(404)
                    return
                q = urllib.parse.parse_qs(parsed.query)
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n).decode()
                try:
                    coll.ingest_push(q.get("kind", ["push"])[0],
                                     q.get("member", [""])[0], body)
                except Exception as e:
                    self._send(400, f"bad push: {e}".encode())
                    return
                self._send(200, b"ok")

            def log_message(self, *a):
                return

        self._http = http.server.ThreadingHTTPServer((addr, port),
                                                     _Handler)
        httpd = self._http
        threading.Thread(target=httpd.serve_forever, daemon=True,
                         name="paddle-tpu-collector-http").start()
        return self._http.server_address[1]

    def stop(self):
        with self._lock:
            t, self._thread = self._thread, None
        self._stop.set()
        if t is not None and t is not threading.current_thread():
            t.join(timeout=self.period_s + 5)
        if self._http is not None:
            self._http.shutdown()
            self._http.server_close()
            self._http = None
        if self._owned_registry is not None:
            self._owned_registry.close()
            self._owned_registry = None

    close = stop


def push_metrics(collector_url: str, kind: str, member: str = "",
                 registry: Optional[metrics_mod.MetricsRegistry] = None,
                 timeout_s: float = 2.0) -> None:
    """Push this process's registry to a collector's /push endpoint —
    the exit hook for processes too short-lived to be scraped."""
    import urllib.parse

    body = exporters.prometheus_text(registry).encode()
    q = urllib.parse.urlencode({"kind": kind, "member": member})
    req = urllib.request.Request(
        f"{collector_url.rstrip('/')}/push?{q}", data=body,
        method="POST")
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        resp.read()


# ---------------------------------------------------------------------------
# cross-process trace assembly
# ---------------------------------------------------------------------------


def _span_to_chrome_event(rec: dict) -> dict:
    return {
        "ph": "X", "cat": "span", "name": rec["name"],
        "ts": rec["ts"] * 1e6, "dur": rec["dur"] * 1e6,
        "pid": rec["pid"], "tid": rec["tid"],
        "args": {"trace_id": rec["trace_id"],
                 "span_id": rec["span_id"],
                 "parent_id": rec["parent_id"], **rec["attrs"]},
    }


def _load_trace_events(trace_dir: str) -> List[dict]:
    events: List[dict] = []
    for path in sorted(glob.glob(os.path.join(trace_dir,
                                              "trace_*.json"))):
        try:
            with open(path) as f:
                payload = json.load(f)
            events.extend(payload.get("traceEvents", []))
        except (OSError, ValueError):
            continue  # a torn file from a crashed process
    for path in sorted(glob.glob(os.path.join(trace_dir,
                                              "flight_*.json"))):
        try:
            with open(path) as f:
                payload = json.load(f)
            for rec in payload.get("spans", []):
                events.append(_span_to_chrome_event(rec))
        except (OSError, ValueError, KeyError, TypeError):
            continue
    return events


def assemble_traces(trace_dir: str, out_dir: Optional[str] = None
                    ) -> Dict[str, str]:
    """Join the per-process trace files of `trace_dir` into ONE Chrome
    trace per trace id: every span whose wire-propagated ``trace_id``
    matches lands in the same file, regardless of which process
    recorded it.  Flight-recorder dumps in the dir contribute their
    span rings too (a SIGKILLed member's last spans join the timeline
    its peers exported).  Returns {trace_id: written path}."""
    out_dir = out_dir or trace_dir
    by_tid: Dict[str, List[dict]] = {}
    for ev in _load_trace_events(trace_dir):
        tid = (ev.get("args") or {}).get("trace_id")
        if tid:
            by_tid.setdefault(tid, []).append(ev)
    os.makedirs(out_dir, exist_ok=True)
    out: Dict[str, str] = {}
    for tid, events in by_tid.items():
        # the same span can appear in both a process's trace export
        # and its flight ring — dedupe on span id
        seen, unique = set(), []
        for ev in events:
            sid = ev["args"].get("span_id")
            if sid in seen:
                continue
            seen.add(sid)
            unique.append(ev)
        unique.sort(key=lambda e: e.get("ts", 0))
        path = os.path.join(out_dir, f"trace_join_{tid}.json")
        with open(path, "w") as f:
            json.dump({"traceEvents": unique,
                       "displayTimeUnit": "ms",
                       "otherData": {"trace_id": tid}}, f)
        out[tid] = path
    return out


def merge_traces(trace_dir: str, out_path: str) -> str:
    """All processes' events in one Chrome trace (pids keep the tracks
    apart) — the whole-run view next to assemble_traces' per-request
    files."""
    events = _load_trace_events(trace_dir)
    events.sort(key=lambda e: e.get("ts", 0))
    d = os.path.dirname(out_path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(out_path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return out_path
