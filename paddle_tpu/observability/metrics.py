"""Process-wide metrics registry: labeled Counter/Gauge/Histogram.

Reference shape: the op-based runtimes this reproduction tracks all
converged on the same substrate — a process-local registry of named,
labeled series exported in Prometheus text format (TF's monitoring/
CollectionRegistry, torch.monitor, the reference's stat sets in
paddle/utils/Stat.h aggregated by ThreadLocalStat).  This module is that
substrate for paddle_tpu: every subsystem (executor, trainer, reader
pipeline, serving, pserver transport, resilience) registers its series
here, and the exporters (observability/exporters.py) render one
coherent dump instead of each subsystem keeping private dicts.

Cost model: instruments are **gated** by a module-level switch
(``PADDLE_TPU_METRICS`` env / the ``metrics`` flag) — when off, every
``inc``/``set``/``observe`` is a single attribute read + boolean test,
so hot paths can instrument unconditionally.  Metrics created with
``always=True`` bypass the gate: they back pre-existing telemetry APIs
(``Executor.cache_stats()``, ``InferenceServer.stats()``) whose
contracts predate the switch and must keep counting regardless.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from . import exemplars as _exemplars

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "registry",
    "counter",
    "gauge",
    "histogram",
    "enabled",
    "set_enabled",
    "quantile_from_buckets",
    "DEFAULT_LATENCY_BUCKETS",
]

# fixed exponential latency buckets: 0.5 ms .. ~16 s doubling — wide
# enough for sub-ms op dispatch and multi-second XLA compiles alike
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = tuple(
    0.0005 * 2 ** i for i in range(16))


def _env_on(raw: Optional[str]) -> bool:
    return (raw or "").strip().lower() in ("1", "on", "true", "yes")


_ENABLED = _env_on(os.environ.get("PADDLE_TPU_METRICS"))


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    global _ENABLED
    _ENABLED = bool(on)


# ---------------------------------------------------------------------------
# metric children (the objects hot paths actually hold)
# ---------------------------------------------------------------------------


class _CounterChild:
    __slots__ = ("_metric", "_lock", "_value")

    def __init__(self, metric: "Counter"):
        self._metric = metric
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if not (_ENABLED or self._metric.always):
            return
        if amount < 0:
            raise ValueError(f"counter {self._metric.name} cannot "
                             f"decrease (inc({amount}))")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _sample(self):
        return self.value


class _GaugeChild:
    __slots__ = ("_metric", "_lock", "_value")

    def __init__(self, metric: "Gauge"):
        self._metric = metric
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        if not (_ENABLED or self._metric.always):
            return
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        if not (_ENABLED or self._metric.always):
            return
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _sample(self):
        return self.value


class _HistogramChild:
    __slots__ = ("_metric", "_lock", "_counts", "_sum", "_count",
                 "_exemplars")

    def __init__(self, metric: "Histogram"):
        self._metric = metric
        self._lock = threading.Lock()
        self._counts = [0] * (len(metric.buckets) + 1)  # +1: overflow
        self._sum = 0.0
        self._count = 0
        self._exemplars = None  # ExemplarReservoir, lazily when armed

    def observe(self, value: float) -> None:
        if not (_ENABLED or self._metric.always):
            return
        buckets = self._metric.buckets
        i = 0
        for i, le in enumerate(buckets):  # noqa: B007 — tiny fixed list
            if value <= le:
                break
        else:
            i = len(buckets)
        tid = (_exemplars.active_trace_id()
               if _exemplars.armed() else None)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1
            if tid is not None:
                if self._exemplars is None:
                    self._exemplars = _exemplars.ExemplarReservoir()
                self._exemplars.record(i, value, tid)

    def exemplars(self) -> Dict[int, list]:
        """{bucket_index: [Exemplar...]} — latest-k per bucket, index
        aligned with the per-bucket counts array (last = +Inf)."""
        with self._lock:
            res = self._exemplars
        return res.snapshot() if res is not None else {}

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def cumulative_buckets(self) -> List[Tuple[float, int]]:
        """[(le, cumulative_count)...] ending with (inf, total) — the
        Prometheus histogram exposition shape."""
        with self._lock:
            counts = list(self._counts)
        out, acc = [], 0
        for le, c in zip(self._metric.buckets, counts):
            acc += c
            out.append((le, acc))
        out.append((float("inf"), acc + counts[-1]))
        return out

    def quantile(self, q: float) -> float:
        """Estimated q-quantile (0 <= q <= 1) by linear interpolation
        inside the bucket that crosses rank q*count — the
        histogram_quantile() estimator, resolved to the recording side
        so the SLO layer and `cli top` need no PromQL engine.  NaN when
        nothing was observed; samples past the top finite bucket clamp
        to that bound (the +Inf bucket has no upper edge to interpolate
        toward)."""
        with self._lock:
            counts = list(self._counts)
            total = self._count
        return quantile_from_buckets(self._metric.buckets, counts,
                                     total, q)

    def _sample(self):
        return {"sum": self.sum, "count": self.count,
                "buckets": [[le, n] for le, n in
                            self.cumulative_buckets()]}


def quantile_from_buckets(buckets: Sequence[float],
                          counts: Sequence[int], total: int,
                          q: float) -> float:
    """Shared quantile math over per-bucket (non-cumulative) counts;
    `counts` has one trailing overflow (+Inf) slot.  Used by the live
    histogram children and by the time-series store's windowed bucket
    deltas (timeseries.py)."""
    if not (0.0 <= q <= 1.0):
        raise ValueError(f"quantile q must be in [0, 1], got {q}")
    if total <= 0:
        return float("nan")
    target = q * total
    cum = 0.0
    prev_le = 0.0
    for le, c in zip(buckets, counts):
        if c and cum + c >= target:
            frac = (target - cum) / c
            return prev_le + (le - prev_le) * min(max(frac, 0.0), 1.0)
        cum += c
        prev_le = le
    return float(buckets[-1])


# ---------------------------------------------------------------------------
# metric families
# ---------------------------------------------------------------------------


class _Metric:
    kind = "untyped"
    _child_cls = None

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (), always: bool = False):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self.always = always
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}
        if not self.labelnames:
            self._default = self._make_child()
        else:
            self._default = None

    def _make_child(self):
        return self._child_cls(self)

    def labels(self, **labelvalues):
        """The child series for one label-value combination (created on
        first use; subsequent calls return the same object, so hot paths
        should hold the child)."""
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name} has labels {self.labelnames}, "
                f"got {sorted(labelvalues)}")
        key = tuple(str(labelvalues[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._make_child()
                self._children[key] = child
            return child

    def remove(self, **labelvalues) -> None:
        """Drop one label combination's series from the family (no-op if
        absent) — instance-scoped series (per-Executor, per-server) call
        this on close() so a process that churns instances does not grow
        the registry and every dump without bound.  A child object the
        instance still holds keeps counting; it is just no longer
        exported."""
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name} has labels {self.labelnames}, "
                f"got {sorted(labelvalues)}")
        key = tuple(str(labelvalues[n]) for n in self.labelnames)
        with self._lock:
            self._children.pop(key, None)

    def samples(self) -> List[Tuple[Dict[str, str], object]]:
        """[(label dict, child)...] for every live series."""
        if self._default is not None:
            return [({}, self._default)]
        with self._lock:
            items = list(self._children.items())
        return [(dict(zip(self.labelnames, key)), child)
                for key, child in items]

    def snapshot(self) -> dict:
        return {
            "type": self.kind,
            "help": self.help,
            "labelnames": list(self.labelnames),
            "samples": [{"labels": labels, "value": child._sample()}
                        for labels, child in self.samples()],
        }

    # unlabeled convenience: metric itself acts as its single child
    def _default_child(self):
        if self._default is None:
            raise ValueError(
                f"metric {self.name} is labeled {self.labelnames}; "
                "call .labels(...) first")
        return self._default


class Counter(_Metric):
    kind = "counter"
    _child_cls = _CounterChild

    # family-level fast gate before the child indirection: unlabeled
    # hot-path instruments call these directly, and the disabled cost
    # must stay at one method call + boolean test
    def inc(self, amount: float = 1.0):
        if not (_ENABLED or self.always):
            return
        self._default_child().inc(amount)

    @property
    def value(self) -> float:
        return self._default_child().value


class Gauge(_Metric):
    kind = "gauge"
    _child_cls = _GaugeChild

    def set(self, value: float):
        if not (_ENABLED or self.always):
            return
        self._default_child().set(value)

    def inc(self, amount: float = 1.0):
        if not (_ENABLED or self.always):
            return
        self._default_child().inc(amount)

    def dec(self, amount: float = 1.0):
        self.inc(-amount)

    @property
    def value(self) -> float:
        return self._default_child().value


class Histogram(_Metric):
    kind = "histogram"
    _child_cls = _HistogramChild

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (), always: bool = False,
                 buckets: Optional[Sequence[float]] = None):
        self.buckets = tuple(sorted(buckets if buckets is not None
                                    else DEFAULT_LATENCY_BUCKETS))
        if not self.buckets:
            raise ValueError(f"histogram {name} needs >= 1 bucket")
        super().__init__(name, help, labelnames, always)

    def observe(self, value: float):
        if not (_ENABLED or self.always):
            return
        self._default_child().observe(value)

    @property
    def sum(self) -> float:
        return self._default_child().sum

    @property
    def count(self) -> int:
        return self._default_child().count

    def quantile(self, q: float) -> float:
        return self._default_child().quantile(q)


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


class MetricsRegistry:
    """Thread-safe name -> metric map; get-or-create semantics so every
    subsystem can declare its series at import/instance time without
    coordinating creation order."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def get_or_create(self, cls, name: str, help: str = "",
                      labelnames: Sequence[str] = (),
                      always: bool = False, **kwargs) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls) or \
                        m.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{m.kind} with labels {m.labelnames}; cannot "
                        f"re-register as {cls.kind} with labels "
                        f"{tuple(labelnames)}")
                return m
            m = cls(name, help, labelnames, always, **kwargs)
            self._metrics[name] = m
            return m

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def quantile(self, name: str, q: float,
                 labels: Optional[Dict[str, str]] = None) -> float:
        """q-quantile of one histogram series: the unlabeled child, or
        the `labels` combination of a labeled family.  Raises KeyError
        for an unknown metric and ValueError for a non-histogram —
        a typo'd SLO must fail loudly, not read as 'no data'."""
        m = self.get(name)
        if m is None:
            raise KeyError(f"no metric named {name!r} in the registry")
        if not isinstance(m, Histogram):
            raise ValueError(
                f"metric {name!r} is a {m.kind}, not a histogram")
        if labels:
            # look up WITHOUT the get-or-create of .labels(): a read
            # API with a typo'd label value must raise, not mint (and
            # forever export) an empty child series
            if set(labels) != set(m.labelnames):
                raise ValueError(
                    f"metric {name} has labels {m.labelnames}, "
                    f"got {sorted(labels)}")
            key = tuple(str(labels[n]) for n in m.labelnames)
            with m._lock:
                child = m._children.get(key)
            if child is None:
                raise KeyError(
                    f"metric {name!r} has no series with labels "
                    f"{labels}")
            return child.quantile(q)
        return m.quantile(q)

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return sorted(self._metrics.values(), key=lambda m: m.name)

    def snapshot(self) -> Dict[str, dict]:
        return {m.name: m.snapshot() for m in self.metrics()}

    def clear(self):
        """Drop every registered metric (tests only — live subsystems
        hold child references that become orphans)."""
        with self._lock:
            self._metrics.clear()


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _REGISTRY


def counter(name: str, help: str = "", labelnames: Sequence[str] = (),
            always: bool = False,
            registry: Optional[MetricsRegistry] = None) -> Counter:
    return (registry or _REGISTRY).get_or_create(
        Counter, name, help, labelnames, always)


def gauge(name: str, help: str = "", labelnames: Sequence[str] = (),
          always: bool = False,
          registry: Optional[MetricsRegistry] = None) -> Gauge:
    return (registry or _REGISTRY).get_or_create(
        Gauge, name, help, labelnames, always)


_ENV_BUCKETS: Optional[Dict[str, Tuple[float, ...]]] = None
_ENV_BUCKETS_LOCK = threading.Lock()


def _env_bucket_overrides() -> Dict[str, Tuple[float, ...]]:
    """Per-family bucket overrides from PADDLE_TPU_HIST_BUCKETS
    (``name=0.01,0.1,1,20;other=...``), parsed once.  Lets operators
    make slow objectives representable — the default ladder tops out at
    16.384 s, and quantiles clamp at the top finite bucket
    (docs/observability.md "Time attribution")."""
    global _ENV_BUCKETS
    with _ENV_BUCKETS_LOCK:
        if _ENV_BUCKETS is None:
            parsed: Dict[str, Tuple[float, ...]] = {}
            raw = os.environ.get("PADDLE_TPU_HIST_BUCKETS", "")
            for part in raw.split(";"):
                name, sep, vals = part.strip().partition("=")
                if not sep or not name.strip():
                    continue
                try:
                    bs = tuple(float(v) for v in vals.split(",")
                               if v.strip())
                except ValueError:
                    continue  # a typo'd env must not break import
                if bs:
                    parsed[name.strip()] = bs
            _ENV_BUCKETS = parsed
        return _ENV_BUCKETS


def reset_env_bucket_overrides() -> None:
    """Re-read PADDLE_TPU_HIST_BUCKETS on next use (tests only)."""
    global _ENV_BUCKETS
    with _ENV_BUCKETS_LOCK:
        _ENV_BUCKETS = None


def histogram(name: str, help: str = "", labelnames: Sequence[str] = (),
              always: bool = False,
              buckets: Optional[Sequence[float]] = None,
              registry: Optional[MetricsRegistry] = None) -> Histogram:
    # env override wins over the call-site default: the operator tuning
    # a family's resolution must not need a code change.  Applies at
    # first registration only (get_or_create returns extant families).
    env = _env_bucket_overrides().get(name)
    if env is not None:
        buckets = env
    return (registry or _REGISTRY).get_or_create(
        Histogram, name, help, labelnames, always, buckets=buckets)
