"""Flight recorder: an always-on ring of recent spans, structured
events and metric snapshots, reconstructable after a crash.

Metrics tell you a pserver's p99 was fine until 12:03:07; they cannot
tell you what it was DOING in its last 800 ms before the OOM killer got
it.  The flight recorder is the post-mortem side of the telemetry
plane: three bounded rings per process —

  * **spans** — finished trace spans, tapped straight off
    tracing's recorder via a span listener.  Arming the recorder makes
    span() live even with full tracing off, so the ring always holds
    the last ~N spans without growing the 100k export buffer;
  * **events** — structured notes (``note("trainer.step", step=i)``,
    faults fired, view changes) appended by the runtimes;
  * **metric snapshots** — a few recent compact registry snapshots,
    so the dump carries the counters' final values too.

The ring is flushed to ``<dir>/flight_<pid>.json`` on a short period
(default 0.5 s, atomic tmp+rename), so a SIGKILLed process leaves its
last seconds on disk — no handler required.  Catchable endings dump
eagerly: SIGTERM (chained to any prior handler), uncaught exceptions
(sys.excepthook wrap), injected faults (core/resilience calls
:func:`on_fault`), and interpreter exit.  On-demand, live processes
answer the pserver ``FLIGHT`` wire verb / the replica ``flight`` op
with the same dump (parallel/pserver.py, serving/replica.py).

Arming: ``PADDLE_TPU_FLIGHT_DIR=<dir>`` at process start (checked at
package import), or ``flightrecorder.install(dir=...)``.  Cost when
armed is one deque append per span/note and a tiny periodic flush —
held under the same <5% hot-loop guard as the disabled metric
instruments (tests/test_observability.py).
"""
from __future__ import annotations

import atexit
import json
import os
import signal
import sys
import threading
import time
from collections import deque
from typing import Optional

from . import metrics as metrics_mod
from . import tracing

__all__ = ["FlightRecorder", "install", "uninstall", "recorder",
           "armed", "note", "on_fault", "dump_dict"]

_REC: Optional["FlightRecorder"] = None


def _ring_snapshot(d: deque) -> list:
    """Copy a ring that other threads keep appending to.  Appends are
    deliberately lock-free (they sit on the span hot path); list()
    raises RuntimeError if the deque mutates mid-copy, so retry a few
    times and settle for the ring as-of the last attempt."""
    for _ in range(8):
        try:
            return list(d)
        except RuntimeError:
            continue
    return []


class FlightRecorder:
    """One process's always-on telemetry ring; use the module-level
    :func:`install` rather than constructing directly."""

    def __init__(self, dir: Optional[str] = None, flush_s: float = 0.5,
                 max_spans: int = 2048, max_events: int = 2048,
                 max_snapshots: int = 8, capture_spans: bool = True):
        self.dir = dir
        self.flush_s = float(flush_s)
        self._spans: deque = deque(maxlen=max_spans)
        self._events: deque = deque(maxlen=max_events)
        self._snaps: deque = deque(maxlen=max_snapshots)
        self._seq = 0            # bumped per append; flush skips idle
        self._flushed_seq = -1
        self._capture_spans = capture_spans
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._prev_sigterm = None
        self._prev_excepthook = None
        self._hooks_installed = False

    # -- ingestion (hot paths) ---------------------------------------------
    def _on_span(self, rec: dict) -> None:
        self._spans.append(rec)
        self._seq += 1

    def note(self, event: str, /, **data) -> None:
        # positional-only: the data dict may itself carry a "kind" key
        # (e.g. fault events)
        self._events.append({"ts": time.time(), "kind": event,
                             "data": data})
        self._seq += 1

    def _snapshot_metrics(self) -> None:
        try:
            snap = metrics_mod.registry().snapshot()
        except Exception:
            return  # a half-registered metric must not kill the flusher
        if self._snaps and self._snaps[-1]["metrics"] == snap:
            return  # idle registry: no new point, no flush
        self._snaps.append({"ts": time.time(), "metrics": snap})
        # counter movement alone (a span-less process like the router)
        # must still refresh the on-disk dump
        self._seq += 1

    # -- dump ---------------------------------------------------------------
    def dump_dict(self, reason: str = "on-demand") -> dict:
        return {
            "pid": os.getpid(),
            "time": time.time(),
            "reason": reason,
            "spans": _ring_snapshot(self._spans),
            "events": _ring_snapshot(self._events),
            "metric_snapshots": _ring_snapshot(self._snaps),
        }

    def default_path(self) -> Optional[str]:
        if not self.dir:
            return None
        return os.path.join(self.dir, f"flight_{os.getpid()}.json")

    def write(self, path: Optional[str] = None,
              reason: str = "on-demand") -> Optional[str]:
        """Write the dump atomically (tmp + rename: a reader — or the
        SIGKILL that interrupts the NEXT flush — never sees a torn
        file).  Returns the path, or None when no dir is configured."""
        path = path or self.default_path()
        if not path:
            return None
        payload = self.dump_dict(reason)
        d = os.path.dirname(path)
        try:
            if d:
                os.makedirs(d, exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(payload, f, default=str)
            os.replace(tmp, path)
        except OSError:
            return None  # best-effort: read-only FS etc.
        return path

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "FlightRecorder":
        if self._capture_spans:
            tracing.add_span_listener(self._on_span)
        self._snapshot_metrics()
        if self.dir and self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name="paddle-tpu-flightrec")
            self._thread.start()
        self._install_hooks()
        return self

    def _run(self):
        while not self._stop.wait(self.flush_s):
            self._snapshot_metrics()
            if self._seq != self._flushed_seq:
                self._flushed_seq = self._seq
                self.write(reason="periodic")

    def _install_hooks(self):
        if self._hooks_installed:  # start() may run again (dir upgrade)
            return
        self._hooks_installed = True
        # SIGTERM: dump, then hand the signal to whoever owned it
        # (only the main thread may set handlers; a recorder installed
        # from a worker thread simply skips the hook)
        try:
            self._prev_sigterm = signal.signal(signal.SIGTERM,
                                               self._on_sigterm)
        except (ValueError, OSError):
            self._prev_sigterm = None
        hook = sys.excepthook

        def _crash_hook(exc_type, exc, tb):
            try:
                self.note("crash", type=exc_type.__name__,
                          message=str(exc))
                self.write(reason="crash")
            except Exception:
                pass
            hook(exc_type, exc, tb)

        self._prev_excepthook = hook
        sys.excepthook = _crash_hook
        atexit.register(self._atexit)

    def _on_sigterm(self, signum, frame):
        self.note("sigterm")
        self.write(reason="sigterm")
        prev = self._prev_sigterm
        if callable(prev):
            prev(signum, frame)
        elif prev == signal.SIG_IGN:
            return  # the process deliberately ignores SIGTERM: arming
            # the recorder must not turn an ignored signal fatal
        else:
            # restore the default disposition and re-deliver so the
            # process still dies of SIGTERM (exit status intact)
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)

    def _atexit(self):
        self._snapshot_metrics()
        self.write(reason="exit")

    def close(self):
        if self._capture_spans:
            tracing.remove_span_listener(self._on_span)
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None and t is not threading.current_thread():
            t.join(timeout=self.flush_s + 5)
        try:
            atexit.unregister(self._atexit)
        except Exception:
            pass
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
            self._prev_excepthook = None
        if self._prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except (ValueError, OSError):
                pass
            self._prev_sigterm = None
        self._hooks_installed = False


# ---------------------------------------------------------------------------
# module-level surface (what the runtimes call)
# ---------------------------------------------------------------------------


def install(dir: Optional[str] = None, flush_s: float = 0.5,
            max_spans: int = 2048, max_events: int = 2048,
            capture_spans: bool = True) -> FlightRecorder:
    """Arm the process flight recorder (idempotent: a second install
    with a dir upgrades a memory-only one; otherwise the existing
    recorder is returned).  With `dir`, the ring is flushed to
    ``<dir>/flight_<pid>.json`` every `flush_s` seconds."""
    global _REC
    if _REC is not None:
        if dir and not _REC.dir:
            _REC.dir = dir
            _REC.start()  # starts the flusher now that there is a dir
        return _REC
    _REC = FlightRecorder(dir=dir, flush_s=flush_s,
                          max_spans=max_spans, max_events=max_events,
                          capture_spans=capture_spans).start()
    return _REC


def uninstall() -> None:
    """Disarm and drop the recorder (tests)."""
    global _REC
    rec, _REC = _REC, None
    if rec is not None:
        rec.close()


def recorder() -> Optional[FlightRecorder]:
    return _REC


def armed() -> bool:
    return _REC is not None


def note(event: str, /, **data) -> None:
    """Append one structured event to the ring; a no-op costing one
    global read when no recorder is armed, so runtimes can call it
    unconditionally."""
    rec = _REC
    if rec is not None:
        rec.note(event, **data)


def on_fault(site: str, kind: str,
             trace_id: "str | None" = None) -> None:
    """Called by core/resilience when the chaos injector fires: the
    injected fault is exactly the moment whose surrounding seconds the
    post-mortem wants, so dump eagerly instead of waiting for a flush
    tick.  ``trace_id`` (the trace active at the fire site, when any)
    links the dump's fault event to the request trace it hit — `cli
    flight`/`trace-of` can then join chaos to its victim."""
    rec = _REC
    if rec is not None:
        if trace_id is not None:
            rec.note("fault", site=site, kind=kind, trace_id=trace_id)
        else:
            rec.note("fault", site=site, kind=kind)
        rec.write(reason=f"fault:{site}")


def dump_dict(reason: str = "on-demand") -> dict:
    """The current dump, armed or not — the wire verbs answer with
    this, so an un-armed process replies with an honest empty ring
    instead of an error."""
    rec = _REC
    if rec is not None:
        return rec.dump_dict(reason)
    return {"pid": os.getpid(), "time": time.time(), "reason": reason,
            "armed": False, "spans": [], "events": [],
            "metric_snapshots": []}


def maybe_install_from_env() -> Optional[FlightRecorder]:
    """PADDLE_TPU_FLIGHT_DIR=<dir> arms the recorder at import;
    PADDLE_TPU_FLIGHT=on arms a memory-only ring (wire-verb dumps
    only)."""
    d = os.environ.get("PADDLE_TPU_FLIGHT_DIR", "")
    if d:
        return install(dir=d)
    raw = os.environ.get("PADDLE_TPU_FLIGHT", "").strip().lower()
    if raw in ("1", "on", "true", "yes"):
        return install()
    return None


def _after_fork_in_child():
    """A forked child shares the parent's ring object but not its
    flusher thread; re-arm cleanly so the child's dump carries its own
    pid and its flusher exists."""
    global _REC
    rec = _REC
    if rec is None:
        return
    tracing.remove_span_listener(rec._on_span)
    _REC = None
    install(dir=rec.dir, flush_s=rec.flush_s,
            capture_spans=rec._capture_spans)


if hasattr(os, "register_at_fork"):  # posix
    os.register_at_fork(after_in_child=_after_fork_in_child)
