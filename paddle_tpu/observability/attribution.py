"""Time attribution: phase breakdowns, why-tables, stragglers, drift.

PR 13's telemetry plane can say THAT a latency objective regressed;
this layer says WHERE the time went and WHO is slow:

  * **Phase instrumentation** — :func:`phase(kind, name)` wraps one
    phase of a serving tick (admit / prefill / decode / draft_verify /
    sample / deliver / kv_alloc / kv_release), a training iteration
    (feed_pack / h2d / compute / send_round / barrier_wait / get) or a
    pserver round (optimize / recv / barrier) in a labeled child span
    PLUS an observation into the per-kind
    ``paddle_tpu_<kind>_phase_seconds{phase=...}`` histogram family.
    Cost: one no-op context manager when both metrics and tracing are
    off; two perf_counter reads + a cached-child observe when on.
  * **Why-table** — :func:`why_rows` (live TimeSeriesStore) /
    :func:`why_rows_from_parsed` (a federated Prometheus dump) compute
    the fleet "where does the time go" table behind ``cli why``: per
    (kind, member, phase) seconds-of-phase-per-second and its share of
    the member's attributed time.
  * **Straggler detection** — :func:`straggler_scores` z-scores each
    endpoint's windowed mean of
    ``paddle_tpu_comm_endpoint_round_seconds`` against its PEERS
    (leave-one-out, sigma floored at 10% of the peer mean so two
    healthy endpoints never read as mutual stragglers), published by
    the collector as the SLO-able ``paddle_tpu_comm_straggler_score``
    gauge and surfaced in ``cli top``.
  * **Calibration drift** — member processes publish the PR 11 static
    roofline floor per phase (``*_phase_static_seconds`` gauges via
    :func:`publish_static_floor`); :func:`calibration_ratios` bands
    measured phase time against it and the collector republishes
    ``paddle_tpu_calibration_ratio{kind,member,phase}`` for burn-rate
    alerting (tools/slo.json pins the static_vs_measured band).

The collector calls :func:`run_detectors` after every scrape pass.
See docs/observability.md "Time attribution".
"""
from __future__ import annotations

import math
import time
from typing import Dict, List, Optional, Tuple

from . import metrics as metrics_mod
from . import tracing

__all__ = [
    "KINDS",
    "PHASES",
    "PHASE_BUCKETS",
    "phase",
    "observe_phase",
    "phase_family",
    "publish_static_floor",
    "why_rows",
    "why_rows_from_parsed",
    "format_why_table",
    "straggler_scores",
    "calibration_ratios",
    "run_detectors",
    "pick_exemplar",
]

# the attributed member kinds and their canonical phase vocabularies —
# docs/observability.md "Time attribution" mirrors these tables; adding
# a phase needs only a new phase() call site, the label carries it
KINDS = ("generation", "trainer", "pserver")

PHASES: Dict[str, Tuple[str, ...]] = {
    "generation": ("admit", "prefill", "decode", "draft_verify",
                   "sample", "deliver", "kv_alloc", "kv_release"),
    "trainer": ("feed_pack", "h2d", "compute", "send_round",
                "barrier_wait", "get"),
    "pserver": ("optimize", "recv", "barrier"),
}

# phases run from tens of µs (KV alloc) to seconds (a cold compile in
# the compute phase): a wider, finer ladder than the request-latency
# default (50 µs .. ~26 s doubling)
PHASE_BUCKETS: Tuple[float, ...] = tuple(
    0.00005 * 2 ** i for i in range(20))


def phase_family(kind: str) -> metrics_mod.Histogram:
    return metrics_mod.histogram(
        f"paddle_tpu_{kind}_phase_seconds",
        f"seconds spent per {kind} phase",
        labelnames=("phase",), buckets=PHASE_BUCKETS)


def _static_family(kind: str) -> metrics_mod.Gauge:
    return metrics_mod.gauge(
        f"paddle_tpu_{kind}_phase_static_seconds",
        "static roofline floor (seconds) for the phase",
        labelnames=("phase",))


# child cache keyed on family identity: registry().clear() in tests
# mints a new family, and observing into an orphaned child would make
# phase data silently vanish for the rest of the process
_children: Dict[Tuple[str, str], Tuple[object, object]] = {}


def observe_phase(kind: str, name: str, seconds: float) -> None:
    """Record one phase duration into the kind's histogram family (a
    no-op when metrics are disabled)."""
    if not metrics_mod.enabled():
        return
    key = (kind, name)
    fam = phase_family(kind)
    hit = _children.get(key)
    if hit is None or hit[0] is not fam:
        hit = (fam, fam.labels(phase=name))
        _children[key] = hit
    hit[1].observe(seconds)


class _NoopCtx:
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NOOP = _NoopCtx()


class _PhaseCtx:
    __slots__ = ("_kind", "_name", "_span_cm", "_span", "_t0")

    def __init__(self, kind: str, name: str):
        self._kind = kind
        self._name = name

    def __enter__(self):
        self._span_cm = tracing.span(f"{self._kind}.phase.{self._name}")
        self._span = self._span_cm.__enter__()
        self._t0 = time.perf_counter()
        return self._span

    def __exit__(self, exc_type, exc, tb):
        dt = time.perf_counter() - self._t0
        if exc_type is not None and self._span is not None:
            # an error attr makes the tail sampler keep the trace
            self._span.set_attr("error", exc_type.__name__)
        self._span_cm.__exit__(exc_type, exc, tb)
        observe_phase(self._kind, self._name, dt)
        return False


def phase(kind: str, name: str):
    """Context manager attributing the block to (kind, phase): a child
    span named ``<kind>.phase.<name>`` under the active trace plus an
    observation into ``paddle_tpu_<kind>_phase_seconds``.  One boolean
    test and a shared no-op when the whole observability stack is off —
    safe on per-tick hot paths."""
    if not (metrics_mod.enabled() or tracing.enabled()
            or tracing._listeners):
        return _NOOP
    return _PhaseCtx(kind, name)


def publish_static_floor(kind: str,
                         floors: Dict[str, float]) -> None:
    """Export the static roofline floor (seconds) per phase as
    ``paddle_tpu_<kind>_phase_static_seconds{phase=...}`` gauges —
    the calibration detector's denominator.  No-op when metrics are
    off or a floor is non-positive (no model, no band)."""
    if not metrics_mod.enabled():
        return
    fam = _static_family(kind)
    for p, v in floors.items():
        if v and v > 0:
            fam.labels(phase=p).set(float(v))


# ---------------------------------------------------------------------------
# the why-table ("where does the time go")
# ---------------------------------------------------------------------------


def _with_shares(rows: List[dict], seconds_key: str) -> List[dict]:
    totals: Dict[Tuple[str, str], float] = {}
    for r in rows:
        k = (r["kind"], r["member"])
        totals[k] = totals.get(k, 0.0) + max(r[seconds_key], 0.0)
    for r in rows:
        t = totals[(r["kind"], r["member"])]
        r["share"] = (max(r[seconds_key], 0.0) / t) if t > 0 else 0.0
    rows.sort(key=lambda r: (r["kind"], r["member"], -r["share"]))
    return rows


def why_rows(series, kind: Optional[str] = None,
             window_s: float = 60.0,
             now: Optional[float] = None) -> List[dict]:
    """Per (kind, member, phase) attribution over a live fleet
    TimeSeriesStore: ``seconds_per_s`` (windowed rate of the phase
    histogram's _sum — seconds of phase time per wall second),
    ``mean_s``, ``calls_per_s`` and the phase's ``share`` of the
    member's total attributed time."""
    rows: List[dict] = []
    for k in (KINDS if kind is None else (kind,)):
        name = f"paddle_tpu_{k}_phase_seconds"
        members = series.label_values(name, "member") or [""]
        for m in members:
            base = {"member": m} if m else {}
            for p in series.label_values(name, "phase",
                                         base or None):
                lbl = {**base, "phase": p}
                sr = series.sum_rate(name, window_s, lbl, now)
                if sr is None:
                    continue
                mean = series.mean(name, window_s, lbl, now)
                rate = series.rate(name, window_s, lbl, now)
                rows.append({
                    "kind": k, "member": m or "-", "phase": p,
                    "seconds_per_s": sr,
                    "mean_s": mean if mean == mean else 0.0,
                    "calls_per_s": rate or 0.0,
                })
    return _with_shares(rows, "seconds_per_s")


def why_rows_from_parsed(parsed: Dict[str, dict],
                         kind: Optional[str] = None) -> List[dict]:
    """The why-table from a PARSED Prometheus dump (a federated file or
    one process's exit dump) — lifetime totals instead of windowed
    rates, so it works on a single snapshot with no history."""
    rows: List[dict] = []
    for k in (KINDS if kind is None else (kind,)):
        fam = parsed.get(f"paddle_tpu_{k}_phase_seconds")
        if not fam or fam.get("type") != "histogram":
            continue
        for s in fam["samples"]:
            v = s["value"]
            rows.append({
                "kind": k,
                "member": s["labels"].get("member", "-"),
                "phase": s["labels"].get("phase", "?"),
                "seconds": v["sum"],
                "count": v["count"],
                "mean_s": (v["sum"] / v["count"]) if v["count"] else 0.0,
            })
    return _with_shares(rows, "seconds")


def format_why_table(rows: List[dict]) -> str:
    """Render why-rows as the ``cli why`` table."""
    if not rows:
        return ("no phase data — run with PADDLE_TPU_METRICS=on and "
                "phase instrumentation armed")
    live = "seconds_per_s" in rows[0]
    head = ["kind", "member", "phase", "share",
            "sec/s" if live else "seconds",
            "mean", "calls/s" if live else "count"]
    table: List[List[str]] = [head]
    for r in rows:
        table.append([
            r["kind"], r["member"], r["phase"],
            f"{r['share'] * 100:5.1f}%",
            (f"{r['seconds_per_s']:.4f}" if live
             else f"{r['seconds']:.4f}"),
            f"{r['mean_s'] * 1000:.3f}ms",
            (f"{r['calls_per_s']:.1f}" if live
             else str(r["count"])),
        ])
    widths = [max(len(row[i]) for row in table)
              for i in range(len(head))]
    out = []
    for i, row in enumerate(table):
        out.append("  ".join(c.ljust(w)
                             for c, w in zip(row, widths)).rstrip())
        if i == 0:
            out.append("  ".join("-" * w for w in widths))
    return "\n".join(out)


# ---------------------------------------------------------------------------
# straggler detection (comm endpoints)
# ---------------------------------------------------------------------------

ENDPOINT_ROUND_METRIC = "paddle_tpu_comm_endpoint_round_seconds"
STRAGGLER_METRIC = "paddle_tpu_comm_straggler_score"
CALIBRATION_METRIC = "paddle_tpu_calibration_ratio"


def straggler_scores(series, name: str = ENDPOINT_ROUND_METRIC,
                     window_s: float = 60.0,
                     now: Optional[float] = None) -> Dict[str, float]:
    """Per-endpoint straggler z-score: how many (floored) standard
    deviations an endpoint's windowed mean round time sits ABOVE its
    peers' (leave-one-out).  Sigma is floored at 10% of the peer mean —
    near-identical healthy peers must not amplify µs jitter into a
    flag.  Negative drift (faster than peers) clamps to 0: only slow
    is a straggler."""
    means: Dict[str, float] = {}
    for ep in series.label_values(name, "endpoint"):
        m = series.mean(name, window_s, {"endpoint": ep}, now)
        if m == m:  # not NaN
            means[ep] = m
    if len(means) < 2:
        return {}
    out: Dict[str, float] = {}
    for ep, v in means.items():
        peers = [x for e, x in means.items() if e != ep]
        mu = sum(peers) / len(peers)
        var = sum((x - mu) ** 2 for x in peers) / len(peers)
        sigma = max(math.sqrt(var), 0.1 * abs(mu), 1e-9)
        out[ep] = max(0.0, (v - mu) / sigma)
    return out


# ---------------------------------------------------------------------------
# calibration drift (static roofline vs measured)
# ---------------------------------------------------------------------------


def calibration_ratios(series, window_s: float = 120.0,
                       now: Optional[float] = None) -> List[dict]:
    """measured/static per (kind, member, phase): the windowed mean of
    the phase histogram over the member's published static roofline
    floor.  >1 means production is slower than the model predicts
    (expected — the floor ignores overheads); a drifting ratio is the
    alert signal, banded by tools/slo.json."""
    out: List[dict] = []
    for k in KINDS:
        sname = f"paddle_tpu_{k}_phase_static_seconds"
        mname = f"paddle_tpu_{k}_phase_seconds"
        members = series.label_values(sname, "member") or [""]
        for m in members:
            base = {"member": m} if m else {}
            for p in series.label_values(sname, "phase",
                                         base or None):
                static = series.latest(sname, {**base, "phase": p})
                if not static or static <= 0:
                    continue
                measured = series.mean(mname, window_s,
                                       {**base, "phase": p}, now)
                if measured != measured:  # NaN: no observations yet
                    continue
                out.append({"kind": k, "member": m or "-",
                            "phase": p, "static_s": static,
                            "measured_s": measured,
                            "ratio": measured / static})
    return out


def run_detectors(series, window_s: float = 60.0,
                  now: Optional[float] = None) -> Dict[str, dict]:
    """One detector pass over a fleet TimeSeriesStore -> synthetic
    gauge families in the parsed-snapshot shape the collector merges
    into its federation output."""
    synth: Dict[str, dict] = {}
    scores = straggler_scores(series, window_s=window_s, now=now)
    if scores:
        synth[STRAGGLER_METRIC] = {
            "type": "gauge",
            "help": ("z-score of an endpoint's mean round time vs its "
                     "peers (leave-one-out, sigma floored)"),
            "samples": [{"labels": {"endpoint": ep}, "value": v}
                        for ep, v in sorted(scores.items())]}
    ratios = calibration_ratios(series,
                                window_s=max(window_s, 120.0), now=now)
    if ratios:
        synth[CALIBRATION_METRIC] = {
            "type": "gauge",
            "help": ("measured phase seconds / static roofline floor "
                     "(static_vs_measured band)"),
            "samples": [{"labels": {"kind": r["kind"],
                                    "member": r["member"],
                                    "phase": r["phase"]},
                         "value": r["ratio"]} for r in ratios]}
    return synth


# ---------------------------------------------------------------------------
# exemplar -> trace resolution (the `cli trace-of` core)
# ---------------------------------------------------------------------------


def pick_exemplar(parsed: Dict[str, dict], metric: str,
                  q: float = 0.99) -> Optional[dict]:
    """From a parsed (federated) dump, pick the exemplar that best
    represents the metric's q-quantile: pool the family's buckets,
    compute the lifetime quantile, and return the freshest exemplar at
    or above it (falling back to the largest-valued one).  Returns
    ``{"trace_id", "value", "ts", "labels", "quantile_s"}`` or None
    when the family has no exemplars."""
    from .metrics import quantile_from_buckets
    from .timeseries import cum_to_per_bucket

    fam = parsed.get(metric)
    if not fam or fam.get("type") != "histogram":
        return None
    les: Optional[List[float]] = None
    agg: Optional[List[float]] = None
    total = 0
    exs: List[Tuple[dict, dict]] = []  # (sample labels, exemplar)
    for s in fam["samples"]:
        v = s["value"]
        for ex in (v.get("exemplars") or {}).values():
            if ex.get("labels", {}).get("trace_id"):
                exs.append((s["labels"], ex))
        ls, counts = cum_to_per_bucket(v["buckets"])
        if not ls:
            continue
        if les is None:
            les, agg = ls, [0.0] * len(counts)
        elif ls != les or len(counts) != len(agg):
            continue  # mismatched member layout: skip from the pool
        for i, c in enumerate(counts):
            agg[i] += c
        total += v["count"]
    if not exs:
        return None
    thr = (quantile_from_buckets(les, agg, total, q)
           if les and total else 0.0)
    qualifying = [(lbl, ex) for lbl, ex in exs
                  if ex.get("value", 0.0) >= thr]
    if qualifying:
        lbl, ex = max(qualifying,
                      key=lambda t: t[1].get("ts") or 0.0)
    else:  # quantile fell between exemplared buckets: take the worst
        lbl, ex = max(exs, key=lambda t: t[1].get("value", 0.0))
    return {"trace_id": ex["labels"]["trace_id"],
            "value": ex.get("value"), "ts": ex.get("ts"),
            "labels": dict(lbl),
            "quantile_s": thr if thr == thr else None}
