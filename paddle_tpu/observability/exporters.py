"""Exporters: Prometheus text format, localhost HTTP endpoint, JSON
snapshot, Chrome-trace JSON.

Formats:
  * **Prometheus text exposition** — ``prometheus_text()`` /
    ``write_prometheus(path)`` render every registered metric with
    `# HELP`/`# TYPE` headers, label sets, and cumulative histogram
    buckets (`_bucket{le=...}` + `_sum` + `_count`), scrape-able by any
    Prometheus-compatible collector.  ``start_http_server(port)`` serves
    the same text at ``http://127.0.0.1:<port>/metrics`` from a daemon
    thread (stdlib http.server — no new dependencies).
  * **JSON snapshot** — ``json_snapshot()`` / ``write_json(path)``: the
    registry's structured dump plus pid/timestamp meta, consumed by
    tests, bench.py and ``python -m paddle_tpu.cli metrics``.
  * **Chrome trace** — ``chrome_trace(path)`` re-exports
    tracing.write_chrome_trace (spans + profiler ranges) for symmetry.

``PADDLE_TPU_METRICS_DUMP=<path>`` auto-writes the Prometheus text file
at process exit, so multi-process runs (trainers + pservers under a
launcher) each drop a scrape-able dump without code changes.
"""
from __future__ import annotations

import atexit
import os
import threading
import time
from typing import Optional

from . import exemplars as exemplars_mod
from . import metrics as metrics_mod
from . import tracing

__all__ = [
    "prometheus_text",
    "write_prometheus",
    "json_snapshot",
    "write_json",
    "format_metrics_table",
    "start_http_server",
    "chrome_trace",
]


def _escape_label_value(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _fmt_labels(labels: dict, extra: Optional[dict] = None) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(str(v))}"'
                     for k, v in merged.items())
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


def prometheus_text(registry: Optional[metrics_mod.MetricsRegistry]
                    = None) -> str:
    """The whole registry in Prometheus text exposition format."""
    reg = registry or metrics_mod.registry()
    lines = []
    for m in reg.metrics():
        if m.help:
            lines.append(f"# HELP {m.name} {m.help}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        for labels, child in m.samples():
            if m.kind == "histogram":
                # exemplars attach to their bucket line in OpenMetrics
                # syntax (`value # {trace_id="..."} exemplar_value ts`)
                # — absent unless PADDLE_TPU_EXEMPLARS armed them
                exs = child.exemplars()
                for i, (le, n) in enumerate(
                        child.cumulative_buckets()):
                    line = (f"{m.name}_bucket"
                            f"{_fmt_labels(labels, {'le': _fmt_value(le)})}"
                            f" {n}")
                    bucket_exs = exs.get(i)
                    if bucket_exs:
                        line += " " + exemplars_mod.format_exemplar(
                            bucket_exs[-1])
                    lines.append(line)
                lines.append(f"{m.name}_sum{_fmt_labels(labels)} "
                             f"{_fmt_value(child.sum)}")
                lines.append(f"{m.name}_count{_fmt_labels(labels)} "
                             f"{child.count}")
            else:
                lines.append(f"{m.name}{_fmt_labels(labels)} "
                             f"{_fmt_value(child.value)}")
    return "\n".join(lines) + "\n"


def write_prometheus(path: str,
                     registry: Optional[metrics_mod.MetricsRegistry]
                     = None) -> str:
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        f.write(prometheus_text(registry))
    return path


def json_snapshot(registry: Optional[metrics_mod.MetricsRegistry]
                  = None) -> dict:
    reg = registry or metrics_mod.registry()
    return {
        "pid": os.getpid(),
        "time": time.time(),
        "metrics": reg.snapshot(),
    }


def write_json(path: str,
               registry: Optional[metrics_mod.MetricsRegistry]
               = None) -> str:
    import json

    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(json_snapshot(registry), f, indent=1)
    return path


def format_metrics_table(snapshot: dict) -> str:
    """Human-readable table from a json_snapshot() dict (the
    ``cli metrics`` renderer).  Histograms render as count/sum/mean;
    counters and gauges as their value."""
    rows = []
    for name, m in sorted(snapshot.get("metrics", {}).items()):
        for s in m["samples"]:
            label = _fmt_labels(s["labels"])
            v = s["value"]
            if m["type"] == "histogram":
                count = v["count"]
                mean = (v["sum"] / count) if count else 0.0
                val = (f"count={count} sum={v['sum']:.6g} "
                       f"mean={mean:.6g}")
            else:
                val = _fmt_value(v)
            rows.append((f"{name}{label}", m["type"], val))
    name_w = max([len(r[0]) for r in rows] + [6])
    out = [f"{'Metric':<{name_w}}  {'Type':<9}  Value"]
    for n, t, v in rows:
        out.append(f"{n:<{name_w}}  {t:<9}  {v}")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# HTTP endpoint (optional, localhost-only)
# ---------------------------------------------------------------------------


class PrometheusServer:
    """Tiny localhost /metrics endpoint over the process registry."""

    def __init__(self, port: int = 0, addr: str = "127.0.0.1",
                 registry: Optional[metrics_mod.MetricsRegistry] = None):
        import http.server

        reg = registry

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib casing)
                if self.path.split("?")[0] not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                body = prometheus_text(reg).encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # scrapes must not spam stderr
                return

        self._httpd = http.server.ThreadingHTTPServer((addr, port),
                                                      _Handler)
        self.port = self._httpd.server_address[1]
        self.addr = addr
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="paddle-tpu-metrics-http")
        self._thread.start()

    def url(self) -> str:
        return f"http://{self.addr}:{self.port}/metrics"

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def start_http_server(port: int = 0, addr: str = "127.0.0.1",
                      registry: Optional[metrics_mod.MetricsRegistry]
                      = None) -> PrometheusServer:
    return PrometheusServer(port, addr, registry)


def chrome_trace(path: Optional[str] = None,
                 include_profiler: bool = True) -> str:
    """Write the Chrome-trace JSON (spans + profiler ranges); see
    tracing.write_chrome_trace."""
    return tracing.write_chrome_trace(path, include_profiler)


_DUMP_PATH = os.environ.get("PADDLE_TPU_METRICS_DUMP", "")


def _atexit_dump():
    if _DUMP_PATH:
        try:
            write_prometheus(_DUMP_PATH)
        except OSError:
            pass  # exit-time dump is best-effort


atexit.register(_atexit_dump)
