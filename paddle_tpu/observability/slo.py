"""Declarative service-level objectives over the telemetry plane.

An SLO here is one sentence — ``"serving p99 < 0.5s over 60s"`` — that
the fleet either meets or burns.  Objectives evaluate against a
:class:`~paddle_tpu.observability.timeseries.TimeSeriesStore` (a local
process's sampler, or a TelemetryCollector's fleet store) with
**multiwindow burn-rate alerting**: each consecutive-sample interval in
the window gets a good/bad verdict (the windowed p99 of that interval's
bucket deltas, the counter slope, or the gauge value), the violating
fraction is divided by the error budget, and the objective ALERTS only
when the burn rate reaches the alert factor over BOTH the fast window
(`window_s`) and the slow window (`window_s * slow_factor`) — the
standard two-window rule: fast catches a live regression, slow keeps a
single noisy sample from paging anyone.

Spec forms (mix freely in one ``slo.json``):

  * compact grammar — ``"<metric|alias> <stat> <op> <value>[s|ms]
    [over <N>s]"``, e.g. ``"pserver.barrier_wait p99 < 1s"``,
    ``"serving qps > 0.5 over 120s"``;
  * dict — ``{"name", "metric", "stat", "op", "threshold",
    "window_s", "labels", "budget", "slow_factor"}`` (labels filter
    the fleet store, e.g. ``{"kind": "generation"}``).

Stats: ``p50``/``p90``/``p99``/any ``p<q>`` (histogram window
quantiles), ``rate``/``qps`` (counter or histogram-count slope per
second), ``mean`` (windowed sum/count delta), ``value`` (gauge).

Surfaces: ``cli slo --check`` (exit nonzero on violation; live mode
samples a registry/collector, snapshot mode gates a Prometheus dump)
and the SLO column of ``cli top``.  docs/observability.md "Fleet
telemetry" documents the grammar; tools/slo.json is the checked-in
fleet baseline CI enforces.
"""
from __future__ import annotations

import json
import math
import re
from typing import Dict, List, Optional

from .metrics import quantile_from_buckets
from .timeseries import TimeSeriesStore, cum_to_per_bucket

__all__ = [
    "SLOSpec",
    "SLOStatus",
    "ALIASES",
    "parse_slo",
    "load_slos",
    "evaluate",
    "evaluate_snapshot",
    "format_slo_table",
    "failed",
]

# short names for the series operators actually write SLOs against —
# the full paddle_tpu_* name is always accepted too
ALIASES = {
    "serving": "paddle_tpu_serving_generation_seconds",
    "serving.request": "paddle_tpu_serving_request_seconds",
    "serving.first_token": "paddle_tpu_serving_first_token_seconds",
    "serving.queue": "paddle_tpu_serving_generation_queue_depth",
    "serving.kv_util": "paddle_tpu_serving_kv_pool_utilization",
    "serving.requests": "paddle_tpu_serving_generation_requests_total",
    "router": "paddle_tpu_serving_router_request_seconds",
    "router.failed": "paddle_tpu_serving_router_requests_total",
    "fleet.replicas": "paddle_tpu_autoscaler_replicas_live",
    "fleet.crashloops": "paddle_tpu_autoscaler_crashloops_total",
    "fleet.spawn": "paddle_tpu_autoscaler_spawn_seconds",
    "pserver.barrier_wait": "paddle_tpu_pserver_barrier_wait_seconds",
    "pserver.optimize": "paddle_tpu_pserver_optimize_seconds",
    "pserver.requests": "paddle_tpu_pserver_requests_total",
    "trainer.step": "paddle_tpu_trainer_step_seconds",
    "trainer.steps": "paddle_tpu_trainer_steps_total",
    # time-attribution plane (observability/attribution.py)
    "serving.phases": "paddle_tpu_generation_phase_seconds",
    "trainer.phases": "paddle_tpu_trainer_phase_seconds",
    "pserver.phases": "paddle_tpu_pserver_phase_seconds",
    "comm.endpoint_round": "paddle_tpu_comm_endpoint_round_seconds",
    "comm.straggler": "paddle_tpu_comm_straggler_score",
    "calibration": "paddle_tpu_calibration_ratio",
}

_OPS = {
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
}

_GRAMMAR = re.compile(
    r"^\s*(?P<metric>\S+)\s+(?P<stat>\S+)\s*"
    r"(?P<op><=|>=|<|>)\s*(?P<value>[0-9.eE+-]+)\s*(?P<unit>ms|s)?"
    r"(?:\s+over\s+(?P<window>[0-9.]+)\s*s)?\s*$")


class SLOSpec:
    """One objective; construct via parse_slo()/load_slos() or directly
    with keyword arguments."""

    def __init__(self, metric: str, stat: str, op: str,
                 threshold: float, window_s: float = 60.0,
                 labels: Optional[Dict[str, str]] = None,
                 name: str = "", budget: float = 0.05,
                 slow_factor: float = 5.0, source: str = ""):
        self.metric = ALIASES.get(metric, metric)
        self.stat = stat.lower()
        if self.stat == "qps":
            self.stat = "rate"
        if op not in _OPS:
            raise ValueError(f"SLO op must be one of {sorted(_OPS)}, "
                             f"got {op!r}")
        if not (self.stat in ("rate", "value", "mean")
                or re.fullmatch(r"p\d{1,2}(\.\d+)?", self.stat)):
            raise ValueError(f"unknown SLO stat {self.stat!r}")
        self.op = op
        self.threshold = float(threshold)
        self.window_s = float(window_s)
        self.labels = dict(labels or {})
        self.name = name or source or \
            f"{self.metric} {self.stat} {op} {threshold}"
        # budget: tolerated violating fraction of intervals; burn rate
        # = fraction / budget, alerting at burn >= 1 in both windows.
        # 0 means zero tolerance (any bad interval alerts).
        self.budget = float(budget)
        self.slow_factor = float(slow_factor)
        self.source = source

    @property
    def quantile_q(self) -> Optional[float]:
        if self.stat.startswith("p") and self.stat != "value":
            return float(self.stat[1:]) / 100.0
        return None

    def meets(self, value: float) -> bool:
        if value is None or (isinstance(value, float)
                             and math.isnan(value)):
            return True  # no data is not a violation
        return _OPS[self.op](value, self.threshold)

    def to_dict(self) -> dict:
        return {"name": self.name, "metric": self.metric,
                "stat": self.stat, "op": self.op,
                "threshold": self.threshold, "window_s": self.window_s,
                "labels": self.labels, "budget": self.budget,
                "slow_factor": self.slow_factor}

    def __repr__(self):
        return f"SLOSpec({self.name!r})"


def parse_slo(text: str, **overrides) -> SLOSpec:
    """Parse the compact grammar (module docstring).  A trailing
    ``ms`` unit divides the threshold by 1000; the default window is
    60 s."""
    m = _GRAMMAR.match(text)
    if m is None:
        raise ValueError(
            f"cannot parse SLO {text!r}; expected "
            "'<metric> <stat> <op> <value>[s|ms] [over <N>s]'")
    threshold = float(m.group("value"))
    if m.group("unit") == "ms":
        threshold /= 1000.0
    kw = dict(metric=m.group("metric"), stat=m.group("stat"),
              op=m.group("op"), threshold=threshold,
              window_s=float(m.group("window") or 60.0),
              source=text.strip())
    kw.update(overrides)
    return SLOSpec(**kw)


def load_slos(path: str) -> List[SLOSpec]:
    """Read a spec file: ``{"slos": [<grammar string> | <spec dict>,
    ...]}`` (tools/slo.json is the checked-in example)."""
    with open(path) as f:
        doc = json.load(f)
    entries = doc.get("slos")
    if not isinstance(entries, list) or not entries:
        raise ValueError(
            f"{path}: expected a non-empty 'slos' list "
            "(docs/observability.md 'SLO specs')")
    out = []
    for e in entries:
        if isinstance(e, str):
            out.append(parse_slo(e))
        elif isinstance(e, dict):
            out.append(SLOSpec(**e))
        else:
            raise ValueError(f"{path}: bad slo entry {e!r}")
    return out


class SLOStatus:
    """One spec's evaluation: the windowed stat, the burn rates, and
    the alert verdict."""

    def __init__(self, spec: SLOSpec, value: float, ok: bool,
                 burn_fast: float, burn_slow: float, alerting: bool,
                 no_data: bool):
        self.spec = spec
        self.value = value
        self.ok = ok
        self.burn_fast = burn_fast
        self.burn_slow = burn_slow
        self.alerting = alerting
        self.no_data = no_data

    def to_dict(self) -> dict:
        return {"slo": self.spec.name, "value": self.value,
                "ok": self.ok, "burn_fast": self.burn_fast,
                "burn_slow": self.burn_slow,
                "alerting": self.alerting, "no_data": self.no_data}

    def __repr__(self):
        state = "ALERT" if self.alerting else \
            ("no-data" if self.no_data else "ok")
        return f"SLOStatus({self.spec.name!r}: {state})"


def _window_stat(spec: SLOSpec, series: TimeSeriesStore,
                 window_s: float, now: Optional[float]):
    q = spec.quantile_q
    if q is not None:
        return series.quantile(spec.metric, q, window_s,
                               labels=spec.labels, now=now)
    if spec.stat == "rate":
        return series.rate(spec.metric, window_s, labels=spec.labels,
                           now=now)
    if spec.stat == "mean":
        return series.mean(spec.metric, window_s, labels=spec.labels,
                           now=now)
    return series.latest(spec.metric, labels=spec.labels)


def _burn(spec: SLOSpec, series: TimeSeriesStore, window_s: float,
          now: Optional[float]):
    """(burn_rate, n_intervals) over one window."""
    verdicts = series.interval_verdicts(
        spec.metric, window_s,
        check=lambda v: not spec.meets(v),
        labels=spec.labels, now=now, stat_q=spec.quantile_q,
        stat_mean=(spec.stat == "mean"))
    if not verdicts:
        return 0.0, 0
    frac = sum(verdicts) / len(verdicts)
    if spec.budget <= 0:
        return (math.inf if frac > 0 else 0.0), len(verdicts)
    return frac / spec.budget, len(verdicts)


def evaluate(specs: List[SLOSpec], series: TimeSeriesStore,
             now: Optional[float] = None,
             alert_factor: float = 1.0) -> List[SLOStatus]:
    """Evaluate every spec against the store.  `alerting` needs the
    burn rate at/over `alert_factor` in BOTH the fast and the slow
    window; `ok` is the instantaneous fast-window stat vs the
    threshold (what `cli top` shows even before a burn alert)."""
    out = []
    for spec in specs:
        value = _window_stat(spec, series, spec.window_s, now)
        no_data = value is None or (isinstance(value, float)
                                    and math.isnan(value))
        ok = spec.meets(value)
        burn_fast, n_fast = _burn(spec, series, spec.window_s, now)
        burn_slow, n_slow = _burn(
            spec, series, spec.window_s * spec.slow_factor, now)
        alerting = (n_fast > 0 and n_slow > 0
                    and burn_fast >= alert_factor
                    and burn_slow >= alert_factor)
        out.append(SLOStatus(spec, value, ok, burn_fast, burn_slow,
                             alerting, no_data))
    return out


def evaluate_snapshot(specs: List[SLOSpec],
                      families: Dict[str, dict]) -> List[SLOStatus]:
    """Gate a single Prometheus dump (collector federation output or
    any scrape) — no windows, so quantiles/means are lifetime values
    and `rate` cannot be checked (reported as no-data).  The smoke-gate
    mode ``cli slo --check --prom`` uses in CI."""
    out = []
    for spec in specs:
        fam = families.get(spec.metric)
        value: float = float("nan")
        if fam is not None:
            matching = [s for s in fam["samples"]
                        if all(s["labels"].get(k) == v
                               for k, v in spec.labels.items())]
            q = spec.quantile_q
            if q is not None and fam["type"] == "histogram":
                agg: List[float] = []
                buckets: List[float] = []
                total = 0
                for s in matching:
                    les, counts = cum_to_per_bucket(
                        s["value"]["buckets"])
                    if not buckets:
                        buckets, agg = les, [0.0] * len(counts)
                    elif les != buckets or len(counts) != len(agg):
                        continue
                    agg = [a + c for a, c in zip(agg, counts)]
                    total += s["value"]["count"]
                if buckets and total:
                    value = quantile_from_buckets(buckets, agg, total,
                                                  q)
            elif spec.stat == "mean" and fam["type"] == "histogram":
                tot = sum(s["value"]["count"] for s in matching)
                ssum = sum(s["value"]["sum"] for s in matching)
                value = (ssum / tot) if tot else float("nan")
            elif spec.stat == "value" and matching:
                value = sum(float(s["value"]) for s in matching)
            # rate over one snapshot is undefined: stays NaN/no-data
        no_data = isinstance(value, float) and math.isnan(value)
        ok = spec.meets(value)
        out.append(SLOStatus(spec, value, ok, 0.0, 0.0,
                             alerting=not ok, no_data=no_data))
    return out


def format_slo_table(statuses: List[SLOStatus]) -> str:
    rows = []
    for st in statuses:
        if st.no_data:
            state, val = "no-data", "-"
        else:
            state = "ALERT" if st.alerting else \
                ("ok" if st.ok else "burning")
            val = f"{st.value:.6g}"
        burn = (f"{st.burn_fast:.2f}/{st.burn_slow:.2f}"
                if (st.burn_fast or st.burn_slow) else "-")
        rows.append((st.spec.name, val, burn, state))
    name_w = max([len(r[0]) for r in rows] + [3])
    val_w = max([len(r[1]) for r in rows] + [5])
    out = [f"{'SLO':<{name_w}}  {'value':>{val_w}}  "
           f"{'burn f/s':>10}  state"]
    for name, val, burn, state in rows:
        out.append(f"{name:<{name_w}}  {val:>{val_w}}  {burn:>10}  "
                   f"{state}")
    return "\n".join(out)


def failed(statuses: List[SLOStatus]) -> bool:
    """The --check verdict: any alerting objective fails the gate."""
    return any(st.alerting for st in statuses)
