"""Histogram exemplars: trace-id samples attached to histogram buckets.

A latency histogram can say the p99 regressed; it cannot say WHICH
request landed in the top bucket.  Exemplars close that gap: when armed
(``PADDLE_TPU_EXEMPLARS=on``), every ``Histogram.observe`` that runs
under an active trace span records (trace_id, value, timestamp) into a
bounded latest-k reservoir for the bucket the value fell in.  The
Prometheus exporter renders them in OpenMetrics exemplar syntax —

    name_bucket{le="0.064"} 7 # {trace_id="4bf9..."} 0.0431 1700000000.0

— the collector parses and federates them, and ``cli trace-of`` joins
an exemplar's trace id against the fleet's trace/flight dumps to pull
up the actual Chrome trace of a tail request (docs/observability.md
"Time attribution").

Cost model: recording is gated on :func:`armed` (one module-global
read) AND on an active span, so un-armed processes pay one boolean
test per observe; armed processes pay one thread-local read plus a
deque append.  The reservoir keeps the latest ``PADDLE_TPU_EXEMPLAR_K``
(default 2) exemplars per bucket — memory is O(buckets * k) per child,
bounded regardless of traffic.
"""
from __future__ import annotations

import os
import re
import threading
import time
from collections import deque
from typing import Dict, List, NamedTuple, Optional, Tuple

from . import tracing

__all__ = [
    "Exemplar",
    "ExemplarReservoir",
    "armed",
    "set_armed",
    "exemplar_k",
    "active_trace_id",
    "format_exemplar",
    "parse_exemplar",
    "render_exemplar",
    "split_sample_line",
]


def _env_on(raw: Optional[str]) -> bool:
    return (raw or "").strip().lower() in ("1", "on", "true", "yes")


_ARMED = _env_on(os.environ.get("PADDLE_TPU_EXEMPLARS"))


def armed() -> bool:
    return _ARMED


def set_armed(on: bool) -> None:
    global _ARMED
    _ARMED = bool(on)


def exemplar_k() -> int:
    """Latest-k reservoir depth per bucket (PADDLE_TPU_EXEMPLAR_K,
    default 2, floor 1)."""
    try:
        return max(1, int(os.environ.get("PADDLE_TPU_EXEMPLAR_K", "2")))
    except ValueError:
        return 2


def active_trace_id() -> Optional[str]:
    """The current thread's active trace id, or None outside any span."""
    ctx = tracing.current_context()
    return ctx.trace_id if ctx is not None else None


class Exemplar(NamedTuple):
    trace_id: str
    value: float
    ts: float


class ExemplarReservoir:
    """Per-bucket latest-k exemplars for one histogram child.

    Bucket indices follow the child's per-bucket counts array: index i
    is the i-th finite bucket, the last index is the +Inf overflow.
    Bounded by construction — k per bucket, evicting oldest."""

    __slots__ = ("_lock", "_buckets", "_k")

    def __init__(self, k: Optional[int] = None):
        self._lock = threading.Lock()
        self._k = int(k) if k is not None else exemplar_k()
        self._buckets: Dict[int, deque] = {}

    def record(self, bucket_index: int, value: float,
               trace_id: str) -> None:
        ex = Exemplar(trace_id, float(value), time.time())
        with self._lock:
            d = self._buckets.get(bucket_index)
            if d is None:
                d = self._buckets[bucket_index] = deque(maxlen=self._k)
            d.append(ex)

    def snapshot(self) -> Dict[int, List[Exemplar]]:
        with self._lock:
            return {i: list(d) for i, d in self._buckets.items() if d}


# ---------------------------------------------------------------------------
# OpenMetrics exemplar wire format
# ---------------------------------------------------------------------------

# `# {label="value",...} <value> [<timestamp>]` appended to a sample
# line; only trace_id travels today but the parser keeps the general
# label set so foreign exposition round-trips
_EXEMPLAR_RE = re.compile(
    r"^\{(?P<labels>[^}]*)\}\s+(?P<value>\S+)(?:\s+(?P<ts>\S+))?\s*$")
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def format_exemplar(ex: Exemplar) -> str:
    """The OpenMetrics suffix for one exemplar (including the leading
    ``# ``), appended after a ``_bucket`` sample's value."""
    return (f'# {{trace_id="{ex.trace_id}"}} '
            f"{ex.value} {ex.ts}")


def parse_exemplar(text: str) -> Optional[dict]:
    """Parse the part AFTER ``# `` of an exemplar-bearing sample line ->
    ``{"labels": {...}, "value": float, "ts": float|None}``; None on
    malformed input (foreign exposition must not kill a scrape)."""
    m = _EXEMPLAR_RE.match(text.strip())
    if m is None:
        return None
    labels = {k: v.replace('\\"', '"').replace("\\\\", "\\")
              for k, v in _LABEL_RE.findall(m.group("labels"))}
    try:
        value = float(m.group("value"))
        ts = float(m.group("ts")) if m.group("ts") else None
    except ValueError:
        return None
    return {"labels": labels, "value": value, "ts": ts}


def render_exemplar(ex: dict) -> str:
    """Inverse of :func:`parse_exemplar`: the ``# {...} value [ts]``
    suffix from a parsed exemplar dict — how the collector re-emits
    exemplars it federated from member scrapes."""
    labels = ",".join(
        '{}="{}"'.format(k, str(v).replace("\\", "\\\\")
                         .replace('"', '\\"'))
        for k, v in ex.get("labels", {}).items())
    out = f"# {{{labels}}} {ex['value']}"
    if ex.get("ts") is not None:
        out += f" {ex['ts']}"
    return out


def split_sample_line(rest: str) -> Tuple[str, Optional[dict]]:
    """Split a Prometheus sample line's value part from a trailing
    OpenMetrics exemplar: ``"7 # {trace_id=\\"..\\"} 0.04 170.."`` ->
    ``("7", {...})``.  Lines without an exemplar pass through as
    ``(rest, None)``."""
    if " # " not in rest:
        return rest, None
    value_part, _, ex_part = rest.partition(" # ")
    return value_part.strip(), parse_exemplar(ex_part)
