"""Bounded in-process time-series history over the metrics registry.

The PR 4 registry answers "what is the value NOW"; everything the fleet
plane needs — the router's autoscaling signal, `cli top`'s qps column,
SLO burn rates — is a question about a WINDOW: "what was the p99 over
the last 60 s", "how fast is this counter moving".  This module is that
substrate: a :class:`TimeSeriesStore` samples registry counters, gauges
and histogram bucket vectors into per-series ring buffers
(``collections.deque(maxlen=capacity)``) at a configurable period, and
answers window queries without Prometheus:

  * ``rate(name, window_s)`` — counter / histogram-count slope over the
    window (qps, tokens/s), summed across matching label sets;
  * ``quantile(name, q, window_s)`` / ``p99`` / ``p50`` — the TRUE
    windowed quantile from bucket-count deltas between the window's
    edge samples (not the lifetime quantile a raw histogram gives);
  * ``latest(name)`` — most recent value, summed across matches;
  * ``interval_verdicts(...)`` — per-sample-interval good/bad flags,
    the SLO layer's burn-rate input (slo.py).

Series are keyed (name, sorted label items); queries match by label
SUBSET, so ``rate("requests_total", 60, labels={"kind": "pserver"})``
aggregates every member of that kind in a fleet store.  The store can
sample a local :class:`~paddle_tpu.observability.metrics.MetricsRegistry`
(``sample_once`` / the ``start()`` daemon thread) or be fed parsed
remote scrapes by the TelemetryCollector (``ingest*``, collector.py).

Memory is bounded by construction: ``capacity`` points per series, and
``drop(labels)`` reclaims a departed member's series the way
``Metric.remove`` reclaims a closed instance's.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from . import metrics as metrics_mod
from .metrics import quantile_from_buckets

__all__ = ["TimeSeriesStore", "HistPoint", "cum_to_per_bucket"]


class HistPoint(tuple):
    """One histogram sample: (count, sum, per-bucket counts incl. the
    trailing overflow slot).  A plain tuple subclass so deque storage
    stays compact."""

    __slots__ = ()

    def __new__(cls, count: int, total: float, counts: Sequence[int]):
        return tuple.__new__(cls, (int(count), float(total),
                                   tuple(counts)))

    @property
    def count(self) -> int:
        return self[0]

    @property
    def sum(self) -> float:
        return self[1]

    @property
    def counts(self) -> Tuple[int, ...]:
        return self[2]


def cum_to_per_bucket(buckets) -> Tuple[List[float], List[int]]:
    """Prometheus-exposition cumulative buckets ``[[le, cumulative],
    ...]`` (incl. the +Inf line when present) -> ``(finite les,
    per-bucket counts incl. the trailing overflow slot)`` — the shape
    ingest_histogram and quantile_from_buckets consume.  ONE owner:
    the collector's live ingestion and slo.evaluate_snapshot must
    never disagree about the same dump."""
    les, counts, prev = [], [], 0
    for le, cum in buckets:
        counts.append(int(cum) - prev)
        prev = int(cum)
        if le != float("inf"):
            les.append(le)
    if len(counts) == len(les):  # no explicit +Inf line
        counts.append(0)
    return les, counts


def _labels_key(labels: Optional[dict]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v))
                        for k, v in (labels or {}).items()))


class _Series:
    __slots__ = ("name", "labels", "kind", "buckets", "points")

    def __init__(self, name: str, labels: dict, kind: str,
                 buckets: Optional[Tuple[float, ...]], capacity: int):
        self.name = name
        self.labels = dict(labels)
        self.kind = kind
        self.buckets = buckets  # finite les only (histograms)
        self.points: deque = deque(maxlen=capacity)


class TimeSeriesStore:
    """Ring-buffered samples of metric series, queryable as windows."""

    def __init__(self, registry: Optional[metrics_mod.MetricsRegistry]
                 = None, period_s: float = 1.0, capacity: int = 720,
                 clock=time.monotonic):
        self._registry = registry  # None = the process registry, late-
        # bound so set-up order does not matter
        self.period_s = float(period_s)
        self.capacity = int(capacity)
        self._clock = clock
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, tuple], _Series] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- ingestion ----------------------------------------------------------
    def _put(self, name: str, labels: dict, kind: str, ts: float, value,
             buckets: Optional[Tuple[float, ...]] = None) -> None:
        key = (name, _labels_key(labels))
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = _Series(name, labels, kind, buckets, self.capacity)
                self._series[key] = s
            elif buckets is not None and s.buckets != buckets:
                # a member restarted with different bucket bounds:
                # deltas against the old points would be garbage
                s.buckets = buckets
                s.points.clear()
            s.points.append((float(ts), value))

    def ingest_value(self, name: str, kind: str, labels: dict,
                     value: float, ts: Optional[float] = None) -> None:
        """Record one counter/gauge observation (collector scrape)."""
        self._put(name, labels, kind,
                  self._clock() if ts is None else ts, float(value))

    def ingest_histogram(self, name: str, labels: dict,
                         buckets: Sequence[float],
                         counts: Sequence[int], count: int, total: float,
                         ts: Optional[float] = None) -> None:
        """Record one histogram observation: `buckets` are the finite
        les, `counts` the PER-BUCKET (non-cumulative) counts including
        the trailing overflow slot."""
        self._put(name, labels, "histogram",
                  self._clock() if ts is None else ts,
                  HistPoint(count, total, counts), tuple(buckets))

    def sample_once(self, now: Optional[float] = None) -> int:
        """Sample every series of the registry once; returns the number
        of series touched."""
        reg = self._registry or metrics_mod.registry()
        ts = self._clock() if now is None else now
        n = 0
        for m in reg.metrics():
            for labels, child in m.samples():
                if m.kind == "histogram":
                    _, counts = cum_to_per_bucket(
                        child.cumulative_buckets())
                    self.ingest_histogram(
                        name=m.name, labels=labels, buckets=m.buckets,
                        counts=counts, count=child.count,
                        total=child.sum, ts=ts)
                else:
                    self.ingest_value(m.name, m.kind, labels,
                                      child.value, ts=ts)
                n += 1
        return n

    # -- sampler thread -----------------------------------------------------
    def start(self) -> "TimeSeriesStore":
        """Start the periodic sampler (daemon thread); idempotent."""
        with self._lock:
            if self._thread is not None:
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name="paddle-tpu-timeseries")
            self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.period_s):
            try:
                self.sample_once()
            except Exception:  # sampling must never kill the host
                pass

    def stop(self):
        with self._lock:
            t, self._thread = self._thread, None
        self._stop.set()
        if t is not None and t is not threading.current_thread():
            t.join(timeout=self.period_s + 5)

    close = stop

    # -- series access ------------------------------------------------------
    def _matching(self, name: str,
                  labels: Optional[dict]) -> List[_Series]:
        want = _labels_key(labels)
        with self._lock:
            return [s for (n, lk), s in self._series.items()
                    if n == name and set(want) <= set(lk)]

    def series_names(self) -> List[str]:
        with self._lock:
            return sorted({n for n, _ in self._series})

    def points(self, name: str, labels: Optional[dict] = None
               ) -> List[Tuple[float, object]]:
        """All retained (ts, value) points of the single series matching
        `labels` exactly-or-by-subset; raises if the subset is
        ambiguous (window math on mixed series would be meaningless)."""
        matches = self._matching(name, labels)
        if not matches:
            return []
        if len(matches) > 1:
            raise ValueError(
                f"{name}: labels {labels or {}} match "
                f"{len(matches)} series; narrow the label set")
        with self._lock:
            return list(matches[0].points)

    def drop(self, labels: dict) -> int:
        """Drop every series whose labels are a superset of `labels`
        (e.g. ``drop({"member": "pserver-0"})`` after its lease
        expires); returns how many were dropped."""
        want = set(_labels_key(labels))
        with self._lock:
            doomed = [k for k in self._series if want <= set(k[1])]
            for k in doomed:
                del self._series[k]
        return len(doomed)

    # -- window queries -----------------------------------------------------
    def _edges(self, s: _Series, window_s: float, now: float):
        """(baseline, last) points for a window ending at `now`: the
        latest point at-or-before the window start (so the delta covers
        the FULL window), else the earliest retained point."""
        with self._lock:  # a sampler thread may be appending
            pts = list(s.points)
        if not pts:
            return None
        start = now - window_s
        base = None
        for p in pts:
            if p[0] <= start:
                base = p
            else:
                break
        if base is None:
            base = pts[0]
        last = pts[-1]
        if last[0] <= base[0] and last is not base:
            return None
        return base, last

    def rate(self, name: str, window_s: float,
             labels: Optional[dict] = None,
             now: Optional[float] = None) -> Optional[float]:
        """Per-second slope over the window, summed across matching
        series.  Counters/gauges use the raw value; histograms the
        observation count (request rate).  None when no series has two
        usable points yet."""
        now = self._clock() if now is None else now
        total, seen = 0.0, False
        for s in self._matching(name, labels):
            edges = self._edges(s, window_s, now)
            if edges is None:
                continue
            (t0, v0), (t1, v1) = edges
            if t1 <= t0:
                continue
            if s.kind == "histogram":
                v0, v1 = v0.count, v1.count
            total += (v1 - v0) / (t1 - t0)
            seen = True
        return total if seen else None

    def sum_rate(self, name: str, window_s: float,
                 labels: Optional[dict] = None,
                 now: Optional[float] = None) -> Optional[float]:
        """Per-second slope of a histogram's ``_sum`` over the window,
        summed across matching series — for a ``*_phase_seconds``
        family this is "seconds of phase time per wall second", the
        phase-share signal behind `cli why`.  None when no matching
        histogram series has a usable window yet."""
        now = self._clock() if now is None else now
        total, seen = 0.0, False
        for s in self._matching(name, labels):
            if s.kind != "histogram":
                continue
            edges = self._edges(s, window_s, now)
            if edges is None:
                continue
            (t0, v0), (t1, v1) = edges
            if t1 <= t0:
                continue
            total += (v1.sum - v0.sum) / (t1 - t0)
            seen = True
        return total if seen else None

    def label_values(self, name: str, label: str,
                     labels: Optional[dict] = None) -> List[str]:
        """Distinct values of `label` across the series of `name`
        (optionally restricted to a label subset) — how the attribution
        layer enumerates phases, endpoints and members it should group
        by."""
        out = set()
        for s in self._matching(name, labels):
            v = s.labels.get(label)
            if v is not None:
                out.add(v)
        return sorted(out)

    def latest(self, name: str, labels: Optional[dict] = None
               ) -> Optional[float]:
        """Most recent value summed across matching series (histograms:
        observation count)."""
        total, seen = 0.0, False
        for s in self._matching(name, labels):
            with self._lock:
                pt = s.points[-1] if s.points else None
            if pt is None:
                continue
            v = pt[1]
            total += v.count if s.kind == "histogram" else v
            seen = True
        return total if seen else None

    def quantile(self, name: str, q: float, window_s: float,
                 labels: Optional[dict] = None,
                 now: Optional[float] = None) -> float:
        """Windowed q-quantile: per-bucket count DELTAS between each
        matching series' window edges, summed across series (bucket
        layouts must agree — mismatched members are skipped), then the
        shared interpolation (metrics.quantile_from_buckets).  NaN when
        the window saw no observations."""
        now = self._clock() if now is None else now
        agg: Optional[List[float]] = None
        buckets: Optional[Tuple[float, ...]] = None
        total = 0
        for s in self._matching(name, labels):
            if s.kind != "histogram" or s.buckets is None:
                continue
            edges = self._edges(s, window_s, now)
            if edges is None:
                continue
            (_, v0), (_, v1) = edges
            if v1 is v0:
                # single retained point: everything it counted happened
                # since the store began watching — treat as in-window
                v0 = HistPoint(0, 0.0, [0] * len(v1.counts))
            if buckets is None:
                buckets = s.buckets
                agg = [0.0] * len(v1.counts)
            elif s.buckets != buckets or len(v1.counts) != len(agg):
                continue
            for i, (a, b) in enumerate(zip(v0.counts, v1.counts)):
                agg[i] += max(b - a, 0)
            total += max(v1.count - v0.count, 0)
        if agg is None:
            return float("nan")
        return quantile_from_buckets(buckets, agg, total, q)

    def mean(self, name: str, window_s: float,
             labels: Optional[dict] = None,
             now: Optional[float] = None) -> float:
        """Windowed mean of a histogram: (sum delta) / (count delta)
        between each matching series' window edges, pooled across
        matches.  NaN when the window saw no observations."""
        now = self._clock() if now is None else now
        total_sum = total_count = 0.0
        seen = False
        for s in self._matching(name, labels):
            if s.kind != "histogram":
                continue
            edges = self._edges(s, window_s, now)
            if edges is None:
                continue
            (_, v0), (_, v1) = edges
            if v1 is v0:
                # single retained point: treat its history as
                # in-window, like quantile() does
                total_sum += v1.sum
                total_count += v1.count
            else:
                total_sum += v1.sum - v0.sum
                total_count += v1.count - v0.count
            seen = True
        if not seen or total_count <= 0:
            return float("nan")
        return total_sum / total_count

    def p99(self, name: str, window_s: float,
            labels: Optional[dict] = None,
            now: Optional[float] = None) -> float:
        return self.quantile(name, 0.99, window_s, labels, now)

    def p50(self, name: str, window_s: float,
            labels: Optional[dict] = None,
            now: Optional[float] = None) -> float:
        return self.quantile(name, 0.50, window_s, labels, now)

    def interval_verdicts(self, name: str, window_s: float, check,
                          labels: Optional[dict] = None,
                          now: Optional[float] = None,
                          stat_q: Optional[float] = None,
                          stat_mean: bool = False) -> List[bool]:
        """Per-consecutive-sample-interval verdicts inside the window —
        the SLO burn-rate input.  For each matching series and each
        adjacent point pair in the window, `check(value)` is called
        with the interval's instantaneous statistic: for histograms
        the bucket-delta q-quantile when `stat_q` is given, the
        interval mean (sum delta / count delta) when `stat_mean`, else
        the per-second observation rate; the newer point's value for
        gauges; the per-second slope for counters.  Intervals with no
        signal (no observations in the delta) are skipped.  Verdicts
        from all matching series pool into one list: a fleet-level SLO
        burns when ANY member burns."""
        now = self._clock() if now is None else now
        start = now - window_s
        out: List[bool] = []
        for s in self._matching(name, labels):
            with self._lock:
                pts = [p for p in s.points if p[0] >= start]
            for (t0, v0), (t1, v1) in zip(pts, pts[1:]):
                if s.kind == "histogram":
                    if stat_mean:
                        n = v1.count - v0.count
                        if n <= 0:
                            continue  # idle interval: no latency signal
                        stat = (v1.sum - v0.sum) / n
                    elif stat_q is None:
                        # rate semantics, like the counter branch: a
                        # raw count delta would scale the verdict with
                        # the sample period
                        if t1 <= t0:
                            continue
                        stat = (v1.count - v0.count) / (t1 - t0)
                    else:
                        deltas = [max(b - a, 0) for a, b in
                                  zip(v0.counts, v1.counts)]
                        n = max(v1.count - v0.count, 0)
                        if not n:
                            continue  # idle interval: no latency signal
                        stat = quantile_from_buckets(
                            s.buckets, deltas, n, stat_q)
                elif s.kind == "counter":
                    if t1 <= t0:
                        continue
                    stat = (v1 - v0) / (t1 - t0)
                else:
                    stat = v1
                out.append(bool(check(stat)))
        return out
