"""Unified observability layer: metrics registry, trace spans, exporters.

    from paddle_tpu.observability import metrics, tracing, exporters

    STEPS = metrics.counter("paddle_tpu_trainer_steps_total", "steps")
    with tracing.span("trainer.step", batch_id=i):
        ...
        STEPS.inc()
    exporters.write_prometheus("/tmp/metrics.prom")
    tracing.write_chrome_trace("/tmp/trace.json")

Switches (env at import, or flags/`set_flags` at runtime):
  * ``PADDLE_TPU_METRICS=on`` — arm the gated instruments (metrics
    created with ``always=True`` count regardless; everything else is a
    boolean-test no-op when off).
  * ``PADDLE_TPU_TRACE=on`` / ``PADDLE_TPU_TRACE_DIR=<dir>`` — record
    spans; with a dir, auto-write ``trace_<pid>.json`` at exit.
  * ``PADDLE_TPU_METRICS_DUMP=<path>`` — auto-write the Prometheus text
    dump at exit.

See docs/observability.md for the full tour.
"""
from __future__ import annotations

from . import attribution, collector, exemplars, exporters, flightrecorder, metrics, slo, timeseries, tracing  # noqa: F401,E501
from .metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    counter,
    gauge,
    histogram,
    registry,
)
from .timeseries import TimeSeriesStore  # noqa: F401
from .tracing import SpanContext, activate, current_context, span  # noqa: F401

__all__ = [
    "metrics",
    "tracing",
    "exporters",
    "exemplars",
    "attribution",
    "timeseries",
    "flightrecorder",
    "slo",
    "collector",
    "TimeSeriesStore",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "counter",
    "gauge",
    "histogram",
    "registry",
    "SpanContext",
    "span",
    "activate",
    "current_context",
]


def _sync_from_flags():
    """Keep the module switches in step with the flag registry so
    `set_flags({"metrics": True})` / PADDLE_TPU_METRICS both work."""
    from ..core.flags import get_flag

    metrics.set_enabled(bool(get_flag("metrics")) or metrics.enabled())
    d = get_flag("trace_dir")
    if d and not tracing.trace_dir():
        tracing.set_trace_dir(d)


def _wire_flags():
    from ..core import flags as flags_mod
    from ..core.flags import get_flag

    flags_mod.on_flag_change(
        "metrics", lambda: metrics.set_enabled(get_flag("metrics")))

    def _trace_dir_changed():
        d = get_flag("trace_dir")
        if d:
            tracing.set_trace_dir(d)

    flags_mod.on_flag_change("trace_dir", _trace_dir_changed)
    _sync_from_flags()


_wire_flags()
# PADDLE_TPU_FLIGHT_DIR / PADDLE_TPU_FLIGHT arm the always-on flight
# recorder at import (docs/observability.md "Fleet telemetry")
flightrecorder.maybe_install_from_env()
