"""Cross-process trace spans: span() context managers + wire propagation.

The reference attributes cost per op with `platform::profiler`
RecordEvent ranges inside ONE process; a distributed step (trainer ->
VariableClient -> VariableServer -> optimize block) needs ranges that
compose ACROSS processes.  This module provides the minimal
OpenTelemetry-shaped substrate for that:

  * ``span(name, **attrs)`` — a context manager carrying a 128-bit
    trace id, a 64-bit span id and its parent's span id.  Spans nest via
    a thread-local context stack, so `with span("trainer.step"):` makes
    every span opened inside it (same thread) a child.
  * thread handoff — ``ctx = current_context()`` in the producer,
    ``with activate(ctx):`` in the worker thread (used by the prefetch
    pipeline and the serving worker), so background work records under
    the step that scheduled it.
  * wire propagation — ``inject()`` returns a small dict to ship in a
    protocol header (the pserver frame protocol carries it in the JSON
    head; frames without it keep working), ``extract(head)`` +
    ``activate`` on the receiving side parents the server-side span
    under the remote caller: one training step yields a single coherent
    trace across trainer, pserver and master.

Finished spans collect in a bounded in-process buffer and export as
Chrome-trace JSON (``chrome://tracing`` / Perfetto; see
observability/exporters.py).  Tracing is off (spans cost one boolean
test) unless ``PADDLE_TPU_TRACE=on`` or ``PADDLE_TPU_TRACE_DIR`` is set
— the latter also auto-writes ``trace_<pid>.json`` into the directory
at process exit, so a multi-process run drops one merge-able trace file
per process.
"""
from __future__ import annotations

import atexit
import os
import random
import threading
import time
from typing import Dict, List, NamedTuple, Optional

__all__ = [
    "SpanContext",
    "span",
    "activate",
    "current_context",
    "current_trace_id",
    "inject",
    "extract",
    "enabled",
    "set_enabled",
    "add_span_listener",
    "remove_span_listener",
    "trace_dir",
    "finished_spans",
    "clear",
    "chrome_trace_events",
    "write_chrome_trace",
    "TailSampler",
    "arm_tail_sampler",
    "disarm_tail_sampler",
    "tail_sampler",
]

_TRACE_DIR = os.environ.get("PADDLE_TPU_TRACE_DIR", "")
_ENABLED = bool(_TRACE_DIR) or (os.environ.get("PADDLE_TPU_TRACE", "")
                                .strip().lower() in ("1", "on", "true",
                                                     "yes"))

# bounded buffer: a runaway loop under tracing must degrade (drop +
# count) instead of eating the host's memory
_MAX_SPANS = 100_000
_spans: List[dict] = []
_dropped = 0
_lock = threading.Lock()
_tls = threading.local()
_rng = random.Random()
# span listeners (the flight recorder's tap): when any is registered,
# spans are CREATED and delivered to listeners even with full tracing
# off — the recorder's always-on ring wants the last seconds of spans
# without paying for (or growing) the 100k export buffer
_listeners: List = []


def _after_fork_in_child():
    """A forked worker must not share the parent's id stream (identical
    trace/span ids across processes) nor its span buffer (the child
    would re-dump the parent's spans under its own pid), and the buffer
    lock may have been held by a parent thread at fork time."""
    global _spans, _dropped, _lock, _TAIL
    _rng.seed()  # fresh OS entropy
    _lock = threading.Lock()
    _spans = []
    _dropped = 0
    # a forked child shares the parent's tail buffer: re-arm with a
    # fresh one so the child's dump carries only its own spans
    t = _TAIL
    if t is not None:
        remove_span_listener(t)
        _TAIL = None
        arm_tail_sampler(threshold_s=t.threshold_s, out_dir=t._dir,
                         max_open=t._max_open,
                         max_spans_per_trace=t._max_spans,
                         max_kept=t._max_kept, flush_s=t._flush_s)


if hasattr(os, "register_at_fork"):  # posix
    os.register_at_fork(after_in_child=_after_fork_in_child)


class SpanContext(NamedTuple):
    trace_id: str  # 32 hex chars
    span_id: str   # 16 hex chars


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    global _ENABLED
    _ENABLED = bool(on)


def set_trace_dir(path: str) -> None:
    """Point the exit-time auto-dump at `path` (also enables tracing)."""
    global _TRACE_DIR
    _TRACE_DIR = path
    if path:
        set_enabled(True)


def trace_dir() -> str:
    return _TRACE_DIR


def _new_trace_id() -> str:
    return f"{_rng.getrandbits(128):032x}"


def _new_span_id() -> str:
    return f"{_rng.getrandbits(64):016x}"


def _stack() -> list:
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


def current_context() -> Optional[SpanContext]:
    """The active span's context on THIS thread (or an activated remote
    context), else None."""
    s = _stack()
    return s[-1] if s else None


def current_trace_id() -> Optional[str]:
    """The active trace id on this thread (exemplar hook), else None."""
    s = _stack()
    return s[-1].trace_id if s else None


def inject() -> Optional[Dict[str, str]]:
    """Wire header for the current context: ``{"tid": ..., "sid": ...}``
    — small enough to ride in any JSON protocol head.  None when there
    is no active span (callers must omit the field, keeping old peers'
    parsers untouched)."""
    ctx = current_context()
    if ctx is None:
        return None
    return {"tid": ctx.trace_id, "sid": ctx.span_id}


def extract(header) -> Optional[SpanContext]:
    """SpanContext from a wire header produced by inject(); tolerant of
    None / missing / malformed values (old peers)."""
    if not isinstance(header, dict):
        return None
    tid, sid = header.get("tid"), header.get("sid")
    if not (isinstance(tid, str) and isinstance(sid, str) and tid and sid):
        return None
    return SpanContext(tid, sid)


class Span:
    """Mutable handle yielded by span() — attrs set during the block are
    recorded at exit."""

    __slots__ = ("name", "context", "parent_id", "attrs",
                 "_t0", "_wall")

    def __init__(self, name: str, context: SpanContext,
                 parent_id: Optional[str], attrs: dict):
        self.name = name
        self.context = context
        self.parent_id = parent_id
        self.attrs = attrs
        self._wall = time.time()
        self._t0 = time.perf_counter()

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value


def add_span_listener(fn) -> None:
    """Register `fn(rec_dict)` to receive every finished span.  While
    any listener is registered, span() is live even when full tracing
    is off — records then flow ONLY to listeners, not the export
    buffer.  Listeners must be cheap and must not raise."""
    if fn not in _listeners:
        _listeners.append(fn)


def remove_span_listener(fn) -> None:
    if fn in _listeners:
        _listeners.remove(fn)


def _record(s: Span, duration: float) -> None:
    global _dropped
    rec = {
        "name": s.name,
        "trace_id": s.context.trace_id,
        "span_id": s.context.span_id,
        "parent_id": s.parent_id,
        "ts": s._wall,
        "dur": duration,
        "pid": os.getpid(),
        "tid": threading.get_ident(),
        "thread": threading.current_thread().name,
        "attrs": dict(s.attrs),
    }
    if _ENABLED:
        with _lock:
            if len(_spans) >= _MAX_SPANS:
                _dropped += 1
            else:
                _spans.append(rec)
    for fn in _listeners:
        fn(rec)


class _NoopCtx:
    """Singleton returned on every disabled span()/activate(): hot paths
    pay one boolean test + a pre-built `with` target, never a generator
    frame (contextlib.contextmanager costs ~µs per entry — too much for
    per-op/per-request sites when tracing is off)."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NOOP = _NoopCtx()


class _SpanCtx:
    __slots__ = ("_name", "_attrs", "_span")

    def __init__(self, name: str, attrs: dict):
        self._name = name
        self._attrs = attrs

    def __enter__(self) -> Span:
        stack = _stack()
        parent = stack[-1] if stack else None
        ctx = SpanContext(
            parent.trace_id if parent is not None else _new_trace_id(),
            _new_span_id())
        s = Span(self._name, ctx,
                 parent.span_id if parent is not None else None,
                 self._attrs)
        stack.append(ctx)
        self._span = s
        return s

    def __exit__(self, *exc):
        _stack().pop()
        _record(self._span, time.perf_counter() - self._span._t0)
        return False


def span(name: str, **attrs):
    """Open a trace span around the block.  No-op (yields None) when
    tracing is off and no listener is tapped; otherwise the `with`
    target is the Span (set_attr for values known only mid-block)."""
    if not (_ENABLED or _listeners):
        return _NOOP
    return _SpanCtx(name, attrs)


class _ActivateCtx:
    __slots__ = ("_ctx",)

    def __init__(self, ctx: SpanContext):
        self._ctx = ctx

    def __enter__(self):
        _stack().append(self._ctx)
        return self._ctx

    def __exit__(self, *exc):
        _stack().pop()
        return False


def activate(ctx: Optional[SpanContext]):
    """Install `ctx` as this thread's current context WITHOUT recording
    a span — the receiving half of a thread handoff or wire extract.
    `None` is a no-op so call sites need no conditional."""
    if ctx is None or not (_ENABLED or _listeners):
        return _NOOP
    return _ActivateCtx(ctx)


def record_span(name: str, ts: float, dur: float,
                parent: Optional[SpanContext] = None,
                **attrs) -> Optional[SpanContext]:
    """Record an already-timed span WITHOUT touching the thread's
    context stack — for ranges that outlive a `with` frame (e.g. a
    generator-held work window, where an abandoned consumer would leave
    a context-managed span permanently pushed).  `ts` is wall-clock
    seconds (time.time()), `dur` seconds; `parent` parents it into an
    existing trace, else it starts its own.  Returns the recorded
    context (None when tracing is off)."""
    global _dropped
    if not (_ENABLED or _listeners):
        return None
    ctx = SpanContext(
        parent.trace_id if parent is not None else _new_trace_id(),
        _new_span_id())
    rec = {
        "name": name,
        "trace_id": ctx.trace_id,
        "span_id": ctx.span_id,
        "parent_id": parent.span_id if parent is not None else None,
        "ts": ts,
        "dur": dur,
        "pid": os.getpid(),
        "tid": threading.get_ident(),
        "thread": threading.current_thread().name,
        "attrs": dict(attrs),
    }
    if _ENABLED:
        with _lock:
            if len(_spans) >= _MAX_SPANS:
                _dropped += 1
            else:
                _spans.append(rec)
    for fn in _listeners:
        fn(rec)
    return ctx


def finished_spans() -> List[dict]:
    with _lock:
        return list(_spans)


def dropped_spans() -> int:
    with _lock:
        return _dropped


def clear() -> None:
    global _dropped
    with _lock:
        _spans.clear()
        _dropped = 0


# ---------------------------------------------------------------------------
# tail sampling: keep full span trees only for slow or errored traces
# ---------------------------------------------------------------------------


class TailSampler:
    """Span listener that retains complete span trees ONLY for traces
    that breach a latency threshold or carry an error attr — head
    sampling decides before the outcome is known, tail sampling after.

    Buffering is bounded everywhere: at most `max_open` in-progress
    traces (oldest evicted first), at most `max_spans_per_trace` spans
    buffered per trace (extras counted, not stored), at most `max_kept`
    finalized kept traces (oldest dropped).  A trace is MARKED for
    keeping the moment any of its finished spans qualifies (duration >=
    threshold_s, or an `error` attr), and finalized when its root span
    (parent_id None) completes or it is evicted.  Marked traces —
    including still-open ones, e.g. the remote half of a cross-process
    trace whose root lives elsewhere — are flushed to
    ``<dir>/trace_tail_<pid>.json`` (Chrome-trace JSON, same shape as
    the atexit dump) on a debounced cadence, so a live replica's tail
    traces are joinable by the collector without waiting for exit.

    Arm via :func:`arm_tail_sampler` or ``PADDLE_TPU_TAIL_SAMPLE``
    (``on`` or a threshold in seconds; docs/observability.md "Time
    attribution")."""

    def __init__(self, threshold_s: float = 0.25,
                 max_open: int = 256,
                 max_spans_per_trace: int = 512,
                 max_kept: int = 64,
                 out_dir: Optional[str] = None,
                 flush_s: float = 0.5):
        self.threshold_s = float(threshold_s)
        self._max_open = int(max_open)
        self._max_spans = int(max_spans_per_trace)
        self._max_kept = int(max_kept)
        self._dir = out_dir
        self._flush_s = float(flush_s)
        self._lock = threading.Lock()
        # trace_id -> {"spans": [...], "keep": bool, "dropped": int};
        # plain dicts keep insertion (= first-seen) order for eviction
        self._open: Dict[str, dict] = {}
        self._kept: Dict[str, dict] = {}
        self._kept_total = 0
        self._evicted_open = 0
        self._dirty = False
        self._last_flush = 0.0

    # -- listener hot path --------------------------------------------------
    def __call__(self, rec: dict) -> None:
        tid = rec.get("trace_id")
        if not tid:
            return
        qualifies = ((rec.get("dur") or 0.0) >= self.threshold_s
                     or bool(rec.get("attrs", {}).get("error")))
        do_flush = False
        with self._lock:
            buf = self._open.get(tid)
            if buf is None:
                kept = self._kept.get(tid)
                if kept is not None:
                    # straggling span of an already-finalized keeper
                    if len(kept["spans"]) < self._max_spans:
                        kept["spans"].append(rec)
                        self._dirty = True
                    do_flush = self._flush_due_locked()
                else:
                    buf = self._open[tid] = {"spans": [rec],
                                             "keep": qualifies,
                                             "dropped": 0}
                    while len(self._open) > self._max_open:
                        old_tid = next(iter(self._open))
                        old = self._open.pop(old_tid)
                        self._evicted_open += 1
                        if old["keep"]:
                            self._keep_locked(old_tid, old)
            if buf is not None:
                if buf is not self._open.get(tid):
                    pass  # already finalized by eviction above
                elif len(buf["spans"]) < self._max_spans:
                    if buf["spans"][-1] is not rec:
                        buf["spans"].append(rec)
                else:
                    buf["dropped"] += 1
                if qualifies:
                    buf["keep"] = True
                if rec.get("parent_id") is None:
                    # local root completed: the trace's fate is decided
                    self._open.pop(tid, None)
                    if buf["keep"]:
                        self._keep_locked(tid, buf)
                elif buf["keep"]:
                    # cross-process half with a remote root: stream it
                    # out on the debounce so the fleet join sees it
                    self._dirty = True
                do_flush = self._flush_due_locked()
        if do_flush:
            self.flush()

    def _keep_locked(self, tid: str, buf: dict) -> None:
        self._kept[tid] = buf
        self._kept_total += 1
        self._dirty = True
        while len(self._kept) > self._max_kept:
            self._kept.pop(next(iter(self._kept)))

    def _flush_due_locked(self) -> bool:
        return (self._dirty and self._dir is not None
                and time.monotonic() - self._last_flush
                >= self._flush_s)

    # -- introspection / export --------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "open_traces": len(self._open),
                "open_spans": sum(len(b["spans"])
                                  for b in self._open.values()),
                "kept_traces": len(self._kept),
                "kept_spans": sum(len(b["spans"])
                                  for b in self._kept.values()),
                "kept_total": self._kept_total,
                "evicted_open": self._evicted_open,
            }

    def kept_trace_ids(self) -> List[str]:
        with self._lock:
            return list(self._kept)

    def _sampled_spans_locked(self) -> List[dict]:
        spans: List[dict] = []
        for buf in self._kept.values():
            spans.extend(buf["spans"])
        for buf in self._open.values():
            if buf["keep"]:
                spans.extend(buf["spans"])
        return spans

    def flush(self, path: Optional[str] = None,
              force: bool = False) -> Optional[str]:
        """Write the sampled traces as Chrome-trace JSON (atomic tmp +
        rename).  Default path ``<out_dir>/trace_tail_<pid>.json`` —
        the ``trace_*`` prefix is what the collector's assemble_traces
        globs, so tail files join the fleet dump like any other
        process dump.  Debounced unless `force`."""
        import json

        with self._lock:
            if path is None and self._dir is None:
                return None
            if not force and not self._dirty:
                return None
            self._dirty = False
            self._last_flush = time.monotonic()
            spans = self._sampled_spans_locked()
        out = path or os.path.join(self._dir,
                                   f"trace_tail_{os.getpid()}.json")
        events = [{
            "ph": "X", "cat": "span", "name": s["name"],
            "ts": s["ts"] * 1e6, "dur": s["dur"] * 1e6,
            "pid": s["pid"], "tid": s["tid"],
            "args": {"trace_id": s["trace_id"],
                     "span_id": s["span_id"],
                     "parent_id": s["parent_id"], **s["attrs"]},
        } for s in spans]
        payload = {"traceEvents": events, "displayTimeUnit": "ms",
                   "otherData": {"producer":
                                 "paddle_tpu.observability.tail",
                                 "threshold_s": self.threshold_s}}
        d = os.path.dirname(out)
        try:
            if d:
                os.makedirs(d, exist_ok=True)
            tmp = f"{out}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, out)
        except OSError:
            return None  # best-effort, like the flight recorder
        return out


_TAIL: Optional[TailSampler] = None


def arm_tail_sampler(threshold_s: float = 0.25,
                     out_dir: Optional[str] = None,
                     **kw) -> TailSampler:
    """Install the process tail sampler as a span listener (making
    span() live even with full tracing off, like the flight recorder's
    tap).  Re-arming replaces the previous sampler.  `out_dir` defaults
    to the trace dir when one is configured."""
    global _TAIL
    disarm_tail_sampler()
    _TAIL = TailSampler(threshold_s=threshold_s,
                        out_dir=out_dir or (_TRACE_DIR or None), **kw)
    add_span_listener(_TAIL)
    return _TAIL


def disarm_tail_sampler() -> None:
    global _TAIL
    t, _TAIL = _TAIL, None
    if t is not None:
        remove_span_listener(t)
        t.flush(force=True)


def tail_sampler() -> Optional[TailSampler]:
    return _TAIL


def maybe_arm_tail_from_env() -> Optional[TailSampler]:
    """``PADDLE_TPU_TAIL_SAMPLE=on`` arms at the default threshold;
    a numeric value is the threshold in seconds."""
    raw = os.environ.get("PADDLE_TPU_TAIL_SAMPLE", "").strip().lower()
    if not raw:
        return None
    if raw in ("1", "on", "true", "yes"):
        return arm_tail_sampler()
    try:
        return arm_tail_sampler(threshold_s=float(raw))
    except ValueError:
        return None


# ---------------------------------------------------------------------------
# Chrome-trace ("catapult") export — open in chrome://tracing or Perfetto
# ---------------------------------------------------------------------------


def chrome_trace_events(include_profiler: bool = True) -> List[dict]:
    """Finished spans (and, optionally, the profiler's aggregated range
    events) as Chrome-trace event dicts (`ph: "X"`, microsecond ts/dur,
    trace/span ids in args)."""
    events = []
    for s in finished_spans():
        events.append({
            "ph": "X",
            "cat": "span",
            "name": s["name"],
            "ts": s["ts"] * 1e6,
            "dur": s["dur"] * 1e6,
            "pid": s["pid"],
            "tid": s["tid"],
            "args": {
                "trace_id": s["trace_id"],
                "span_id": s["span_id"],
                "parent_id": s["parent_id"],
                **s["attrs"],
            },
        })
    if include_profiler:
        events.extend(_profiler_chrome_events())
    return events


def _profiler_chrome_events() -> List[dict]:
    """The profiler's per-name duration lists as back-to-back events on
    one synthetic track per name.  The profiler stores durations only
    (no wall placement), so these tracks visualize per-event COST
    distribution, not real concurrency — the span tracks carry the
    wall-clock story."""
    from paddle_tpu import profiler

    events = []
    pid = os.getpid()
    with profiler._events_lock:
        snapshot = {name: list(ts) for name, ts in
                    profiler._events.items()}
    for i, (name, durations) in enumerate(sorted(snapshot.items())):
        tid = 1_000_000 + i
        events.append({
            "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
            "args": {"name": f"profiler:{name}"},
        })
        ts = 0.0
        for dur in durations:
            events.append({
                "ph": "X", "cat": "profiler", "name": name,
                "ts": ts, "dur": dur * 1e6, "pid": pid, "tid": tid,
            })
            ts += dur * 1e6
    return events


def write_chrome_trace(path: Optional[str] = None,
                       include_profiler: bool = True) -> str:
    """Write `{"traceEvents": [...]}` JSON; default path is
    ``<trace_dir>/trace_<pid>.json``.  Returns the path written."""
    import json

    if path is None:
        if not _TRACE_DIR:
            raise ValueError(
                "no path given and PADDLE_TPU_TRACE_DIR is not set")
        os.makedirs(_TRACE_DIR, exist_ok=True)
        path = os.path.join(_TRACE_DIR, f"trace_{os.getpid()}.json")
    payload = {
        "traceEvents": chrome_trace_events(include_profiler),
        "displayTimeUnit": "ms",
        "otherData": {"producer": "paddle_tpu.observability",
                      "dropped_spans": dropped_spans()},
    }
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(payload, f)
    return path


def _atexit_dump():
    # only when the env asked for it AND something was recorded — an
    # idle import must not litter the trace dir with empty files
    if _TRACE_DIR and finished_spans():
        try:
            write_chrome_trace()
        except OSError:
            pass  # exit-time dump is best-effort (read-only FS, etc.)
    if _TAIL is not None:
        _TAIL.flush(force=True)


atexit.register(_atexit_dump)
maybe_arm_tail_from_env()
