"""Parameter initializers — append init ops to the startup program.

Reference: /root/reference/python/paddle/v2/fluid/initializer.py:1-437
(Constant/Uniform/Normal/Xavier/MSRA, each emitting fill_constant /
uniform_random / gaussian_random ops into the startup block).
"""
from __future__ import annotations

import contextlib
import math

__all__ = [
    "force_init_on_cpu",
    "init_on_cpu",
    "Constant",
    "Uniform",
    "Normal",
    "Xavier",
    "MSRA",
    "ConstantInitializer",
    "UniformInitializer",
    "NormalInitializer",
    "XavierInitializer",
    "MSRAInitializer",
]


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0):
        self.value = float(value)

    def __call__(self, var, block):
        block.append_op(
            "fill_constant", {}, {"Out": [var.name]},
            {"shape": list(var.shape), "dtype": var.dtype,
             "value": self.value,
             "force_cpu": force_init_on_cpu()})


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        block.append_op(
            "uniform_random", {}, {"Out": [var.name]},
            {"shape": list(var.shape), "dtype": var.dtype,
             "min": self.low, "max": self.high, "seed": self.seed,
             "force_cpu": force_init_on_cpu()})


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op(
            "gaussian_random", {}, {"Out": [var.name]},
            {"shape": list(var.shape), "dtype": var.dtype,
             "mean": self.loc, "std": self.scale, "seed": self.seed,
             "force_cpu": force_init_on_cpu()})


def _fan_in_out(var):
    """Reference initializer.py _compute_fans: for conv filters
    [out_c, in_c, k...] fan_in = in_c*prod(k), fan_out = out_c*prod(k)."""
    shape = var.shape
    if len(shape) == 1:
        return shape[0], shape[0]
    recep = 1
    for d in shape[2:]:
        recep *= d
    return shape[1] * recep, shape[0] * recep


class XavierInitializer(Initializer):
    """Glorot init (reference initializer.py XavierInitializer)."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out = uniform, fan_in, fan_out
        self.seed = seed

    def __call__(self, var, block):
        fi, fo = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / (fi + fo))
            NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    """He init (reference initializer.py MSRAInitializer)."""

    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fi, _ = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / fi)
            NormalInitializer(0.0, std, self.seed)(var, block)


Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer


# ---------------------------------------------------------------------------
# init-on-cpu context (reference initializer.py:24-46).  On TPU the flag
# marks init ops to run host-side (the interpreter path) — useful for huge
# embeddings initialized once and sharded onto the mesh afterwards.
# ---------------------------------------------------------------------------

_force_init_on_cpu_ = False


def force_init_on_cpu() -> bool:
    return _force_init_on_cpu_


@contextlib.contextmanager
def init_on_cpu():
    """`with init_on_cpu():` — initializer ops created inside carry
    force_cpu=True (reference initializer.py init_on_cpu)."""
    global _force_init_on_cpu_
    pre_state = _force_init_on_cpu_
    _force_init_on_cpu_ = True
    try:
        yield
    finally:
        _force_init_on_cpu_ = pre_state
