"""Program debugging utilities: pseudo-code printer + graphviz drawing.

Mirror of the reference's
/root/reference/python/paddle/v2/fluid/debuger.py (pprint_program_codes,
draw_block_graphviz) and graphviz.py/net_drawer.py: render a Program as
readable pseudo-code and as a .dot graph.  Pure text emission — no
graphviz python package required; feed the .dot to `dot -Tpng` offline.
"""
from __future__ import annotations

import re
from typing import Optional, Set

from .core.framework import Parameter, Program

__all__ = ["program_to_code", "print_program", "draw_block_graphviz"]


def _attr_repr(value, maxlen=40):
    s = repr(value)
    return s if len(s) <= maxlen else s[: maxlen - 3] + "..."


def _op_to_code(op) -> str:
    outs = ", ".join(
        f"{slot}={names}" if len(op.outputs) > 1 else ", ".join(names)
        for slot, names in sorted(op.outputs.items()) if names
    )
    ins = ", ".join(
        f"{slot}={names}" for slot, names in sorted(op.inputs.items())
        if names
    )
    attrs = ", ".join(
        f"{k}={_attr_repr(v)}" for k, v in sorted(op.attrs.items())
        if not k.startswith("_") and k != "sub_block"
    )
    parts = [p for p in (ins, attrs) if p]
    return f"{outs or '()'} = {op.type}({', '.join(parts)})"


def _var_to_code(v) -> str:
    kind = "param" if isinstance(v, Parameter) else (
        "persist" if getattr(v, "persistable", False) else "var")
    return (f"{kind} {v.name} : shape={list(v.shape) if v.shape else '?'}"
            f", dtype={v.dtype}, lod={getattr(v, 'lod_level', 0)}")


def _diag_index(diagnostics):
    """{(block_idx, op_idx): [Diagnostic]} (program/block-level entries
    keyed with op_idx None are kept under (block_idx, None))."""
    index = {}
    for d in diagnostics or ():
        index.setdefault((d.block_idx, d.op_idx), []).append(d)
    return index


def program_to_code(program: Program, skip_vars: bool = False,
                    diagnostics=None, verify: bool = False) -> str:
    """Render every block of `program` as indented pseudo-code
    (reference debuger.py pprint_program_codes).

    `diagnostics`: analysis Diagnostic list (Program.verify output) —
    flagged ops get `// !! [severity] pass-id: message` annotations so a
    dump shows WHERE the verifier complained.  `verify=True` runs the
    analyzer itself (never raising) and annotates with its findings.
    """
    if verify and diagnostics is None:
        diagnostics = program.verify(level=None)
    index = _diag_index(diagnostics)
    lines = []
    for block in program.blocks:
        head = f"// block {block.idx}"
        if block.parent_idx >= 0:
            head += f" (parent {block.parent_idx})"
        lines.append(head + " {")
        for d in index.get((block.idx, None), ()):
            lines.append(f"  // !! [{d.severity}] {d.pass_id}: "
                         f"{d.message}")
        if not skip_vars:
            for name in sorted(block.vars):
                lines.append("  " + _var_to_code(block.vars[name]))
            if block.vars and block.ops:
                lines.append("")
        for i, op in enumerate(block.ops):
            lines.append("  " + _op_to_code(op))
            for d in index.get((block.idx, i), ()):
                lines.append(f"    // !! [{d.severity}] {d.pass_id}: "
                             f"{d.message}")
            sub = op.attrs.get("sub_block")
            if sub is not None:
                lines.append(f"    // -> sub_block {sub}")
        lines.append("}")
    return "\n".join(lines)


def print_program(program: Program, **kw) -> None:
    print(program_to_code(program, **kw))


def _dot_id(name: str) -> str:
    return re.sub(r"[^0-9a-zA-Z_]", "_", name)


_SEVERITY_COLORS = {"error": "salmon", "warning": "orange",
                    "info": "khaki"}


def draw_block_graphviz(block, path: Optional[str] = None,
                        highlights: Optional[Set[str]] = None,
                        diagnostics=None) -> str:
    """Emit a graphviz digraph for one block: op nodes (boxes) wired
    through var nodes (ellipses; params shaded).  Returns the .dot text
    and writes it to `path` if given (reference debuger.py
    draw_block_graphviz).

    `diagnostics` (analysis Diagnostic list): ops flagged by the
    verifier are colored by worst severity (error=salmon,
    warning=orange, info=khaki) with the pass ids in the label."""
    highlights = highlights or set()
    diag_by_op = {}
    for d in diagnostics or ():
        if d.block_idx == block.idx and d.op_idx is not None:
            diag_by_op.setdefault(d.op_idx, []).append(d)
    lines = ["digraph G {", "  rankdir=TB;"]
    seen_vars: Set[str] = set()

    def var_node(name):
        if name in seen_vars or not name:
            return
        seen_vars.add(name)
        style = ["shape=ellipse"]
        try:
            v = block.var(name)
        except KeyError:
            v = None
        if isinstance(v, Parameter):
            style.append('style=filled fillcolor="lightgrey"')
        if name in highlights:
            style.append('color="red"')
        label = name
        if v is not None and v.shape is not None:
            label += f"\\n{list(v.shape)}"
        lines.append(f'  var_{_dot_id(name)} [{" ".join(style)} '
                     f'label="{label}"];')

    from .analysis import max_severity

    for i, op in enumerate(block.ops):
        color, label = "lightblue", op.type
        flagged = diag_by_op.get(i)
        if flagged:
            color = _SEVERITY_COLORS[max_severity(flagged)]
            label += "\\n!! " + ",".join(
                sorted({d.pass_id for d in flagged}))
        lines.append(f'  op_{i} [shape=box style=filled '
                     f'fillcolor="{color}" label="{label}"];')
        for names in op.inputs.values():
            for n in names:
                if not n:
                    continue
                var_node(n)
                lines.append(f"  var_{_dot_id(n)} -> op_{i};")
        for names in op.outputs.values():
            for n in names:
                if not n:
                    continue
                var_node(n)
                lines.append(f"  op_{i} -> var_{_dot_id(n)};")
    lines.append("}")
    dot = "\n".join(lines)
    if path:
        with open(path, "w") as f:
            f.write(dot)
    return dot
