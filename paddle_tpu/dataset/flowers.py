"""flowers: 102-category Oxford flowers surface — (3x224x224 float image,
int label).

Reference: /root/reference/python/paddle/v2/dataset/flowers.py
(train/test/valid readers over the tarball + mapper pipeline).  Synthetic
(zero-egress) class-template images with per-sample noise, same reader
contract.
"""
from __future__ import annotations

import numpy as np

from .common import cached, fixed_rng

__all__ = ["train", "test", "valid"]

_CLASSES = 102
_IMG = 3 * 224 * 224
_N = {"train": 512, "test": 128, "valid": 128}


@cached
def _templates():
    r = fixed_rng("flowers")
    # low-res class templates upsampled: keeps memory small but images
    # class-separable like the real data
    small = r.randn(_CLASSES, 3, 8, 8).astype(np.float32)
    return small


def _reader(tag, mapper=None):
    def reader():
        t = _templates()
        r = fixed_rng(f"flowers/{tag}")
        for _ in range(_N[tag]):
            label = int(r.randint(0, _CLASSES))
            img = np.kron(t[label], np.ones((28, 28), np.float32))
            img = img + 0.3 * r.randn(3, 224, 224).astype(np.float32)
            sample = (np.clip(img, -2.0, 2.0).astype(np.float32).ravel(),
                      label)
            yield mapper(sample) if mapper is not None else sample

    return reader


def train(mapper=None, buffered_size=1024, use_xmap=True):
    return _reader("train", mapper)


def test(mapper=None, buffered_size=1024, use_xmap=True):
    return _reader("test", mapper)


def valid(mapper=None, buffered_size=1024, use_xmap=True):
    return _reader("valid", mapper)
