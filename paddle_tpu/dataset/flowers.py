"""flowers: 102-category Oxford flowers — (flattened CHW float image,
int label in [1, 102]).

Reference: /root/reference/python/paddle/v2/dataset/flowers.py —
102flowers.tgz (jpg/image_XXXXX.jpg) + imagelabels.mat (1-based labels)
+ setid.mat split indices; the reference swaps trnid/tstid (tstid is the
larger set, used for training).  Default mapper: resize-short 256,
224-crop (random for train), CHW float32 minus the BGR mean, flattened.
Real corpus under PADDLE_TPU_DATASET=auto|real; synthetic
class-template fallback offline.
"""
from __future__ import annotations

import functools
import tarfile

import numpy as np

from . import common
from .common import cached, fixed_rng

__all__ = ["train", "test", "valid", "reader_creator"]

DATA_URL = ("http://www.robots.ox.ac.uk/~vgg/data/flowers/102/"
            "102flowers.tgz")
LABEL_URL = ("http://www.robots.ox.ac.uk/~vgg/data/flowers/102/"
             "imagelabels.mat")
SETID_URL = ("http://www.robots.ox.ac.uk/~vgg/data/flowers/102/"
             "setid.mat")
DATA_MD5 = "33bfc11892f1e405ca193ae9a9f2a118"
LABEL_MD5 = "e0620be6f572b9609742df49c70aed4d"
SETID_MD5 = "a5357ecc9cb78c4bef273ce3793fc85c"
# official readme calls tstid test, but tstid is the larger split — the
# reference swaps them so training has more images
TRAIN_FLAG = "tstid"
TEST_FLAG = "trnid"
VALID_FLAG = "valid"

_CLASSES = 102
_N = {"train": 512, "test": 128, "valid": 128}  # synthetic sizes


def default_mapper(is_train, sample):
    from .. import image

    img_bytes, label = sample
    img = image.load_image_bytes(img_bytes)
    img = image.simple_transform(img, 256, 224, is_train,
                                 mean=[103.94, 116.78, 123.68])
    return img.flatten().astype("float32"), label


train_mapper = functools.partial(default_mapper, True)
test_mapper = functools.partial(default_mapper, False)


def reader_creator(data_file, label_file, setid_file, dataset_name,
                   mapper):
    """Real parser: yields mapper((jpg bytes, 1-based label)) for every
    image index in setid.mat[dataset_name]."""
    import scipy.io as scio

    labels = scio.loadmat(label_file)["labels"][0]
    indexes = scio.loadmat(setid_file)[dataset_name][0]

    def reader():
        with tarfile.open(data_file) as tf:
            members = {m.name: m for m in tf.getmembers()}
            for idx in indexes:
                name = f"jpg/image_{int(idx):05d}.jpg"
                data = tf.extractfile(members[name]).read()
                sample = (data, int(labels[int(idx) - 1]))
                yield mapper(sample) if mapper is not None else sample

    return reader


def _fetch():
    return (common.download(DATA_URL, "flowers", DATA_MD5),
            common.download(LABEL_URL, "flowers", LABEL_MD5),
            common.download(SETID_URL, "flowers", SETID_MD5))


# -- synthetic fallback ------------------------------------------------------


@cached
def _templates():
    r = fixed_rng("flowers")
    # low-res class templates upsampled: keeps memory small but images
    # class-separable like the real data
    return r.randn(_CLASSES, 3, 8, 8).astype(np.float32)


def _synthetic_reader(tag, mapper):
    # synthetic samples are already decoded flat float images, so the
    # jpeg-decoding DEFAULT mappers don't apply — but a user-supplied
    # mapper still does (same contract as the real path)
    apply = mapper if mapper not in (None, train_mapper, test_mapper) \
        else None

    def reader():
        t = _templates()
        r = fixed_rng(f"flowers/{tag}")
        for _ in range(_N[tag]):
            label = int(r.randint(0, _CLASSES))
            img = np.kron(t[label], np.ones((28, 28), np.float32))
            img = img + 0.3 * r.randn(3, 224, 224).astype(np.float32)
            sample = (np.clip(img, -2.0, 2.0).astype(np.float32).ravel(),
                      label)
            yield apply(sample) if apply is not None else sample

    return reader


def _make(tag, flag, mapper):
    paths = common.fetch_real("flowers", _fetch)
    if paths is None:
        return _synthetic_reader(tag, mapper)
    return reader_creator(*paths, flag, mapper)


def train(mapper=train_mapper, buffered_size=1024, use_xmap=True):
    return _make("train", TRAIN_FLAG, mapper)


def test(mapper=test_mapper, buffered_size=1024, use_xmap=True):
    return _make("test", TEST_FLAG, mapper)


def valid(mapper=test_mapper, buffered_size=1024, use_xmap=True):
    return _make("valid", VALID_FLAG, mapper)
