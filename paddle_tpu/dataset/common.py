"""Shared synthetic-data helpers for the dataset package."""
from __future__ import annotations

import zlib

import numpy as np

__all__ = ["fixed_rng", "cached"]


def fixed_rng(tag: str) -> np.random.RandomState:
    """Deterministic per-dataset RNG (stable across processes/runs)."""
    return np.random.RandomState(zlib.crc32(tag.encode()) & 0x7FFFFFFF)


def cached(fn):
    """Memoize a dataset builder on its (hashable) arguments."""
    store = {}

    def wrapper(*args, **kwargs):
        k = (args, tuple(sorted(kwargs.items())))
        if k not in store:
            store[k] = fn(*args, **kwargs)
        return store[k]

    return wrapper
