"""Dataset acquisition machinery + synthetic-data helpers.

Reference: /root/reference/python/paddle/v2/dataset/common.py (md5file :43,
download :62 — cache under DATA_HOME/<module>/, verify md5, retry up to 3;
split :151, cluster_files_reader :184).

Real corpora are downloaded, md5-verified and cached exactly like the
reference.  Because this stack must also run in zero-egress CI, every
dataset module keeps a deterministic SYNTHETIC generator with the same
schema, selected by ``PADDLE_TPU_DATASET``:

  * ``auto`` (default) — use the cached/downloaded real corpus; if the
    download fails (offline), warn once and serve synthetic data.
  * ``real`` — real data or raise.
  * ``synthetic`` — never touch the network.
"""
from __future__ import annotations

import hashlib
import os
import shutil
import sys
import threading as _threading
import urllib.request
import zlib

import numpy as np

__all__ = ["DATA_HOME", "data_home", "md5file", "download", "data_mode",
           "fetch_real", "fixed_rng", "cached", "split",
           "cluster_files_reader"]

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))


def data_home() -> str:
    """DATA_HOME, env-overridable per call (tests point it at a tmpdir)."""
    return os.path.expanduser(
        os.environ.get("PADDLE_TPU_DATA_HOME", DATA_HOME))


def md5file(fname: str) -> str:
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 16), b""):
            h.update(chunk)
    return h.hexdigest()


def download(url: str, module_name: str, md5sum: str,
             save_name: str = None, retry_policy=None) -> str:
    """Fetch `url` into DATA_HOME/<module_name>/, verify md5, return the
    local path.  A cached file with the right md5 short-circuits; corrupt
    or missing files are re-fetched under an exponential-backoff
    RetryPolicy (3 attempts by default; tune via
    PADDLE_TPU_DOWNLOAD_RETRY_* env vars) instead of hammering the
    mirror with immediate re-downloads."""
    from ..core.resilience import RetryPolicy, fault_injector

    dirname = os.path.join(data_home(), module_name)
    os.makedirs(dirname, exist_ok=True)
    filename = os.path.join(
        dirname, save_name if save_name else url.split("/")[-1])

    if os.path.exists(filename) and md5file(filename) == md5sum:
        return filename  # cached and valid: hashed exactly once

    policy = retry_policy or RetryPolicy.from_env(
        "DOWNLOAD_RETRY", max_attempts=3, base_delay=1.0, max_delay=30.0,
        deadline=600.0)
    state = policy.begin()
    while True:
        if _cache_only():
            raise RuntimeError(f"{filename} is not cached and downloads "
                               "are disabled (offline fallback probe)")
        try:
            fault_injector().fire("dataset.download")
            sys.stderr.write(f"Cache file {filename} not found, "
                             f"downloading {url}\n")
            tmp = filename + ".part"
            with urllib.request.urlopen(url, timeout=30) as r, \
                    open(tmp, "wb") as f:
                shutil.copyfileobj(r, f)
            os.replace(tmp, filename)
            got = md5file(filename)
            if got != md5sum:
                raise IOError(f"md5 mismatch for {filename}: got {got}, "
                              f"want {md5sum}")
            return filename
        except Exception as e:
            state.record(e, what=f"Cannot download {url}")
            state.sleep()


def data_mode() -> str:
    mode = os.environ.get("PADDLE_TPU_DATASET", "auto").lower()
    if mode not in ("auto", "real", "synthetic"):
        raise ValueError(f"PADDLE_TPU_DATASET={mode!r}: expected "
                         "auto|real|synthetic")
    return mode


_offline_warned: set = set()
# Thread-local: download() raises instead of fetching when set.  Must be
# per-thread, not module-global — reader prefetch threads (xmap_readers /
# native_pipeline) can load datasets concurrently, and one call's
# cache-only window must not make another thread's first-time download
# raise and silently degrade to synthetic data.
_CACHE_ONLY = _threading.local()


def _cache_only() -> bool:
    return getattr(_CACHE_ONLY, "flag", False)


def fetch_real(module_name: str, fetch_fn):
    """Run `fetch_fn` (downloads, returns paths) under the dataset-mode
    policy.  Returns its result, or None meaning "serve synthetic".  In
    `auto` mode a failed download warns once per module; subsequent calls
    for that module still consult the on-disk cache (download()'s md5
    short-circuit) but never retry the network."""
    mode = data_mode()
    if mode == "synthetic":
        return None
    if mode == "auto" and module_name in _offline_warned:
        # a previous download failed — serve already-cached files if the
        # fetch can complete from disk alone, else fall back quietly
        try:
            _CACHE_ONLY.flag = True
            return fetch_fn()
        except Exception:
            return None
        finally:
            _CACHE_ONLY.flag = False
    try:
        return fetch_fn()
    except Exception as e:
        if mode == "real":
            raise
        if module_name not in _offline_warned:
            _offline_warned.add(module_name)
            sys.stderr.write(
                f"paddle_tpu.dataset.{module_name}: download failed "
                f"({type(e).__name__}: {e}); serving synthetic data. "
                "Set PADDLE_TPU_DATASET=real to require the corpus.\n")
        return None


# ---------------------------------------------------------------------------
# synthetic helpers (zero-egress fallback generators)
# ---------------------------------------------------------------------------


def fixed_rng(tag: str) -> np.random.RandomState:
    """Deterministic per-dataset RNG (stable across processes/runs)."""
    return np.random.RandomState(zlib.crc32(tag.encode()) & 0x7FFFFFFF)


def cached(fn):
    """Memoize a dataset builder on its (hashable) arguments."""
    store = {}

    def wrapper(*args, **kwargs):
        k = (args, tuple(sorted(kwargs.items())))
        if k not in store:
            store[k] = fn(*args, **kwargs)
        return store[k]

    return wrapper


# ---------------------------------------------------------------------------
# cluster helpers (reference common.py split/cluster_files_reader)
# ---------------------------------------------------------------------------


def split(reader, line_count, suffix="%05d.pickle", dumper=None):
    """Materialize `reader` into numbered chunk files of `line_count`
    samples each; returns the number of files written."""
    import pickle

    dumper = dumper or pickle.dump
    lines = []
    index = 0
    for sample in reader():
        lines.append(sample)
        if len(lines) == line_count:
            with open(suffix % index, "wb") as f:
                dumper(lines, f)
            lines = []
            index += 1
    if lines:
        with open(suffix % index, "wb") as f:
            dumper(lines, f)
        index += 1
    return index


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=None):
    """Reader over this trainer's round-robin shard of chunk files."""
    import glob
    import pickle

    loader = loader or pickle.load

    def reader():
        file_list = sorted(glob.glob(files_pattern))
        for idx, fn in enumerate(file_list):
            if idx % trainer_count == trainer_id:
                with open(fn, "rb") as f:
                    for sample in loader(f):
                        yield sample

    return reader
