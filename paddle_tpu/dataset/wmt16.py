"""wmt16: Multi30k-style en<->de translation surface — (src_ids,
trg_ids, trg_ids_next) with <s>/<e>/<unk> conventions.

Reference: /root/reference/python/paddle/v2/dataset/wmt16.py
(train/test/validation parameterized by dict sizes + get_dict).
Synthetic (zero-egress): source sentences are random token streams and
the "translation" is a deterministic per-token mapping with a length
change, so seq2seq models can learn it.
"""
from __future__ import annotations

import numpy as np

from .common import fixed_rng

__all__ = ["train", "test", "validation", "get_dict"]

_N = {"train": 2048, "test": 256, "validation": 256}

# special ids, reference wmt16.py: <s>=0, <e>=1, <unk>=2
START_ID, END_ID, UNK_ID = 0, 1, 2
_RESERVED = 3


def _clip_size(n):
    return max(int(n), _RESERVED + 2)


def _translate(tokens, trg_dict_size):
    # deterministic affine token mapping into the target vocab
    return [(_RESERVED + (7 * t + 3) % (trg_dict_size - _RESERVED))
            for t in tokens]


def _reader(tag, src_dict_size, trg_dict_size, src_lang):
    src_dict_size = _clip_size(src_dict_size)
    trg_dict_size = _clip_size(trg_dict_size)

    def reader():
        r = fixed_rng(f"wmt16/{tag}/{src_lang}")
        for _ in range(_N[tag]):
            n = int(r.randint(3, 12))
            src = r.randint(_RESERVED, src_dict_size, n).tolist()
            trg = _translate(src, trg_dict_size)
            src_ids = [START_ID] + src + [END_ID]
            trg_ids = [START_ID] + trg
            trg_next = trg + [END_ID]
            yield src_ids, trg_ids, trg_next

    return reader


def train(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader("train", src_dict_size, trg_dict_size, src_lang)


def test(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader("test", src_dict_size, trg_dict_size, src_lang)


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader("validation", src_dict_size, trg_dict_size, src_lang)


def get_dict(lang, dict_size, reverse=False):
    """id<->token table; synthetic tokens are '<lang>_<id>'."""
    dict_size = _clip_size(dict_size)
    words = {START_ID: "<s>", END_ID: "<e>", UNK_ID: "<unk>"}
    for i in range(_RESERVED, dict_size):
        words[i] = f"{lang}_{i}"
    if reverse:
        return {w: i for i, w in words.items()}
    return words
