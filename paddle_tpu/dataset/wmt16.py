"""wmt16: Multi30k-style en<->de translation — (src_ids, trg_ids,
trg_ids_next) with <s>/<e>/<unk> conventions.

Reference: /root/reference/python/paddle/v2/dataset/wmt16.py — a tarball
whose wmt16/{train,val,test} members hold tab-separated "en\tde" lines;
dicts are built from the train split ordered by frequency, written to
DATA_HOME/wmt16/<lang>_<size>.dict with the three specials first, then
reused.  Real corpus under PADDLE_TPU_DATASET=auto|real; deterministic
affine-mapping synthetic fallback offline.
"""
from __future__ import annotations

import os
from collections import defaultdict

from . import common
from .common import fixed_rng

__all__ = ["train", "test", "validation", "get_dict", "fetch"]

DATA_URL = ("http://paddlepaddle.cdn.bcebos.com/demo/wmt_shrinked_data/"
            "wmt16.tar.gz")
DATA_MD5 = "0c38be43600334966403524a40dcd81e"

TOTAL_EN_WORDS = 11250
TOTAL_DE_WORDS = 19220

START_MARK = "<s>"
END_MARK = "<e>"
UNK_MARK = "<unk>"

# special ids: <s>=0, <e>=1, <unk>=2 (dict files list them first)
START_ID, END_ID, UNK_ID = 0, 1, 2
_RESERVED = 3

_N = {"train": 2048, "test": 256, "validation": 256}  # synthetic sizes


def _build_dict(tar_file, dict_size, save_path, lang):
    import tarfile

    word_freq = defaultdict(int)
    with tarfile.open(tar_file, mode="r") as f:
        for line in f.extractfile("wmt16/train"):
            parts = line.decode("utf-8", errors="replace").strip() \
                .split("\t")
            if len(parts) != 2:
                continue
            sen = parts[0] if lang == "en" else parts[1]
            for w in sen.split():
                word_freq[w] += 1
    with open(save_path, "w") as fout:
        fout.write(f"{START_MARK}\n{END_MARK}\n{UNK_MARK}\n")
        for idx, (word, _) in enumerate(
                sorted(word_freq.items(), key=lambda x: x[1],
                       reverse=True)):
            if idx + _RESERVED == dict_size:
                break
            fout.write(word + "\n")


def _load_dict(tar_file, dict_size, lang, reverse=False):
    dict_dir = os.path.join(common.data_home(), "wmt16")
    os.makedirs(dict_dir, exist_ok=True)
    dict_path = os.path.join(dict_dir, f"{lang}_{dict_size}.dict")
    # the file name encodes (lang, dict_size), so an existing file is
    # authoritative — it may legitimately hold FEWER lines than dict_size
    # when the corpus vocab (+3 specials) is smaller; rebuilding on a
    # count mismatch would rescan the train split every call
    if not os.path.exists(dict_path):
        _build_dict(tar_file, dict_size, dict_path, lang)
    word_dict = {}
    with open(dict_path) as fdict:
        for idx, line in enumerate(fdict):
            if reverse:
                word_dict[idx] = line.strip()
            else:
                word_dict[line.strip()] = idx
    return word_dict


def _clip_size(n, lang="en"):
    total = TOTAL_EN_WORDS if lang == "en" else TOTAL_DE_WORDS
    return min(max(int(n), _RESERVED + 2), total)


def reader_creator(tar_file, file_name, src_dict_size, trg_dict_size,
                   src_lang):
    """Yield (src_ids incl. <s>/<e>, trg_ids with leading <s>,
    trg_ids_next with trailing <e>) per tab-separated line."""

    # dicts load once per creator, not once per epoch
    src_dict = _load_dict(tar_file, src_dict_size, src_lang)
    trg_dict = _load_dict(tar_file, trg_dict_size,
                          "de" if src_lang == "en" else "en")
    src_col = 0 if src_lang == "en" else 1

    def reader():
        import tarfile

        with tarfile.open(tar_file, mode="r") as f:
            for line in f.extractfile(file_name):
                parts = line.decode("utf-8", errors="replace").strip() \
                    .split("\t")
                if len(parts) != 2:
                    continue
                src_ids = [START_ID] + [
                    src_dict.get(w, UNK_ID)
                    for w in parts[src_col].split()] + [END_ID]
                trg_raw = [trg_dict.get(w, UNK_ID)
                           for w in parts[1 - src_col].split()]
                yield (src_ids, [START_ID] + trg_raw, trg_raw + [END_ID])

    return reader


def fetch():
    return common.download(DATA_URL, "wmt16", DATA_MD5, "wmt16.tar.gz")


# -- synthetic fallback ------------------------------------------------------


def _translate(tokens, trg_dict_size):
    # deterministic affine token mapping into the target vocab
    return [(_RESERVED + (7 * t + 3) % (trg_dict_size - _RESERVED))
            for t in tokens]


def _synthetic_reader(tag, src_dict_size, trg_dict_size, src_lang):
    def reader():
        r = fixed_rng(f"wmt16/{tag}/{src_lang}")
        for _ in range(_N[tag]):
            n = int(r.randint(3, 12))
            src = r.randint(_RESERVED, src_dict_size, n).tolist()
            trg = _translate(src, trg_dict_size)
            yield ([START_ID] + src + [END_ID], [START_ID] + trg,
                   trg + [END_ID])

    return reader


def _make(tag, file_name, src_dict_size, trg_dict_size, src_lang):
    src_dict_size = _clip_size(src_dict_size, src_lang)
    trg_dict_size = _clip_size(trg_dict_size,
                               "de" if src_lang == "en" else "en")
    tar = common.fetch_real("wmt16", fetch)
    if tar is None:
        return _synthetic_reader(tag, src_dict_size, trg_dict_size,
                                 src_lang)
    return reader_creator(tar, f"wmt16/{file_name}", src_dict_size,
                          trg_dict_size, src_lang)


def train(src_dict_size, trg_dict_size, src_lang="en"):
    return _make("train", "train", src_dict_size, trg_dict_size, src_lang)


def test(src_dict_size, trg_dict_size, src_lang="en"):
    return _make("test", "test", src_dict_size, trg_dict_size, src_lang)


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    return _make("validation", "val", src_dict_size, trg_dict_size,
                 src_lang)


def get_dict(lang, dict_size, reverse=False):
    """id<->token table.  Real mode loads/builds the cached dict file;
    synthetic tokens are '<lang>_<id>'."""
    dict_size = _clip_size(dict_size, lang)
    tar = common.fetch_real("wmt16", fetch)
    if tar is not None:
        return _load_dict(tar, dict_size, lang, reverse)
    words = {START_ID: "<s>", END_ID: "<e>", UNK_ID: "<unk>"}
    for i in range(_RESERVED, dict_size):
        words[i] = f"{lang}_{i}"
    if reverse:
        return words
    return {w: i for i, w in words.items()}
