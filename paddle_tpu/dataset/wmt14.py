"""wmt14: (src ids, trg ids, trg_next ids) translation triples.

Reference: /root/reference/python/paddle/v2/dataset/wmt14.py (train/test
readers over a bpe-ish dict with <s>=0, <e>=1, <unk>=2).  Synthetic copy
task: target = source shifted into the target id space.
"""
from __future__ import annotations

from .common import fixed_rng

__all__ = ["train", "test", "start_id", "end_id", "unk_id"]

start_id, end_id, unk_id = 0, 1, 2


def _reader(tag, n, dict_size):
    def reader():
        r = fixed_rng("wmt14/" + tag)
        for _ in range(n):
            ln = int(r.randint(3, 10))
            src = [int(w) for w in r.randint(3, dict_size, ln)]
            trg = src  # copy task keeps convergence measurable
            yield src, [start_id] + trg, trg + [end_id]

    return reader


def train(dict_size):
    return _reader("train", 1024, dict_size)


def test(dict_size):
    return _reader("test", 256, dict_size)
