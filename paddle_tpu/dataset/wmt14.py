"""wmt14: (src ids, trg ids, trg_next ids) translation triples.

Reference: /root/reference/python/paddle/v2/dataset/wmt14.py — a shrunk
tarball whose members end in ``src.dict`` / ``trg.dict`` (one token per
line, first `dict_size` lines kept; <s>=0, <e>=1, <unk>=2) and
``train/train`` / ``test/test`` tab-separated parallel text; sequences
longer than 80 tokens are dropped.  Real corpus under
PADDLE_TPU_DATASET=auto|real; synthetic copy-task fallback offline.
"""
from __future__ import annotations

import tarfile

from . import common
from .common import fixed_rng

__all__ = ["train", "test", "get_dict", "reader_creator", "fetch",
           "start_id", "end_id", "unk_id"]

URL_TRAIN = ("http://paddlepaddle.cdn.bcebos.com/demo/"
             "wmt_shrinked_data/wmt14.tgz")
MD5_TRAIN = "0791583d57d5beb693b9414c5b36798c"

START = "<s>"
END = "<e>"
UNK = "<unk>"
start_id, end_id, unk_id = 0, 1, 2
UNK_IDX = unk_id
MAX_LEN = 80


def read_dicts(tar_file, dict_size):
    """(src_dict, trg_dict): first `dict_size` lines of the members
    ending in src.dict / trg.dict, token -> line number."""

    def to_dict(fd, size):
        out = {}
        for i, line in enumerate(fd):
            if i >= size:
                break
            out[line.decode("utf-8", errors="replace").strip()] = i
        return out

    with tarfile.open(tar_file, mode="r") as f:
        src_names = [m.name for m in f if m.name.endswith("src.dict")]
        trg_names = [m.name for m in f if m.name.endswith("trg.dict")]
        assert len(src_names) == 1 and len(trg_names) == 1, \
            (src_names, trg_names)
        src_dict = to_dict(f.extractfile(src_names[0]), dict_size)
        trg_dict = to_dict(f.extractfile(trg_names[0]), dict_size)
    return src_dict, trg_dict


def reader_creator(tar_file, file_name, dict_size):
    def reader():
        src_dict, trg_dict = read_dicts(tar_file, dict_size)
        with tarfile.open(tar_file, mode="r") as f:
            names = [m.name for m in f if m.name.endswith(file_name)]
            for name in names:
                for line in f.extractfile(name):
                    parts = line.decode("utf-8", errors="replace") \
                        .strip().split("\t")
                    if len(parts) != 2:
                        continue
                    src_ids = [src_dict.get(w, UNK_IDX)
                               for w in [START] + parts[0].split() +
                               [END]]
                    trg_ids = [trg_dict.get(w, UNK_IDX)
                               for w in parts[1].split()]
                    if len(src_ids) > MAX_LEN or len(trg_ids) > MAX_LEN:
                        continue
                    yield (src_ids, [trg_dict[START]] + trg_ids,
                           trg_ids + [trg_dict[END]])

    return reader


def fetch():
    return common.download(URL_TRAIN, "wmt14", MD5_TRAIN)


def get_dict(dict_size, reverse=False):
    """(src_dict, trg_dict); reverse=True returns id -> token tables
    (reference wmt14.py get_dict)."""
    tar = common.fetch_real("wmt14", fetch)
    if tar is None:
        words = {START: start_id, END: end_id, UNK: unk_id}
        for i in range(3, dict_size):
            words[f"w{i}"] = i
        d = ({i: w for w, i in words.items()} if reverse else words)
        return d, dict(d)
    src_dict, trg_dict = read_dicts(tar, dict_size)
    if reverse:
        src_dict = {i: w for w, i in src_dict.items()}
        trg_dict = {i: w for w, i in trg_dict.items()}
    return src_dict, trg_dict


# -- synthetic fallback ------------------------------------------------------


def _synthetic_reader(tag, n, dict_size):
    def reader():
        r = fixed_rng("wmt14/" + tag)
        for _ in range(n):
            ln = int(r.randint(3, 10))
            src = [int(w) for w in r.randint(3, dict_size, ln)]
            trg = src  # copy task keeps convergence measurable
            yield src, [start_id] + trg, trg + [end_id]

    return reader


def _make(tag, file_name, n_synth, dict_size):
    tar = common.fetch_real("wmt14", fetch)
    if tar is None:
        return _synthetic_reader(tag, n_synth, dict_size)
    return reader_creator(tar, file_name, dict_size)


def train(dict_size):
    return _make("train", "train/train", 1024, dict_size)


def test(dict_size):
    return _make("test", "test/test", 256, dict_size)
