"""diabetes: REAL regression corpus, available fully offline.

The Efron et al. diabetes study — 442 real patients, 10 standardized
physiological/serum features, disease-progression target — ships inside
scikit-learn (`sklearn.datasets.load_diabetes`), so it needs no egress.
It is this repo's offline `data: real` stand-in for the reference's
fit-a-line corpus (uci_housing.py downloads housing.data when the
network allows; reference python/paddle/v2/dataset/uci_housing.py).

Samples follow the uci_housing convention: (features float32 [10],
target float32 [1]); the target is standardized to zero mean / unit
variance over the TRAIN split so an mse threshold reads as a fraction
of target variance.  Deterministic 80/20 split.
"""
from __future__ import annotations

import numpy as np

from .common import cached

__all__ = ["train", "test", "load_data", "feature_dim"]

feature_dim = 10


@cached
def load_data():
    from sklearn.datasets import load_diabetes

    d = load_diabetes()
    # sklearn ships columns scaled to unit NORM (variance ~1/n);
    # restandardize to unit variance so SGD steps are well-conditioned
    x = d.data.astype(np.float32)
    y = d.target.astype(np.float32)[:, None]
    idx = np.random.RandomState(42).permutation(len(y))
    x, y = x[idx], y[idx]
    n_train = int(len(y) * 0.8)
    x = (x - x[:n_train].mean(0)) / x[:n_train].std(0)
    mu, sd = y[:n_train].mean(), y[:n_train].std()
    y = (y - mu) / sd
    return (x[:n_train], y[:n_train]), (x[n_train:], y[n_train:])


def _reader(part):
    def reader():
        xs, ys = load_data()[part]
        for i in range(len(ys)):
            yield xs[i], ys[i]

    return reader


def train():
    """353 real patient rows as (features[10], standardized target[1])."""
    return _reader(0)


def test():
    """89 held-out rows."""
    return _reader(1)
