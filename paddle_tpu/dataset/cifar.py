"""CIFAR-10/100: 3072 floats (3x32x32) in [0, 1] -> int label.

Reference: /root/reference/python/paddle/v2/dataset/cifar.py — downloads
the python-pickle tarballs from cs.toronto.edu, yields
(sample/255 float32[3072], int label) batch-file by batch-file.
Real corpus under PADDLE_TPU_DATASET=auto|real; synthetic fallback
matches the [0, 1] range.
"""
from __future__ import annotations

import pickle
import tarfile

import numpy as np

from . import common
from .common import cached, fixed_rng

__all__ = ["train10", "test10", "train100", "test100", "reader_creator",
           "fetch"]

URL_PREFIX = "https://www.cs.toronto.edu/~kriz/"
CIFAR10_URL = URL_PREFIX + "cifar-10-python.tar.gz"
CIFAR10_MD5 = "c58f30108f718f92721af3b95e74349a"
CIFAR100_URL = URL_PREFIX + "cifar-100-python.tar.gz"
CIFAR100_MD5 = "eb9058c3a382ffc7106e4002c42a8d85"

_N_TRAIN, _N_TEST = 1024, 256  # synthetic-fallback sizes


def reader_creator(filename, sub_name):
    """Real parser: members of the tarball whose name contains `sub_name`
    are python pickles holding {'data': uint8 [N, 3072], 'labels' or
    'fine_labels': [N]}; yields (data/255 float32, int label)."""

    def read_batch(batch):
        data = batch[b"data"]
        labels = batch.get(b"labels", batch.get(b"fine_labels"))
        assert labels is not None, "batch has neither labels nor fine_labels"
        for sample, label in zip(data, labels):
            yield (sample / 255.0).astype(np.float32), int(label)

    def reader():
        with tarfile.open(filename, mode="r") as f:
            names = sorted(m.name for m in f if sub_name in m.name)
            for name in names:
                batch = pickle.load(f.extractfile(name), encoding="bytes")
                yield from read_batch(batch)

    return reader


def fetch():
    common.download(CIFAR10_URL, "cifar", CIFAR10_MD5)
    common.download(CIFAR100_URL, "cifar", CIFAR100_MD5)


# -- synthetic fallback ------------------------------------------------------


@cached
def _templates():
    r = fixed_rng("cifar")
    return r.rand(100, 3072).astype(np.float32)


def _synthetic_reader(tag, n, num_classes):
    def reader():
        t = _templates()
        r = fixed_rng(f"cifar/{tag}/{num_classes}")
        for _ in range(n):
            label = int(r.randint(0, num_classes))
            img = t[label] + 0.25 * r.randn(3072).astype(np.float32)
            yield np.clip(img, 0.0, 1.0).astype(np.float32), label

    return reader


def _make(url, md5, sub_name, tag, n_synth, num_classes):
    path = common.fetch_real("cifar",
                             lambda: common.download(url, "cifar", md5))
    if path is None:
        return _synthetic_reader(tag, n_synth, num_classes)
    return reader_creator(path, sub_name)


def train10():
    return _make(CIFAR10_URL, CIFAR10_MD5, "data_batch", "train",
                 _N_TRAIN, 10)


def test10():
    return _make(CIFAR10_URL, CIFAR10_MD5, "test_batch", "test",
                 _N_TEST, 10)


def train100():
    return _make(CIFAR100_URL, CIFAR100_MD5, "train", "train",
                 _N_TRAIN, 100)


def test100():
    return _make(CIFAR100_URL, CIFAR100_MD5, "test", "test",
                 _N_TEST, 100)
