"""cifar: 3072 floats (3x32x32) -> int label; cifar10 + cifar100 surfaces.

Reference: /root/reference/python/paddle/v2/dataset/cifar.py.
"""
from __future__ import annotations

import numpy as np

from .common import cached, fixed_rng

__all__ = ["train10", "test10", "train100", "test100"]

_N_TRAIN, _N_TEST = 1024, 256


@cached
def _templates():
    r = fixed_rng("cifar")
    return r.randn(100, 3072).astype(np.float32)


def _reader(tag, n, num_classes):
    def reader():
        t = _templates()
        r = fixed_rng(f"cifar/{tag}/{num_classes}")
        for _ in range(n):
            label = int(r.randint(0, num_classes))
            img = t[label] + 0.5 * r.randn(3072).astype(np.float32)
            yield np.clip(img, -1.0, 1.0).astype(np.float32), label

    return reader


def train10():
    return _reader("train", _N_TRAIN, 10)


def test10():
    return _reader("test", _N_TEST, 10)


def train100():
    return _reader("train", _N_TRAIN, 100)


def test100():
    return _reader("test", _N_TEST, 100)
