"""sentiment (NLTK movie_reviews): word-id sequence -> 0/1 polarity
(neg=0, pos=1).

Reference: /root/reference/python/paddle/v2/dataset/sentiment.py — the
nltk movie_reviews corpus (downloaded into DATA_HOME), a frequency-
sorted word dict over the whole corpus, neg/pos files interleaved, the
first 1600 samples as train and the last 400 as test.  Real corpus
under PADDLE_TPU_DATASET=auto|real (also served when the corpus is
already cached in DATA_HOME or on nltk's default path); synthetic
half-vocab fallback offline.
"""
from __future__ import annotations

import collections
from itertools import chain

from . import common
from .common import cached, fixed_rng

__all__ = ["get_word_dict", "train", "test"]

NUM_TRAINING_INSTANCES = 1600
NUM_TOTAL_INSTANCES = 2000

_VOCAB = 3000  # synthetic vocab


def _movie_reviews():
    """The nltk movie_reviews corpus reader, or None offline (download
    lands in DATA_HOME like every other corpus here)."""

    def fetch():
        import nltk
        from nltk.corpus import movie_reviews

        home = common.data_home()
        if home not in nltk.data.path:
            nltk.data.path.append(home)
        try:
            movie_reviews.categories()
        except LookupError:
            if not nltk.download("movie_reviews", download_dir=home,
                                 quiet=True):
                raise RuntimeError("nltk movie_reviews download failed")
            movie_reviews.categories()
        return movie_reviews

    return common.fetch_real("sentiment", fetch)


@cached
def _real_data():
    movie_reviews = _movie_reviews()
    if movie_reviews is None:
        return None
    word_freq = collections.defaultdict(int)
    for category in movie_reviews.categories():
        for fid in movie_reviews.fileids(category):
            for w in movie_reviews.words(fid):
                word_freq[w.lower()] += 1
    # frequency-sorted dict (ties by word for reproducibility; the
    # reference's py2 sort left ties unspecified)
    ranked = sorted(word_freq.items(), key=lambda kv: (-kv[1], kv[0]))
    word_dict = {w: i for i, (w, _) in enumerate(ranked)}
    # interleave neg/pos files (reference sort_files)
    files = list(chain.from_iterable(
        zip(movie_reviews.fileids("neg"), movie_reviews.fileids("pos"))))
    data = []
    for fid in files:
        label = 0 if "neg" in fid else 1
        data.append(([word_dict[w.lower()]
                      for w in movie_reviews.words(fid)], label))
    return word_dict, data


# -- synthetic fallback ------------------------------------------------------


def _synthetic_dict():
    return {f"w{i}": i for i in range(_VOCAB)}


def _synthetic_reader(tag, n):
    def reader():
        r = fixed_rng("sentiment/" + tag)
        half = _VOCAB // 2
        for _ in range(n):
            label = int(r.randint(0, 2))
            ln = int(r.randint(10, 50))
            lo, hi = (0, half) if label == 0 else (half, _VOCAB)
            yield [int(t) for t in r.randint(lo, hi, ln)], label

    return reader


# -- public surface ----------------------------------------------------------


def get_word_dict():
    real = _real_data()
    return _synthetic_dict() if real is None else real[0]


def train():
    real = _real_data()
    if real is None:
        return _synthetic_reader("train", 1024)

    def reader():
        yield from real[1][:NUM_TRAINING_INSTANCES]

    return reader


def test():
    real = _real_data()
    if real is None:
        return _synthetic_reader("test", 256)

    def reader():
        yield from real[1][NUM_TRAINING_INSTANCES:NUM_TOTAL_INSTANCES]

    return reader
