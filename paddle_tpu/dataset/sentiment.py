"""sentiment (movie reviews): word-id sequence -> 0/1 polarity.

Reference: /root/reference/python/paddle/v2/dataset/sentiment.py
(NLTK movie_reviews based).
"""
from __future__ import annotations

from .common import cached, fixed_rng

__all__ = ["get_word_dict", "train", "test"]

_VOCAB = 3000


@cached
def get_word_dict():
    return {f"w{i}": i for i in range(_VOCAB)}


def _reader(tag, n):
    def reader():
        r = fixed_rng("sentiment/" + tag)
        half = _VOCAB // 2
        for _ in range(n):
            label = int(r.randint(0, 2))
            ln = int(r.randint(10, 50))
            lo, hi = (0, half) if label == 0 else (half, _VOCAB)
            yield [int(t) for t in r.randint(lo, hi, ln)], label

    return reader


def train():
    return _reader("train", 1024)


def test():
    return _reader("test", 256)
