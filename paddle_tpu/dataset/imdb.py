"""IMDB sentiment: variable-length word-id sequence -> label (pos=0, neg=1).

Reference: /root/reference/python/paddle/v2/dataset/imdb.py — streams the
aclImdb_v1 tarball, ad-hoc tokenization (strip punctuation, lowercase,
split), build_dict(pattern, cutoff) ordered by (-freq, word) with a
trailing <unk>.  Real corpus under PADDLE_TPU_DATASET=auto|real;
synthetic half-vocab fallback offline.
"""
from __future__ import annotations

import re
import string
import tarfile

from . import common
from .common import cached, fixed_rng

__all__ = ["build_dict", "word_dict", "train", "test", "tokenize", "fetch"]

URL = "https://ai.stanford.edu/~amaas/data/sentiment/aclImdb_v1.tar.gz"
MD5 = "7c2ac02c03563afcf9b574c7e56c153a"

_VOCAB = 5148  # synthetic-fallback vocab size

_PUNCT_TABLE = str.maketrans("", "", string.punctuation)


def tokenize(pattern, tar_path=None):
    """Yield one token list per tar member whose name matches `pattern`
    (sequential tar scan — extractfile-by-name random access thrashes)."""
    tar_path = tar_path or common.download(URL, "imdb", MD5)
    with tarfile.open(tar_path) as tarf:
        tf = tarf.next()
        while tf is not None:
            if bool(pattern.match(tf.name)):
                text = tarf.extractfile(tf).read().decode(
                    "utf-8", errors="replace")
                yield (text.rstrip("\n\r").translate(_PUNCT_TABLE)
                       .lower().split())
            tf = tarf.next()


def build_dict(pattern, cutoff, tar_path=None):
    """Word -> zero-based id, most-frequent first (ties alphabetical),
    words with freq <= cutoff dropped, '<unk>' appended last."""
    import collections

    word_freq = collections.defaultdict(int)
    for doc in tokenize(pattern, tar_path):
        for word in doc:
            word_freq[word] += 1
    kept = [(w, f) for w, f in word_freq.items() if f > cutoff]
    kept.sort(key=lambda x: (-x[1], x[0]))
    word_idx = {w: i for i, (w, _) in enumerate(kept)}
    word_idx["<unk>"] = len(word_idx)
    return word_idx


def reader_creator(pos_pattern, neg_pattern, word_idx, tar_path=None):
    """ONE sequential tar scan (lazy, on first iteration) labels each
    matching doc pos=0 / neg=1 — the reference's two tokenize() passes
    re-decompress the 80MB tarball per pattern."""
    UNK = word_idx["<unk>"]
    ins = []
    loaded = [False]

    def _load():
        resolved = tar_path or common.download(URL, "imdb", MD5)
        with tarfile.open(resolved) as tarf:
            tf = tarf.next()
            while tf is not None:
                label = (0 if pos_pattern.match(tf.name)
                         else 1 if neg_pattern.match(tf.name) else None)
                if label is not None:
                    text = tarf.extractfile(tf).read().decode(
                        "utf-8", errors="replace")
                    doc = (text.rstrip("\n\r").translate(_PUNCT_TABLE)
                           .lower().split())
                    ins.append(([word_idx.get(w, UNK) for w in doc],
                                label))
                tf = tarf.next()
        # reference reader order: all pos docs, then all neg docs
        ins.sort(key=lambda rec: rec[1])
        loaded[0] = True

    def reader():
        if not loaded[0]:
            _load()
        yield from ins

    return reader


def fetch():
    common.download(URL, "imdb", MD5)


# -- synthetic fallback ------------------------------------------------------


def _synthetic_dict():
    return {f"w{i}": i for i in range(_VOCAB)}


def _synthetic_reader(tag, n, vocab_size):
    def reader():
        r = fixed_rng("imdb/" + tag)
        v = vocab_size or _VOCAB
        half = v // 2
        for _ in range(n):
            label = int(r.randint(0, 2))
            ln = int(r.randint(8, 64))
            lo, hi = (0, half) if label == 0 else (half, v)
            seq = [int(t) for t in r.randint(lo, hi, ln)]
            yield seq, label

    return reader


@cached
def word_dict():
    """Full-corpus dictionary (reference imdb.py word_dict: cutoff 150
    over train+test docs)."""
    tar_path = common.fetch_real(
        "imdb", lambda: common.download(URL, "imdb", MD5))
    if tar_path is None:
        return _synthetic_dict()
    return build_dict(re.compile(r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$"),
                      150, tar_path)


def _make(tag, n_synth, word_idx):
    tar_path = common.fetch_real(
        "imdb", lambda: common.download(URL, "imdb", MD5))
    if tar_path is None:
        return _synthetic_reader(
            tag, n_synth, len(word_idx) if word_idx else None)
    if word_idx is None:
        word_idx = word_dict()
    return reader_creator(
        re.compile(rf"aclImdb/{tag}/pos/.*\.txt$"),
        re.compile(rf"aclImdb/{tag}/neg/.*\.txt$"), word_idx, tar_path)


def train(word_idx=None):
    return _make("train", 1024, word_idx)


def test(word_idx=None):
    return _make("test", 256, word_idx)
