"""imdb: variable-length word-id sequence -> 0/1 sentiment.

Reference: /root/reference/python/paddle/v2/dataset/imdb.py (word_dict,
train/test readers).  Synthetic: class decided by which vocabulary half
dominates the sequence.
"""
from __future__ import annotations

from .common import cached, fixed_rng

__all__ = ["word_dict", "train", "test"]

_VOCAB = 5148  # reference word_dict size ballpark; any fixed value works


@cached
def word_dict():
    return {f"w{i}": i for i in range(_VOCAB)}


def _reader(tag, n, vocab_size):
    def reader():
        r = fixed_rng("imdb/" + tag)
        v = vocab_size or _VOCAB
        half = v // 2
        for _ in range(n):
            label = int(r.randint(0, 2))
            ln = int(r.randint(8, 64))
            lo, hi = (0, half) if label == 0 else (half, v)
            seq = [int(t) for t in r.randint(lo, hi, ln)]
            yield seq, label

    return reader


def train(word_idx=None):
    return _reader("train", 1024, len(word_idx) if word_idx else None)


def test(word_idx=None):
    return _reader("test", 256, len(word_idx) if word_idx else None)
