"""conll05: semantic-role-labeling tuples (word, predicate contexts, mark,
IOB label sequence).

Reference: /root/reference/python/paddle/v2/dataset/conll05.py
(get_dict -> word/verb/label dicts, test reader yielding 9 slots:
word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, verb_ids, mark, labels).
"""
from __future__ import annotations

from .common import cached, fixed_rng

__all__ = ["get_dict", "test", "train"]

_WORDS, _VERBS, _LABELS = 4000, 300, 59  # label dict ~ 2*roles+1 IOB tags


@cached
def get_dict():
    word_dict = {f"w{i}": i for i in range(_WORDS)}
    verb_dict = {f"v{i}": i for i in range(_VERBS)}
    label_dict = {f"l{i}": i for i in range(_LABELS)}
    return word_dict, verb_dict, label_dict


def _reader(tag, n):
    def reader():
        r = fixed_rng("conll05/" + tag)
        for _ in range(n):
            ln = int(r.randint(4, 12))
            words = [int(w) for w in r.randint(0, _WORDS, ln)]
            verb_pos = int(r.randint(0, ln))
            verb = int(r.randint(0, _VERBS))
            ctx = [words[max(0, min(ln - 1, verb_pos + d))]
                   for d in (-2, -1, 0, 1, 2)]
            mark = [1 if i == verb_pos else 0 for i in range(ln)]
            labels = [int(l) for l in r.randint(0, _LABELS, ln)]
            yield (words, [ctx[0]] * ln, [ctx[1]] * ln, [ctx[2]] * ln,
                   [ctx[3]] * ln, [ctx[4]] * ln, [verb] * ln, mark, labels)

    return reader


def test():
    return _reader("test", 256)


def train():
    return _reader("train", 1024)
