"""conll05: semantic-role-labeling tuples (word, predicate contexts, mark,
IOB label sequence).

Reference: /root/reference/python/paddle/v2/dataset/conll05.py — the
public CoNLL-2005 test split (gzipped parallel words/props streams inside
a tarball; props' bracketed spans converted to B-/I-/O tags) plus
downloaded word/verb/label dicts and a Wikipedia embedding table; the
reader emits 9 slots per (sentence, predicate) pair: word_ids, five
predicate-context id sequences (broadcast to sentence length), verb_ids,
a 5-token predicate-window mark, IOB label ids.  Real corpus under
PADDLE_TPU_DATASET=auto|real; synthetic fallback offline.
"""
from __future__ import annotations

import gzip
import tarfile

from . import common
from .common import cached, fixed_rng

__all__ = ["get_dict", "get_embedding", "test", "train", "fetch",
           "corpus_reader", "reader_creator", "load_dict"]

DATA_URL = "http://www.cs.upc.edu/~srlconll/conll05st-tests.tar.gz"
DATA_MD5 = "387719152ae52d60422c016e92a742fc"
_DICT_BASE = "http://paddlepaddle.bj.bcebos.com/demo/srl_dict_and_embedding/"
WORDDICT_URL = _DICT_BASE + "wordDict.txt"
WORDDICT_MD5 = "ea7fb7d4c75cc6254716f0177a506baa"
VERBDICT_URL = _DICT_BASE + "verbDict.txt"
VERBDICT_MD5 = "0d2977293bbb6cbefab5b0f97db1e77c"
TRGDICT_URL = _DICT_BASE + "targetDict.txt"
TRGDICT_MD5 = "d8c7f03ceb5fc2e5a0fa7503a4353751"
EMB_URL = _DICT_BASE + "emb"
EMB_MD5 = "bf436eb0faa1f6f9103017f8be57cdb7"

UNK_IDX = 0
WORDS_NAME = "conll05st-release/test.wsj/words/test.wsj.words.gz"
PROPS_NAME = "conll05st-release/test.wsj/props/test.wsj.props.gz"

_WORDS, _VERBS, _LABELS = 4000, 300, 59  # synthetic dims


def load_dict(filename):
    d = {}
    with open(filename) as f:
        for i, line in enumerate(f):
            d[line.strip()] = i
    return d


def _props_to_iob(lbl):
    """One predicate's props column (e.g. ``(A0* * *) (V*) *``) ->
    B-/I-/O tag sequence (reference conll05.py:86-106)."""
    out = []
    cur = "O"
    in_bracket = False
    for token in lbl:
        if token == "*" and not in_bracket:
            out.append("O")
        elif token == "*" and in_bracket:
            out.append("I-" + cur)
        elif token == "*)":
            out.append("I-" + cur)
            in_bracket = False
        elif "(" in token and ")" in token:
            cur = token[1:token.find("*")]
            out.append("B-" + cur)
            in_bracket = False
        elif "(" in token:
            cur = token[1:token.find("*")]
            out.append("B-" + cur)
            in_bracket = True
        else:
            raise RuntimeError(f"Unexpected label: {token}")
    return out


def corpus_reader(data_path, words_name=WORDS_NAME,
                  props_name=PROPS_NAME):
    """Yield (sentence words, predicate, IOB tag sequence) per
    (sentence, predicate) pair of the gzipped parallel streams."""

    def reader():
        with tarfile.open(data_path) as tf, \
                gzip.GzipFile(fileobj=tf.extractfile(words_name)) as wf, \
                gzip.GzipFile(fileobj=tf.extractfile(props_name)) as pf:
            sentence = []
            columns = []  # one row per word: [verb-col, tag-col...]
            for wline, pline in zip(wf, pf):
                word = wline.decode().strip()
                fields = pline.decode().strip().split()
                if not fields:  # blank line: end of sentence
                    if columns:
                        n_cols = len(columns[0])
                        verbs = [row[0] for row in columns
                                 if row[0] != "-"]
                        for i in range(1, n_cols):
                            tags = _props_to_iob(
                                [row[i] for row in columns])
                            yield sentence, verbs[i - 1], tags
                    sentence = []
                    columns = []
                else:
                    sentence.append(word)
                    columns.append(fields)

    return reader


def reader_creator(corpus, word_dict, predicate_dict, label_dict):
    """9-slot samples with the predicate 5-token context window
    broadcast to sentence length and the window marked (reference
    conll05.py:130-178)."""

    def reader():
        for sentence, predicate, labels in corpus():
            n = len(sentence)
            v = labels.index("B-V")
            mark = [0] * n

            def ctx(off, fallback):
                i = v + off
                if 0 <= i < n:
                    mark[i] = 1
                    return sentence[i]
                return fallback

            ctx_n2 = ctx(-2, "bos")
            ctx_n1 = ctx(-1, "bos")
            ctx_0 = ctx(0, sentence[v])
            ctx_p1 = ctx(1, "eos")
            ctx_p2 = ctx(2, "eos")

            def widx(w):
                return word_dict.get(w, UNK_IDX)

            yield ([widx(w) for w in sentence],
                   [widx(ctx_n2)] * n, [widx(ctx_n1)] * n,
                   [widx(ctx_0)] * n, [widx(ctx_p1)] * n,
                   [widx(ctx_p2)] * n,
                   [predicate_dict.get(predicate, UNK_IDX)] * n,
                   mark,
                   [label_dict[t] for t in labels])

    return reader


def fetch():
    common.download(WORDDICT_URL, "conll05st", WORDDICT_MD5)
    common.download(VERBDICT_URL, "conll05st", VERBDICT_MD5)
    common.download(TRGDICT_URL, "conll05st", TRGDICT_MD5)
    common.download(EMB_URL, "conll05st", EMB_MD5)
    return common.download(DATA_URL, "conll05st", DATA_MD5)


# -- synthetic fallback ------------------------------------------------------


def _synthetic_dicts():
    return ({f"w{i}": i for i in range(_WORDS)},
            {f"v{i}": i for i in range(_VERBS)},
            {f"l{i}": i for i in range(_LABELS)})


def _synthetic_reader(tag, n):
    def reader():
        r = fixed_rng("conll05/" + tag)
        for _ in range(n):
            ln = int(r.randint(4, 12))
            words = [int(w) for w in r.randint(0, _WORDS, ln)]
            verb_pos = int(r.randint(0, ln))
            verb = int(r.randint(0, _VERBS))
            ctx = [words[max(0, min(ln - 1, verb_pos + d))]
                   for d in (-2, -1, 0, 1, 2)]
            mark = [1 if i == verb_pos else 0 for i in range(ln)]
            labels = [int(lab) for lab in r.randint(0, _LABELS, ln)]
            yield (words, [ctx[0]] * ln, [ctx[1]] * ln, [ctx[2]] * ln,
                   [ctx[3]] * ln, [ctx[4]] * ln, [verb] * ln, mark,
                   labels)

    return reader


@cached
def get_dict():
    """(word_dict, verb_dict, label_dict)."""
    paths = common.fetch_real("conll05st", lambda: (
        common.download(WORDDICT_URL, "conll05st", WORDDICT_MD5),
        common.download(VERBDICT_URL, "conll05st", VERBDICT_MD5),
        common.download(TRGDICT_URL, "conll05st", TRGDICT_MD5)))
    if paths is None:
        return _synthetic_dicts()
    return tuple(load_dict(p) for p in paths)


def get_embedding():
    """Path to the pretrained Wikipedia embedding table (raw file, as the
    reference returns), or None offline."""
    return common.fetch_real(
        "conll05st", lambda: common.download(EMB_URL, "conll05st",
                                             EMB_MD5))


def test():
    tar = common.fetch_real(
        "conll05st", lambda: common.download(DATA_URL, "conll05st",
                                             DATA_MD5))
    if tar is None:
        return _synthetic_reader("test", 256)
    word_dict, verb_dict, label_dict = get_dict()
    return reader_creator(corpus_reader(tar), word_dict, verb_dict,
                          label_dict)


def train():
    """CoNLL-2005 train is not freely distributable (reference ships only
    the public test split); offline and real mode both serve the
    synthetic generator here unless users repoint DATA_URL at their own
    licensed copy."""
    return _synthetic_reader("train", 1024)
