"""uci_digits: REAL handwritten digits, available fully offline.

The UCI "Optical Recognition of Handwritten Digits" test corpus — 1,797
real scanned digits at 8x8 resolution — ships INSIDE scikit-learn
(`sklearn.datasets.load_digits`), so unlike the reference's 28x28 MNIST
(python/paddle/v2/dataset/mnist.py, network download) this real corpus
needs no egress at all.  It exists to give the convergence artifacts a
`data: real` row in offline environments (VERDICT r4 next #5): the
recognize-digits book model trains on actual human handwriting here,
with mnist.py remaining the reference-parity 28x28 path when the
network allows.

Samples follow the mnist.py convention: (image float32 [64] scaled to
[-1, 1], label int).  Deterministic 80/20 train/test split.
"""
from __future__ import annotations

import numpy as np

from .common import cached

__all__ = ["train", "test", "load_data"]


@cached
def load_data():
    from sklearn.datasets import load_digits

    d = load_digits()
    # pixel values are 0..16 ink counts; scale to [-1, 1] like mnist.py
    x = (d.data.astype(np.float32) / 8.0) - 1.0
    y = d.target.astype(np.int64)
    # deterministic shuffle so the split is class-balanced
    idx = np.random.RandomState(42).permutation(len(y))
    x, y = x[idx], y[idx]
    n_train = int(len(y) * 0.8)
    return (x[:n_train], y[:n_train]), (x[n_train:], y[n_train:])


def _reader(part):
    def reader():
        (xs, ys) = load_data()[part]
        for i in range(len(ys)):
            yield xs[i], int(ys[i])

    return reader


def train():
    """1,437 real training digits as (image[64] in [-1,1], label)."""
    return _reader(0)


def test():
    """360 held-out real digits."""
    return _reader(1)
