"""MNIST: 784 floats in [-1, 1] -> int label 0..9.

Reference: /root/reference/python/paddle/v2/dataset/mnist.py — downloads
the IDX-format ubyte gzips, yields (img/255*2-1 float32[784], int label).
Real corpus under PADDLE_TPU_DATASET=auto|real; deterministic synthetic
gaussian-blob fallback offline (common.py policy).
"""
from __future__ import annotations

import gzip
import struct

import numpy as np

from . import common
from .common import cached, fixed_rng

__all__ = ["train", "test", "reader_creator", "fetch"]

URL_PREFIX = "https://storage.googleapis.com/cvdf-datasets/mnist/"
TEST_IMAGE_MD5 = "9fb629c4189551a2d022fa330f9573f3"
TEST_LABEL_MD5 = "ec29112dd5afa0611ce80d1b7f02629c"
TRAIN_IMAGE_MD5 = "f68b3c2dcbeaaa9fbdd348bbdeb94873"
TRAIN_LABEL_MD5 = "d53e105ee54ea40749a09fcbcd1e9432"

_N_TRAIN, _N_TEST = 2048, 512  # synthetic-fallback sizes


def reader_creator(image_filename, label_filename, buffer_size=100):
    """Real IDX parser: gzip'd images (magic 2051) + labels (magic 2049);
    yields (float32[784] in [-1, 1], int label)."""

    def reader():
        with gzip.open(image_filename, "rb") as imgf, \
                gzip.open(label_filename, "rb") as lblf:
            magic, n_img, rows, cols = struct.unpack(">IIII", imgf.read(16))
            if magic != 2051:
                raise ValueError(f"{image_filename}: bad IDX image magic "
                                 f"{magic}")
            magic, n_lbl = struct.unpack(">II", lblf.read(8))
            if magic != 2049:
                raise ValueError(f"{label_filename}: bad IDX label magic "
                                 f"{magic}")
            if n_img != n_lbl:
                raise ValueError(f"image/label count mismatch: "
                                 f"{n_img} vs {n_lbl}")
            px = rows * cols
            remaining = n_img
            while remaining > 0:
                k = min(buffer_size, remaining)
                imgs = np.frombuffer(imgf.read(k * px), np.uint8)
                lbls = np.frombuffer(lblf.read(k), np.uint8)
                imgs = imgs.reshape(k, px).astype(np.float32)
                imgs = imgs / 255.0 * 2.0 - 1.0
                for i in range(k):
                    yield imgs[i, :], int(lbls[i])
                remaining -= k

    return reader


def _fetch(tag):
    img_md5, lbl_md5 = ((TRAIN_IMAGE_MD5, TRAIN_LABEL_MD5) if tag == "train"
                        else (TEST_IMAGE_MD5, TEST_LABEL_MD5))
    stem = "train" if tag == "train" else "t10k"
    return (common.download(f"{URL_PREFIX}{stem}-images-idx3-ubyte.gz",
                            "mnist", img_md5),
            common.download(f"{URL_PREFIX}{stem}-labels-idx1-ubyte.gz",
                            "mnist", lbl_md5))


def fetch():
    _fetch("train")
    _fetch("test")


# -- synthetic fallback ------------------------------------------------------


@cached
def _templates():
    r = fixed_rng("mnist")
    return r.randn(10, 784).astype(np.float32)


def _synthetic_reader(tag, n):
    def reader():
        t = _templates()
        r = fixed_rng("mnist/" + tag)
        for _ in range(n):
            label = int(r.randint(0, 10))
            img = t[label] + 0.5 * r.randn(784).astype(np.float32)
            img = np.clip(img, -1.0, 1.0).astype(np.float32)
            yield img, label

    return reader


def _make(tag, n_synth):
    paths = common.fetch_real("mnist", lambda: _fetch(tag))
    if paths is None:
        return _synthetic_reader(tag, n_synth)
    return reader_creator(*paths)


def train():
    return _make("train", _N_TRAIN)


def test():
    return _make("test", _N_TEST)
