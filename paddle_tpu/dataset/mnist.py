"""mnist: 784 floats in [-1, 1] -> int label 0..9.

Reference: /root/reference/python/paddle/v2/dataset/mnist.py.  Synthetic:
each class is a gaussian blob around a class-specific template so simple
models reach high accuracy.
"""
from __future__ import annotations

import numpy as np

from .common import cached, fixed_rng

__all__ = ["train", "test"]

_N_TRAIN, _N_TEST = 2048, 512


@cached
def _templates():
    r = fixed_rng("mnist")
    return r.randn(10, 784).astype(np.float32)


def _reader(tag, n):
    def reader():
        t = _templates()
        r = fixed_rng("mnist/" + tag)
        for _ in range(n):
            label = int(r.randint(0, 10))
            img = t[label] + 0.5 * r.randn(784).astype(np.float32)
            img = np.clip(img, -1.0, 1.0).astype(np.float32)
            yield img, label

    return reader


def train():
    return _reader("train", _N_TRAIN)


def test():
    return _reader("test", _N_TEST)
