"""uci_housing: 13 normalized float features -> 1 float target.

Reference: /root/reference/python/paddle/v2/dataset/uci_housing.py
(506 rows, feature-normalized).  Synthetic: linear ground truth + noise.
"""
from __future__ import annotations

import numpy as np

from .common import cached, fixed_rng

__all__ = ["train", "test", "feature_names"]

feature_names = [
    "CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS", "RAD", "TAX",
    "PTRATIO", "B", "LSTAT",
]


@cached
def _data():
    r = fixed_rng("uci_housing")
    n = 506
    x = r.randn(n, 13).astype(np.float32)
    w = r.randn(13, 1).astype(np.float32)
    y = (x @ w + 0.1 * r.randn(n, 1)).astype(np.float32)
    return x, y


def _reader(lo, hi):
    def reader():
        x, y = _data()
        for i in range(lo, hi):
            yield x[i], y[i]

    return reader


def train():
    return _reader(0, 406)


def test():
    return _reader(406, 506)
