"""uci_housing: 13 normalized float features -> 1 float target.

Reference: /root/reference/python/paddle/v2/dataset/uci_housing.py —
downloads housing.data (506 rows x 14 space-separated floats), mean-
centers each feature scaled by its range, splits 80/20 train/test.
Real corpus under PADDLE_TPU_DATASET=auto|real; linear-ground-truth
synthetic fallback offline (common.py policy).
"""
from __future__ import annotations

import numpy as np

from . import common
from .common import cached, fixed_rng

__all__ = ["train", "test", "feature_names", "load_data", "fetch"]

URL = ("https://archive.ics.uci.edu/ml/machine-learning-databases/"
       "housing/housing.data")
MD5 = "d4accdce7a25600298819f8e28e8d593"

feature_names = [
    "CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS", "RAD", "TAX",
    "PTRATIO", "B", "LSTAT",
]


def load_data(filename, feature_num=14, ratio=0.8):
    """Parse + normalize the real corpus: (x - avg) / (max - min) per
    feature column (target column untouched); 80/20 row split.  Returns
    (train_rows, test_rows) as float32 [n, 14] arrays."""
    data = np.fromfile(filename, sep=" ", dtype=np.float32)
    if data.size % feature_num != 0:
        raise ValueError(
            f"{filename}: {data.size} values is not a multiple of "
            f"{feature_num} columns")
    data = data.reshape(-1, feature_num)
    maximums = data.max(axis=0)
    minimums = data.min(axis=0)
    avgs = data.mean(axis=0)
    for i in range(feature_num - 1):
        data[:, i] = (data[:, i] - avgs[i]) / (maximums[i] - minimums[i])
    offset = int(data.shape[0] * ratio)
    return data[:offset], data[offset:]


def fetch():
    return common.download(URL, "uci_housing", MD5)


# -- synthetic fallback ------------------------------------------------------


@cached
def _synthetic():
    r = fixed_rng("uci_housing")
    n = 506
    x = r.randn(n, 13).astype(np.float32)
    w = r.randn(13, 1).astype(np.float32)
    y = (x @ w + 0.1 * r.randn(n, 1)).astype(np.float32)
    return x, y


def _synthetic_reader(lo, hi):
    def reader():
        x, y = _synthetic()
        for i in range(lo, hi):
            yield x[i], y[i]

    return reader


@cached
def _real_split():
    path = common.fetch_real("uci_housing", fetch)
    if path is None:
        return None
    return load_data(path)


def _make(part):
    split = _real_split()
    if split is None:
        return _synthetic_reader(0, 406) if part == 0 else \
            _synthetic_reader(406, 506)
    rows = split[part]

    def reader():
        for row in rows:
            yield row[:-1], row[-1:]

    return reader


def train():
    return _make(0)


def test():
    return _make(1)
