"""movielens: [user..., movie..., [rating]] samples + metadata accessors.

Reference: /root/reference/python/paddle/v2/dataset/movielens.py — the
ml-1m zip's ::-separated {movies,users,ratings}.dat (latin-1), MovieInfo/
UserInfo metadata, a seeded random 90/10 train/test split of the ratings
stream, ratings rescaled to `r*2-5`.  Real corpus under
PADDLE_TPU_DATASET=auto|real; deterministic synthetic fallback offline.
Dictionaries (title words, categories) are SORTED here — the reference
relied on py2 set iteration order, which was not reproducible.
"""
from __future__ import annotations

import random
import re
import zipfile

from . import common
from .common import cached, fixed_rng

__all__ = [
    "train", "test", "max_user_id", "max_movie_id", "max_job_id",
    "age_table", "movie_categories", "user_info", "movie_info",
    "get_movie_title_dict", "fetch",
]

age_table = [1, 18, 25, 35, 45, 50, 56]

URL = "http://files.grouplens.org/datasets/movielens/ml-1m.zip"
MD5 = "c4d9eecfca2ab87c1945afe126590906"

_N_USERS, _N_MOVIES, _N_CATS, _N_JOBS = 943, 1682, 18, 20  # synthetic dims


class MovieInfo:
    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self, categories_dict, title_dict):
        return [self.index,
                [categories_dict[c] for c in self.categories],
                [title_dict[w.lower()] for w in self.title.split()]]

    def __repr__(self):
        return (f"<MovieInfo id({self.index}), title({self.title}), "
                f"categories({self.categories})>")


class UserInfo:
    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = age_table.index(int(age)) if int(age) in age_table \
            else int(age)
        self.job_id = int(job_id)

    def value(self):
        return [self.index, 0 if self.is_male else 1, self.age,
                self.job_id]

    def __repr__(self):
        return (f"<UserInfo id({self.index}), "
                f"gender({'M' if self.is_male else 'F'}), "
                f"age({age_table[self.age]}), job({self.job_id})>")


def fetch():
    return common.download(URL, "movielens", MD5)


def parse_meta(zip_path):
    """-> (movies {id: MovieInfo}, users {id: UserInfo},
    title_dict, categories_dict) from an ml-1m-layout zip."""
    pattern = re.compile(r"^(.*)\((\d+)\)$")
    movies = {}
    users = {}
    title_words = set()
    categories = set()
    with zipfile.ZipFile(zip_path) as z:
        with z.open("ml-1m/movies.dat") as f:
            for line in f:
                mid, title, cats = line.decode("latin-1").strip() \
                    .split("::")
                cats = cats.split("|")
                categories.update(cats)
                m = pattern.match(title)
                title = m.group(1).strip() if m else title
                movies[int(mid)] = MovieInfo(mid, cats, title)
                title_words.update(w.lower() for w in title.split())
        with z.open("ml-1m/users.dat") as f:
            for line in f:
                uid, gender, age, job = line.decode("latin-1").strip() \
                    .split("::")[:4]
                users[int(uid)] = UserInfo(uid, gender, age, job)
    title_dict = {w: i for i, w in enumerate(sorted(title_words))}
    categories_dict = {c: i for i, c in enumerate(sorted(categories))}
    return movies, users, title_dict, categories_dict


@cached
def _real_meta():
    path = common.fetch_real("movielens", fetch)
    if path is None:
        return None
    return (path,) + parse_meta(path)


def _ratings_reader(zip_path, movies, users, title_dict, categories_dict,
                    is_test, rand_seed=0, test_ratio=0.1):
    def reader():
        rand = random.Random(x=rand_seed)
        with zipfile.ZipFile(zip_path) as z:
            with z.open("ml-1m/ratings.dat") as f:
                for line in f:
                    if (rand.random() < test_ratio) != is_test:
                        continue
                    uid, mid, rating, _ = line.decode("latin-1").strip() \
                        .split("::")
                    mov = movies[int(mid)]
                    usr = users[int(uid)]
                    yield (usr.value() +
                           mov.value(categories_dict, title_dict) +
                           [[float(rating) * 2 - 5.0]])

    return reader


# -- synthetic fallback ------------------------------------------------------


@cached
def _synthetic_movie_info():
    r = fixed_rng("movielens/movies")
    out = {}
    for i in range(1, _N_MOVIES + 1):
        cats = [f"cat{c}" for c in r.choice(_N_CATS, size=2,
                                            replace=False)]
        out[i] = MovieInfo(i, cats, " ".join(
            f"t{int(w)}" for w in r.randint(0, 100, 3)))
    return out


@cached
def _synthetic_user_info():
    r = fixed_rng("movielens/users")
    out = {}
    for i in range(1, _N_USERS + 1):
        out[i] = UserInfo(i, "M" if r.rand() < 0.5 else "F",
                          int(age_table[r.randint(0, len(age_table))]),
                          int(r.randint(0, _N_JOBS)))
    return out


def _synthetic_reader(tag, n):
    def reader():
        r = fixed_rng("movielens/" + tag)
        for _ in range(n):
            uid = int(r.randint(1, _N_USERS + 1))
            mid = int(r.randint(1, _N_MOVIES + 1))
            gender = int(r.randint(0, 2))
            age_idx = int(r.randint(0, len(age_table)))
            job = int(r.randint(0, _N_JOBS))
            cat = int(r.randint(0, _N_CATS))
            title = [int(t) for t in r.randint(0, 100, 3)]
            # rating correlates with (uid + mid) parity-ish signal
            rating = float((uid * 7 + mid * 13) % 5 + 1)
            yield [uid, gender, age_idx, job, mid, [cat], title, [rating]]

    return reader


# -- public surface ----------------------------------------------------------


def train():
    meta = _real_meta()
    if meta is None:
        return _synthetic_reader("train", 2048)
    return _ratings_reader(*meta, is_test=False)


def test():
    meta = _real_meta()
    if meta is None:
        return _synthetic_reader("test", 512)
    return _ratings_reader(*meta, is_test=True)


def movie_info():
    meta = _real_meta()
    return _synthetic_movie_info() if meta is None else meta[1]


def user_info():
    meta = _real_meta()
    return _synthetic_user_info() if meta is None else meta[2]


def get_movie_title_dict():
    meta = _real_meta()
    if meta is None:
        return {f"t{i}": i for i in range(100)}
    return meta[3]


def movie_categories():
    meta = _real_meta()
    if meta is None:
        return {f"cat{i}": i for i in range(_N_CATS)}
    return meta[4]


def max_user_id():
    meta = _real_meta()
    if meta is None:
        return _N_USERS
    return max(meta[2])


def max_movie_id():
    meta = _real_meta()
    if meta is None:
        return _N_MOVIES
    return max(meta[1])


def max_job_id():
    meta = _real_meta()
    if meta is None:
        return _N_JOBS - 1
    return max(u.job_id for u in meta[2].values())
