"""movielens: (user_id, gender, age, job, movie_id, categories, title) ->
rating.

Reference: /root/reference/python/paddle/v2/dataset/movielens.py
(MovieInfo/UserInfo metadata + train/test readers).
"""
from __future__ import annotations

from .common import cached, fixed_rng

__all__ = [
    "train", "test", "max_user_id", "max_movie_id", "max_job_id",
    "age_table", "movie_categories", "user_info", "movie_info",
]

age_table = [1, 18, 25, 35, 45, 50, 56]

_N_USERS, _N_MOVIES, _N_CATS, _N_JOBS = 943, 1682, 18, 20


def max_user_id():
    return _N_USERS


def max_movie_id():
    return _N_MOVIES


def max_job_id():
    return _N_JOBS - 1


def movie_categories():
    return {f"cat{i}": i for i in range(_N_CATS)}


class MovieInfo:
    def __init__(self, index, categories, title):
        self.index = index
        self.categories = categories
        self.title = title


class UserInfo:
    def __init__(self, index, gender, age, job_id):
        self.index = index
        self.is_male = gender == "M"
        self.age = age
        self.job_id = job_id


@cached
def movie_info():
    r = fixed_rng("movielens/movies")
    out = {}
    for i in range(1, _N_MOVIES + 1):
        cats = [f"cat{c}" for c in r.choice(_N_CATS, size=2, replace=False)]
        out[i] = MovieInfo(i, cats, [f"t{int(w)}" for w in
                                     r.randint(0, 100, 3)])
    return out


@cached
def user_info():
    r = fixed_rng("movielens/users")
    out = {}
    for i in range(1, _N_USERS + 1):
        out[i] = UserInfo(i, "M" if r.rand() < 0.5 else "F",
                          int(age_table[r.randint(0, len(age_table))]),
                          int(r.randint(0, _N_JOBS)))
    return out


def _reader(tag, n):
    def reader():
        r = fixed_rng("movielens/" + tag)
        for _ in range(n):
            uid = int(r.randint(1, _N_USERS + 1))
            mid = int(r.randint(1, _N_MOVIES + 1))
            gender = int(r.randint(0, 2))
            age_idx = int(r.randint(0, len(age_table)))
            job = int(r.randint(0, _N_JOBS))
            cat = int(r.randint(0, _N_CATS))
            title = [int(t) for t in r.randint(0, 100, 3)]
            # rating correlates with (uid + mid) parity-ish signal
            rating = float((uid * 7 + mid * 13) % 5 + 1)
            yield [uid, gender, age_idx, job, mid, [cat], title, [rating]]

    return reader


def train():
    return _reader("train", 2048)


def test():
    return _reader("test", 512)
