"""imikolov: n-gram language-model tuples of word ids.

Reference: /root/reference/python/paddle/v2/dataset/imikolov.py
(build_dict, train/test readers yielding N-gram tuples).  Synthetic: word
sequences from a sticky markov chain so n-gram models learn structure.
"""
from __future__ import annotations

from .common import cached, fixed_rng

__all__ = ["build_dict", "train", "test"]

_VOCAB = 2073  # reference dict ~2073 for min_word_freq=50


@cached
def build_dict(min_word_freq=50):
    return {f"w{i}": i for i in range(_VOCAB)}


def _reader(tag, n_samples, word_idx, n):
    v = len(word_idx)

    def reader():
        r = fixed_rng("imikolov/" + tag)
        for _ in range(n_samples):
            # sticky chain: next word near the previous one
            w = int(r.randint(0, v))
            gram = [w]
            for _ in range(n - 1):
                w = (w + int(r.randint(0, 5))) % v
                gram.append(w)
            yield tuple(gram)

    return reader


def train(word_idx, n):
    return _reader("train", 2048, word_idx, n)


def test(word_idx, n):
    return _reader("test", 512, word_idx, n)
