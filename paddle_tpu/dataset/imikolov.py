"""imikolov (PTB): n-gram tuples or (src, trg) sequences of word ids.

Reference: /root/reference/python/paddle/v2/dataset/imikolov.py —
downloads simple-examples.tgz, build_dict(min_word_freq) over
ptb.train.txt + ptb.valid.txt ordered by (-freq, word) with trailing
<unk>; NGRAM readers pad with <s>/<e>.  Real corpus under
PADDLE_TPU_DATASET=auto|real; sticky-markov synthetic fallback offline.
"""
from __future__ import annotations

import collections
import tarfile

from . import common
from .common import cached, fixed_rng

__all__ = ["train", "test", "build_dict", "DataType", "fetch"]

URL = "http://www.fit.vutbr.cz/~imikolov/rnnlm/simple-examples.tgz"
MD5 = "30177ea32e27c525793142b6bf2c8e2d"

TRAIN_FILE = "./simple-examples/data/ptb.train.txt"
TEST_FILE = "./simple-examples/data/ptb.valid.txt"

_VOCAB = 2073  # synthetic-fallback dict size (~reference min_word_freq=50)


class DataType:
    NGRAM = 1
    SEQ = 2


def word_count(f, word_freq=None):
    """Accumulate word frequencies over a text stream; every line also
    counts one <s> and one <e> (reference imikolov.py word_count)."""
    if word_freq is None:
        word_freq = collections.defaultdict(int)
    for line in f:
        if isinstance(line, bytes):
            line = line.decode("utf-8", errors="replace")
        for w in line.strip().split():
            word_freq[w] += 1
        word_freq["<s>"] += 1
        word_freq["<e>"] += 1
    return word_freq


def build_dict_from_tar(tar_path, min_word_freq=50):
    with tarfile.open(tar_path) as tf:
        word_freq = word_count(tf.extractfile(TEST_FILE),
                               word_count(tf.extractfile(TRAIN_FILE)))
    word_freq.pop("<unk>", None)  # re-added as the last index
    kept = [(w, f) for w, f in word_freq.items() if f > min_word_freq]
    kept.sort(key=lambda x: (-x[1], x[0]))
    word_idx = {w: i for i, (w, _) in enumerate(kept)}
    word_idx["<unk>"] = len(word_idx)
    return word_idx


def reader_creator(tar_path, filename, word_idx, n, data_type):
    """NGRAM: every n-gram of <s> + line + <e>; SEQ: (<s>+line, line+<e>)
    pairs, skipping sources longer than n when n > 0."""

    def reader():
        with tarfile.open(tar_path) as tf:
            UNK = word_idx["<unk>"]
            for line in tf.extractfile(filename):
                line = line.decode("utf-8", errors="replace")
                if data_type == DataType.NGRAM:
                    assert n > -1, "Invalid gram length"
                    words = ["<s>"] + line.strip().split() + ["<e>"]
                    if len(words) >= n:
                        ids = [word_idx.get(w, UNK) for w in words]
                        for i in range(n, len(ids) + 1):
                            yield tuple(ids[i - n:i])
                elif data_type == DataType.SEQ:
                    ids = [word_idx.get(w, UNK)
                           for w in line.strip().split()]
                    src_seq = [word_idx["<s>"]] + ids
                    trg_seq = ids + [word_idx["<e>"]]
                    if n > 0 and len(src_seq) > n:
                        continue
                    yield src_seq, trg_seq
                else:
                    raise ValueError(f"unknown data_type {data_type}")

    return reader


def fetch():
    common.download(URL, "imikolov", MD5)


# -- synthetic fallback ------------------------------------------------------


def _synthetic_dict():
    return {f"w{i}": i for i in range(_VOCAB)}


def _synthetic_reader(tag, n_samples, word_idx, n,
                      data_type=DataType.NGRAM):
    v = len(word_idx)

    def chain(r, length):
        # sticky chain: next word near the previous one
        w = int(r.randint(0, v))
        seq = [w]
        for _ in range(length - 1):
            w = (w + int(r.randint(0, 5))) % v
            seq.append(w)
        return seq

    def reader():
        r = fixed_rng("imikolov/" + tag)
        for _ in range(n_samples):
            if data_type == DataType.SEQ:
                seq = chain(r, int(r.randint(3, max(4, n or 12))))
                yield [word_idx.get("<s>", 0)] + seq, \
                    seq + [word_idx.get("<e>", 1)]
            else:
                yield tuple(chain(r, n))

    return reader


@cached
def build_dict(min_word_freq=50):
    tar_path = common.fetch_real(
        "imikolov", lambda: common.download(URL, "imikolov", MD5))
    if tar_path is None:
        return _synthetic_dict()
    return build_dict_from_tar(tar_path, min_word_freq)


def _make(tag, filename, n_synth, word_idx, n,
          data_type=DataType.NGRAM):
    tar_path = common.fetch_real(
        "imikolov", lambda: common.download(URL, "imikolov", MD5))
    if tar_path is None:
        return _synthetic_reader(tag, n_synth, word_idx, n, data_type)
    return reader_creator(tar_path, filename, word_idx, n, data_type)


def train(word_idx, n, data_type=DataType.NGRAM):
    return _make("train", TRAIN_FILE, 2048, word_idx, n, data_type)


def test(word_idx, n, data_type=DataType.NGRAM):
    return _make("test", TEST_FILE, 512, word_idx, n, data_type)
