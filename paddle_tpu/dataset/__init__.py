"""Dataset package with the reference's `paddle.v2.dataset` surface.

Reference: /root/reference/python/paddle/v2/dataset/ (uci_housing, mnist,
cifar, imdb, imikolov, movielens, conll05, wmt14, wmt16, sentiment,
flowers, voc2012, mq2007).

mnist/cifar/imdb/imikolov/wmt16 download, md5-verify, cache and parse the
real corpora (reference common.py machinery, see `common.py`); when the
network is unavailable — or `PADDLE_TPU_DATASET=synthetic` — every module
serves DETERMINISTIC SYNTHETIC data with the same schema
(shapes/dtypes/vocab accessors), so models and book tests exercise
identical code paths offline.  `PADDLE_TPU_DATASET=real` makes a failed
download an error instead of a fallback.

Two REAL corpora need no network at all (they ship inside scikit-learn):
`uci_digits` (1,797 real 8x8 handwritten digits) and `diabetes` (442
real patient regression rows) — the offline `data: real` convergence
evidence (benchmark/run_book.py tags every row with its data source).
"""
from . import (  # noqa: F401
    cifar,
    conll05,
    diabetes,
    flowers,
    imdb,
    imikolov,
    mnist,
    movielens,
    mq2007,
    sentiment,
    uci_digits,
    uci_housing,
    voc2012,
    wmt14,
    wmt16,
)

__all__ = [
    "uci_housing",
    "mnist",
    "cifar",
    "flowers",
    "voc2012",
    "imdb",
    "imikolov",
    "movielens",
    "mq2007",
    "conll05",
    "wmt14",
    "wmt16",
    "sentiment",
]
