"""Dataset package with the reference's `paddle.v2.dataset` surface.

Reference: /root/reference/python/paddle/v2/dataset/ (uci_housing, mnist,
cifar, imdb, imikolov, movielens, conll05, wmt14, wmt16, sentiment,
flowers, voc2012, mq2007).

This environment has no network egress, so each module serves DETERMINISTIC
SYNTHETIC data with the same schema (shapes/dtypes/vocab accessors) as the
reference downloads — models and book tests exercise identical code paths;
swap in real data by pointing the loaders at files with the same layout.
"""
from . import (  # noqa: F401
    cifar,
    conll05,
    flowers,
    imdb,
    imikolov,
    mnist,
    movielens,
    mq2007,
    sentiment,
    uci_housing,
    voc2012,
    wmt14,
    wmt16,
)

__all__ = [
    "uci_housing",
    "mnist",
    "cifar",
    "flowers",
    "voc2012",
    "imdb",
    "imikolov",
    "movielens",
    "mq2007",
    "conll05",
    "wmt14",
    "wmt16",
    "sentiment",
]
