"""voc2012: segmentation — (HWC image array, HxW class-index mask).

Reference: /root/reference/python/paddle/v2/dataset/voc2012.py — the
VOCtrainval tar's ImageSets/Segmentation/{train,trainval,val}.txt name
lists select JPEGImages/<name>.jpg + SegmentationClass/<name>.png pairs,
decoded to numpy (the palette PNG decodes to class indices).  Real
corpus under PADDLE_TPU_DATASET=auto|real; synthetic blocky-mask
fallback offline (same (image, mask) contract, float CHW image).
"""
from __future__ import annotations

import io
import tarfile

import numpy as np

from . import common
from .common import fixed_rng

__all__ = ["train", "test", "val", "reader_creator"]

VOC_URL = ("http://host.robots.ox.ac.uk/pascal/VOC/voc2012/"
           "VOCtrainval_11-May-2012.tar")
VOC_MD5 = "6cd6e144f989b92b3379bac3b3de84fd"
SET_FILE = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
DATA_FILE = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
LABEL_FILE = "VOCdevkit/VOC2012/SegmentationClass/{}.png"

_CLASSES = 21
_H = _W = 64
_N = {"train": 256, "test": 64, "val": 64}  # synthetic sizes

# reference split selection: train -> 'trainval', test -> 'train',
# val -> 'val' (voc2012.py train/test/val docstrings)
_SPLIT = {"train": "trainval", "test": "train", "val": "val"}


def reader_creator(filename, sub_name):
    """Real parser over the VOC tar: (np.array(jpg), np.array(png))
    per name in the split's ImageSets list."""
    from PIL import Image

    def reader():
        with tarfile.open(filename) as tf:
            members = {m.name: m for m in tf.getmembers()}
            sets = tf.extractfile(members[SET_FILE.format(sub_name)])
            for line in sets:
                name = line.decode().strip()
                if not name:
                    continue
                data = tf.extractfile(
                    members[DATA_FILE.format(name)]).read()
                label = tf.extractfile(
                    members[LABEL_FILE.format(name)]).read()
                yield (np.array(Image.open(io.BytesIO(data))),
                       np.array(Image.open(io.BytesIO(label))))

    return reader


def _fetch():
    return common.download(VOC_URL, "voc2012", VOC_MD5)


# -- synthetic fallback ------------------------------------------------------


def _sample(r):
    mask = np.zeros((_H, _W), np.int64)
    for _ in range(int(r.randint(1, 4))):
        c = int(r.randint(1, _CLASSES))
        y0, x0 = r.randint(0, _H // 2, 2)
        h, w = r.randint(_H // 8, _H // 2, 2)
        mask[y0:y0 + h, x0:x0 + w] = c
    img = (mask[None, :, :] / float(_CLASSES)
           + 0.1 * r.randn(3, _H, _W)).astype(np.float32)
    return img, mask


def _synthetic_reader(tag):
    def reader():
        r = fixed_rng(f"voc2012/{tag}")
        for _ in range(_N[tag]):
            yield _sample(r)

    return reader


def _make(tag):
    path = common.fetch_real("voc2012", _fetch)
    if path is None:
        return _synthetic_reader(tag)
    return reader_creator(path, _SPLIT[tag])


def train():
    return _make("train")


def test():
    return _make("test")


def val():
    return _make("val")
