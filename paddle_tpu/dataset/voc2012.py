"""voc2012: segmentation surface — (3xHxW float image, HxW int mask).

Reference: /root/reference/python/paddle/v2/dataset/voc2012.py
(train/test/val readers yielding image + per-pixel label).  Synthetic
(zero-egress): blocky masks with 21 classes (20 objects + background),
images correlated with their mask so segmentation is learnable.
"""
from __future__ import annotations

import numpy as np

from .common import fixed_rng

__all__ = ["train", "test", "val"]

_CLASSES = 21
_H = _W = 64
_N = {"train": 256, "test": 64, "val": 64}


def _sample(r):
    mask = np.zeros((_H, _W), np.int64)
    for _ in range(int(r.randint(1, 4))):
        c = int(r.randint(1, _CLASSES))
        y0, x0 = r.randint(0, _H // 2, 2)
        h, w = r.randint(_H // 8, _H // 2, 2)
        mask[y0:y0 + h, x0:x0 + w] = c
    img = (mask[None, :, :] / float(_CLASSES)
           + 0.1 * r.randn(3, _H, _W)).astype(np.float32)
    return img, mask


def _reader(tag):
    def reader():
        r = fixed_rng(f"voc2012/{tag}")
        for _ in range(_N[tag]):
            yield _sample(r)

    return reader


def train():
    return _reader("train")


def test():
    return _reader("test")


def val():
    return _reader("val")
