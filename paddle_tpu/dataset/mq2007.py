"""mq2007: LETOR learning-to-rank surface — pointwise / pairwise /
listwise readers over 46-dim query-document feature vectors.

Reference: /root/reference/python/paddle/v2/dataset/mq2007.py (gen_point,
gen_pair, gen_list over Query/QueryList records parsed from the LETOR
text format ``rel qid:N 1:v 2:v ... #docid = ...``).  The corpus ships
as a RAR archive (no rar extractor in this environment), so the REAL
path reads pre-extracted fold files from
``$DATA_HOME/mq2007/MQ2007/Fold1/{train,test}.txt`` when present
(`load_from_text` is the parser, fixture-tested); otherwise a
deterministic synthetic generator with learnable ranking signal serves
the same three formats.
"""
from __future__ import annotations

import os

import numpy as np

from . import common
from .common import cached, fixed_rng

__all__ = ["train", "test", "load_from_text"]


def load_from_text(filepath, fill_missing=-1.0):
    """Parse a LETOR-format file into [(feats [n_docs, 46] f32,
    rel [n_docs] int64)] grouped per qid (order preserved).  Missing
    feature ids get `fill_missing`."""
    queries = {}
    order = []
    with open(filepath) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            parts = line.split()
            rel = int(parts[0])
            assert parts[1].startswith("qid:"), parts[1]
            qid = parts[1][4:]
            feats = np.full(NDIM, fill_missing, np.float32)
            for tok in parts[2:]:
                idx, val = tok.split(":")
                i = int(idx) - 1
                if 0 <= i < NDIM:
                    feats[i] = float(val)
            if qid not in queries:
                queries[qid] = ([], [])
                order.append(qid)
            queries[qid][0].append(feats)
            queries[qid][1].append(rel)
    return [(np.stack(queries[q][0]),
             np.asarray(queries[q][1], np.int64)) for q in order]


def _real_fold_file(which):
    path = os.path.join(common.data_home(), "mq2007", "MQ2007", "Fold1",
                        f"{which}.txt")
    return path if os.path.exists(path) else None

NDIM = 46
_N_QUERY = {"train": 120, "test": 30}
_DOCS_PER_QUERY = 8


@cached
def _weights():
    return fixed_rng("mq2007/w").randn(NDIM).astype(np.float32)


def _queries(tag):
    r = fixed_rng(f"mq2007/{tag}")
    w = _weights()
    out = []
    for _ in range(_N_QUERY[tag]):
        feats = r.randn(_DOCS_PER_QUERY, NDIM).astype(np.float32)
        score = feats @ w + 0.25 * r.randn(_DOCS_PER_QUERY)
        rel = np.digitize(score, np.percentile(score, [50, 80]))
        out.append((feats, rel.astype(np.int64)))
    return out


def _reader(tag, format):
    real = _real_fold_file(tag)

    def source():
        if real is not None:
            return load_from_text(real)
        return _queries(tag)

    def pointwise():
        for feats, rel in source():
            for f, y in zip(feats, rel):
                yield f, int(y)

    def pairwise():
        for feats, rel in source():
            for i in range(len(rel)):
                for j in range(len(rel)):
                    if rel[i] > rel[j]:
                        yield feats[i], feats[j]

    def listwise():
        for feats, rel in source():
            yield feats, rel

    table = {"pointwise": pointwise, "pairwise": pairwise,
             "listwise": listwise}
    if format not in table:
        raise ValueError(f"format must be one of {sorted(table)}, "
                         f"got {format!r}")
    return table[format]


def train(format="pairwise"):
    return _reader("train", format)


def test(format="pairwise"):
    return _reader("test", format)
