"""mq2007: LETOR learning-to-rank surface — pointwise / pairwise /
listwise readers over 46-dim query-document feature vectors.

Reference: /root/reference/python/paddle/v2/dataset/mq2007.py (gen_point,
gen_pair, gen_list over Query/QueryList records).  Synthetic
(zero-egress): per-query documents whose relevance (0-2) correlates with
a known weight vector, so rankers have learnable signal.
"""
from __future__ import annotations

import numpy as np

from .common import cached, fixed_rng

__all__ = ["train", "test"]

NDIM = 46
_N_QUERY = {"train": 120, "test": 30}
_DOCS_PER_QUERY = 8


@cached
def _weights():
    return fixed_rng("mq2007/w").randn(NDIM).astype(np.float32)


def _queries(tag):
    r = fixed_rng(f"mq2007/{tag}")
    w = _weights()
    out = []
    for _ in range(_N_QUERY[tag]):
        feats = r.randn(_DOCS_PER_QUERY, NDIM).astype(np.float32)
        score = feats @ w + 0.25 * r.randn(_DOCS_PER_QUERY)
        rel = np.digitize(score, np.percentile(score, [50, 80]))
        out.append((feats, rel.astype(np.int64)))
    return out


def _reader(tag, format):
    def pointwise():
        for feats, rel in _queries(tag):
            for f, y in zip(feats, rel):
                yield f, int(y)

    def pairwise():
        for feats, rel in _queries(tag):
            for i in range(len(rel)):
                for j in range(len(rel)):
                    if rel[i] > rel[j]:
                        yield feats[i], feats[j]

    def listwise():
        for feats, rel in _queries(tag):
            yield feats, rel

    table = {"pointwise": pointwise, "pairwise": pairwise,
             "listwise": listwise}
    if format not in table:
        raise ValueError(f"format must be one of {sorted(table)}, "
                         f"got {format!r}")
    return table[format]


def train(format="pairwise"):
    return _reader("train", format)


def test(format="pairwise"):
    return _reader("test", format)
