"""Model persistence: save/load vars and the inference-model format.

Reference: /root/reference/python/paddle/v2/fluid/io.py:1-442
(save_vars/save_params/save_persistables, save_inference_model/
load_inference_model) and framework/prune.cc (drop ops not reachable from
the fetch targets).

Layout mirrors the reference: one file per variable named after the var
inside `dirname` (or a single combined file when `filename` is given), plus
a `__model__` file holding the serialized (pruned, inference-mode) Program.
The Program schema is JSON (core/framework.py to_dict/from_dict) rather than
protobuf — see that module's rationale.
"""
from __future__ import annotations

import json
import os
from typing import List, Sequence

from .core.framework import (
    Parameter,
    Program,
    Variable,
    default_main_program,
)

__all__ = [
    "save_vars",
    "save_params",
    "save_persistables",
    "load_vars",
    "load_params",
    "load_persistables",
    "save_inference_model",
    "load_inference_model",
    "export_aot_model",
    "load_aot_model",
    "save_checkpoint",
    "load_checkpoint",
    "latest_checkpoint",
    "prune",
    "get_inference_program",
]

MODEL_FILENAME = "__model__"


def is_persistable(var: Variable) -> bool:
    return bool(var.persistable)


def is_parameter(var: Variable) -> bool:
    return isinstance(var, Parameter)


def _build_save_load_program(op_type, var_names, dirname, filename):
    """A little program of save/load ops, run through the executor — the
    persistence path exercises the same op machinery as the reference
    (io.py appends save/load ops and executes them)."""
    prog = Program()
    block = prog.global_block()
    for name in var_names:
        block.create_var(name=name, dtype=None, persistable=True)
    if filename is None:
        for name in var_names:
            path = os.path.join(dirname, name)
            if op_type == "save":
                block.append_op("save", inputs={"X": [name]},
                                attrs={"file_path": path})
            else:
                block.append_op("load", outputs={"Out": [name]},
                                attrs={"file_path": path})
    else:
        path = os.path.join(dirname, filename)
        if op_type == "save":
            block.append_op("save_combine", inputs={"X": list(var_names)},
                            attrs={"file_path": path})
        else:
            block.append_op("load_combine",
                            outputs={"Out": list(var_names)},
                            attrs={"file_path": path})
    return prog


def _select_vars(program, predicate, vars):
    if vars is not None:
        return [v.name if isinstance(v, Variable) else str(v) for v in vars]
    return sorted(
        v.name for v in program.list_vars() if predicate(v)
    )


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=is_persistable, filename=None, scope=None):
    """Save variables selected by `vars` or `predicate` (reference
    io.py:save_vars)."""
    program = main_program or default_main_program()
    names = _select_vars(program, predicate, vars)
    os.makedirs(dirname, exist_ok=True)
    prog = _build_save_load_program("save", names, dirname, filename)
    executor.run(prog, scope=scope)
    return names


def save_params(executor, dirname, main_program=None, filename=None,
                scope=None):
    return save_vars(executor, dirname, main_program,
                     predicate=is_parameter, filename=filename, scope=scope)


def save_persistables(executor, dirname, main_program=None, filename=None,
                      scope=None):
    return save_vars(executor, dirname, main_program,
                     predicate=is_persistable, filename=filename,
                     scope=scope)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=is_persistable, filename=None, scope=None):
    program = main_program or default_main_program()
    names = _select_vars(program, predicate, vars)
    prog = _build_save_load_program("load", names, dirname, filename)
    executor.run(prog, scope=scope)
    return names


def load_params(executor, dirname, main_program=None, filename=None,
                scope=None):
    return load_vars(executor, dirname, main_program,
                     predicate=is_parameter, filename=filename, scope=scope)


def load_persistables(executor, dirname, main_program=None, filename=None,
                      scope=None):
    return load_vars(executor, dirname, main_program,
                     predicate=is_persistable, filename=filename,
                     scope=scope)


# ---------------------------------------------------------------------------
# prune + inference model
# ---------------------------------------------------------------------------


def prune(program: Program, targets: Sequence,
          for_test: bool = False) -> Program:
    """Drop ops in block 0 not needed to compute `targets` (reference
    framework/prune.cc, driven by pybind `prune` for save_inference_model).
    An op with sub-blocks is kept whole if any of its outputs is needed;
    names read anywhere inside its sub-blocks count as its inputs so their
    block-0 producers are kept too."""
    target_names = {
        t.name if isinstance(t, Variable) else str(t) for t in targets
    }
    pruned = program.clone(for_test=for_test)

    def op_reads(op):
        names = set(op.input_names())
        for attr in op.attrs:
            sub = op.sub_block(attr) if attr.endswith("block") else None
            if sub is not None:
                for sub_op in sub.ops:
                    names.update(op_reads(sub_op))
        return names

    block = pruned.global_block()
    needed = set(target_names)
    keep = []
    for op in reversed(block.ops):
        if any(n in needed for n in op.output_names()):
            keep.append(op)
            needed.update(op_reads(op))
    keep.reverse()
    block.ops = keep
    referenced = set()
    for op in keep:
        referenced.update(op_reads(op))
        referenced.update(op.output_names())
    referenced.update(target_names)
    block.vars = {
        n: v for n, v in block.vars.items() if n in referenced
    }
    pruned.bump_version()
    return pruned


def get_inference_program(target_vars, main_program=None) -> Program:
    program = main_program or default_main_program()
    return prune(program, target_vars, for_test=True)


def save_inference_model(dirname, feeded_var_names: Sequence[str],
                         target_vars, executor, main_program=None,
                         model_filename=None, params_filename=None,
                         scope=None) -> List[str]:
    """Prune to the fetch targets, flip is_test, write `__model__` +
    persistables (reference io.py:save_inference_model)."""
    program = main_program or default_main_program()
    inference_program = get_inference_program(target_vars, program)
    fetch_names = [
        t.name if isinstance(t, Variable) else str(t) for t in target_vars
    ]
    os.makedirs(dirname, exist_ok=True)
    model_path = os.path.join(dirname, model_filename or MODEL_FILENAME)
    payload = {
        "program": inference_program.to_dict(),
        "feed_var_names": list(feeded_var_names),
        "fetch_var_names": fetch_names,
    }
    with open(model_path, "w") as f:
        json.dump(payload, f)
    save_persistables(executor, dirname, inference_program,
                      filename=params_filename, scope=scope)
    return fetch_names


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None, scope=None):
    """-> (inference_program, feed_var_names, fetch_var_names)
    (reference io.py:load_inference_model)."""
    model_path = os.path.join(dirname, model_filename or MODEL_FILENAME)
    with open(model_path) as f:
        payload = json.load(f)
    program = Program.from_dict(payload["program"])
    # deserialized programs come from disk, not from this process's
    # builders — verify (PADDLE_TPU_VERIFY-gated inside preflight)
    # before executing anything against them
    from .analysis import preflight

    preflight(program, feed_names=payload.get("feed_var_names"),
              fetch_names=payload.get("fetch_var_names"))
    load_persistables(executor, dirname, program,
                      filename=params_filename, scope=scope)
    return (program, payload["feed_var_names"],
            payload["fetch_var_names"])


AOT_FILENAME = "__aot_stablehlo__"


def export_aot_model(dirname, feed_specs: dict, target_vars, executor,
                     main_program=None, scope=None) -> str:
    """AOT-export the pruned inference function as a portable serialized
    StableHLO artifact plus a side-car weights snapshot.

    The reference's C-API ships a CPython-free inference surface
    (paddle/capi/gradient_machine.cpp); the TPU-native analogue of "a
    host without Python consumes the model" is the standard jax.export
    artifact: a version-stable serialized StableHLO module any PJRT
    runtime (C/C++ via the PJRT C API, IFRT proxy, or a python runtime
    via `load_aot_model`) can load and execute without this framework —
    no Program interpreter, no op registry, no Python model code.

    Params are exported as ARGUMENTS (ordered by the name list in the
    meta json) with values snapshotted to `<artifact>.params.npz` —
    baking them in as closure constants would both bloat the module by
    the full parameter size and hit the weights-as-XLA-literals
    constant-folding trap (measured ~10x slower decode on-chip,
    docs/design/generation.md).

    `feed_specs`: {feed_name: (shape, dtype)} — AOT artifacts are
    compiled for concrete input shapes (use several exports or a
    bucketed set for multiple shapes).

    Returns the artifact path (`<dirname>/__aot_stablehlo__`).
    """
    import numpy as np

    import jax
    from jax import export as jax_export

    from .core.executor import program_to_fn

    program = main_program or default_main_program()
    inference_program = get_inference_program(target_vars, program)
    fetch_names = [
        t.name if isinstance(t, Variable) else str(t) for t in target_vars
    ]
    feed_names = list(feed_specs)
    fn = program_to_fn(inference_program, feed_names, fetch_names)
    from .core.executor import global_scope as _gs

    scope = scope or _gs()
    states = {n: np.asarray(scope.find_var(n))
              for n in fn.state_in_names}
    key = jax.random.key(inference_program.seed or 0)

    def infer(states, feeds):
        fetches, _ = fn(feeds, states, key)
        return [fetches[n] for n in fetch_names]

    from .core.types import np_dtype

    feed_structs = {
        n: jax.ShapeDtypeStruct(tuple(shape), np_dtype(dtype))
        for n, (shape, dtype) in feed_specs.items()
    }
    state_structs = {n: jax.ShapeDtypeStruct(v.shape, v.dtype)
                     for n, v in states.items()}
    # multi-platform lowering: one artifact serves CPU hosts and TPU
    # serving runtimes (single-platform exports refuse to run elsewhere)
    exported = jax_export.export(
        jax.jit(infer), platforms=("cpu", "tpu"))(state_structs,
                                                  feed_structs)
    os.makedirs(dirname, exist_ok=True)
    path = os.path.join(dirname, AOT_FILENAME)
    with open(path, "wb") as f:
        f.write(bytes(exported.serialize()))
    np.savez(path + ".params.npz", **states)
    with open(path + ".json", "w") as f:
        json.dump({"feed_specs": {n: [list(s), str(d)]
                                  for n, (s, d) in feed_specs.items()},
                   "param_names": sorted(states),
                   "fetch_var_names": fetch_names}, f)
    return path


def load_aot_model(dirname):
    """-> (callable(feed_dict) -> [fetch arrays], feed_specs,
    fetch_var_names).  Loads the serialized-StableHLO artifact written by
    `export_aot_model` and its side-car weights snapshot; runs on
    whatever backend jax is using — no Program, scope, or framework op
    registry involved."""
    import numpy as np

    from jax import export as jax_export

    path = os.path.join(dirname, AOT_FILENAME)
    with open(path, "rb") as f:
        exported = jax_export.deserialize(bytearray(f.read()))
    with open(path + ".json") as f:
        meta = json.load(f)
    with np.load(path + ".params.npz") as z:
        params = {n: z[n] for n in z.files}

    def call(feeds):
        return exported.call(params, feeds)

    return call, meta["feed_specs"], meta["fetch_var_names"]


# ---------------------------------------------------------------------------
# checkpoint / resume with {uuid, md5, timestamp} metadata
# ---------------------------------------------------------------------------
#
# Reference: the Go pserver's checkpoint protocol
# (/root/reference/go/pserver/service.go:120-203,346 — periodic snapshot of
# parameter + optimizer state to disk plus a {uuid, md5, timestamp} record in
# etcd; restore-on-restart) and
# doc/design/cluster_train/checkpointing.md (atomic publish, stale-file GC).
# Here the meta record is a JSON file next to the snapshot and the "latest"
# pointer is an atomically renamed file; on shared storage this serves
# multi-host resume the way etcd served the Go pservers.

CHECKPOINT_PREFIX = "checkpoint"
LATEST_FILENAME = "__latest__"
META_FILENAME = "__meta__"


def _md5_of_dir(path: str) -> str:
    import hashlib

    h = hashlib.md5()
    for name in sorted(os.listdir(path)):
        if name.startswith("__"):
            continue
        h.update(name.encode())
        with open(os.path.join(path, name), "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
    return h.hexdigest()


def save_checkpoint(executor, dirname, main_program=None, trainer_args=None,
                    scope=None, max_keep: int = 3) -> str:
    """Snapshot persistables (params + optimizer accumulators + LR vars)
    into `dirname/checkpoint_<uuid>/` with a {uuid, md5, timestamp,
    trainer_args} meta record, atomically publish it as latest, and GC old
    snapshots beyond `max_keep`.  Returns the checkpoint uuid."""
    import time as time_mod
    import uuid as uuid_mod

    from .core.resilience import fault_injector
    from .observability import metrics as obs_metrics
    from .observability import tracing as obs_tracing

    if max_keep < 0:
        raise ValueError(f"max_keep must be >= 0, got {max_keep}")
    # chaos hook: a process dying mid-snapshot leaves a meta-less (or
    # md5-mismatched) dir that restore must skip and GC must reap
    fault_injector().fire("checkpoint.save")
    t0 = time_mod.perf_counter()
    with obs_tracing.span("checkpoint.save", dirname=dirname):
        cp_uuid = uuid_mod.uuid4().hex
        cp_dir = os.path.join(dirname, f"{CHECKPOINT_PREFIX}_{cp_uuid}")
        os.makedirs(cp_dir, exist_ok=True)
        save_persistables(executor, cp_dir, main_program, scope=scope)
        publish_checkpoint(dirname, cp_uuid, cp_dir, trainer_args,
                           max_keep)
    obs_metrics.histogram(
        "paddle_tpu_checkpoint_save_seconds",
        "save_checkpoint wall latency (persistables + md5 publish)"
    ).observe(time_mod.perf_counter() - t0)
    return cp_uuid


def publish_checkpoint(dirname, cp_uuid, cp_dir, trainer_args=None,
                       max_keep: int = 3) -> dict:
    """Finalize a snapshot directory: write the {uuid, md5, timestamp,
    trainer_args} meta record, atomically publish it as latest, GC old
    snapshots.  Shared by the serial save_checkpoint and the sharded
    ParallelExecutor/PipelineExecutor checkpoints."""
    import time

    meta = {
        "uuid": cp_uuid,
        "md5": _md5_of_dir(cp_dir),
        "timestamp": time.time(),
        "trainer_args": trainer_args or {},
    }
    with open(os.path.join(cp_dir, META_FILENAME), "w") as f:
        json.dump(meta, f)
    # atomic publish (checkpointing.md: write tmp then rename)
    latest_tmp = os.path.join(dirname, LATEST_FILENAME + ".tmp")
    with open(latest_tmp, "w") as f:
        f.write(cp_uuid)
    os.replace(latest_tmp, os.path.join(dirname, LATEST_FILENAME))
    _gc_checkpoints(dirname, keep=max_keep, always_keep={cp_uuid})
    return meta


def _checkpoints_by_time(dirname):
    out = []
    for name in os.listdir(dirname):
        if not name.startswith(CHECKPOINT_PREFIX + "_"):
            continue
        meta_path = os.path.join(dirname, name, META_FILENAME)
        try:
            with open(meta_path) as f:
                meta = json.load(f)
            out.append((meta["timestamp"], name, meta))
        except (OSError, ValueError, KeyError):
            continue  # partially written snapshot: GC candidate
    out.sort()
    return out


# incomplete (meta-less) snapshots younger than this are assumed to be
# another writer mid-save on shared storage and are left alone
_GC_INCOMPLETE_GRACE_S = 30 * 60


def _gc_checkpoints(dirname, keep: int, always_keep=()):
    """Remove all but the newest `keep` complete snapshots, plus any *stale*
    incomplete ones (stale-file GC, checkpointing.md).  Incomplete dirs with
    recent mtime get a grace period — another host may be mid-save.
    `always_keep` uuids survive regardless of timestamp ordering (guards
    against wall-clock steps sorting the just-published snapshot oldest)."""
    import shutil
    import time

    if keep < 0:
        raise ValueError(f"max_keep must be >= 0, got {keep}")
    complete = _checkpoints_by_time(dirname)
    keep_names = ({name for _, name, _ in complete[-keep:]} if keep else
                  set())
    keep_names |= {f"{CHECKPOINT_PREFIX}_{u}" for u in always_keep}
    complete_names = {name for _, name, _ in complete}
    now = time.time()
    for name in os.listdir(dirname):
        if not name.startswith(CHECKPOINT_PREFIX + "_"):
            continue
        if name in keep_names:
            continue
        path = os.path.join(dirname, name)
        if name not in complete_names:
            try:
                if now - os.path.getmtime(path) < _GC_INCOMPLETE_GRACE_S:
                    continue  # possibly being written by another host
            except OSError:
                continue
        shutil.rmtree(path, ignore_errors=True)


def latest_checkpoint(dirname, require=None):
    """-> (checkpoint_dir, meta dict) of the latest valid snapshot, or
    (None, None).  `require(cp_dir)` optionally filters candidates (e.g.
    the sharded restore path requires its npz file); __latest__-pointer
    preference and md5 verification apply either way."""
    if not os.path.isdir(dirname):
        return None, None
    latest = os.path.join(dirname, LATEST_FILENAME)
    candidates = []
    if os.path.exists(latest):
        with open(latest) as f:
            candidates.append(f.read().strip())
    # fall back to newest-by-timestamp if the pointer is missing/corrupt
    candidates.extend(
        meta["uuid"] for _, _, meta in reversed(_checkpoints_by_time(dirname))
    )
    seen = set()
    for cp_uuid in candidates:
        if cp_uuid in seen:
            continue
        seen.add(cp_uuid)
        cp_dir = os.path.join(dirname, f"{CHECKPOINT_PREFIX}_{cp_uuid}")
        meta_path = os.path.join(cp_dir, META_FILENAME)
        try:
            with open(meta_path) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            continue
        if require is not None and not require(cp_dir):
            continue
        if _md5_of_dir(cp_dir) == meta.get("md5"):
            return cp_dir, meta
        # the pserver restore contract (go/pserver/service.go:346): a
        # snapshot whose bytes don't match its md5 record is CORRUPT,
        # never served — fall through to the next-newest valid one, but
        # loudly, since resuming from it rewinds training state
        import warnings

        warnings.warn(
            f"checkpoint {cp_uuid} under {dirname} failed md5 "
            "verification (corrupt or torn write); falling back to an "
            "older snapshot", RuntimeWarning, stacklevel=2)
    return None, None


def load_checkpoint(executor, dirname, main_program=None, scope=None):
    """Restore persistables from the latest valid snapshot under `dirname`
    (md5-verified; falls back to older snapshots if the newest is corrupt).
    Returns the snapshot's meta dict, or None if no usable snapshot."""
    import time as time_mod

    from .observability import metrics as obs_metrics
    from .observability import tracing as obs_tracing

    t0 = time_mod.perf_counter()
    with obs_tracing.span("checkpoint.load", dirname=dirname):
        cp_dir, meta = latest_checkpoint(dirname)
        if cp_dir is None:
            return None
        load_persistables(executor, cp_dir, main_program, scope=scope)
    obs_metrics.histogram(
        "paddle_tpu_checkpoint_load_seconds",
        "load_checkpoint wall latency (restore of the newest valid "
        "snapshot)").observe(time_mod.perf_counter() - t0)
    return meta
