"""Memory-optimization transpiler: liveness-based variable reuse.

Mirror of the reference's
/root/reference/python/paddle/v2/fluid/memory_optimization_transpiler.py
(ControlFlowGraph :33, dataflow analysis :90): walk the program, compute
per-op live sets, and rename each newly-defined temporary onto a dead
variable of identical shape+dtype, so consecutive ops reuse buffers
instead of growing the scope.

TPU-native framing: for XLA-compiled blocks buffer reuse already happens
inside the compiler, so the win here is the op-by-op CPU interpreter path
(debugging, host-side programs) and the scope footprint between runs —
a renamed-over var is overwritten in the interpreter env, dropping the
old buffer's last reference.  Semantics are unchanged either way; this is
the rebuild's analogue of the reference's "memory_optimize then train"
book tests (tests/book_memory_optimization/).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set

from .core.framework import Parameter, Program

__all__ = ["ControlFlowGraph", "memory_optimize"]


class ControlFlowGraph:
    """Def/use + liveness over one straight-line block (reference :33).

    live_out[i] = union of live_in of successors (straight line: i+1);
    live_in[i]  = use[i] | (live_out[i] - def[i]).
    """

    def __init__(self, ops):
        self.ops = list(ops)
        n = len(self.ops)
        self.uses: List[Set[str]] = [set() for _ in range(n)]
        self.defs: List[Set[str]] = [set() for _ in range(n)]
        for i, op in enumerate(self.ops):
            for names in op.inputs.values():
                self.uses[i].update(n_ for n_ in names if n_)
            for names in op.outputs.values():
                self.defs[i].update(n_ for n_ in names if n_)
        self.live_in: List[Set[str]] = [set() for _ in range(n)]
        self.live_out: List[Set[str]] = [set() for _ in range(n)]
        self._dataflow()

    def _dataflow(self):
        for i in range(len(self.ops) - 1, -1, -1):
            self.live_out[i] = (set(self.live_in[i + 1])
                                if i + 1 < len(self.ops) else set())
            self.live_in[i] = self.uses[i] | (self.live_out[i]
                                              - self.defs[i])


def _sub_block_names(program: Program) -> Set[str]:
    """All names referenced anywhere in non-global blocks: sub-blocks
    resolve names against the parent scope, so renaming them is unsafe."""
    names: Set[str] = set()
    for block in program.blocks[1:]:
        names.update(block.vars.keys())
        for op in block.ops:
            for ns in op.inputs.values():
                names.update(ns)
            for ns in op.outputs.values():
                names.update(ns)
    return names


def memory_optimize(program: Program,
                    skip_vars: Optional[Sequence] = None,
                    level: int = 0) -> int:
    """Rewrite `program` in place so dead temporaries are reused; returns
    the number of variables eliminated.

    skip_vars: names (or Variables) never to optimize — pass everything
    you intend to fetch after the final op (same contract as the
    reference: fetch targets must survive to the end of the run).
    level=0 requires exact shape+dtype match for reuse (reference
    memory_optimization_transpiler.py level semantics).
    """
    del level  # only exact-match (level 0) reuse is implemented
    block = program.global_block()
    if isinstance(skip_vars, str) or not hasattr(skip_vars or [],
                                                 "__iter__"):
        skip_vars = [skip_vars]  # a bare name/Variable, not a collection
    skip: Set[str] = set()
    for v in skip_vars or []:
        skip.add(v if isinstance(v, str) else v.name)
    skip |= _sub_block_names(program)

    cfg = ControlFlowGraph(block.ops)
    n = len(cfg.ops)

    # a name's buffer is finished once past its last def AND last use
    last_touch: Dict[str, int] = {}
    defined: Set[str] = set()
    for i in range(n):
        for name in cfg.uses[i] | cfg.defs[i]:
            last_touch[name] = i
        defined |= cfg.defs[i]

    def eligible(name: str) -> bool:
        if name in skip or name not in defined or not block.has_var(name):
            return False
        v = block.var(name)
        if isinstance(v, Parameter) or getattr(v, "persistable", False):
            return False
        if v.shape is None or v.dtype is None:
            return False
        return True

    def key_of(name):
        v = block.var(name)
        return tuple(v.shape), str(v.dtype)

    pool: List[str] = []          # finished var names, buffers reusable
    rename: Dict[str, str] = {}   # original name -> reused name
    eliminated = 0

    for i, op in enumerate(cfg.ops):
        for slot, names in op.inputs.items():
            op.inputs[slot] = [rename.get(nm, nm) for nm in names]

        for slot, names in op.outputs.items():
            out = []
            for name in names:
                if name in rename:
                    out.append(rename[name])
                    continue
                if eligible(name):
                    for cand in pool:
                        if key_of(cand) == key_of(name):
                            pool.remove(cand)
                            rename[name] = cand
                            block.vars.pop(name, None)
                            eliminated += 1
                            name = cand
                            break
                out.append(name)
            op.outputs[slot] = out

        # buffers finished at this op become reusable for later ops (for a
        # renamed var the reuse target carries the buffer, so check THAT)
        for name in cfg.uses[i] | cfg.defs[i]:
            if last_touch.get(name) != i:
                continue
            cur = rename.get(name, name)
            if eligible(cur) and cur not in pool:
                pool.append(cur)

    program.bump_version()
    return eliminated
