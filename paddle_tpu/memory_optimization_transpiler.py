"""Memory-optimization transpiler: the whole-program memory layer.

Mirror of the reference's
/root/reference/python/paddle/v2/fluid/memory_optimization_transpiler.py
(ControlFlowGraph :33, dataflow analysis :90), grown from a standalone
rename pass into the planning layer both executors consume:

  * `memory_optimize` — the classic liveness-based RENAME pass: walk the
    program, compute per-op live sets, and rename each newly-defined
    temporary onto a dead variable of identical shape+dtype, so
    consecutive ops reuse buffers instead of growing the scope (the
    interpreter-path win; XLA does this internally for compiled blocks).
  * `plan_donation` — the liveness-backed DONATION plan for the jitted
    step: every feed/state buffer whose last use is inside the step is
    safe to hand to XLA as a donated input (its HBM is reused for
    intermediates / the updated state), and every unsafe request —
    a fetched var, a read-only state — is rejected AT BUILD TIME with a
    `DonationError` instead of crashing or corrupting at runtime.
    Consumed by `core.executor.Executor._run_compiled` and
    `parallel.executor.ParallelExecutor` (which previously hardcoded a
    single donated slot), and linted by the `donation-safety` analysis
    pass (docs/analysis.md).
  * `plan_dead_frees` — per-op-index lists of names whose last use has
    passed, so the interpreter/segmented executor drops scope references
    mid-run and the footprint stops growing with program size.

Rematerialization-for-memory (the `remat` flag + `layers.recompute`)
follows Chen et al., *Training Deep Nets with Sublinear Memory Cost*;
see docs/performance.md ("Memory").
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Set

from .core.framework import Parameter, Program

__all__ = ["ControlFlowGraph", "memory_optimize", "DonationPlan",
           "DonationError", "plan_donation", "plan_dead_frees"]


class DonationError(ValueError):
    """A requested buffer donation is provably unsafe (the buffer is
    needed after the jitted step).  Raised at plan/build time — before
    any tracing or dispatch — so the failure names the variable and the
    reason instead of surfacing as a deleted-buffer crash mid-train."""


class ControlFlowGraph:
    """Def/use + liveness over one straight-line block (reference :33).

    live_out[i] = union of live_in of successors (straight line: i+1);
    live_in[i]  = use[i] | (live_out[i] - def[i]).
    """

    def __init__(self, ops):
        self.ops = list(ops)
        n = len(self.ops)
        self.uses: List[Set[str]] = [set() for _ in range(n)]
        self.defs: List[Set[str]] = [set() for _ in range(n)]
        for i, op in enumerate(self.ops):
            for names in op.inputs.values():
                self.uses[i].update(n_ for n_ in names if n_)
            for names in op.outputs.values():
                self.defs[i].update(n_ for n_ in names if n_)
        self.live_in: List[Set[str]] = [set() for _ in range(n)]
        self.live_out: List[Set[str]] = [set() for _ in range(n)]
        self._dataflow()

    def _dataflow(self):
        for i in range(len(self.ops) - 1, -1, -1):
            self.live_out[i] = (set(self.live_in[i + 1])
                                if i + 1 < len(self.ops) else set())
            self.live_in[i] = self.uses[i] | (self.live_out[i]
                                              - self.defs[i])

    def last_touch(self) -> Dict[str, int]:
        """name -> index of the op that last reads OR writes it; past
        that index the name's buffer is finished."""
        last: Dict[str, int] = {}
        for i in range(len(self.ops)):
            for name in self.uses[i] | self.defs[i]:
                last[name] = i
        return last


def _sub_block_names(program: Program) -> Set[str]:
    """All names referenced anywhere in non-global blocks: sub-blocks
    resolve names against the parent scope, so renaming/freeing them
    out from under a sub-block is unsafe."""
    names: Set[str] = set()
    for block in program.blocks[1:]:
        names.update(block.vars.keys())
        for op in block.ops:
            for ns in op.inputs.values():
                names.update(ns)
            for ns in op.outputs.values():
                names.update(ns)
    return names


def _normalize_names(vars_or_names) -> List[str]:
    """Uniform skip/fetch list handling: accepts a bare name, a bare
    Variable, or any mix of both inside an iterable."""
    if vars_or_names is None:
        return []
    if isinstance(vars_or_names, str) or not hasattr(vars_or_names,
                                                     "__iter__"):
        vars_or_names = [vars_or_names]  # bare name/Variable
    return [v if isinstance(v, str) else v.name for v in vars_or_names]


# ---------------------------------------------------------------------------
# donation planning
# ---------------------------------------------------------------------------


class DonationPlan:
    """Result of `plan_donation`: which buffers of one jitted step may be
    handed to XLA with `donate_argnums` semantics.

    `feeds`  — feed names whose last use is inside the step (not fetched,
               actually consumed): their device buffers are dead once the
               executable returns, so XLA may reuse the HBM.
    `states` — read-write persistable names: the step returns the NEW
               value, so the OLD buffer is dead (the in-place parameter
               update the reference gets via Param->ParamOut aliasing).
    `rejected` — {name: reason} for every REQUESTED donation that is
               provably unsafe; `check()` raises DonationError on any.
    """

    def __init__(self, feeds: Iterable[str], states: Iterable[str],
                 rejected: Optional[Dict[str, str]] = None):
        self.feeds = frozenset(feeds)
        self.states = frozenset(states)
        self.rejected = dict(rejected or {})

    def check(self):
        """Raise DonationError if any explicitly requested donation was
        rejected (build-time failure, never a runtime crash)."""
        if self.rejected:
            detail = "; ".join(f"{n!r}: {r}"
                               for n, r in sorted(self.rejected.items()))
            raise DonationError(
                f"unsafe buffer donation(s) rejected at build time — "
                f"{detail}.  Remove the donate hint, or stop using the "
                "buffer after the step (drop it from fetch_list)")
        return self

    def __repr__(self):
        return (f"DonationPlan(feeds={sorted(self.feeds)}, "
                f"states={sorted(self.states)}, "
                f"rejected={self.rejected})")


def plan_donation(program: Program,
                  feed_names: Iterable[str],
                  fetch_names: Iterable[str] = (),
                  state_rw_names: Iterable[str] = (),
                  requested: Iterable[str] = ()) -> DonationPlan:
    """Derive the per-program donation plan from liveness.

    A buffer is donatable when its last use is inside the jitted step:
      * a feed var that some op consumes and that is NOT a fetch target
        (a fetched feed must survive the call — its buffer is the
        return value the caller reads);
      * a read-write state (`state_rw_names`, from
        `Executor._analyze_states`): the executable returns the updated
        value, so the pre-step buffer dies with the call.

    `requested` names (explicit `donate=True` hints on variables) are
    validated strictly: a request for a fetched var, a read-only
    persistable, a Parameter that is never rewritten, or a var the
    program never consumes lands in `plan.rejected` — call
    `plan.check()` to turn that into a build-time DonationError.
    """
    feed_names = set(_normalize_names(feed_names))
    fetch_names = set(_normalize_names(fetch_names))
    state_rw = set(_normalize_names(state_rw_names))
    requested = _normalize_names(requested)

    block = program.global_block()
    consumed: Set[str] = set()
    for blk in program.blocks:
        for op in blk.ops:
            for ns in op.inputs.values():
                consumed.update(ns)

    feeds = {n for n in feed_names
             if n in consumed and n not in fetch_names}
    states = set(state_rw)  # old buffer dead once the new value returns

    rejected: Dict[str, str] = {}
    for n in requested:
        if n in feeds or n in states:
            continue
        if n in fetch_names:
            rejected[n] = ("fetched after the step — the caller reads "
                           "this buffer once the executable returns")
            continue
        v = block.vars.get(n)
        if v is not None and (isinstance(v, Parameter)
                              or getattr(v, "persistable", False)):
            rejected[n] = ("read-only persistable state — the next step "
                           "reads the same buffer again")
            continue
        if n not in consumed:
            rejected[n] = ("never consumed by the program — the "
                           "donation could not be fulfilled")
            continue
        rejected[n] = "not provably dead inside the step"
    return DonationPlan(feeds, states, rejected)


# ---------------------------------------------------------------------------
# dead-variable freeing
# ---------------------------------------------------------------------------


def plan_dead_frees(program: Program,
                    fetch_names: Iterable[str] = ()) -> Dict[int, List[str]]:
    """{op index -> [names]} safe to drop from the local scope right
    after that op runs: the liveness pass proves nothing later reads
    them.  Protected: fetch targets (read after the last op),
    persistables/Parameters (scope-carried state), and any name
    referenced from a sub-block (resolved dynamically against the
    parent scope).  Consumed by the interpreter and segmented executor
    paths so scope footprint tracks LIVE values, not program size."""
    block = program.global_block()
    fetch = set(_normalize_names(fetch_names))
    protected = fetch | _sub_block_names(program)
    for v in program.list_vars():
        if v.persistable or isinstance(v, Parameter):
            protected.add(v.name)

    cfg = ControlFlowGraph(block.ops)
    frees: Dict[int, List[str]] = {}
    for name, idx in cfg.last_touch().items():
        if name and name not in protected:
            frees.setdefault(idx, []).append(name)
    return frees


# ---------------------------------------------------------------------------
# liveness-based rename (buffer reuse for the interpreter path)
# ---------------------------------------------------------------------------


def memory_optimize(program: Program,
                    skip_vars: Optional[Sequence] = None,
                    level: int = 0) -> int:
    """Rewrite `program` in place so dead temporaries are reused; returns
    the number of variables eliminated.

    skip_vars: names or Variables (any mix) never to optimize — pass
    everything you intend to fetch after the final op (same contract as
    the reference: fetch targets must survive to the end of the run).
    When the executor invokes this pass itself (`memory_optimize` flag),
    it passes the current feed and fetch lists automatically.
    level=0 requires exact shape+dtype match for reuse (reference
    memory_optimization_transpiler.py level semantics).
    """
    del level  # only exact-match (level 0) reuse is implemented
    block = program.global_block()
    skip: Set[str] = set(_normalize_names(skip_vars))
    skip |= _sub_block_names(program)

    cfg = ControlFlowGraph(block.ops)

    # a name's buffer is finished once past its last def AND last use
    last_touch = cfg.last_touch()
    defined: Set[str] = set()
    for d in cfg.defs:
        defined |= d

    def eligible(name: str) -> bool:
        if name in skip or name not in defined or not block.has_var(name):
            return False
        v = block.var(name)
        if isinstance(v, Parameter) or getattr(v, "persistable", False):
            return False
        if v.shape is None or v.dtype is None:
            return False
        return True

    def key_of(name):
        v = block.var(name)
        return tuple(v.shape), str(v.dtype)

    pool: List[str] = []          # finished var names, buffers reusable
    rename: Dict[str, str] = {}   # original name -> reused name
    eliminated = 0

    for i, op in enumerate(cfg.ops):
        for slot, names in op.inputs.items():
            op.inputs[slot] = [rename.get(nm, nm) for nm in names]

        for slot, names in op.outputs.items():
            out = []
            for name in names:
                if name in rename:
                    out.append(rename[name])
                    continue
                if eligible(name):
                    for cand in pool:
                        if key_of(cand) == key_of(name):
                            pool.remove(cand)
                            rename[name] = cand
                            block.vars.pop(name, None)
                            eliminated += 1
                            name = cand
                            break
                out.append(name)
            op.outputs[slot] = out

        # buffers finished at this op become reusable for later ops (for a
        # renamed var the reuse target carries the buffer, so check THAT)
        for name in cfg.uses[i] | cfg.defs[i]:
            if last_touch.get(name) != i:
                continue
            cur = rename.get(name, name)
            if eligible(cur) and cur not in pool:
                pool.append(cur)

    program.bump_version()
    return eliminated
