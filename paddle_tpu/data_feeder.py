"""DataFeeder: python minibatch -> feed dict of arrays / LoDTensors.

Reference: /root/reference/python/paddle/v2/fluid/data_feeder.py:1-115
(DataToLoDTensorConverter).
"""
from __future__ import annotations

import numpy as np

from .core.framework import Variable
from .core.lod import LoDTensor, lod_from_seq_lens
from .core.types import np_dtype

__all__ = ["DataFeeder"]


class DataFeeder:
    def __init__(self, feed_list, place=None, program=None):
        self.feed_list = feed_list
        self.place = place

    def feed(self, iterable):
        """iterable of rows; each row has one slot value per feed var.
        lod_level==0 slots are stacked dense; lod_level==1 slots are lists of
        variable-length sequences, packed flat + offset table (LoD).

        Emits a `feed.pack` profiler event: in the serial loop this is
        host time the device sits idle; the prefetch pipeline
        (reader/pipeline.py) moves it onto the worker thread."""
        from . import profiler

        with profiler.record_event("feed.pack"):
            return self._feed(iterable)

    def _feed(self, iterable):
        rows = list(iterable)
        out = {}
        for i, var in enumerate(self.feed_list):
            name = var.name if isinstance(var, Variable) else str(var)
            dtype = np_dtype(var.dtype if isinstance(var, Variable)
                             else "float32")
            lod_level = getattr(var, "lod_level", 0)
            col = [r[i] for r in rows]
            if lod_level == 0:
                arr = np.asarray(col, dtype=dtype)
                shape = getattr(var, "shape", None)
                if shape is not None and len(shape) > arr.ndim:
                    # rows carried flat features: reshape to declared shape
                    arr = arr.reshape((len(rows),) + tuple(
                        d if d > 0 else -1 for d in shape[1:]))
                out[name] = arr
            elif lod_level == 1:
                seqs = [np.asarray(s, dtype=dtype) for s in col]
                seq_lens = [len(s) for s in seqs]
                flat = (np.concatenate(seqs, axis=0) if seqs
                        else np.zeros((0,), dtype=dtype))
                if flat.ndim == 1:
                    flat = flat.reshape(-1, 1)
                out[name] = LoDTensor(flat, [lod_from_seq_lens(seq_lens)])
            else:  # nested sequences: col is list of list of sequences
                outer_lens, inner, flat_parts = [], [], []
                for doc in col:
                    outer_lens.append(len(doc))
                    for s in doc:
                        s = np.asarray(s, dtype=dtype)
                        inner.append(len(s))
                        flat_parts.append(s)
                flat = (np.concatenate(flat_parts, axis=0) if flat_parts
                        else np.zeros((0,), dtype=dtype))
                if flat.ndim == 1:
                    flat = flat.reshape(-1, 1)
                # paddle LoD convention: level-k offsets index into level-k+1
                # entries (rows for the last level)
                inner_offsets = lod_from_seq_lens(inner)
                outer_offsets = lod_from_seq_lens(outer_lens)
                out[name] = LoDTensor(flat, [outer_offsets, inner_offsets])
        return out
