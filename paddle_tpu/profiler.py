"""Profiler: per-op/segment range events with an aggregated summary table,
plus XLA trace capture.

Reference: /root/reference/paddle/fluid/platform/profiler.{h,cc}
(thread-local EventList, RecordEvent RAII around every op in
Executor::Run, EnableProfiler/DisableProfiler -> sorted table of
calls/total/min/max/ave) and python/paddle/v2/fluid/profiler.py
(`profiler` and `cuda_profiler` context managers).

TPU mapping: interpreter/segmented modes time each op (or compiled
segment) with `block_until_ready` fencing — the analogue of the
reference's cudaEvent timing on the op stream.  Whole-block compiled mode
is one fused XLA executable, so per-op attribution comes from
`xla_profiler` (jax.profiler trace, viewable in TensorBoard/Perfetto)
instead — the TPU answer to `cuda_profiler`'s nvprof output.
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, List, Optional

from .observability import tracing as _tracing

__all__ = [
    "enable_profiler",
    "disable_profiler",
    "reset_profiler",
    "profiler",
    "cuda_profiler",
    "xla_profiler",
    "record_event",
    "profiler_summary",
    "profile_compiled_ops",
    "lowered_ir_text",
    "event_totals",
    "host_blocked_fraction",
]


def lowered_ir_text(lowered) -> str:
    """Debug-info MLIR text of a `jax.jit(...).lower(...)` result — the
    loc() metadata carries the per-op named_scope the compiled executor
    emits, so scope assertions and debugging work on it.  Spans the jax
    API split: `as_text(debug_info=True)` where available, else the
    MLIR printer with debug info enabled."""
    try:
        return lowered.as_text(debug_info=True)
    except TypeError:
        from jax._src.interpreters import mlir

        return mlir.module_to_string(lowered.compiler_ir(),
                                     enable_debug_info=True)

_enabled = False
_events: Dict[str, List[float]] = {}
# events are recorded from the prefetch worker thread too
# (reader/pipeline.py): the store must tolerate concurrent
# record_event vs event_totals/profiler_summary readers
_events_lock = threading.Lock()


def is_enabled() -> bool:
    return _enabled


@contextlib.contextmanager
def record_event(name: str, sync=None):
    """RAII range event (reference platform::RecordEvent).  `sync` is
    called before reading the clock (device fence, e.g. block_until_ready).

    When trace-span recording is on (observability.tracing), the range
    also opens a span, so profiler events land in the Chrome trace with
    real wall-clock placement alongside the subsystem spans."""
    if not _enabled and not _tracing.enabled():
        yield
        return
    t0 = time.perf_counter()
    span_cm = _tracing.span(name)
    span_cm.__enter__()
    try:
        yield
    finally:
        try:
            if sync is not None:
                sync()
        finally:
            # close the span AFTER the fence so span and event time the
            # same range, but ALWAYS close it (inner finally): a raising
            # fence must not leave the context pushed on the thread's
            # span stack, which would mis-parent every later span.  Exc
            # info deliberately not forwarded: a raising op still
            # records its range, same as the event list.
            span_cm.__exit__(None, None, None)
            if _enabled:
                dt = time.perf_counter() - t0
                with _events_lock:
                    _events.setdefault(name, []).append(dt)


def enable_profiler(state: str = "All"):
    global _enabled
    assert state in ("CPU", "GPU", "TPU", "All"), state
    _enabled = True


def reset_profiler():
    with _events_lock:
        _events.clear()


def disable_profiler(sorted_key: Optional[str] = None, print_table=True):
    """Stop profiling; print/return the aggregated table
    (reference DisableProfiler + PrintProfiler)."""
    global _enabled
    _enabled = False
    table = profiler_summary(sorted_key)
    if print_table:
        print(format_summary(table))
    return table


def profiler_summary(sorted_key: Optional[str] = None):
    """Aggregated rows; `sorted_key=None` defaults to "total" descending
    (the reference PrintProfiler's default ordering — insertion order was
    a bug: the table's point is ranking hotspots).  Pass "insertion" to
    keep recording order."""
    rows = []
    with _events_lock:
        snapshot = {name: list(ts) for name, ts in _events.items()}
    for name, ts in snapshot.items():
        rows.append({
            "name": name, "calls": len(ts), "total": sum(ts),
            "min": min(ts), "max": max(ts), "ave": sum(ts) / len(ts),
        })
    key = sorted_key if sorted_key is not None else "total"
    if key in ("calls", "total", "min", "max", "ave"):
        rows.sort(key=lambda r: -r[key])
    return rows


def event_totals() -> Dict[str, float]:
    """{event name: total seconds} recorded so far — the programmatic
    view of the summary table, for user telemetry over the pipeline
    stage events (feed.pack / pipeline.*; see docs/performance.md).
    bench.py measures its loops directly instead: enabling the profiler
    fences compiled-mode dispatches and would serialize what it times."""
    with _events_lock:
        return {name: sum(ts) for name, ts in _events.items()}


def host_blocked_fraction(wall_seconds: float, events) -> float:
    """Fraction of `wall_seconds` spent inside the named host-side
    events.  Which events block the loop depends on the pipeline mode:
    the serial loop blocks in `feed.pack` (DataFeeder) + `pipeline.h2d`;
    the prefetched loop's worker absorbs those, and the loop itself only
    blocks in `pipeline.wait` (queue empty) and `pipeline.fetch_sync`
    (LazyFetch reads) — pass the event set matching the mode measured."""
    if wall_seconds <= 0:
        return 0.0
    with _events_lock:
        total = sum(sum(_events.get(e, ())) for e in events)
    return min(total / wall_seconds, 1.0)


def format_summary(rows) -> str:
    out = [f"{'Event':<40}{'Calls':>8}{'Total(ms)':>12}{'Min(ms)':>10}"
           f"{'Max(ms)':>10}{'Ave(ms)':>10}"]
    for r in rows:
        out.append(
            f"{r['name']:<40}{r['calls']:>8}{r['total'] * 1e3:>12.3f}"
            f"{r['min'] * 1e3:>10.3f}{r['max'] * 1e3:>10.3f}"
            f"{r['ave'] * 1e3:>10.3f}")
    return "\n".join(out)


@contextlib.contextmanager
def profiler(state: str = "CPU", sorted_key: Optional[str] = None,
             print_table=True):
    """`with profiler.profiler('All', 'total'):` (reference
    fluid/profiler.py:76)."""
    enable_profiler(state)
    reset_profiler()
    try:
        yield
    finally:
        disable_profiler(sorted_key, print_table=print_table)


@contextlib.contextmanager
def xla_profiler(log_dir: str = "/tmp/paddle_tpu_trace"):
    """Capture an XLA device trace via jax.profiler (TensorBoard/Perfetto
    viewable) — the TPU replacement for nvprof capture."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


# API-compat alias: reference scripts say cuda_profiler; on this stack the
# device tracer is the XLA profiler.
@contextlib.contextmanager
def cuda_profiler(output_file=None, output_mode=None, config=None):
    with xla_profiler() as d:
        yield d


# ---------------------------------------------------------------------------
# compiled-mode per-op table (reference profiler.h:120-146 semantics for
# whole-block XLA executables)
# ---------------------------------------------------------------------------


def _scope_map(hlo_text: str) -> Dict[str, str]:
    """HLO instruction name -> source op_name metadata (carries the
    per-op jax.named_scope the compiled executor emits)."""
    import re

    out = {}
    for m in re.finditer(
            r"%?([\w.\-]+) = [^\n]*metadata={[^}]*op_name=\"([^\"]+)\"",
            hlo_text):
        out[m.group(1)] = m.group(2)
    return out


def profile_compiled_ops(run_fn, steps: int = 3, hlo_text: str = "",
                         print_table: bool = True):
    """Per-op timing table for a COMPILED block: trace `steps` calls of
    `run_fn` with jax.profiler, digest the xplane into the reference's
    sorted calls/total/min/max/ave table (profiler.h:120-146) — compiled
    -mode hotspots become rankable without leaving the framework.

    Whole-block jit means the interpreter's per-op RecordEvent cannot
    see inside the fused executable; the device trace can: each XLA op
    (fusions included) is one event.  Pass the executable's
    `.as_text()` as `hlo_text` to annotate rows with the originating
    `named_scope` (framework op) each fused op belongs to.

    Returns rows: [{"name", "scope", "calls", "total", "min", "max",
    "ave"}] sorted by total desc (seconds, like profiler_summary).
    """
    import glob
    import shutil
    import tempfile

    import jax

    tmp = tempfile.mkdtemp(prefix="pt_prof_")
    try:
        with jax.profiler.trace(tmp):
            for _ in range(steps):
                out = run_fn()
                jax.block_until_ready(out)
        per_op: Dict[str, List[float]] = {}
        if hasattr(jax.profiler, "ProfileData"):
            pbs = glob.glob(tmp + "/**/*.xplane.pb", recursive=True)
            if not pbs:
                raise RuntimeError("jax.profiler produced no xplane capture")
            pd = jax.profiler.ProfileData.from_file(pbs[0])
            for plane in pd.planes:
                for line in plane.lines:
                    for ev in line.events:
                        try:
                            stats = dict(ev.stats)
                        except Exception:
                            stats = {}
                        hlo = stats.get("hlo_op")
                        if not hlo:
                            continue
                        dur = getattr(ev, "duration_ns", 0.0) or 0.0
                        if dur <= 0:
                            continue
                        per_op.setdefault(str(hlo), []).append(dur / 1e9)
        else:
            # jax without the xplane reader: the same capture also writes
            # a Chrome trace whose complete events carry args.hlo_op and
            # microsecond durations — digest that instead
            import gzip
            import json

            traces = glob.glob(tmp + "/**/*.trace.json.gz", recursive=True)
            if not traces:
                raise RuntimeError("jax.profiler produced no trace capture")
            for path in traces:
                with gzip.open(path, "rt") as fh:
                    events = json.load(fh).get("traceEvents", [])
                for ev in events:
                    hlo = (ev.get("args") or {}).get("hlo_op")
                    dur = ev.get("dur", 0)
                    if ev.get("ph") != "X" or not hlo or dur <= 0:
                        continue
                    per_op.setdefault(str(hlo), []).append(dur / 1e6)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    scopes = _scope_map(hlo_text) if hlo_text else {}
    rows = []
    for name, ts in per_op.items():
        rows.append({
            "name": name,
            "scope": scopes.get(name, ""),
            "calls": len(ts), "total": sum(ts),
            "min": min(ts), "max": max(ts), "ave": sum(ts) / len(ts),
        })
    rows.sort(key=lambda r: -r["total"])
    if print_table:
        print(format_op_table(rows))
    return rows


def format_op_table(rows, limit: int = 30) -> str:
    out = [f"{'XLA op':<44}{'Scope':<36}{'Calls':>6}{'Total(ms)':>11}"
           f"{'Min(ms)':>9}{'Max(ms)':>9}{'Ave(ms)':>9}"]
    for r in rows[:limit]:
        out.append(
            f"{r['name'][:43]:<44}{r['scope'][-35:]:<36}{r['calls']:>6}"
            f"{r['total'] * 1e3:>11.3f}{r['min'] * 1e3:>9.3f}"
            f"{r['max'] * 1e3:>9.3f}{r['ave'] * 1e3:>9.3f}")
    return "\n".join(out)
