"""Endpoint-assignment policies for pserver-mode transpiling.

Reference: /root/reference/python/paddle/v2/fluid/distributed_spliter.py
(hash_name :17, round_robin :34).  Each policy maps a list of variables to
one pserver endpoint per variable.
"""
from __future__ import annotations

import hashlib
from collections import namedtuple

__all__ = ["hash_name", "round_robin", "balanced_split", "VarDesc",
           "placement_map"]

# Minimal variable stand-in for placement decisions made OUTSIDE a
# Program (the elastic cluster controller re-runs balanced_split on
# membership changes and only carries name/shape/dtype over the wire).
# Any object with these attributes works with every split policy here.
VarDesc = namedtuple("VarDesc", ("name", "shape", "dtype"))


def _stable_hash(name: str) -> int:
    # python's builtin hash() is salted per-process; trainers and pservers
    # must agree on placement across processes, so hash the name stably
    return int.from_bytes(hashlib.md5(name.encode()).digest()[:8], "little")


def hash_name(varlist, pserver_endpoints):
    """Assign each var to endpoint[stable_hash(var.name) % n]."""
    return [pserver_endpoints[_stable_hash(v.name) % len(pserver_endpoints)]
            for v in varlist]


def round_robin(varlist, pserver_endpoints):
    """Cycle endpoints in var order (reference round_robin :34)."""
    eps = []
    i = 0
    for _ in varlist:
        eps.append(pserver_endpoints[i])
        i = (i + 1) % len(pserver_endpoints)
    return eps


def _var_nbytes(v) -> int:
    """Best-effort serialized size from program metadata: product of
    |dims| (unknown/-1 dims count 1) x dtype itemsize.  Trainer and
    pserver compute this from the SAME var descs, so placement stays
    deterministic across processes."""
    import numpy as np

    n = 1
    for d in (getattr(v, "shape", None) or ()):
        try:
            n *= max(abs(int(d)), 1)
        except (TypeError, ValueError):
            pass
    try:
        item = np.dtype(str(getattr(v, "dtype", None) or
                            "float32")).itemsize
    except TypeError:
        item = 4
    return n * item


def balanced_split(varlist, pserver_endpoints):
    """Size-weighted placement: largest var first, greedily onto the
    least-loaded endpoint (ties broken by endpoint order).  round_robin
    and hash_name count VARIABLES, so one pserver can end up owning
    nearly all the BYTES (one embedding table next to dozens of bias
    vectors); weighting by serialized size keeps per-round traffic and
    optimize work near-even.  Deterministic: same varlist + endpoints
    -> same placement in every process."""
    varlist = list(varlist)
    sizes = [_var_nbytes(v) for v in varlist]
    order = sorted(range(len(varlist)),
                   key=lambda i: (-sizes[i],
                                  getattr(varlist[i], "name", ""), i))
    load = [0] * len(pserver_endpoints)
    assign = [0] * len(varlist)
    for i in order:
        j = min(range(len(load)), key=lambda k: (load[k], k))
        assign[i] = j
        load[j] += sizes[i]
    return [pserver_endpoints[j] for j in assign]


def placement_map(varlist, pserver_endpoints, method=None):
    """{var name -> endpoint} under `method` (default balanced_split).
    The elastic runtime's canonical form: every process that re-runs
    this with the same var descs + endpoint list derives the SAME
    placement, so a cluster view only needs to carry the inputs."""
    method = method or balanced_split
    return {getattr(v, "name", str(v)): ep
            for v, ep in zip(varlist, method(varlist, pserver_endpoints))}
