"""Endpoint-assignment policies for pserver-mode transpiling.

Reference: /root/reference/python/paddle/v2/fluid/distributed_spliter.py
(hash_name :17, round_robin :34).  Each policy maps a list of variables to
one pserver endpoint per variable.
"""
from __future__ import annotations

import hashlib

__all__ = ["hash_name", "round_robin"]


def _stable_hash(name: str) -> int:
    # python's builtin hash() is salted per-process; trainers and pservers
    # must agree on placement across processes, so hash the name stably
    return int.from_bytes(hashlib.md5(name.encode()).digest()[:8], "little")


def hash_name(varlist, pserver_endpoints):
    """Assign each var to endpoint[stable_hash(var.name) % n]."""
    return [pserver_endpoints[_stable_hash(v.name) % len(pserver_endpoints)]
            for v in varlist]


def round_robin(varlist, pserver_endpoints):
    """Cycle endpoints in var order (reference round_robin :34)."""
    eps = []
    i = 0
    for _ in varlist:
        eps.append(pserver_endpoints[i])
        i = (i + 1) % len(pserver_endpoints)
    return eps
