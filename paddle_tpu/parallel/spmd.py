"""GSPMD-style sharding propagation over the Program IR.

The bridge between the annotation surface (`layers.shard`,
`layers.data(sharding=...)` — core/framework.py Variable.sharding /
op dist_attr) and the proven mesh executors: given one annotated
Program, complete a per-variable sharding table by walking the forward
ops, derive the parameter placements (Megatron column/row alternation
for matmuls, bias-follows-activation, batch over the data axis), and
report every inconsistency as a structured finding the
`sharding-consistency` analysis pass re-emits as Diagnostics.

This mirrors the reference's own evolution (PAPER.md): Fluid's
`DistributeTranspiler` rewrote programs into send/recv pserver graphs;
its successor annotated programs for collective execution.  Here the
"transpiled" artifact is a placement PLAN — sharding is an execution
property on a TPU mesh, so `transpile(mode="spmd")` records specs and
the executors place arrays under the derived NamedShardings
(configuration-as-compilation, parallel/executor.py).

The propagation is deliberately conservative: it understands the op
families the strategy implementations use (matmul, elementwise, LN,
row-wise losses, reshape/lookup plumbing) and degrades to "replicated /
batch-sharded dim 0" elsewhere — an unknown op never silently invents a
split.  XLA's own propagation then refines anything left replicated.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from ..core.framework import (GRAD_SUFFIX, Parameter, normalize_sharding,
                              sharding_axes)

__all__ = ["SpmdPlan", "propagate_sharding", "spec_to_partition",
           "backward_start_index", "has_annotations"]


def has_annotations(block) -> bool:
    """True when any var or op desc in `block` carries a sharding
    annotation — the one predicate gating both the spmd derivation in
    ParallelExecutor and the sharding-consistency pass."""
    return (any(v.sharding is not None for v in block.vars.values())
            or any(op.dist_attr.get("sharding") for op in block.ops))


# op families for propagation (forward section only)
_ELEMENTWISE = {
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min",
    "elementwise_pow",
}
_UNARY = {
    "relu", "tanh", "sigmoid", "exp", "abs", "square", "softsign",
    "reciprocal", "sqrt", "log", "softplus", "softmax", "scale", "cast",
    "dropout", "clip", "leaky_relu", "elu", "relu6", "pow", "stanh",
    "hard_shrink", "soft_shrink", "brelu",
}
# row-wise ops: batch dim preserved, features consumed
_ROWWISE = {
    "cross_entropy", "softmax_with_cross_entropy", "square_error_cost",
    "sigmoid_cross_entropy_with_logits", "accuracy", "one_hot",
    "smooth_l1",
}


@dataclasses.dataclass
class Finding:
    """One propagation finding, Diagnostic-shaped but dependency-free
    (the analysis pass converts; the transpiler prints/raises)."""

    severity: str          # "error" | "warning" | "info"
    message: str
    op_idx: Optional[int] = None
    op_type: Optional[str] = None
    hint: str = ""


@dataclasses.dataclass
class SpmdPlan:
    """Output of propagate_sharding: the placement table the spmd
    transpiler hands the executors."""

    mesh_axes: Optional[Dict[str, int]]
    batch_axis: str
    var_specs: Dict[str, tuple]          # every var with a derived spec
    param_specs: Dict[str, tuple]        # Parameter subset (placements)
    feed_specs: Dict[str, tuple]         # feed vars (data shardings)
    reduce_ops: Dict[int, Tuple[str, ...]]  # op idx -> pending-psum axes
    findings: List[Finding] = dataclasses.field(default_factory=list)

    @property
    def model_axes(self) -> Tuple[str, ...]:
        """Mesh axes used by parameter placements (the tensor-parallel
        axes), in first-use order."""
        seen: List[str] = []
        for spec in self.param_specs.values():
            for a in sharding_axes(spec):
                if a != self.batch_axis and a not in seen:
                    seen.append(a)
        return tuple(seen)

    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "error"]

    def check(self) -> "SpmdPlan":
        """Raise on error-severity findings (the memory layer's
        plan.check() convention) — the one gate both the transpiler and
        ParallelExecutor call before lowering."""
        errs = self.errors()
        if errs:
            raise ValueError(
                "sharding annotations are inconsistent:\n  "
                + "\n  ".join(f.message for f in errs))
        return self


def spec_to_partition(spec):
    """Normalized tuple spec -> jax PartitionSpec (imported lazily so
    the propagation itself stays importable without a device runtime)."""
    from jax.sharding import PartitionSpec as P

    if spec is None:
        return P()
    return P(*[tuple(e) if isinstance(e, tuple) else e for e in spec])


def _static_spec_findings(v, spec, mesh_axes, out: List[Finding]):
    """Arity/axis checks for one annotated var (shared with the
    analysis pass via the plan's findings)."""
    ndim = v.ndim
    if ndim is not None and len(spec) > ndim:
        out.append(Finding(
            "error",
            f"sharding spec {spec} of {v.name!r} has {len(spec)} "
            f"entries but the variable is rank {ndim}",
            hint="one spec entry per tensor dim (trailing dims may be "
                 "omitted)"))
        return
    axes = sharding_axes(spec)
    dups = sorted({a for a in axes if axes.count(a) > 1})
    if dups:
        out.append(Finding(
            "error",
            f"sharding spec {spec} of {v.name!r} names mesh axis(es) "
            f"{dups} more than once",
            hint="an axis may shard at most one dim of a tensor"))
    if mesh_axes is not None:
        unknown = sorted({a for a in axes if a not in mesh_axes})
        if unknown:
            out.append(Finding(
                "error",
                f"sharding spec {spec} of {v.name!r} references "
                f"undeclared mesh axis(es) {unknown} "
                f"(mesh has {sorted(mesh_axes)})"))
        elif v.shape is not None:
            for i, e in enumerate(spec):
                if e is None:
                    continue
                size = 1
                for a in (e if isinstance(e, tuple) else (e,)):
                    size *= int(mesh_axes[a])
                dim = v.shape[i]
                if dim > 0 and dim % size:
                    out.append(Finding(
                        "warning",
                        f"{v.name!r} dim {i} ({dim}) is not divisible "
                        f"by the {e!r} axis size {size} — GSPMD will "
                        "pad (correct but wasteful)",
                        hint="size the dim to a multiple of its mesh "
                             "axes"))


def backward_start_index(block) -> int:
    """Index of the first backward op (the fill_constant seeding a
    @GRAD), or len(ops) for inference programs — same detection as
    PipelineExecutor._partition."""
    for i, op in enumerate(block.ops):
        outs = op.output_names()
        if (op.type == "fill_constant" and len(outs) == 1
                and outs[0].endswith(GRAD_SUFFIX)):
            return i
    return len(block.ops)


def _desc_annotations(block, out: List[Finding]) -> Dict[str, tuple]:
    """Explicit annotations: Variable.sharding plus op-level dist_attr
    riders (deserialized programs may carry either); a var-vs-desc
    mismatch is the textbook contradictory-spec error."""
    explicit: Dict[str, tuple] = {}
    for v in block.vars.values():
        if v.sharding is not None:
            explicit[v.name] = v.sharding
    for idx, op in enumerate(block.ops):
        for name, spec in (op.dist_attr.get("sharding") or {}).items():
            spec = normalize_sharding(spec)
            if spec is None:
                continue
            if name in explicit and explicit[name] != spec:
                out.append(Finding(
                    "error",
                    f"contradictory sharding specs for {name!r}: "
                    f"variable annotation {explicit[name]} vs op "
                    f"dist_attr {spec}",
                    op_idx=idx, op_type=op.type,
                    hint="re-annotate through layers.shard (it rejects "
                         "conflicts at build time)"))
            else:
                explicit.setdefault(name, spec)
    return explicit


def _batch_entry(spec):
    return spec[0] if spec else None


def _merge(explicit, prop):
    """Merge a user annotation with a propagated spec: a None entry in
    either is an unconstrained dim the other side may fill (users
    annotate the model-parallel dims; the batch dim rides along from
    propagation).  Returns (merged, conflict_dims)."""
    n = max(len(explicit), len(prop))
    e = tuple(explicit) + (None,) * (n - len(explicit))
    p = tuple(prop) + (None,) * (n - len(prop))
    out, conflicts = [], []
    for i, (a, b) in enumerate(zip(e, p)):
        if a is None:
            out.append(b)
        elif b is None or a == b:
            out.append(a)
        else:
            out.append(a)  # the user's word wins (intentional reshard)
            conflicts.append(i)
    return tuple(out), conflicts


def _feature_entry(spec, ndim=None):
    """The trailing (feature) entry of a spec padded to ndim."""
    if not spec:
        return None
    if ndim is not None and len(spec) < ndim:
        return None  # trailing dims implicitly replicated
    return spec[-1]


def propagate_sharding(program, mesh_axes: Optional[Dict[str, int]] = None,
                       batch_axis: str = "dp") -> SpmdPlan:
    """Complete the sharding table for `program`'s global block.

    Seeds: explicit annotations (Variable.sharding / op dist_attr).
    Unannotated FEED vars (leading -1 dim, not persistable, no producer)
    default to batch-over-`batch_axis` — the dp strategy every mesh run
    uses.  The walk covers the forward section only: backward specs
    mirror forward ones and the executors/XLA derive them.

    Rules (the Megatron discipline, pipeline_program._derive_tp_specs
    generalized to explicit specs):
      * mul: a column-split weight (None, a) makes the output
        feature-sharded over `a`; a row-split weight (a, None) consumes
        a feature-sharded input and emits a pending psum over `a`
        (recorded in plan.reduce_ops; XLA inserts the all-reduce).  A
        feature-sharded input meeting an UNannotated weight infers the
        row split; an annotated output feature spec back-infers the
        column split.
      * elementwise: specs join; a rank-1 parameter operand (bias)
        inherits the activation's feature entry.
      * layer_norm / row-wise losses: full-feature ops — feature
        sharding is consumed (a sharded input is flagged as a reshard),
        batch sharding passes through.
      * everything else: dim-0 batch sharding propagates when the
        output keeps a leading batch dim; feature specs do not (no
        silent invention of splits).
    """
    block = program.global_block()
    mesh_axes = dict(mesh_axes) if mesh_axes is not None \
        else (dict(program.mesh_axes) if program.mesh_axes else None)
    findings: List[Finding] = []
    explicit = _desc_annotations(block, findings)

    for name, spec in explicit.items():
        if name in block.vars:
            _static_spec_findings(block.vars[name], spec, mesh_axes,
                                  findings)

    produced = {n for op in block.ops for n in op.output_names()}
    specs: Dict[str, tuple] = dict(explicit)
    feed_specs: Dict[str, tuple] = {}
    for v in block.vars.values():
        if v.persistable or v.name in produced:
            continue
        if v.name in explicit:
            # annotated feed (e.g. a replicated shared table)
            feed_specs[v.name] = explicit[v.name]
        elif v.shape and v.shape[0] == -1:
            specs[v.name] = (batch_axis,)
            feed_specs[v.name] = specs[v.name]

    param_specs: Dict[str, tuple] = {
        n: s for n, s in explicit.items()
        if n in block.vars and isinstance(block.vars[n], Parameter)}
    reduce_ops: Dict[int, Tuple[str, ...]] = {}

    def is_param(n):
        v = block.vars.get(n)
        return v is not None and isinstance(v, Parameter)

    def ndim_of(n):
        v = block.vars.get(n)
        return v.ndim if v is not None else None

    stop = backward_start_index(block)
    # reverse pre-pass: a user annotates the activation they HOLD (the
    # post-bias/post-activation fc output); push that intent backward
    # through the feature-preserving chain so the producing matmul can
    # back-infer its column split
    goals: Dict[str, tuple] = dict(explicit)
    for op in reversed(block.ops[:stop]):
        if op.type not in _UNARY and op.type not in _ELEMENTWISE:
            continue
        outs = op.outputs.get("Out") or op.outputs.get("Y") or []
        x = op.inputs.get("X", [None])[0]
        if not (outs and x) or x in goals:
            continue
        g = goals.get(outs[0])
        if g is not None:
            goals[x] = g

    def set_spec(name, spec, idx, op):
        """Record a propagated spec, merging with any explicit
        annotation; a hard per-dim disagreement keeps the user's word
        and is flagged as an intentional reshard."""
        spec = spec if spec is None or any(e is not None for e in spec) \
            else None
        if spec is None:
            return
        if name in explicit:
            merged, conflicts = _merge(explicit[name], spec)
            if conflicts:
                findings.append(Finding(
                    "warning",
                    f"{op.type} output {name!r} propagates as {spec} "
                    f"but is annotated {explicit[name]} (dims "
                    f"{conflicts} disagree) — GSPMD will reshard here",
                    op_idx=idx, op_type=op.type,
                    hint="intentional reshards are fine; otherwise "
                         "align the annotation with its producer"))
            specs[name] = merged
            return
        specs.setdefault(name, spec)

    def batch_through(idx, op):
        """Default rule: leading batch sharding follows any output that
        keeps a leading -1 batch dim."""
        b = None
        for n in op.input_names():
            e = _batch_entry(specs.get(n))
            if e is not None:
                b = e
                break
        if b is None:
            return
        for n in op.output_names():
            v = block.vars.get(n)
            if v is not None and v.shape and v.shape[0] == -1:
                set_spec(n, (b,), idx, op)

    for idx, op in enumerate(block.ops[:stop]):
        t = op.type
        if t == "mul" or (t == "matmul"
                          and not op.attrs.get("transpose_X")
                          and not op.attrs.get("transpose_Y")):
            x = op.inputs.get("X", [None])[0]
            y = op.inputs.get("Y", [None])[0]
            out = op.outputs.get("Out", [None])[0]
            if not (x and y and out):
                continue
            xs, ys = specs.get(x), specs.get(y)
            x_feat = _feature_entry(xs, ndim_of(x))
            y_nd = ndim_of(y) or 2
            # back-infer a column split from an annotated output (the
            # annotation may sit downstream past bias/activation ops —
            # the reverse `goals` pre-pass carried it here)
            goal = goals.get(out)
            if ys is None and is_param(y) and goal is not None:
                o_feat = _feature_entry(goal, ndim_of(out))
                if o_feat is not None and x_feat is None:
                    ys = (None,) * (y_nd - 1) + (o_feat,)
                    param_specs[y] = ys
                    specs[y] = ys
            # infer a row split from a feature-sharded input
            if ys is None and is_param(y) and x_feat is not None:
                ys = (x_feat,) + (None,) * (y_nd - 1)
                param_specs[y] = ys
                specs[y] = ys
            if ys is not None:
                y_contract = ys[0] if ys else None
                y_out = _feature_entry(ys, y_nd)
                if x_feat is not None and y_contract is None:
                    findings.append(Finding(
                        "warning",
                        f"{t} at op {idx}: input {x!r} is "
                        f"feature-sharded ({xs}) but weight {y!r} "
                        f"({ys}) does not split the contraction dim — "
                        "GSPMD will all-gather the activation",
                        op_idx=idx, op_type=t,
                        hint="row-split the weight (axis, None) to "
                             "contract locally with one psum"))
                if (x_feat is not None and y_contract is not None
                        and x_feat != y_contract):
                    findings.append(Finding(
                        "error",
                        f"{t} at op {idx}: contraction dim of {x!r} is "
                        f"sharded over {x_feat!r} but weight {y!r} "
                        f"splits it over {y_contract!r} — "
                        "contradictory specs for one contraction",
                        op_idx=idx, op_type=t))
                if y_contract is not None and x_feat == y_contract:
                    # row-parallel matmul: local contraction + psum
                    reduce_ops[idx] = tuple(
                        y_contract if isinstance(y_contract, tuple)
                        else (y_contract,))
                    y_out = None if y_out == y_contract else y_out
                b = _batch_entry(xs)
                o_nd = ndim_of(out) or 2
                o_spec = (b,) + (None,) * max(o_nd - 2, 0) + (y_out,)
                set_spec(out, o_spec, idx, op)
            else:
                batch_through(idx, op)
        elif t in _ELEMENTWISE:
            x = op.inputs.get("X", [None])[0]
            y = op.inputs.get("Y", [None])[0]
            out = op.outputs.get("Out", [None])[0]
            xs = specs.get(x)
            x_feat = _feature_entry(xs, ndim_of(x))
            if (y and is_param(y) and ndim_of(y) == 1
                    and y not in param_specs and x_feat is not None):
                # bias follows its activation's feature sharding
                param_specs[y] = (x_feat,)
                specs[y] = (x_feat,)
            ysp = specs.get(y)
            if (xs is not None and ysp is not None
                    and ndim_of(x) == ndim_of(y) and xs != ysp):
                findings.append(Finding(
                    "warning",
                    f"{t} at op {idx}: operands {x!r} {xs} and {y!r} "
                    f"{ysp} carry different shardings — GSPMD will "
                    "reshard one side (resharding hotspot)",
                    op_idx=idx, op_type=t,
                    hint="annotate both operands alike"))
            if out and xs is not None:
                set_spec(out, xs, idx, op)
        elif t in _UNARY:
            x = op.inputs.get("X", [None])[0]
            xs = specs.get(x)
            if xs is not None:
                for n in op.outputs.get("Out", []):
                    set_spec(n, xs, idx, op)
        elif t in ("layer_norm", "batch_norm"):
            x = op.inputs.get("X", [None])[0]
            xs = specs.get(x)
            x_feat = _feature_entry(xs, ndim_of(x))
            if x_feat is not None:
                findings.append(Finding(
                    "warning",
                    f"{t} at op {idx}: input {x!r} is feature-sharded "
                    f"({xs}) but normalization needs the full feature "
                    "dim — GSPMD will all-gather (resharding hotspot)",
                    op_idx=idx, op_type=t,
                    hint="keep the residual stream replicated between "
                         "Megatron-split sublayers"))
            if xs is not None:
                out = (op.outputs.get("Y") or op.outputs.get("Out")
                       or [None])[0]
                if out:
                    nd = ndim_of(x)
                    # clear the FEATURE entry only when the spec
                    # actually reaches it; a short batch-only spec
                    # passes through unchanged (batch sharding must
                    # survive normalization layers)
                    if nd is not None and len(xs) >= nd and xs:
                        o_spec = tuple(xs[:-1]) + (None,)
                    else:
                        o_spec = xs
                    set_spec(out, o_spec, idx, op)
        elif t in _ROWWISE:
            # per-row losses/metrics consume the full feature dim: a
            # feature-sharded input forces a gather (the docstring's
            # "feature sharding is consumed" rule)
            x = (op.inputs.get("X", [None])[0]
                 or op.inputs.get("Logits", [None])[0])
            xs = specs.get(x)
            x_feat = _feature_entry(xs, ndim_of(x))
            if x_feat is not None:
                findings.append(Finding(
                    "warning",
                    f"{t} at op {idx}: input {x!r} is feature-sharded "
                    f"({xs}) but the op reduces over the full feature "
                    "dim — GSPMD will all-gather (resharding hotspot)",
                    op_idx=idx, op_type=t,
                    hint="psum the row-parallel matmul before the "
                         "loss (keep the logits replicated)"))
            batch_through(idx, op)
        else:
            batch_through(idx, op)

    # parameters never inferred stay replicated — by design
    plan = SpmdPlan(mesh_axes=mesh_axes, batch_axis=batch_axis,
                    var_specs=specs, param_specs=param_specs,
                    feed_specs=feed_specs, reduce_ops=reduce_ops,
                    findings=findings)
    return plan
